"""Build/install — ≙ the reference's ``setup.py`` (L0).

The reference conditionally compiles ~20 CUDA extensions behind flags
(``--cpp_ext --cuda_ext --fmha ...``).  Here the device side is JAX/XLA/
Pallas (nothing to compile), and the one native piece — the host-ops
library (flatten/unflatten, masked-LM input pipeline;
``apex_tpu/_native/host_ops.cpp``) — is built on first import with a
graceful numpy fallback, so a plain ``pip install .`` always works.
``python setup.py build_native`` prebuilds it eagerly (the ``--cpp_ext``
analog).
"""

import subprocess
import sys

from setuptools import Command, find_packages, setup


class build_native(Command):
    """Eagerly compile the host-ops library (≙ ``--cpp_ext``)."""

    description = "compile apex_tpu/_native/host_ops.cpp"
    user_options = []

    def initialize_options(self):
        pass

    def finalize_options(self):
        pass

    def run(self):
        code = subprocess.call(
            [
                sys.executable,
                "-c",
                "import apex_tpu._native as n; n._load(); "
                "print('native available:', n.NATIVE_AVAILABLE)",
            ]
        )
        if code:
            raise SystemExit(code)


setup(
    name="apex_tpu",
    version="0.1.0",
    description=(
        "TPU-native training-acceleration framework with the capabilities "
        "of NVIDIA Apex: fused ops (Pallas), fused optimizers, precision "
        "policies, and dp/tp/sp/pp/cp parallelism over a jax.sharding.Mesh"
    ),
    packages=find_packages(include=["apex_tpu", "apex_tpu.*"]),
    package_data={"apex_tpu._native": ["host_ops.cpp"]},
    python_requires=">=3.10",
    install_requires=["jax", "flax", "optax", "numpy", "einops"],
    cmdclass={"build_native": build_native},
)
