"""apex_tpu — a TPU-native training-acceleration framework.

A brand-new, idiomatic JAX/XLA/Pallas framework with the capabilities of
NVIDIA Apex (reference: CodeFisheng/apex).  Where the reference ships CUDA
kernels (``csrc/``), NCCL process groups (``apex/parallel``,
``apex/transformer``) and torch monkey-patching (``apex/amp``), this framework
ships Pallas TPU kernels, a single named ``jax.sharding.Mesh``, and a
functional precision-policy layer.

Subpackages
-----------
- :mod:`apex_tpu.parallel_state` — mesh / axis registry
  (≙ ``apex/transformer/parallel_state.py``).
- :mod:`apex_tpu.ops` — fused ops: LayerNorm/RMSNorm, scaled masked softmax,
  RoPE, softmax-xentropy, flash attention (≙ ``csrc/``, ``apex/normalization``,
  ``apex/contrib/{xentropy,multihead_attn,fmha}``).
- :mod:`apex_tpu.optimizers` — fused multi-tensor optimizers
  (≙ ``apex/optimizers``, ``csrc/multi_tensor_*``).
- :mod:`apex_tpu.amp` — precision policies + dynamic loss scaling
  (≙ ``apex/amp``, ``apex/fp16_utils``).
- :mod:`apex_tpu.parallel` — data parallelism + SyncBatchNorm + LARC
  (≙ ``apex/parallel``).
- :mod:`apex_tpu.transformer` — tensor/sequence/pipeline parallelism
  (≙ ``apex/transformer``).
- :mod:`apex_tpu.contrib` — contrib parity layer (≙ ``apex/contrib``).
- :mod:`apex_tpu.models` — reference models used by the benchmark configs
  (BERT-Large, GPT, ResNet-50).
- :mod:`apex_tpu.checkpoint` — sharded save/restore + step-numbered
  checkpoint management (orbax-backed).
- :mod:`apex_tpu.resilience` — fault injection, guarded steps,
  retry/backoff, and the preemption-safe auto-resume loop.
- :mod:`apex_tpu.observability` — unified step telemetry: device-side
  metric registry, MFU/goodput meters, JSONL/CSV/TensorBoard export,
  and scheduled trace windows.
- :mod:`apex_tpu.analysis` — jaxpr/HLO graph linter: transfer /
  promotion / donation / retrace / collective-consistency passes over
  traced and compiled step programs.
- :mod:`apex_tpu.train` — the single composable training entry point:
  a declarative dp×tp trainer with framework-chosen (ZeRO-style)
  update sharding, self-verified against the analysis passes at build.
- :mod:`apex_tpu.serve` — AOT-compiled serving: paged KV cache,
  continuous batching, TTFT SLOs.
"""

__version__ = "0.1.0"

# Light-weight eager imports only; heavy subpackages are imported lazily so
# `import apex_tpu` stays cheap (the reference's `apex/__init__.py` likewise
# defers contrib imports behind availability probes).  _compat must come
# first: it grafts jax.shard_map / jax.lax.axis_size / jax.lax.pcast onto
# pinned jax releases that predate them, which everything else assumes.
from apex_tpu import _compat  # noqa: F401
from apex_tpu import parallel_state  # noqa: F401

_LAZY_SUBMODULES = (
    "analysis",
    "ops",
    "optimizers",
    "amp",
    "parallel",
    "transformer",
    "contrib",
    "models",
    "fp16_utils",
    "normalization",
    "mlp",
    "fused_dense",
    "checkpoint",
    "resilience",
    "observability",
    "serve",
    "train",
)


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        import importlib

        try:
            module = importlib.import_module(f"apex_tpu.{name}")
        except ModuleNotFoundError as e:
            # PEP 562: availability probes (hasattr/getattr) must see
            # AttributeError, mirroring the reference's per-feature
            # try-import probing in apex/contrib/*/__init__.py.
            raise AttributeError(
                f"module 'apex_tpu' has no attribute {name!r}"
            ) from e
        globals()[name] = module
        return module
    raise AttributeError(f"module 'apex_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals().keys()) + list(_LAZY_SUBMODULES))
