"""Mesh and axis registry — the TPU-native model-parallel state.

Capability parity with ``apex/transformer/parallel_state.py`` ::
``initialize_model_parallel``, ``get_tensor_model_parallel_group/_rank/
_world_size``, ``get_pipeline_model_parallel_*``, ``get_data_parallel_*``,
``is_pipeline_first_stage`` / ``is_pipeline_last_stage``,
``set_virtual_pipeline_model_parallel_rank``, ``destroy_model_parallel``.

The reference builds ~10 ``torch.distributed`` process groups over NCCL for a
3D (DP x PP x TP) rank grid.  On TPU there are no process groups: the single
SPMD program runs over a named :class:`jax.sharding.Mesh` and "groups" are
mesh axes.  A collective over the tensor-parallel "group" is simply
``jax.lax.psum(x, axis_name="tp")`` inside :func:`jax.shard_map`.

Axis layout
-----------
The canonical mesh is ``(dp, pp, tp)`` with ``tp`` innermost (fastest
varying) so that tensor-parallel collectives — the highest-bandwidth traffic,
fired twice per transformer layer per direction (see SURVEY.md §3.4) — map to
physically adjacent chips over ICI, while ``dp`` (lowest frequency, gradient
all-reduce once per step) may span DCN on multi-slice topologies.  Megatron
sequence parallelism ("sp") reuses the ``tp`` axis by construction (the SP
all-gather / reduce-scatter pair replaces the TP identity/all-reduce pair over
the *same* ranks), exactly like the reference where SP collectives run on the
TP process group.

Rank queries
------------
In SPMD there is no host-side "my rank": every host traces one program for
all devices.  Rank helpers (:func:`get_tensor_model_parallel_rank` etc.)
return a *traced* index via ``jax.lax.axis_index`` and are therefore valid
only inside ``shard_map`` (or any context binding the axis name).  World-size
helpers are static Python ints valid anywhere.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import _compat

__all__ = [
    "DATA_PARALLEL_AXIS",
    "PIPELINE_PARALLEL_AXIS",
    "CONTEXT_PARALLEL_AXIS",
    "TENSOR_PARALLEL_AXIS",
    "EXPERT_PARALLEL_AXIS",
    "get_expert_model_parallel_world_size",
    "get_expert_model_parallel_rank",
    "initialize_model_parallel",
    "model_parallel_is_initialized",
    "get_mesh",
    "get_data_parallel_world_size",
    "get_context_parallel_world_size",
    "get_context_parallel_rank",
    "get_tensor_model_parallel_world_size",
    "get_pipeline_model_parallel_world_size",
    "get_data_parallel_rank",
    "get_tensor_model_parallel_rank",
    "get_pipeline_model_parallel_rank",
    "get_tensor_model_parallel_src_rank",
    "get_pipeline_model_parallel_next_rank",
    "get_pipeline_model_parallel_prev_rank",
    "is_pipeline_first_stage",
    "is_pipeline_last_stage",
    "get_virtual_pipeline_model_parallel_rank",
    "set_virtual_pipeline_model_parallel_rank",
    "get_virtual_pipeline_model_parallel_world_size",
    "set_virtual_pipeline_model_parallel_world_size",
    "destroy_model_parallel",
    "register_sequence_parallel_param",
    "sequence_parallel_param_paths",
    "clear_sequence_parallel_params",
    "divide",
    "bound_axis_size",
    "axis_is_bound",
    "data_parallel_sharding",
    "named_sharding",
    "replicated_sharding",
]

DATA_PARALLEL_AXIS = "dp"
PIPELINE_PARALLEL_AXIS = "pp"
CONTEXT_PARALLEL_AXIS = "cp"
TENSOR_PARALLEL_AXIS = "tp"
# Expert parallelism rides the dp axis (Megatron's convention: the expert
# group is carved from the data-parallel world; no extra mesh axis) — see
# apex_tpu.transformer.moe.  The alias names the intent at call sites.
EXPERT_PARALLEL_AXIS = DATA_PARALLEL_AXIS

_AXIS_ORDER = (
    DATA_PARALLEL_AXIS,
    PIPELINE_PARALLEL_AXIS,
    CONTEXT_PARALLEL_AXIS,
    TENSOR_PARALLEL_AXIS,
)


@dataclasses.dataclass
class _ParallelState:
    mesh: Mesh
    data_parallel_size: int
    pipeline_model_parallel_size: int
    tensor_model_parallel_size: int
    context_parallel_size: int = 1
    virtual_pipeline_model_parallel_size: Optional[int] = None
    # Virtual-pipeline rank is plain host state mutated by the interleaved
    # 1F1B scheduler, mirroring the reference's module-global
    # (parallel_state.py :: set_virtual_pipeline_model_parallel_rank).
    virtual_pipeline_model_parallel_rank: Optional[int] = None
    # SP partial-grad param marks live ON the state object so that
    # destroy/initialize cycles (and thus different models) can never
    # share marks (advisor r2: process-global registry cross-contamination).
    sequence_parallel_param_paths: set = dataclasses.field(
        default_factory=set
    )


_STATE: Optional[_ParallelState] = None


def _ici_device_mesh(dp, pp, cp, tp, devices):
    """Topology-aware single-granule layout: on a real TPU slice a naive
    reshape of jax.devices() can place a tp group across non-adjacent
    chips; mesh_utils computes an ICI-friendly layout (innermost axis on
    the tightest torus dimension)."""
    import numpy as np
    from jax.experimental import mesh_utils

    try:
        return mesh_utils.create_device_mesh((dp, pp, cp, tp), devices=devices)
    except Exception as e:
        import warnings

        warnings.warn(
            f"mesh_utils.create_device_mesh failed ({type(e).__name__}: {e});"
            " falling back to naive device ordering — tp groups may span"
            " non-adjacent chips, degrading collective bandwidth",
            RuntimeWarning,
            stacklevel=2,
        )
        return np.asarray(devices).reshape(dp, pp, cp, tp)


def initialize_model_parallel(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    context_parallel_size: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    dcn_data_parallel: bool = False,
) -> Mesh:
    """Create and register the global ``(dp, pp, tp)`` mesh.

    ≙ ``apex/transformer/parallel_state.py :: initialize_model_parallel``.
    Where the reference carves ``world_size`` ranks into NCCL groups, this
    reshapes ``jax.devices()`` into a named mesh.  ``dp`` is derived:
    ``n_devices // (tp * pp)``, with the same divisibility requirement the
    reference enforces.

    ``dcn_data_parallel=True`` is the multi-slice layout (≙ the
    reference's convention of putting the DP all-reduce on the
    inter-node fabric and TP inside NVLink islands): the mesh is built
    with ``mesh_utils.create_hybrid_device_mesh`` so that one dp
    sub-axis of size ``jax.process_count()``-granularity spans DCN while
    pp/cp/tp (and the rest of dp) stay on ICI.  Gradient psum over
    ``dp`` then does a hierarchical reduce: ICI first, one DCN hop
    last.  Ignored (with a warning) when the topology gives a single
    slice or the hybrid construction is unavailable.

    Returns the mesh (also retrievable via :func:`get_mesh`).
    """
    global _STATE
    if _STATE is not None:
        # ≙ the reference's "group is already initialized" asserts.
        raise RuntimeError(
            "model parallel state is already initialized — call "
            "destroy_model_parallel() first"
        )
    explicit_devices = devices is not None
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    tp = int(tensor_model_parallel_size)
    pp = int(pipeline_model_parallel_size)
    cp = int(context_parallel_size)
    if tp < 1 or pp < 1 or cp < 1:
        raise ValueError("parallel sizes must be >= 1")
    if n % (tp * pp * cp) != 0:
        raise RuntimeError(
            f"world size ({n}) is not divisible by tensor_model_parallel_size "
            f"({tp}) x pipeline_model_parallel_size ({pp}) x "
            f"context_parallel_size ({cp})"
        )
    dp = n // (tp * pp * cp)
    if virtual_pipeline_model_parallel_size is not None:
        if pp < 2:
            raise RuntimeError(
                "pipeline-model-parallel size should be greater than 1 with "
                "interleaved schedule"
            )
    import numpy as np

    if explicit_devices:
        device_array = np.asarray(devices).reshape(dp, pp, cp, tp)
    elif dcn_data_parallel:
        # Multi-slice: split dp into (dcn_granules, dp_within) and ask
        # mesh_utils for a hybrid mesh — model axes never cross DCN.
        from jax.experimental import mesh_utils

        granules = len({d.process_index for d in devices})
        try:
            if granules == 1 or dp % granules != 0:
                raise ValueError(
                    f"dp={dp} not splittable over {granules} DCN granule(s)"
                )
            # process_is_granule matches the process_index-based granule
            # count above (jax's default groups by slice_index, which CPU
            # devices lack and which disagrees with this count on
            # multi-host-per-slice pods)
            device_array = mesh_utils.create_hybrid_device_mesh(
                (dp // granules, pp, cp, tp),
                (granules, 1, 1, 1),
                devices=devices,
                process_is_granule=True,
            )
        except Exception as e:
            import warnings

            warnings.warn(
                f"hybrid (DCN) mesh unavailable ({type(e).__name__}: {e}); "
                "using the single-granule ICI layout",
                RuntimeWarning,
                stacklevel=2,
            )
            device_array = _ici_device_mesh(dp, pp, cp, tp, devices)
    else:
        device_array = _ici_device_mesh(dp, pp, cp, tp, devices)
    mesh = Mesh(device_array, _AXIS_ORDER)
    _STATE = _ParallelState(
        mesh=mesh,
        data_parallel_size=dp,
        pipeline_model_parallel_size=pp,
        tensor_model_parallel_size=tp,
        context_parallel_size=cp,
        virtual_pipeline_model_parallel_size=virtual_pipeline_model_parallel_size,
        virtual_pipeline_model_parallel_rank=(
            0 if virtual_pipeline_model_parallel_size is not None else None
        ),
    )
    # Fresh mesh epoch ⇒ fresh SP registry: drop any meshless-era marks so
    # they cannot bleed into this mesh's models.
    _SEQUENCE_PARALLEL_PARAM_PATHS.clear()
    return mesh


def model_parallel_is_initialized() -> bool:
    """≙ parallel_state.py :: model_parallel_is_initialized."""
    return _STATE is not None


def _state() -> _ParallelState:
    if _STATE is None:
        raise RuntimeError(
            "model parallel state is not initialized — call "
            "apex_tpu.parallel_state.initialize_model_parallel() first"
        )
    return _STATE


def get_mesh() -> Mesh:
    """The registered global mesh (axes ``dp``, ``pp``, ``cp``, ``tp``)."""
    return _state().mesh


# ---------------------------------------------------------------------------
# World sizes — static host ints.
# ---------------------------------------------------------------------------


def get_data_parallel_world_size() -> int:
    return _state().data_parallel_size


def get_tensor_model_parallel_world_size() -> int:
    return _state().tensor_model_parallel_size


def get_context_parallel_world_size() -> int:
    """Size of the ``cp`` axis (ring/context parallelism; 1 = disabled).

    No reference analog: the reference has no context parallelism
    (SURVEY §2.3 capability envelope) — this is the TPU-native extension
    for long-context scaling over the ICI torus."""
    return _state().context_parallel_size


def get_pipeline_model_parallel_world_size() -> int:
    return _state().pipeline_model_parallel_size


# ---------------------------------------------------------------------------
# Ranks — traced values, valid inside shard_map over the global mesh.
# ---------------------------------------------------------------------------


def axis_is_bound(axis: str) -> bool:
    """Whether ``axis`` is a bound mesh axis here (inside shard_map) —
    regardless of its size (a bound size-1 axis is still bound)."""
    try:
        _compat.axis_size(axis)
        return True
    except (NameError, KeyError):
        return False


def bound_axis_size(axis: str) -> int:
    """Size of ``axis`` if bound (inside shard_map over the mesh), else 1.

    The shared probe for modules that degrade gracefully outside a mesh
    (SyncBatchNorm, groupbn, SwitchMoe): jax raises NameError/KeyError for
    an unbound name depending on the path, both meaning "no such axis
    here".
    """
    try:
        return _compat.axis_size(axis)
    except (NameError, KeyError):
        return 1


def _axis_index(axis: str):
    try:
        return jax.lax.axis_index(axis)
    except NameError as e:  # axis name not bound: not inside shard_map
        raise RuntimeError(
            f"rank query for axis {axis!r} is only meaningful inside "
            "jax.shard_map over the global mesh (SPMD has no host-side rank); "
            "use the *_world_size helpers for host logic"
        ) from e


def get_expert_model_parallel_world_size() -> int:
    """Experts shard over the dp axis; its size is the ep world size.
    (≙ Megatron's get_expert_model_parallel_world_size — absent in the
    reference fork, provided here for the MoE extension.)"""
    return _state().data_parallel_size


def get_expert_model_parallel_rank():
    """Traced ep rank (== dp rank) — call inside shard_map."""
    return _axis_index(EXPERT_PARALLEL_AXIS)


def get_data_parallel_rank():
    return _axis_index(DATA_PARALLEL_AXIS)


def get_tensor_model_parallel_rank():
    return _axis_index(TENSOR_PARALLEL_AXIS)


def get_pipeline_model_parallel_rank():
    return _axis_index(PIPELINE_PARALLEL_AXIS)


def get_context_parallel_rank():
    return _axis_index(CONTEXT_PARALLEL_AXIS)


def get_tensor_model_parallel_src_rank():
    """Rank 0 of the tensor-parallel group.

    ≙ parallel_state.py :: get_tensor_model_parallel_src_rank.  In mesh terms
    the "source" is simply index 0 along ``tp``; data broadcast from it is a
    no-op under SPMD (all members trace identical programs), so this exists
    for API parity and for `tensor_parallel.data.broadcast_data`.
    """
    return 0


def get_pipeline_model_parallel_next_rank():
    pp = get_pipeline_model_parallel_world_size()
    return (get_pipeline_model_parallel_rank() + 1) % pp


def get_pipeline_model_parallel_prev_rank():
    pp = get_pipeline_model_parallel_world_size()
    return (get_pipeline_model_parallel_rank() - 1) % pp


def is_pipeline_first_stage(ignore_virtual: bool = False):
    """Traced boolean (inside shard_map); honors virtual pipeline rank.

    ≙ parallel_state.py :: is_pipeline_first_stage.
    """
    if not ignore_virtual:
        vpp = get_virtual_pipeline_model_parallel_world_size()
        if vpp is not None and get_virtual_pipeline_model_parallel_rank() != 0:
            return False
    return get_pipeline_model_parallel_rank() == 0


def is_pipeline_last_stage(ignore_virtual: bool = False):
    if not ignore_virtual:
        vpp = get_virtual_pipeline_model_parallel_world_size()
        if vpp is not None and (
            get_virtual_pipeline_model_parallel_rank() != vpp - 1
        ):
            return False
    pp = get_pipeline_model_parallel_world_size()
    return get_pipeline_model_parallel_rank() == pp - 1


# ---------------------------------------------------------------------------
# Virtual pipeline (interleaved 1F1B) bookkeeping — host state.
# ---------------------------------------------------------------------------


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    return _state().virtual_pipeline_model_parallel_rank


def set_virtual_pipeline_model_parallel_rank(rank: int) -> None:
    _state().virtual_pipeline_model_parallel_rank = rank


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _state().virtual_pipeline_model_parallel_size


def set_virtual_pipeline_model_parallel_world_size(size: Optional[int]) -> None:
    st = _state()
    st.virtual_pipeline_model_parallel_size = size
    if size is None:
        st.virtual_pipeline_model_parallel_rank = None
    elif st.virtual_pipeline_model_parallel_rank is None:
        # Keep the first/last-stage predicates well-defined when virtual PP
        # is enabled after init (rank defaults to chunk 0, as in __init__).
        st.virtual_pipeline_model_parallel_rank = 0


def destroy_model_parallel() -> None:
    """≙ parallel_state.py :: destroy_model_parallel."""
    global _STATE
    _STATE = None
    clear_sequence_parallel_params()


# ---------------------------------------------------------------------------
# Sequence-parallel partial-gradient param registry.
#
# ≙ Megatron's ``param.sequence_parallel = True`` attribute marking: under
# Megatron-style SP, params used inside the sequence-sharded region (layer
# norms, RowParallelLinear biases, MoE router/experts, position embeddings)
# are REPLICATED across tp but each rank computes their gradient from only
# its S/tp sequence shard — the true gradient is the SUM over tp ranks.
# Torch marks the parameter object; params here are plain arrays, so
# modules register the param's tree path at trace time instead, and
# ``allreduce_sequence_parallel_gradients`` (tensor_parallel.mappings)
# psums exactly the registered paths.
#
# Scoping: marks are stored on the live ``_ParallelState`` when a mesh is
# initialized — destroy/initialize cycles start with a clean registry, so
# two models traced across cycles can never cross-contaminate.  The
# module-level set only backs the meshless case (tp=1 unit tests) and is
# cleared on both destroy AND initialize.
# ---------------------------------------------------------------------------

_SEQUENCE_PARALLEL_PARAM_PATHS: set = set()


def _sp_registry() -> set:
    if _STATE is not None:
        return _STATE.sequence_parallel_param_paths
    return _SEQUENCE_PARALLEL_PARAM_PATHS


def register_sequence_parallel_param(path) -> None:
    """Mark the param at ``path`` (module path + param name, a tuple of
    strings, excluding the "params" collection key) as having tp-partial
    gradients under sequence parallelism."""
    _sp_registry().add(tuple(str(p) for p in path))


def sequence_parallel_param_paths() -> frozenset:
    return frozenset(_sp_registry())


def clear_sequence_parallel_params() -> None:
    _sp_registry().clear()


# ---------------------------------------------------------------------------
# Sharding helpers (no reference analog — mesh idioms the rest of the
# framework builds on).
# ---------------------------------------------------------------------------


def named_sharding(*spec) -> NamedSharding:
    """NamedSharding over the global mesh for a PartitionSpec."""
    return NamedSharding(get_mesh(), P(*spec))


def data_parallel_sharding(ndim: int) -> NamedSharding:
    """Batch-leading sharding: dim 0 split over ``dp``, rest replicated."""
    spec = [DATA_PARALLEL_AXIS] + [None] * (ndim - 1)
    return NamedSharding(get_mesh(), P(*spec))


def replicated_sharding() -> NamedSharding:
    return NamedSharding(get_mesh(), P())


def divide(numerator: int, denominator: int) -> int:
    """≙ apex/transformer/utils.py :: divide (ensure_divisibility + floordiv)."""
    if numerator % denominator != 0:
        raise ValueError(f"{numerator} is not divisible by {denominator}")
    return numerator // denominator
