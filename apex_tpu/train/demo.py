"""The canonical dp×tp demo trainer — ONE program description shared by
``bench.py --config train3d``, ``tools/graph_lint.py --target train``,
``tools/shard_report.py --target train``, the verify_tier1.sh TRAIN
gate, and ``tests/test_train.py`` — so the bench rows, the CI proofs,
and the tests can never describe different programs.

The model is a Megatron-style tensor-parallel MLP block written
directly against :mod:`apex_tpu.transformer.tensor_parallel.mappings`:
``w1`` column-sharded, ``w2`` row-sharded, one fwd all-reduce over
``tp`` (the row-parallel output reduction); the batch shards its row
axis over ``dp``.  Small enough that every configuration builds in
seconds on a mocked 8-device CPU mesh, big enough (≈0.5 MiB of params,
over the demo's 192 KiB ``zero_min_bytes`` floor) that the
update-sharding heuristic genuinely chooses ZeRO on every dp≥2 arm —
the bench rows exercise the headline decision, not a hand-forced mode.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel_state as ps
from apex_tpu.train.config import TrainConfig
from apex_tpu.train.trainer import Trainer, TrainStep

__all__ = [
    "DEMO_DIM",
    "DEMO_HIDDEN",
    "DEMO_ROWS",
    "demo_rules",
    "demo_params",
    "demo_batch",
    "demo_loss",
    "demo_model_collectives",
    "demo_config",
    "build_demo",
]

DEMO_DIM = 128
DEMO_HIDDEN = 512
DEMO_ROWS = 256

#: params ≈ 515 KiB globally, ≈ 257 KiB per tp=2 shard — both over this
#: floor, so ``auto`` shards the update at every dp≥2 arm
DEMO_ZERO_MIN_BYTES = 192 << 10


def demo_rules():
    """The regex→PartitionSpec table (fmengine idiom): column-parallel
    ``w1``/``b1``, row-parallel ``w2``, replicated ``b2``."""
    return [
        (r"^w1$", P(None, "tp")),
        (r"^b1$", P("tp")),
        (r"^w2$", P("tp", None)),
        (r"^b2$", P()),
    ]


def demo_params(seed: int = 0, dim: int = DEMO_DIM,
                hidden: int = DEMO_HIDDEN):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return {
        "w1": 0.05 * jax.random.normal(k1, (dim, hidden), jnp.float32),
        "b1": jnp.zeros((hidden,), jnp.float32),
        "w2": 0.05 * jax.random.normal(k2, (hidden, dim), jnp.float32),
        "b2": jnp.zeros((dim,), jnp.float32),
    }


def demo_batch(seed: int = 1, rows: int = DEMO_ROWS, dim: int = DEMO_DIM):
    """A fixed toy regression batch (x, y) with a learnable mapping, so
    the bench rows can print a falling loss as their sanity signal."""
    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randn(rows, dim), jnp.float32)
    w = jnp.asarray(rs.randn(dim, dim) / np.sqrt(dim), jnp.float32)
    return x, x @ w


def demo_loss(params, batch):
    """Column→row parallel MLP regression loss; runs inside the
    trainer's shard_map with the ``tp`` axis bound (size 1 included:
    the mappings are skipped then, so a tp=1 compile carries no
    degenerate collectives to explain)."""
    from apex_tpu.transformer.tensor_parallel.mappings import (
        copy_to_tensor_model_parallel_region as copy_to,
        reduce_from_tensor_model_parallel_region as reduce_from,
    )

    x, y = batch
    tp = ps.bound_axis_size("tp")
    h = (copy_to(x) if tp > 1 else x) @ params["w1"] + params["b1"]
    h = jax.nn.gelu(h)
    out = h @ params["w2"]
    if tp > 1:
        out = reduce_from(out)
    out = out + params["b2"]
    return jnp.mean(jnp.square(out - y))


def demo_model_collectives(dp: int, tp: int, rows: int = DEMO_ROWS,
                           dim: int = DEMO_DIM):
    """The model's OWN declared plan entries: with tp>1, exactly one
    f32 all-reduce over ``tp`` per step — the row-parallel output
    reduction of (rows/dp, dim) activations.  (The column-parallel
    input copy's backward psum never traces: the batch is not
    differentiated.)"""
    if tp <= 1:
        return []
    act = (rows // max(dp, 1)) * dim * 4
    return [{
        "kind": "all-reduce", "axis": "tp", "count": 1,
        "bytes": [0, act + 1024], "dtypes": ["f32"],
    }]


def demo_config(
    dp: int,
    tp: int,
    *,
    wire: str = "f32",
    update_sharding: str = "auto",
    verify: str = "error",
    hbm_budget: Optional[int] = None,
    chunks: Optional[int] = None,
    optimizer: str = "adam",
    rows: int = DEMO_ROWS,
    dim: int = DEMO_DIM,
    devices=None,
) -> TrainConfig:
    return TrainConfig(
        mesh={"dp": dp, "tp": tp},
        rules=demo_rules(),
        optimizer=optimizer,
        learning_rate=1e-2,
        wire=wire,
        chunks=chunks,
        update_sharding=update_sharding,
        zero_min_bytes=DEMO_ZERO_MIN_BYTES,
        model_collectives=demo_model_collectives(dp, tp, rows, dim),
        verify=verify,
        hbm_budget=hbm_budget,
        devices=devices,
    )


def build_demo(
    dp: int,
    tp: int,
    *,
    wire: str = "f32",
    update_sharding: str = "auto",
    verify: str = "error",
    hbm_budget: Optional[int] = None,
    chunks: Optional[int] = None,
    optimizer: str = "adam",
    seed: int = 0,
    rows: int = DEMO_ROWS,
    dim: int = DEMO_DIM,
    hidden: int = DEMO_HIDDEN,
    devices=None,
) -> TrainStep:
    """Build the demo trainer at (dp, tp) — the exact program the bench
    rows time and the CI gates prove."""
    cfg = demo_config(
        dp, tp, wire=wire, update_sharding=update_sharding,
        verify=verify, hbm_budget=hbm_budget, chunks=chunks,
        optimizer=optimizer, rows=rows, dim=dim, devices=devices,
    )
    trainer = Trainer(cfg)
    params = demo_params(seed, dim, hidden)
    batch = demo_batch(seed + 1, rows, dim)
    return trainer.build(
        demo_loss, params, batch, name=f"train3d/dp{dp}tp{tp}"
    )
