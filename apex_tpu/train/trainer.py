"""`apex_tpu.train.Trainer` — one composable 3D-parallel train step.

The TorchTitan shape (PAPERS.md): a production-default trainer that
composes the framework's parallelisms from ONE declarative config
instead of asking the user to hand-wire DDP, ZeRO, TP and the comm
engine.  ``Trainer(config).build(loss_fn, params, example_batch)``
returns a compiled, donation-aliased SPMD step over a ``(dp, tp)``
mesh with:

- params placed by the config's regex→PartitionSpec rule table (the
  ``fmengine`` idiom, resolved through
  :func:`apex_tpu.analysis.match_partition_rules` so a leaf no rule
  covers fails the build naming the path);
- the gradient sync routed through the shared comm engine
  (:mod:`apex_tpu.parallel.comm` — ``wire=``/``chunks=`` exactly as
  ``docs/comm.md`` defines them);
- the weight update **sharded across dp replicas when the framework's
  heuristic says it pays** (:func:`apex_tpu.train.sharding
  .decide_update_sharding` — "Automatic Cross-Replica Sharding of
  Weight Update in Data-Parallel Training", PAPERS.md; the ZeRO
  machinery of :mod:`apex_tpu.parallel.distributed_fused_optimizers`),
  overridable via ``update_sharding=``;
- a :class:`~apex_tpu.observability.MetricRegistry` fold INSIDE the
  jitted step (no per-step host sync) and a
  :meth:`TrainStep.fit` loop riding
  :func:`apex_tpu.resilience.run_resilient` with goodput accounting
  and the flight recorder armable from the environment.

**Self-verifying builds.**  At build time the trainer runs
:func:`apex_tpu.analysis.check` over the compiled step with
``expect_sharding``/``expect_plan``/``hbm_budget`` DERIVED FROM ITS OWN
CONFIG — the same rule table that built ``in_specs``, the same
:func:`comm.sync_plan`/:func:`comm.zero_plan` arithmetic the traced
sync uses, plus the model's declared collectives.  A trainer that
compiles an unplanned collective, a replicated-but-should-be-sharded
param, or a step over the HBM budget raises
:class:`TrainBuildError` before handing out the step
(``verify="warn"`` demotes to a printed report, ``"off"`` skips).

See ``docs/training.md`` for the config reference and worked examples.
"""

from __future__ import annotations

import sys
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu._tree_util import to_f32
from apex_tpu.parallel import comm
from apex_tpu.train.config import TrainConfig
from apex_tpu.train import sharding as tsh
from apex_tpu.train.sharding import ZERO_TWINS  # noqa: F401 (re-export)

__all__ = ["Trainer", "TrainStep", "TrainBuildError", "ZERO_TWINS"]

_DP = "dp"
_TP = "tp"


class TrainBuildError(RuntimeError):
    """A trainer build that failed its own static verification (or its
    config could not be realized on the visible devices)."""


def _tree_map(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def _replicated_specs(tree):
    return _tree_map(lambda _: P(), tree)


class Trainer:
    """Build compiled 3D-parallel train steps from a
    :class:`~apex_tpu.train.TrainConfig`."""

    def __init__(self, config: TrainConfig):
        self.config = config

    # -- mesh -----------------------------------------------------------
    def mesh(self) -> Mesh:
        cfg = self.config
        need = cfg.dp * cfg.tp
        devices = list(cfg.devices) if cfg.devices else jax.devices()
        if len(devices) < need:
            raise TrainBuildError(
                f"mesh {cfg.mesh_dict()} needs {need} devices, only "
                f"{len(devices)} visible (CPU: XLA_FLAGS="
                "--xla_force_host_platform_device_count=8 mocks a mesh)"
            )
        grid = np.asarray(devices[:need]).reshape(cfg.dp, cfg.tp)
        return Mesh(grid, (_DP, _TP))

    # -- optimizer resolution -------------------------------------------
    # One optimizer_kwargs vocabulary serves BOTH realizations: the
    # replicated optax factories spell the moments beta1=/beta2=, the
    # distributed twins betas=(b1, b2) — translated here, because the
    # update-sharding heuristic may flip a config between the two modes
    # just by the model growing past the floor, and a config that was
    # valid in one mode must stay valid in the other.

    def _replicated_tx(self):
        cfg = self.config
        name = cfg.optimizer_name()
        if name is None:
            return cfg.optimizer
        from apex_tpu import optimizers

        kwargs = dict(cfg.optimizer_kwargs)
        if "betas" in kwargs:
            kwargs["beta1"], kwargs["beta2"] = kwargs.pop("betas")
        factory = optimizers.by_name(name)
        return factory(learning_rate=cfg.learning_rate, **kwargs)

    def _distributed_tx(self):
        cfg = self.config
        from apex_tpu.parallel import (
            DistributedFusedAdam,
            DistributedFusedLAMB,
        )

        cls = {"adam": DistributedFusedAdam, "lamb": DistributedFusedLAMB}[
            cfg.optimizer_name()
        ]
        kwargs = dict(cfg.optimizer_kwargs)
        if "beta1" in kwargs or "beta2" in kwargs:
            kwargs["betas"] = (
                kwargs.pop("beta1", 0.9), kwargs.pop("beta2", 0.999),
            )
        return cls(
            lr=cfg.learning_rate,
            axis_name=_DP,
            wire=cfg.wire,
            param_wire=cfg.param_wire,
            chunks=cfg.chunks,
            block=cfg.block,
            **kwargs,
        )

    # -- the build ------------------------------------------------------
    def build(
        self,
        loss_fn: Callable[[Any, Any], Any],
        params,
        example_batch,
        *,
        name: str = "train",
    ) -> "TrainStep":
        """Compose, compile, and verify the step.  ``loss_fn(params,
        batch) -> scalar`` is traced INSIDE ``shard_map`` over the
        ``(dp, tp)`` mesh: params arrive as their local shards per the
        rule table, the batch as its dp slice; tensor-parallel
        collectives inside the model (``apex_tpu.transformer
        .tensor_parallel``) bind the ``tp`` axis.  ``params`` and
        ``example_batch`` are GLOBAL host trees."""
        cfg = self.config
        mesh = self.mesh()
        mesh_dict = cfg.mesh_dict()

        try:
            param_specs = tsh.resolve_param_specs(cfg.rules, params)
        except ValueError as e:
            raise TrainBuildError(str(e)) from e
        batch_specs = tsh.resolve_batch_specs(cfg.batch_rules,
                                              example_batch)
        decision = tsh.decide_update_sharding(params, cfg, param_specs)
        if decision.shard and cfg.track_grad_norm and cfg.tp > 1:
            raise TrainBuildError(
                "track_grad_norm with a tp axis needs the replicated "
                "update path (the ZeRO flat buffer duplicates "
                "tp-replicated leaves across groups, so a flat-shard "
                "norm would overcount them): set "
                "update_sharding='replicate' or drop track_grad_norm"
            )

        # local (per-device) param template — the dp sync moves these
        local_template = _tree_map(
            lambda l, s: jax.ShapeDtypeStruct(
                tsh.local_shape(l.shape, s, mesh_dict), l.dtype
            ),
            params, param_specs,
        )
        spec_leaves = tsh._spec_leaves(param_specs, params)
        tp_varying = [
            any(
                _TP in [n for n in (
                    (e if isinstance(e, (tuple, list)) else (e,))
                ) if n is not None]
                for e in (tuple(s) if s is not None else ())
            )
            for s in spec_leaves
        ]

        registry = None
        if cfg.metrics:
            from apex_tpu import observability as obs

            registry = obs.MetricRegistry(fetch_every=cfg.fetch_every)
            registry.gauge("train/loss", unit="loss")
            if cfg.track_grad_norm:
                registry.gauge("train/grad_norm")

        if decision.shard:
            dist = self._distributed_tx()
            state, state_specs, body = self._build_zero(
                loss_fn, params, param_specs, local_template, dist,
                registry, tp_varying, mesh_dict,
            )
            plan_entries = comm.zero_plan(
                dist.spec.flat_size, cfg.dp, _DP,
                wire=cfg.wire, param_wire=cfg.param_wire,
                chunks=cfg.chunks, block=cfg.block,
            )
            tx = dist
        else:
            tx = self._replicated_tx()
            state, state_specs, body = self._build_ddp(
                loss_fn, params, param_specs, tx, registry, tp_varying,
            )
            local_sizes = [
                int(np.prod(t.shape) or 1)
                for t in jax.tree_util.tree_leaves(local_template)
            ]
            plan_entries = comm.sync_plan(
                local_sizes, cfg.dp, _DP,
                wire=cfg.wire, chunks=cfg.chunks, block=cfg.block,
                min_size=cfg.min_sync_size,
            )

        expect_plan = {
            "mesh": mesh_dict,
            "collectives": list(plan_entries) + list(
                cfg.model_collectives
            ),
            "allow_unplanned_bytes": cfg.unplanned_tolerance,
        }
        expect_sharding = {
            "mesh": mesh_dict,
            "rules": tsh.exact_entry_rules([
                ("state", state, state_specs),
                ("batch", example_batch, batch_specs),
            ]),
            "min_bytes": cfg.min_shard_bytes,
        }

        aux_specs = {"loss": P()}
        if cfg.track_grad_norm:
            aux_specs["grad_norm"] = P()
        if registry is not None:
            # the metric fold rides the AUX output, not the carried
            # state: every gauge is recomputed per step, so folding it
            # into a donated state would leave a dead (never-aliased)
            # input behind — the build's own donation lint catches
            # exactly that
            aux_specs["metrics"] = _replicated_specs(registry.init())

        smapped = jax.shard_map(
            body, mesh=mesh,
            in_specs=(state_specs, batch_specs),
            out_specs=(state_specs, aux_specs),
            check_vma=False,
        )
        jitted = jax.jit(smapped, donate_argnums=(0,))

        step = TrainStep(
            trainer=self, name=name, mesh=mesh, step=jitted,
            state=state, state_specs=state_specs,
            batch_specs=batch_specs, registry=registry,
            decision=decision, expect_sharding=expect_sharding,
            expect_plan=expect_plan, example_batch=example_batch,
            loss_fn=loss_fn, tx=tx,
        )
        if cfg.verify != "off":
            step.report = step.verify(example_batch)
            errors = step.report.errors()
            if errors and cfg.verify == "error":
                raise TrainBuildError(
                    "trainer build failed its own verification "
                    f"({len(errors)} ERROR finding(s)):\n"
                    + step.report.render()
                )
            if step.report.findings and cfg.verify == "warn":
                print(step.report.render(), file=sys.stderr)
        return step

    def build_guarded(self, loss_fn, params, **kwargs):
        """The two-phase guarded-amp shape (grads program + update
        program with a host boundary between them) — see
        :func:`apex_tpu.train.guarded.build_guarded`."""
        from apex_tpu.train.guarded import build_guarded

        return build_guarded(self, loss_fn, params, **kwargs)

    # -- ddp / replicated-update composition ---------------------------
    def _build_ddp(self, loss_fn, params, param_specs, tx, registry,
                   tp_varying):
        cfg = self.config
        dp = cfg.dp
        opt_state = tx.init(params)
        opt_specs = tsh.mirror_optimizer_specs(
            opt_state, params, param_specs
        )
        state = {"params": params, "opt": opt_state}
        state_specs = {"params": param_specs, "opt": opt_specs}

        def body(state, batch):
            params = state["params"]
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            if dp > 1:
                grads = comm.sync_gradients(
                    grads, _DP, wire=cfg.wire, chunks=cfg.chunks,
                    block=cfg.block, min_size=cfg.min_sync_size,
                )
                loss = jax.lax.pmean(loss, _DP)
            aux = {"loss": loss}
            if cfg.track_grad_norm:
                aux["grad_norm"] = _global_grad_norm(
                    grads, tp_varying, cfg.tp
                )
            updates, new_opt = tx.update(grads, state["opt"], params)
            new_params = _tree_map(
                lambda p, u: p + u.astype(p.dtype), params, updates
            )
            new_state = {"params": new_params, "opt": new_opt}
            if registry is not None:
                folded = {"train/loss": loss}
                if cfg.track_grad_norm:
                    folded["train/grad_norm"] = aux["grad_norm"]
                aux["metrics"] = registry.update(registry.init(), folded)
            return new_state, aux

        return state, state_specs, body

    # -- zero / sharded-update composition ------------------------------
    def _build_zero(self, loss_fn, params, param_specs, local_template,
                    dist, registry, tp_varying, mesh_dict):
        cfg = self.config
        tp = cfg.tp
        # the distributed optimizer's flat spec is built on the LOCAL
        # (tp-sharded) tree: reduce-scatter/all-gather then run per tp
        # group automatically inside the (dp, tp) shard_map
        zeros_local = _tree_map(
            lambda t: jnp.zeros(t.shape, t.dtype), local_template
        )
        st0 = dist.init(zeros_local, world=cfg.dp)
        fspec = dist.spec

        # master shards: tp rank t owns segment t of the concatenated
        # flat state — spec P(("tp", "dp")) tiles tp-major, dp-minor,
        # exactly the (dp, tp) device grid's owner layout
        flats = []
        for t in range(tp):
            local = _tree_map(
                lambda l, s: tsh.slice_local(l, s, _TP, t, tp),
                params, param_specs,
            )
            flat, _ = ravel_pytree(to_f32(local))
            flats.append(jnp.pad(
                flat, (0, fspec.padded_size - fspec.flat_size)
            ))
        master = jnp.concatenate(flats) if tp > 1 else flats[0]
        if tp > 1:
            zeros = jnp.zeros((tp * fspec.padded_size,), jnp.float32)
            opt_state = st0._replace(m=zeros, v=zeros, master=master)
        else:
            opt_state = st0._replace(master=master)
        flat_spec = P((_TP, _DP)) if tp > 1 else P(_DP)
        opt_specs = _tree_map(
            lambda x: flat_spec if getattr(x, "ndim", 0) == 1 else P(),
            opt_state,
        )

        state = {"params": params, "opt": opt_state}
        state_specs = {"params": param_specs, "opt": opt_specs}

        def body(state, batch):
            params = state["params"]
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            loss = jax.lax.pmean(loss, _DP)
            aux = {"loss": loss}
            if cfg.track_grad_norm:
                # exact: the reduce-scattered shards partition the flat
                # buffer (build() rejects track_grad_norm + tp>1, where
                # duplicated replicated leaves would overcount)
                new_params, new_opt, gnorm = dist.update_with_norm(
                    grads, state["opt"], params
                )
                aux["grad_norm"] = gnorm
            else:
                new_params, new_opt = dist.update_inside_shard_map(
                    grads, state["opt"], params
                )
            new_state = {"params": new_params, "opt": new_opt}
            if registry is not None:
                folded = {"train/loss": loss}
                if cfg.track_grad_norm:
                    folded["train/grad_norm"] = aux["grad_norm"]
                aux["metrics"] = registry.update(registry.init(), folded)
            return new_state, aux

        return state, state_specs, body


def _global_grad_norm(grads, tp_varying, tp: int):
    """Global L2 norm of a dp-synced gradient tree whose leaves may be
    tp-sharded: tp-sharded partial square-sums psum over ``tp``,
    replicated leaves count once."""
    leaves = jax.tree_util.tree_leaves(grads)
    sq_rep = sum(
        (jnp.sum(jnp.square(l.astype(jnp.float32)))
         for l, v in zip(leaves, tp_varying) if not v),
        jnp.float32(0),
    )
    sq_tp = sum(
        (jnp.sum(jnp.square(l.astype(jnp.float32)))
         for l, v in zip(leaves, tp_varying) if v),
        jnp.float32(0),
    )
    if tp > 1 and any(tp_varying):
        sq_tp = jax.lax.psum(sq_tp, _TP)
    return jnp.sqrt(sq_rep + sq_tp)


class TrainStep:
    """A built trainer step: the compiled program plus everything the
    verification and run layers need (state template, declared plans,
    registry, the build's lint report)."""

    def __init__(self, *, trainer, name, mesh, step, state, state_specs,
                 batch_specs, registry, decision, expect_sharding,
                 expect_plan, example_batch, loss_fn, tx):
        self.trainer = trainer
        self.config = trainer.config
        self.name = name
        self.mesh = mesh
        self.step = step
        self.state = state
        self.state_specs = state_specs
        self.batch_specs = batch_specs
        self.registry = registry
        self.decision = decision
        self.expect_sharding = expect_sharding
        self.expect_plan = expect_plan
        self.example_batch = example_batch
        self.loss_fn = loss_fn
        self.tx = tx
        self.report = None
        self.goodput = None

    def __call__(self, state, batch):
        return self.step(state, batch)

    @property
    def mode(self) -> str:
        """``"zero"`` (update sharded across dp) or ``"ddp"``."""
        return self.decision.mode

    def collective_plan(self) -> dict:
        """The per-mesh-axis plan this step promises — the
        ``analysis.sharding.reshard_pass`` schema; also what the build
        verified the compiled HLO against."""
        return self.expect_plan

    def place(self, state):
        """Re-place a state tree onto the trainer's mesh per its specs
        — needed after a checkpoint restore, which commits arrays to a
        single device; already-conformant arrays pass through without
        a copy."""
        from jax.sharding import NamedSharding

        shardings = _tree_map(
            lambda spec: NamedSharding(self.mesh, spec), self.state_specs
        )
        return _tree_map(
            lambda x, s: jax.device_put(x, s), state, shardings
        )

    def n_params(self) -> int:
        return sum(
            int(p.size)
            for p in jax.tree_util.tree_leaves(self.state["params"])
        )

    def tokens_per_step(self) -> int:
        leaves = jax.tree_util.tree_leaves(self.example_batch)
        return int(leaves[0].shape[0]) if leaves else 0

    # -- verification ----------------------------------------------------
    def verify(self, batch=None, *, hbm_budget=None):
        """Run the full analysis suite over THIS compiled step against
        the trainer's own derived expectations; returns the
        :class:`apex_tpu.analysis.Report` with the shard-plan/memory
        sections attached (what ``tools/shard_report.py --target
        train`` renders)."""
        from apex_tpu import analysis

        batch = batch if batch is not None else self.example_batch
        budget = (
            hbm_budget if hbm_budget is not None
            else self.config.hbm_budget
        )
        report = analysis.check(
            self.step, self.state, batch,
            donate_argnums=(0,),
            expect_sharding=self.expect_sharding,
            expect_plan=self.expect_plan,
            hbm_budget=budget,
            name=f"{self.name}/{self.mode}",
        )
        analysis.attach_shard_sections(
            report, [(f"{self.name}/{self.mode}", report.hlo_text)],
            expect_sharding=self.expect_sharding,
        )
        return report

    # -- the composed run loop ------------------------------------------
    def fit(
        self,
        batch_fn: Callable[[int], Any],
        num_steps: int,
        *,
        directory,
        save_interval_steps: int = 10,
        max_to_keep: int = 3,
        observer: Any = None,
        flight: Any = None,
        reporter: Any = None,
        report_every: int = 10,
        checkpoint: str = "async",
    ):
        """Drive the step with the production defaults wired in:
        :func:`apex_tpu.resilience.run_resilient` (auto-resume,
        SIGTERM-safe, checkpoint retries), a
        :class:`~apex_tpu.observability.GoodputAccountant` on the
        observer stream, a :class:`~apex_tpu.observability.StepMeter`,
        and a flight recorder armable via ``APEX_TPU_FLIGHT``
        (``flight=`` to pass one explicitly).  ``checkpoint="async"``
        (default) saves through the zero-stall
        :class:`~apex_tpu.goodput.AsyncCheckpointEngine` — host
        snapshot on the step path, background write, drain at
        shutdown (docs/goodput.md); ``"sync"`` keeps the orbax
        manager inline.  Returns the
        :class:`~apex_tpu.resilience.runner.RunResult`; the goodput
        ledger lands on ``self.goodput``."""
        from apex_tpu import observability as obs
        from apex_tpu.resilience import ObserverFanout, run_resilient

        tokens = self.tokens_per_step()
        meter = obs.StepMeter(
            tokens_per_step=tokens,
            flops_per_step=obs.transformer_train_flops(
                self.n_params(), tokens
            ),
        )
        goodput = obs.GoodputAccountant()
        self.goodput = goodput
        registry = self.registry
        counter = {"step": 0}

        def step_fn(state, batch):
            # a restore (auto-resume / rollback) hands back arrays
            # committed to one device; re-place them on the mesh
            new_state, aux = self.step(self.place(state), batch)
            counter["step"] += 1
            if registry is not None:
                registry.observe(counter["step"], aux["metrics"])
            meter.tick()
            if reporter is not None and (
                counter["step"] % report_every == 0
            ):
                reporter.report(counter["step"])
            return new_state, {"skipped": False, "loss": aux["loss"]}

        return run_resilient(
            step_fn,
            self.state,
            batch_fn,
            directory=directory,
            num_steps=num_steps,
            save_interval_steps=save_interval_steps,
            max_to_keep=max_to_keep,
            observer=ObserverFanout([goodput, observer]),
            flight=flight,
            checkpoint=checkpoint,
        )
