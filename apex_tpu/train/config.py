"""Declarative training configuration — the single entry point's knobs.

One :class:`TrainConfig` names everything :class:`apex_tpu.train.Trainer`
needs to compose a 3D-parallel step: the mesh (``dp``/``tp`` axes; ``pp``
is reserved and validated to 1), the regex→PartitionSpec rule table (the
``fmengine`` idiom — :func:`apex_tpu.analysis.match_partition_rules`),
the comm-engine wire knobs (``docs/comm.md``), the update-sharding
policy (``docs/training.md`` "The update-sharding heuristic"), and the
self-verification expectations (budget, tolerance, severity).

The same config drives BOTH surfaces: the trainer builds its
``in_specs``/``in_shardings`` from the rule table AND hands the exact
same table to :func:`apex_tpu.analysis.check` as ``expect_sharding`` —
one table, two consumers, so the plan the step compiles with is the plan
the linter proves (ISSUE 9's machinery, cashed in).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping, Optional, Sequence, Tuple, Union

from apex_tpu.parallel import comm

__all__ = ["TrainConfig", "UPDATE_SHARDING_MODES", "VERIFY_LEVELS"]

#: ``auto`` lets the framework decide (the headline: "Automatic
#: Cross-Replica Sharding of Weight Update in Data-Parallel Training",
#: PAPERS.md); ``shard``/``replicate`` are the explicit overrides.
UPDATE_SHARDING_MODES = ("auto", "shard", "replicate")

#: ``error`` = a build that fails its own analysis raises (the default:
#: a trainer that compiles an unplanned collective or a
#: replicated-but-should-be-sharded param must not hand out the step);
#: ``warn`` = findings print + ride the report; ``off`` = skip checks.
VERIFY_LEVELS = ("error", "warn", "off")


@dataclasses.dataclass
class TrainConfig:
    """Config for :class:`apex_tpu.train.Trainer`.

    Field reference: ``docs/training.md``.
    """

    #: mesh axis sizes, e.g. ``{"dp": 2, "tp": 2}``.  Axis ORDER is the
    #: device-grid order (dp-major).  ``pp`` is reserved: accepted in
    #: the mapping but must be 1 until the pipeline stage lands.
    mesh: Mapping[str, int]

    #: regex → PartitionSpec over PARAM-RELATIVE paths (``"w1"``,
    #: ``"block_0/mlp/kernel"``).  First match wins; a param no rule
    #: covers fails the build loudly naming the path — a plan with
    #: holes is not a plan (silent replication is the defect ISSUE 9's
    #: ``sharding-replicated`` rule exists to catch).
    rules: Sequence[Tuple[str, Any]]

    #: regex → PartitionSpec over BATCH-relative paths.  Default: every
    #: batch leaf shards its leading axis over ``dp``.
    batch_rules: Optional[Sequence[Tuple[str, Any]]] = None

    #: ``"adam"`` | ``"lamb"`` | ``"sgd"`` — resolved through
    #: :func:`apex_tpu.optimizers.by_name` — or an optax-style
    #: GradientTransformation (the latter pins
    #: ``update_sharding="replicate"``: only the named optimizers have
    #: a ZeRO twin).
    optimizer: Union[str, Any] = "adam"
    optimizer_kwargs: Mapping[str, Any] = dataclasses.field(
        default_factory=dict
    )
    learning_rate: float = 1e-3

    # -- comm engine knobs (docs/comm.md), threaded through unchanged --
    wire: str = "f32"
    param_wire: Optional[str] = None
    chunks: Optional[int] = None
    block: int = comm.DEFAULT_BLOCK
    #: leaves under this many ELEMENTS ride the exact psum in the ddp
    #: path (comm.sync_gradients's min_size)
    min_sync_size: int = 1024

    # -- update sharding (the headline) --------------------------------
    update_sharding: str = "auto"
    #: the heuristic's floor: ``auto`` shards the update only when the
    #: f32 param bytes reach this (below it the optimizer state fits
    #: everywhere and the extra all-gather structure buys nothing)
    zero_min_bytes: int = 4 << 20

    # -- model-declared collectives ------------------------------------
    #: plan entries (reshard_pass schema) for the collectives the MODEL
    #: itself traces — tp activation all-reduces, MoE all-to-alls.  The
    #: trainer merges them with the comm engine's own plan; anything
    #: compiled beyond the merged plan fails the build.
    model_collectives: Sequence[Mapping[str, Any]] = ()

    # -- self-verification ---------------------------------------------
    verify: str = "error"
    hbm_budget: Optional[int] = None
    #: conformance floor for the sharding pass (bytes) — small leaves
    #: (biases, scalars) replicate for free
    min_shard_bytes: int = 1 << 10
    #: unplanned-collective latency tolerance (bytes) forwarded to the
    #: reshard pass
    unplanned_tolerance: int = 4096

    # -- observability ---------------------------------------------------
    #: build a MetricRegistry and fold train/loss (+ train/grad_norm
    #: when tracked) INSIDE the jitted step
    metrics: bool = True
    #: fold the post-sync global gradient norm into the metrics.  Costs
    #: one scalar psum (and one over tp for tp-sharded leaves); turn
    #: off to pin exact collective counts in a declared plan.
    track_grad_norm: bool = False
    #: device→host metric fetch cadence (MetricRegistry fetch_every)
    fetch_every: int = 8

    #: explicit device list (default: the first dp·tp of jax.devices())
    devices: Optional[Sequence[Any]] = None

    # ------------------------------------------------------------------
    def __post_init__(self):
        mesh = dict(self.mesh)
        for axis, size in mesh.items():
            if axis not in ("dp", "tp", "pp"):
                raise ValueError(
                    f"unknown mesh axis {axis!r}; the trainer composes "
                    "over dp/tp (pp reserved)"
                )
            if int(size) < 1:
                raise ValueError(f"mesh axis {axis}={size} must be >= 1")
        if int(mesh.get("pp", 1)) != 1:
            raise NotImplementedError(
                "pipeline parallelism (pp) is reserved in TrainConfig: "
                "the axis is part of the schema but the trainer does not "
                "compose it yet — use "
                "apex_tpu.transformer.pipeline_parallel directly"
            )
        comm.check_wire(self.wire)
        if self.param_wire is not None:
            comm.check_wire(self.param_wire)
        if self.update_sharding not in UPDATE_SHARDING_MODES:
            raise ValueError(
                f"update_sharding must be one of {UPDATE_SHARDING_MODES}, "
                f"got {self.update_sharding!r}"
            )
        if self.verify not in VERIFY_LEVELS:
            raise ValueError(
                f"verify must be one of {VERIFY_LEVELS}, "
                f"got {self.verify!r}"
            )
        if not isinstance(self.optimizer, str):
            if not (hasattr(self.optimizer, "init")
                    and hasattr(self.optimizer, "update")):
                raise ValueError(
                    "optimizer must be a name ('adam'/'lamb'/'sgd') or an "
                    "optax-style GradientTransformation with init/update"
                )

    # -- derived ---------------------------------------------------------
    @property
    def dp(self) -> int:
        return int(dict(self.mesh).get("dp", 1))

    @property
    def tp(self) -> int:
        return int(dict(self.mesh).get("tp", 1))

    def mesh_dict(self) -> dict:
        """``{"dp": ..., "tp": ...}`` in device-grid order — the exact
        mapping every ``expect_sharding``/``expect_plan`` carries, so
        :func:`apex_tpu.analysis.sharding.mesh_axis_groups` attributes
        replica groups the same way the trainer laid devices out."""
        return {"dp": self.dp, "tp": self.tp}

    def optimizer_name(self) -> Optional[str]:
        return self.optimizer if isinstance(self.optimizer, str) else None
