"""Guarded two-phase programs — the resilient example's step shape.

The fused :meth:`Trainer.build` step is the production default, but the
chaos-drill path (``examples/simple/resilient``, the verify_tier1 OBS /
FLIGHT gates) needs the gradient tree to surface on the host BETWEEN
gradient computation and the update, so the chaos ``grads`` site can
poison it deterministically.  :func:`build_guarded` composes that
two-program shape from the same config machinery:

- ``compute_grads(params, scaler_state, batch) -> (loss, scaled)`` —
  shard_map over the trainer's mesh; the dp gradient sync runs INSIDE
  via the shared :class:`~apex_tpu.parallel.DistributedDataParallel`
  engine (``wire=``/``chunks=`` from the config; ``accum=K``
  microbatches accumulate locally with ONE boundary sync), and the
  loss scale is applied so the tree that crosses the host boundary is
  the scaled one the guard expects;
- ``apply_update(scaled, state, loss) -> (state, verdict)`` — the
  :func:`apex_tpu.resilience.guards.guarded_amp_update` step
  (NaN/spike skip + budget) with the metric fold INSIDE the jitted
  update when a registry is given.

The returned :class:`GuardedStep` carries the same derived
``expect_sharding`` / ``expect_plan`` the fused build verifies against,
so ``tools/graph_lint.py --target resilient`` keeps proving the EXACT
programs the example dispatches.  Replicated update only (the guarded
ZeRO variant is future work): ``tp`` must be 1 and the update-sharding
override must not demand ``shard``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
from jax.sharding import PartitionSpec as P

from apex_tpu.train.trainer import TrainBuildError

__all__ = ["GuardedStep", "build_guarded"]


@dataclasses.dataclass
class GuardedStep:
    """The two jitted programs plus everything the example / linters /
    profilers consume."""

    compute_grads: Callable
    apply_update: Callable
    state: Any
    mesh: Any
    dp: int
    ddp: Any  # the DistributedDataParallel engine (comm knobs live here)
    tx: Any
    scaler: Any
    guard: Any
    registry: Any
    shard_rules: list
    expect_sharding: dict
    expect_plan: dict


def build_guarded(
    trainer,
    loss_fn: Callable[[Any, Any], Any],
    params,
    *,
    tx,
    scaler,
    guard,
    registry=None,
    accum: int = 1,
    verify: str = "off",
    example_batch=None,
) -> GuardedStep:
    """Compose the guarded two-phase programs from ``trainer``'s config
    (see module docstring).  ``loss_fn(params, microbatch) -> scalar``.

    ``verify="error"|"warn"`` (requires ``example_batch``) runs
    :func:`apex_tpu.analysis.check` over ``compute_grads`` at build with
    the derived expectations — the fused build's self-check.  The
    default ``"off"`` leaves that to the CI lint gate, which audits the
    returned programs against the returned expectations anyway
    (``tools/graph_lint.py --target resilient``) — the example starts
    fast either way.
    """
    from apex_tpu import amp
    from apex_tpu.parallel import DistributedDataParallel
    from apex_tpu.resilience import guard_metrics, guarded_amp_update

    cfg = trainer.config
    if cfg.tp != 1:
        raise TrainBuildError(
            "build_guarded composes a replicated guarded-amp update; "
            f"tp={cfg.tp} needs the fused Trainer.build path"
        )
    if cfg.update_sharding == "shard":
        raise TrainBuildError(
            "build_guarded cannot shard the update (the guard needs the "
            "replicated tree); drop update_sharding='shard' or use "
            "Trainer.build"
        )
    mesh = trainer.mesh()
    dp = cfg.dp

    ddp = DistributedDataParallel(
        loss_fn,
        wire=cfg.wire,
        chunks=cfg.chunks,
        block=cfg.block,
        min_size=cfg.min_sync_size,
    )

    state = {
        "params": params,
        "opt": tx.init(params),
        "scaler": scaler.init(),
        "guard": guard.init(),
    }
    if registry is not None:
        state["metrics"] = registry.init()

    def grads_fn(params, scaler_state, batch):
        # batch leaves: (accum, rows, ...); microbatch grads stay LOCAL
        # inside the scan (no_sync), ONE engine sync on the boundary
        if accum == 1:
            loss, grads = ddp.value_and_grad(
                params, jax.tree_util.tree_map(lambda x: x[0], batch)
            )
        else:
            loss, grads = ddp.accum_value_and_grad(params, batch)
        scaled = jax.tree_util.tree_map(
            lambda g: scaler.scale(g, scaler_state), grads
        )
        return loss, scaled

    compute_grads = jax.jit(
        jax.shard_map(
            grads_fn,
            mesh=mesh,
            in_specs=(P(), P(), P(None, "dp")),
            out_specs=(P(), P()),
        )
    )

    @jax.jit
    def apply_update(scaled, state, loss):
        p, o, s, g, verdict = guarded_amp_update(
            tx, scaler, guard, scaled, state["opt"], state["params"],
            state["scaler"], state["guard"],
        )
        new_state = {"params": p, "opt": o, "scaler": s, "guard": g}
        if registry is not None:
            # device-side metric fold, INSIDE the jitted update: no
            # host sync — the registry fetches on its own cadence
            new_state["metrics"] = registry.update(state["metrics"], {
                "train/loss": loss,
                **guard_metrics(verdict, g, guard),
                **amp.DynamicLossScaler.metrics(s),
            })
        return new_state, verdict

    # -- the declared sharding & collective plan -----------------------
    # ONE resolution drives graph_lint / shard_report AND documents the
    # intent: params/scaler replicated (the DDP contract), batch rows
    # dp-sharded, and only the comm engine's declared gradient sync.
    shard_rules = [
        (r"^params(/|$)", P()),           # replicated: the DDP contract
        (r"^scaler", P()),
        (r"^batch(/|$)", P(None, "dp")),  # (accum, rows, feat)
    ]
    expect_sharding = {
        "mesh": {"dp": dp},
        "rules": shard_rules,
        "min_bytes": cfg.min_shard_bytes,
    }
    expect_plan = ddp.collective_plan(params, dp)

    step = GuardedStep(
        compute_grads=compute_grads,
        apply_update=apply_update,
        state=state,
        mesh=mesh,
        dp=dp,
        ddp=ddp,
        tx=tx,
        scaler=scaler,
        guard=guard,
        registry=registry,
        shard_rules=shard_rules,
        expect_sharding=expect_sharding,
        expect_plan=expect_plan,
    )
    if verify != "off":
        _verify_guarded(step, verify, example_batch)
    return step


def _verify_guarded(step: GuardedStep, level: str, example_batch) -> None:
    import sys

    from apex_tpu import analysis

    if example_batch is None:
        raise TrainBuildError(
            "build_guarded(verify=...) needs example_batch to trace "
            "compute_grads on"
        )
    report = analysis.check(
        step.compute_grads,
        step.state["params"], step.state["scaler"], example_batch,
        expect_sharding=step.expect_sharding,
        expect_plan=step.expect_plan,
        name="guarded/compute_grads",
    )
    if report.errors() and level == "error":
        raise TrainBuildError(
            "guarded build failed its own verification:\n"
            + report.render()
        )
    if report.findings:
        print(report.render(), file=sys.stderr)
