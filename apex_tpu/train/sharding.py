"""Rule tables and the update-sharding heuristic.

Two jobs, both feeding :class:`apex_tpu.train.Trainer`:

1. **Rule-table resolution** — turn the config's regex→PartitionSpec
   table into concrete spec trees for every jit ENTRY argument (params,
   optimizer state, batch, metrics), through
   :func:`apex_tpu.analysis.match_partition_rules` (ISSUE 9's machinery)
   so a leaf no rule covers fails LOUDLY naming the path.  The resolved
   specs then round-trip into an *exact* entry-anchored rule table
   (:func:`exact_entry_rules`) handed to ``analysis.check`` as
   ``expect_sharding`` — one resolution drives both the compiled
   ``in_specs`` and the HLO conformance proof, so they cannot drift.

2. **The update-sharding decision** — the framework (not the user)
   decides whether the optimizer update shards across dp replicas
   ("Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
   Training", PAPERS.md — the ZeRO structure
   :mod:`apex_tpu.parallel.distributed_fused_optimizers` implements).
   The documented heuristic (:func:`decide_update_sharding`):

   - ``dp == 1`` → **replicate** (no replicas to shard across);
   - a custom optimizer object → **replicate** (only the named
     optimizers have a distributed twin);
   - explicit ``update_sharding="shard"|"replicate"`` → that, always
     (the override wins — recorded in the decision's ``reason``);
   - otherwise **shard iff** the f32 param bytes reach
     ``zero_min_bytes`` (default 4 MiB) — below it the replicated
     optimizer state fits everywhere and restructuring the sync buys
     nothing — AND the ZeRO wire plan
     (:func:`apex_tpu.parallel.comm.zero_plan`) moves at most 2x the
     bytes of the DDP sync plan (:func:`~apex_tpu.parallel.comm
     .sync_plan`) under the configured wire, which guards
     pathological trees (thousands of tiny leaves whose per-leaf psum
     is cheaper than the padded flat buffer).

   The decision object records the plan bytes and the memory the
   sharded optimizer state saves (``(dp-1)/dp · 3 · param_bytes`` —
   m, v, and the f32 master shard instead of three replicated copies),
   so "why did the framework shard?" is a printed sentence, not a
   code-read.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, List, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.analysis.sharding import (
    match_partition_rules,
    spec_dim_factors,
    tree_paths,
)
from apex_tpu.parallel import comm

__all__ = [
    "ZERO_TWINS",
    "UpdateShardingDecision",
    "decide_update_sharding",
    "resolve_param_specs",
    "resolve_batch_specs",
    "mirror_optimizer_specs",
    "exact_entry_rules",
    "local_shape",
    "slice_local",
    "plan_wire_bytes",
]


#: named optimizers with a distributed (ZeRO) twin — the ONE source the
#: heuristic, the trainer, and the ``optimizers.by_name`` registry docs
#: all point at; extending it arms the heuristic for the new name
ZERO_TWINS = ("adam", "lamb")


# ---------------------------------------------------------------------------
# rule-table resolution
# ---------------------------------------------------------------------------


def resolve_param_specs(rules, params):
    """PartitionSpec pytree for ``params`` from the config rule table.

    Delegates to :func:`apex_tpu.analysis.match_partition_rules` — the
    SAME resolver the conformance pass uses — so an uncovered leaf
    raises ``ValueError("partition rule not found for param: <path>")``
    instead of silently replicating."""
    return match_partition_rules(list(rules), params)


def resolve_batch_specs(batch_rules, batch):
    """PartitionSpec pytree for the batch; default rule table shards
    every leaf's leading axis over ``dp``."""
    rules = list(batch_rules) if batch_rules else [(r".*", P("dp"))]
    return match_partition_rules(rules, batch)


def mirror_optimizer_specs(opt_state, params, param_specs):
    """Spec tree for an optax-style optimizer state: sub-trees that
    structurally mirror ``params`` (fused_adam/lamb/sgd moments) inherit
    the param specs leaf-for-leaf; scalar leaves replicate; anything
    else is a loud error — an optimizer leaf without a sharding is
    exactly the silent replication the conformance pass hunts."""
    params_def = jax.tree_util.tree_structure(params)
    param_shapes = [
        tuple(getattr(l, "shape", ()))
        for l in jax.tree_util.tree_leaves(params)
    ]

    def assign(path, sub):
        try:
            if jax.tree_util.tree_structure(sub) == params_def:
                shapes = [
                    tuple(getattr(l, "shape", ()))
                    for l in jax.tree_util.tree_leaves(sub)
                ]
                if shapes == param_shapes:
                    return param_specs
        except Exception:  # not a comparable subtree
            pass
        leaves = jax.tree_util.tree_leaves(sub)
        if all(getattr(l, "ndim", 0) == 0 for l in leaves):
            return jax.tree_util.tree_map(lambda _: P(), sub)
        raise ValueError(
            f"cannot infer a sharding for optimizer state field "
            f"{path!r}: it neither mirrors the params tree nor is "
            "scalar — pass explicit rules or a named optimizer"
        )

    # walk the top level of the state (NamedTuple fields / dict values)
    if hasattr(opt_state, "_fields"):  # NamedTuple
        return type(opt_state)(*(
            assign(f, getattr(opt_state, f)) for f in opt_state._fields
        ))
    if isinstance(opt_state, dict):
        return {k: assign(k, v) for k, v in opt_state.items()}
    if isinstance(opt_state, (list, tuple)):
        out = [assign(str(i), v) for i, v in enumerate(opt_state)]
        return type(opt_state)(out)
    return assign("<state>", opt_state)


def _spec_leaves(specs, tree):
    """Spec leaves aligned with ``tree_paths(tree)`` order."""
    treedef = jax.tree_util.tree_structure(tree)
    flat = treedef.flatten_up_to(specs)
    return flat


def exact_entry_rules(sections) -> List[Tuple[str, Any]]:
    """Exact (escaped, anchored) entry rule table from resolved specs.

    ``sections`` is ``[(arg_name, tree, spec_tree), ...]`` — one entry
    per jit argument.  The result matches the ``/``-joined paths GSPMD
    writes into parameter ``op_name`` metadata
    (:func:`apex_tpu.analysis.sharding.normalize_param_path`), e.g.
    ``state/params/w1`` or ``batch/0``, each mapped to the EXACT spec
    the trainer compiled with, plus a replicated catch-all so
    bookkeeping buffers stay covered.  Because the table is generated
    from the same resolution that built ``in_specs``, conformance
    drift is impossible by construction.
    """
    rules: List[Tuple[str, Any]] = []
    for name, tree, specs in sections:
        paths = [p for p, _ in tree_paths(tree)]
        spec_flat = _spec_leaves(specs, tree)
        for path, spec in zip(paths, spec_flat):
            full = f"{name}/{path}" if path else name
            rules.append((rf"^{re.escape(full)}$", spec))
    rules.append((r".*", P()))
    return rules


# ---------------------------------------------------------------------------
# local-shape arithmetic (tp-sharded leaves under manual shard_map)
# ---------------------------------------------------------------------------


def local_shape(shape, spec, mesh: dict) -> tuple:
    """Per-device shape of a leaf under ``spec`` on ``mesh``."""
    factors = spec_dim_factors(spec, mesh, len(shape))
    out = []
    for dim, f in zip(shape, factors):
        if dim % f:
            raise ValueError(
                f"dim {dim} of shape {tuple(shape)} not divisible by "
                f"its sharding factor {f} under {spec}"
            )
        out.append(dim // f)
    return tuple(out)


def slice_local(leaf, spec, axis: str, index: int, size: int):
    """Host-side slice of ``leaf``'s shard along every dim ``spec``
    assigns to ``axis`` (rank ``index`` of ``size``) — how the trainer
    seeds per-tp-rank ZeRO master shards from global params."""
    out = leaf
    entries = tuple(spec) if spec is not None else ()
    for d in range(getattr(leaf, "ndim", 0)):
        e = entries[d] if d < len(entries) else None
        names = e if isinstance(e, (tuple, list)) else (e,)
        if axis in [n for n in names if n is not None]:
            if len([n for n in names if n is not None]) > 1:
                raise NotImplementedError(
                    f"mixed-axis dim sharding {e!r} is not supported by "
                    "the trainer's ZeRO path"
                )
            n = out.shape[d] // size
            out = jax.lax.slice_in_dim(out, index * n, (index + 1) * n,
                                       axis=d)
    return out


# ---------------------------------------------------------------------------
# the update-sharding heuristic
# ---------------------------------------------------------------------------


def plan_wire_bytes(entries: Sequence[dict]) -> int:
    """Upper-bound wire bytes of a collective-plan entry list (the
    ``bytes`` bounds :func:`comm.sync_plan`/:func:`comm.zero_plan`
    emit) — the common currency the heuristic compares plans in."""
    total = 0
    for e in entries:
        b = e.get("bytes")
        if b is None:
            continue
        total += int(b[1] if isinstance(b, (list, tuple)) else b)
    return total


@dataclasses.dataclass(frozen=True)
class UpdateShardingDecision:
    """What the framework decided about the weight update, and why."""

    shard: bool
    reason: str
    param_bytes: int
    ddp_wire_bytes: int
    zero_wire_bytes: int
    #: optimizer-state bytes the sharded layout saves per device
    #: ((dp-1)/dp · 3 · param_bytes: m, v, master)
    state_bytes_saved: int

    @property
    def mode(self) -> str:
        return "zero" if self.shard else "ddp"

    def render(self) -> str:
        mib = 1 << 20
        return (
            f"update-sharding: {self.mode} ({self.reason}; params "
            f"{self.param_bytes / mib:.1f}MiB, wire ddp≤"
            f"{self.ddp_wire_bytes / mib:.1f}MiB zero≤"
            f"{self.zero_wire_bytes / mib:.1f}MiB, state saved "
            f"{self.state_bytes_saved / mib:.1f}MiB/device)"
        )


def decide_update_sharding(
    params,
    config,
    param_specs=None,
) -> UpdateShardingDecision:
    """Apply the documented heuristic (module docstring) to a param
    tree under ``config`` (a :class:`apex_tpu.train.TrainConfig`).

    Sizing uses the LOCAL (tp-sharded) leaf sizes when ``param_specs``
    is given — the dp sync moves local shards, so that is the honest
    wire accounting.
    """
    mesh = config.mesh_dict()
    dp = config.dp
    if param_specs is not None:
        sizes = []
        specs = _spec_leaves(param_specs, params)
        for leaf, spec in zip(jax.tree_util.tree_leaves(params), specs):
            sizes.append(
                int(np.prod(local_shape(leaf.shape, spec, mesh)) or 1)
            )
    else:
        sizes = [int(l.size) for l in jax.tree_util.tree_leaves(params)]
    n_elements = sum(sizes)
    param_bytes = n_elements * 4  # f32 accounting: the master copy

    ddp_wire = plan_wire_bytes(comm.sync_plan(
        sizes, dp, wire=config.wire, chunks=config.chunks,
        block=config.block, min_size=config.min_sync_size,
    ))
    zero_wire = plan_wire_bytes(comm.zero_plan(
        n_elements, dp, wire=config.wire, param_wire=config.param_wire,
        chunks=config.chunks, block=config.block,
    ))
    saved = (dp - 1) * 3 * param_bytes // max(dp, 1)

    def make(shard, reason):
        return UpdateShardingDecision(
            shard=shard, reason=reason, param_bytes=param_bytes,
            ddp_wire_bytes=ddp_wire, zero_wire_bytes=zero_wire,
            state_bytes_saved=saved if shard else 0,
        )

    zero_capable = config.optimizer_name() in ZERO_TWINS
    if config.update_sharding == "shard":
        if dp <= 1:
            raise ValueError(
                "update_sharding='shard' needs a dp axis >= 2 — there "
                "are no replicas to shard the update across"
            )
        if not zero_capable:
            raise ValueError(
                "update_sharding='shard' requires an optimizer with a "
                f"distributed (ZeRO) twin (have {ZERO_TWINS})"
            )
        return make(True, "explicit override")
    if config.update_sharding == "replicate":
        return make(False, "explicit override")
    # -- auto -----------------------------------------------------------
    if dp <= 1:
        return make(False, "dp=1: no replicas to shard across")
    if not zero_capable:
        return make(False, "optimizer has no distributed (ZeRO) twin")
    if param_bytes < config.zero_min_bytes:
        return make(
            False,
            f"params under the {config.zero_min_bytes >> 20} MiB "
            "zero_min_bytes floor",
        )
    if zero_wire > 2 * max(ddp_wire, 1):
        return make(
            False,
            "ZeRO wire plan exceeds 2x the ddp sync plan "
            "(tiny-leaf-dominated tree)",
        )
    return make(True, "auto: param bytes over the floor at comparable wire")
