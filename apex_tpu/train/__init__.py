"""``apex_tpu.train`` — the single composable entry point for training.

One import composes what the rest of the framework ships as parts:
DDP and ZeRO (through the shared comm engine, ``docs/comm.md``),
tensor parallelism (rule-table-placed params over a ``(dp, tp)``
mesh), guarded-amp resilience, observability, and the static-analysis
proofs — the TorchTitan shape (PAPERS.md), with the headline that the
FRAMEWORK decides whether the weight update shards across data-parallel
replicas ("Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training", PAPERS.md)::

    from apex_tpu.train import TrainConfig, Trainer
    from jax.sharding import PartitionSpec as P

    cfg = TrainConfig(
        mesh={"dp": 2, "tp": 2},
        rules=[(r"mlp/kernel", P(None, "tp")),
               (r"attn/out",   P("tp", None)),
               (r".*",         P())],
        wire="int8", optimizer="adam",
    )
    step = Trainer(cfg).build(loss_fn, params, example_batch)
    state, aux = step(step.state, batch)      # compiled, donation-aliased
    step.fit(batch_fn, 1000, directory=ckpt)  # run_resilient + goodput

Builds are self-verifying: the compiled step is checked against the
config-derived sharding rule table, collective plan, and HBM budget
(:mod:`apex_tpu.analysis`) and a violating build raises
:class:`TrainBuildError`.  See ``docs/training.md``.
"""

from apex_tpu.train.config import (  # noqa: F401
    TrainConfig,
    UPDATE_SHARDING_MODES,
    VERIFY_LEVELS,
)
from apex_tpu.train.sharding import (  # noqa: F401
    UpdateShardingDecision,
    decide_update_sharding,
)
from apex_tpu.train.trainer import (  # noqa: F401
    TrainBuildError,
    Trainer,
    TrainStep,
)
from apex_tpu.train.guarded import (  # noqa: F401
    GuardedStep,
    build_guarded,
)
from apex_tpu.train import demo  # noqa: F401
from apex_tpu.train.demo import build_demo  # noqa: F401

__all__ = [
    "TrainConfig",
    "Trainer",
    "TrainStep",
    "TrainBuildError",
    "UpdateShardingDecision",
    "decide_update_sharding",
    "UPDATE_SHARDING_MODES",
    "VERIFY_LEVELS",
    "GuardedStep",
    "build_guarded",
    "build_demo",
    "demo",
]
