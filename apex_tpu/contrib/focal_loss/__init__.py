"""Fused focal loss — ≙ ``apex/contrib/focal_loss``
(``focal_loss.py`` :: ``focal_loss``, native ``focal_loss_cuda.cu`` ::
``focal_loss_forward``; the SSD/detection training loss).

One traced expression (XLA fuses the sigmoid/log/pow chain with the
reduction, which is all the CUDA kernel does).  Matches the reference
semantics: sigmoid focal loss over (anchors, classes) logits with integer
targets where class 0 is background (mapped to the all-negative row),
optional label smoothing, summed and normalized by ``num_positives``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["focal_loss", "sigmoid_focal_loss"]


def sigmoid_focal_loss(
    logits,
    targets_one_hot,
    alpha: float = 0.25,
    gamma: float = 2.0,
    label_smoothing: float = 0.0,
):
    """Elementwise focal term: ``-α_t (1-p_t)^γ log(p_t)``.

    logits/targets_one_hot: broadcastable (..., num_classes) with targets
    in {0, 1} (floats allowed for smoothing).
    """
    # FP32_FUNCS category is structural here: math and return value are
    # unconditionally f32 (no amp_cast hook needed); the named scope
    # marks the widening policy-exempt for analysis' promotion lint.
    with jax.named_scope("focal_f32"):
        lf = logits.astype(jnp.float32)
        t = targets_one_hot.astype(jnp.float32)
    if label_smoothing > 0.0:
        t = t * (1.0 - label_smoothing) + 0.5 * label_smoothing
    p = jax.nn.sigmoid(lf)
    ce = jnp.maximum(lf, 0.0) - lf * t + jnp.log1p(jnp.exp(-jnp.abs(lf)))
    p_t = p * t + (1.0 - p) * (1.0 - t)
    alpha_t = alpha * t + (1.0 - alpha) * (1.0 - t)
    return alpha_t * jnp.power(1.0 - p_t, gamma) * ce


def focal_loss(
    cls_output,
    cls_targets_at_level,
    num_positives_sum,
    num_real_classes: Optional[int] = None,
    alpha: float = 0.25,
    gamma: float = 2.0,
    label_smoothing: float = 0.0,
):
    """≙ focal_loss_cuda.focal_loss_forward.

    cls_output: (..., C) logits; cls_targets_at_level: (...) int targets
    with -1 = background-only anchor... following the reference: target
    t >= 1 marks class t-1 positive, t == 0 all-negative, t == -1
    ignored.  Returns the summed loss / num_positives_sum.
    """
    c = cls_output.shape[-1]
    if num_real_classes is None:
        num_real_classes = c
    t = cls_targets_at_level.astype(jnp.int32)
    one_hot = jax.nn.one_hot(t - 1, c, dtype=jnp.float32)
    one_hot = jnp.where((t >= 1)[..., None], one_hot, 0.0)
    per_elem = sigmoid_focal_loss(
        cls_output, one_hot, alpha, gamma, label_smoothing
    )
    valid = (t >= 0).astype(jnp.float32)[..., None]
    mask = jnp.concatenate(
        [
            jnp.ones((num_real_classes,), jnp.float32),
            jnp.zeros((c - num_real_classes,), jnp.float32),
        ]
    )
    total = jnp.sum(per_elem * valid * mask)
    return total / jnp.maximum(num_positives_sum, 1.0)
