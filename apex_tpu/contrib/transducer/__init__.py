"""RNN-Transducer joint + loss — ≙ ``apex/contrib/transducer``
(``transducer.py`` :: ``TransducerJoint``, ``TransducerLoss``; native
``transducer_joint_kernel.cu``, ``transducer_loss_kernel.cu``).

- :func:`transducer_joint` / :class:`TransducerJoint`: the broadcast-add
  joint ``f (B,T,H) ⊕ g (B,U,H) → (B,T,U,H)`` with optional fused ReLU
  and dropout — a pure XLA fusion (the reference's kernel exists to avoid
  materializing intermediates, which XLA's fusion likewise avoids).
- :func:`transducer_loss` / :class:`TransducerLoss`: the RNN-T negative
  log-likelihood via the standard log-domain alpha recursion, implemented
  as ``lax.scan`` over T (each step is a cumulative-logsumexp sweep over
  U — vectorized across batch on the VPU).  Gradients come from autodiff
  through the scan, which reproduces the alpha-beta gradient the
  reference's hand-written backward computes.

Layouts follow the reference: joint output (B, T, U+1, V) log-probs with
``blank_idx`` the blank class; labels (B, U) int; f_len/y_len valid
lengths (U+1 rows index "labels emitted so far").
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = [
    "transducer_joint",
    "transducer_loss",
    "TransducerJoint",
    "TransducerLoss",
]

# finite stand-in for -inf: keeps logaddexp gradients NaN-free (see
# _row_recurrence) while exp() of any (- _NEG)-shifted term underflows to 0
_NEG = -1e30


def transducer_joint(
    f,
    g,
    *,
    relu: bool = False,
    dropout_p: float = 0.0,
    dropout_rng=None,
):
    """f: (B, T, H); g: (B, U, H) → (B, T, U, H) broadcast add.

    ≙ transducer_joint_cuda (pack/unpack variants collapse to this dense
    form: padding rows are simply ignored by the loss's length masking).
    """
    out = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        out = jax.nn.relu(out)
    if dropout_p > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_p > 0 requires dropout_rng")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_p, out.shape)
        out = jnp.where(keep, out / (1.0 - dropout_p), 0.0)
    return out


def _row_recurrence(c, e):
    """Solve ``a_0 = c_0; a_u = logaddexp(c_u, a_{u-1} + e_{u-1})`` along
    the last axis in O(log U) depth.

    In the (logaddexp, +) semiring each step is the affine map
    ``T_u(a) = logaddexp(b_u, a + w_u)`` with ``w_u = e_{u-1}``,
    ``b_u = c_u`` (and ``w_0 = -inf`` so the chain forgets its seed).
    Affine maps compose associatively — ``(w1,b1)∘(w2,b2) =
    (w1+w2, logaddexp(b2, b1+w2))`` — so the whole row is one
    ``associative_scan`` instead of a U-step serial loop (≙ the
    wavefront parallelism of the reference's transducer_loss_kernel.cu).
    """
    b = c.shape[0]
    # _NEG (finite) instead of -inf: logaddexp(-inf, -inf) has NaN
    # gradients; exp(-1e30 - x) underflows to exactly 0 instead.
    head = jnp.full((b, 1), _NEG, c.dtype)
    ws = jnp.concatenate([head, e], axis=-1)

    def combine(x, y):
        w1, b1 = x
        w2, b2 = y
        return w1 + w2, jnp.logaddexp(b2, b1 + w2)

    _, out = jax.lax.associative_scan(combine, (ws, c), axis=-1)
    return out


def transducer_loss(log_probs, labels, f_len, y_len, blank_idx: int = 0):
    """RNN-T NLL.  log_probs: (B, T, U+1, V) log-softmax scores;
    labels: (B, U); f_len: (B,) valid T; y_len: (B,) valid U.

    alpha recursion (log domain):
      alpha[0, 0] = 0
      alpha[t, u] = logaddexp(alpha[t-1, u] + blank[t-1, u],
                              alpha[t, u-1] + emit[t, u-1])
      loss = -(alpha[T-1, U] + blank[T-1, U])

    The scan over T is inherent (frame recursion); each row is solved in
    O(log U) depth by :func:`_row_recurrence`, with the per-step blank/emit
    rows fed as scan inputs (no dynamic gathers from the full tensors).
    """
    b, t_max, u1, v = log_probs.shape
    u_max = u1 - 1
    lp = log_probs.astype(jnp.float32)

    blank = lp[..., blank_idx]  # (B, T, U+1)
    # emit[t, u] = score of emitting labels[u] at (t, u)
    lab = jnp.clip(labels, 0, v - 1)
    emit = jnp.take_along_axis(
        lp[:, :, :u_max, :], lab[:, None, :, None], axis=-1
    )[..., 0]  # (B, T, U)

    # alpha[0]: only horizontal moves at t=0
    c0 = jnp.concatenate(
        [
            jnp.zeros((b, 1), jnp.float32),
            jnp.full((b, u_max), _NEG, jnp.float32),
        ],
        axis=1,
    )
    alpha0 = _row_recurrence(c0, emit[:, 0, :])

    def step(alpha_prev, rows):
        blank_prev, emit_t = rows  # (B, U+1), (B, U)
        alpha_t = _row_recurrence(alpha_prev + blank_prev, emit_t)
        return alpha_t, alpha_t

    _, alphas = jax.lax.scan(
        step,
        alpha0,
        (
            jnp.moveaxis(blank[:, : t_max - 1, :], 1, 0),  # (T-1, B, U+1)
            jnp.moveaxis(emit[:, 1:, :], 1, 0),  # (T-1, B, U)
        ),
    )
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T, B, U+1)

    # terminal: alpha[f_len-1, y_len] + blank[f_len-1, y_len]
    tl = jnp.clip(f_len - 1, 0, t_max - 1)
    ul = jnp.clip(y_len, 0, u_max)
    batch = jnp.arange(b)
    final_alpha = alphas[tl, batch, ul]
    final_blank = blank[batch, tl, ul]
    return -(final_alpha + final_blank)


class TransducerJoint:
    """≙ TransducerJoint(pack_output=False, relu=False, dropout=False...)."""

    def __init__(
        self,
        pack_output: bool = False,
        relu: bool = False,
        dropout: bool = False,
        dropout_prob: float = 0.0,
    ):
        if pack_output:
            raise NotImplementedError(
                "pack_output=True (varlen packing) defeats XLA's static "
                "shapes; padded output + length masking in the loss is the "
                "TPU-native equivalent"
            )
        self.relu = relu
        self.dropout_prob = dropout_prob if dropout else 0.0

    def __call__(self, f, g, f_len=None, g_len=None, dropout_rng=None):
        return transducer_joint(
            f, g, relu=self.relu, dropout_p=self.dropout_prob,
            dropout_rng=dropout_rng,
        )


class TransducerLoss:
    """≙ TransducerLoss(fuse_softmax_backward=True) — takes raw logits and
    applies log_softmax internally (the fused-softmax-backward semantics
    fall out of autodiff through one traced expression)."""

    def __init__(self, fuse_softmax_backward: bool = True, packed_input: bool = False):
        if packed_input:
            raise NotImplementedError(
                "packed_input=True is N/A on TPU (static shapes); use the "
                "padded layout with f_len/y_len masking"
            )
        self.fuse_softmax_backward = fuse_softmax_backward

    def __call__(self, x, label, f_len, y_len, blank_idx: int = 0):
        log_probs = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
        return transducer_loss(log_probs, label, f_len, y_len, blank_idx)
