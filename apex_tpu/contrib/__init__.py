"""Contrib parity layer — ≙ ``apex/contrib``.

The reference gates each contrib feature behind a build flag
(``setup.py --fmha --fast_multihead_attn ...``) and a try-import probe.
Here every feature is pure JAX/Pallas and always importable; features whose
substance is CUDA-specific plumbing with no TPU meaning (``nccl_p2p``,
``nccl_allocator``, ``peer_memory`` as IPC pools, ``gpu_direct_storage``)
are represented by their *capability* equivalents (ppermute halo exchange,
XLA-managed buffers) or documented as not applicable — see each submodule.

Submodules
----------
- multihead_attn — fused self/enc-dec attention (≙ apex/contrib/multihead_attn)
- fmha          — packed/varlen flash attention (≙ apex/contrib/fmha)
- xentropy      — fused softmax-CE (≙ apex/contrib/xentropy)
- layer_norm    — FastLayerNorm (≙ apex/contrib/layer_norm)
- group_norm    — (NHWC) GroupNorm + SiLU fusion (≙ apex/contrib/group_norm)
- groupbn       — BatchNorm2d NHWC + ReLU/Add fusions (≙ apex/contrib/groupbn)
- clip_grad     — fused clip_grad_norm_ (≙ apex/contrib/clip_grad)
- optimizers    — ZeRO-sharded DistributedFusedAdam/LAMB (≙ contrib/optimizers)
- focal_loss    — fused focal loss (≙ apex/contrib/focal_loss)
- index_mul_2d  — fused gather-multiply (≙ apex/contrib/index_mul_2d)
- transducer    — RNN-T joint + loss (≙ apex/contrib/transducer)
- sparsity      — ASP 2:4 structured sparsity (≙ apex/contrib/sparsity)
- bottleneck    — (spatial-parallel) ResNet bottleneck (≙ contrib/bottleneck)
- peer_memory   — halo exchange over a mesh axis (≙ contrib/peer_memory)
- nccl_p2p      — neighbor send/recv via ppermute (≙ contrib/nccl_p2p)
- conv_bias_relu — fused Conv+Bias(+ReLU/+Add) (≙ contrib/conv_bias_relu)
- cudnn_gbn     — group BatchNorm, shared impl with groupbn (≙ contrib/cudnn_gbn)
- nccl_allocator — documented no-op (≙ contrib/nccl_allocator; N/A on TPU)
- gpu_direct_storage — documented N/A (≙ contrib/gpu_direct_storage)
- openfold      — OpenFold kernels + DAP helpers (≙ contrib/openfold_triton)
"""
