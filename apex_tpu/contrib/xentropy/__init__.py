"""≙ apex/contrib/xentropy — fused softmax cross-entropy.

Same op as :mod:`apex_tpu.ops.xentropy` (the reference likewise re-exports
its xentropy_kernel.cu binding as ``apex.contrib.xentropy.SoftmaxCrossEntropyLoss``).
"""

from apex_tpu.ops.xentropy import (  # noqa: F401
    SoftmaxCrossEntropyLoss,
    softmax_cross_entropy_loss,
)
