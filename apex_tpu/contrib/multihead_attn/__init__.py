"""Fused multihead attention modules — ≙ ``apex/contrib/multihead_attn``.

Reference surface (`apex/contrib/multihead_attn/self_multihead_attn.py`,
``encdec_multihead_attn.py``): ``SelfMultiheadAttn`` / ``EncdecMultiheadAttn``
with options ``bias``, ``include_norm_add`` (fused residual+LayerNorm),
``mask_additive`` (additive vs boolean key-padding mask), ``dropout`` and two
impls (``fast`` CUDA pipeline vs ``default`` torch).  The CUDA pipeline's
fusion (QKV GEMM → scaled masked softmax → dropout → PV GEMM → out-proj) is
realized here as: one fused QKV projection (single MXU GEMM) → Pallas flash
attention (apex_tpu.ops.attention) → out projection, with the norm_add
variant fusing the pre-LayerNorm via apex_tpu Pallas LayerNorm.
"""

from apex_tpu.contrib.multihead_attn.modules import (  # noqa: F401
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)
