"""Self / encoder-decoder fused multihead attention (flax).

≙ ``apex/contrib/multihead_attn/self_multihead_attn.py`` ::
``SelfMultiheadAttn`` and ``encdec_multihead_attn.py`` ::
``EncdecMultiheadAttn``.  Sequence-first layout ``(S, B, E)`` like the
reference (torch MHA convention).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.ops.attention import flash_attention
from apex_tpu.ops.layer_norm import fused_layer_norm_affine
from apex_tpu.ops.pallas.flash_attention import MASK_VALUE


def _padding_bias(key_padding_mask, mask_additive):
    """(B, Sk) mask → (B, 1, 1, Sk) additive bias.

    ``mask_additive=False``: boolean, True = masked (torch convention).
    ``mask_additive=True``: already additive (0 / -inf style), pass through.
    """
    if key_padding_mask is None:
        return None
    if mask_additive:
        bias = key_padding_mask.astype(jnp.float32)
    else:
        bias = jnp.where(key_padding_mask, MASK_VALUE, 0.0)
    return bias[:, None, None, :]


def _merge_attn_mask(bias_, attn_mask):
    """Fold an (Sq, Sk)-shaped attention mask (bool = masked, or additive
    float) into the running additive bias."""
    if attn_mask is None:
        return bias_
    if attn_mask.dtype == jnp.bool_:
        am = jnp.where(attn_mask, MASK_VALUE, 0.0)
    else:
        am = attn_mask.astype(jnp.float32)
    am = am.reshape((1, 1) + am.shape[-2:])
    return am if bias_ is None else bias_ + am


class SelfMultiheadAttn(nn.Module):
    """Fused self-attention.

    Attributes mirror the reference ctor: ``embed_dim``, ``num_heads``,
    ``dropout``, ``bias`` (projection biases), ``include_norm_add`` (fused
    pre-LayerNorm + residual add), ``mask_additive``.  ``impl`` is accepted
    for API parity; both values run the same flash path ("fast" ≙ Pallas
    kernel on TPU, "default" ≙ jnp fallback — selection is automatic).
    """

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    mask_additive: bool = False
    impl: str = "fast"
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(
        self,
        query,
        key_padding_mask=None,
        attn_mask=None,
        *,
        causal: bool = False,
        deterministic: bool = True,
    ):
        s, b, e = query.shape
        assert e == self.embed_dim
        h = self.num_heads
        d = e // h

        residual = query
        if self.include_norm_add:
            # ≙ the reference's *_norm_add variants: LN folded in front of
            # the QKV GEMM, residual added to the attention output.
            lnw = self.param("lyr_nrm_gamma_weights", nn.initializers.ones, (e,))
            lnb = self.param("lyr_nrm_beta_weights", nn.initializers.zeros, (e,))
            query = fused_layer_norm_affine(query, lnw, lnb, (e,))

        qkv = nn.Dense(
            3 * e, use_bias=self.bias, dtype=self.dtype, name="qkv_proj"
        )(query)
        # (S, B, 3E) → three (B, H, S, D)
        qkv = qkv.reshape(s, b, 3, h, d)
        q, k, v = (jnp.transpose(qkv[:, :, i], (1, 2, 0, 3)) for i in range(3))

        bias_ = _merge_attn_mask(
            _padding_bias(key_padding_mask, self.mask_additive),
            attn_mask,
        )

        dropout_rng = None
        p = 0.0 if deterministic else self.dropout
        if p > 0.0:
            dropout_rng = self.make_rng("dropout")
        out = flash_attention(
            q, k, v, bias_, causal=causal, scale=d ** -0.5,
            dropout_p=p, dropout_rng=dropout_rng,
        )
        out = jnp.transpose(out, (2, 0, 1, 3)).reshape(s, b, e)
        out = nn.Dense(
            e, use_bias=self.bias, dtype=self.dtype, name="out_proj"
        )(out)
        if self.include_norm_add:
            out = out + residual
        return out


class EncdecMultiheadAttn(nn.Module):
    """Fused encoder-decoder (cross) attention ≙ ``EncdecMultiheadAttn``:
    Q projected from the decoder stream, fused KV projection from the
    encoder stream."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    mask_additive: bool = False
    impl: str = "fast"
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(
        self,
        query,
        key,
        key_padding_mask=None,
        attn_mask=None,
        *,
        deterministic: bool = True,
    ):
        sq, b, e = query.shape
        sk = key.shape[0]
        h = self.num_heads
        d = e // h

        residual = query
        if self.include_norm_add:
            lnw = self.param("lyr_nrm_gamma_weights", nn.initializers.ones, (e,))
            lnb = self.param("lyr_nrm_beta_weights", nn.initializers.zeros, (e,))
            query = fused_layer_norm_affine(query, lnw, lnb, (e,))

        q = nn.Dense(e, use_bias=self.bias, dtype=self.dtype, name="q_proj")(query)
        kv = nn.Dense(
            2 * e, use_bias=self.bias, dtype=self.dtype, name="kv_proj"
        )(key)
        q = jnp.transpose(q.reshape(sq, b, h, d), (1, 2, 0, 3))
        kv = kv.reshape(sk, b, 2, h, d)
        k, v = (jnp.transpose(kv[:, :, i], (1, 2, 0, 3)) for i in range(2))

        bias_ = _merge_attn_mask(
            _padding_bias(key_padding_mask, self.mask_additive),
            attn_mask,
        )

        dropout_rng = None
        p = 0.0 if deterministic else self.dropout
        if p > 0.0:
            dropout_rng = self.make_rng("dropout")
        out = flash_attention(
            q, k, v, bias_, scale=d ** -0.5,
            dropout_p=p, dropout_rng=dropout_rng,
        )
        out = jnp.transpose(out, (2, 0, 1, 3)).reshape(sq, b, e)
        out = nn.Dense(
            e, use_bias=self.bias, dtype=self.dtype, name="out_proj"
        )(out)
        if self.include_norm_add:
            out = out + residual
        return out
