"""Evoformer pair-stack modules under dynamic axial parallelism.

Deepens the ``openfold`` contrib surface past re-exports (VERDICT r2
item 10 follow-up): the reference's ``apex/contrib/openfold_triton``
ships OpenFold-specific fused kernels (``mha.py``: gated attention with
pair bias; fused LayerNorm; the DAP helpers in ``dap.py`` that shard the
pair representation's axial dims).  The TPU realization keeps the same
model math on this framework's fused primitives:

- gated, pair-biased attention runs on the flash kernel with the
  *trainable-bias* backward (``flash_attention(..., bias_grad=True)``,
  the dedicated dbias kernel) instead of a bespoke Triton kernel;
- the triangle multiplicative updates become two einsum contractions
  whose DAP forms are the two canonical mesh collectives: *outgoing*
  all-gathers one operand, *incoming* reduce-scatters the contraction —
  both ride the same axis the ``dap.py`` transitions use;
- LayerNorm is the tuned Pallas kernel via
  :func:`apex_tpu.ops.layer_norm.fused_layer_norm_affine`.

Layout convention matches :mod:`apex_tpu.contrib.openfold`: under DAP the
leading axial dim is sharded over ``axis_name`` (rank r holds rows
``[r*per, (r+1)*per)``, the ``scatter``/``all_gather(tiled=True)``
order); ``axis_name=None`` runs the identical unsharded math — the
golden path the equivalence tests hold sharded runs against.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu import _compat
from apex_tpu.ops.attention import flash_attention
from apex_tpu.ops.layer_norm import fused_layer_norm_affine

__all__ = [
    "GatedAttention",
    "TriangleAttention",
    "TriangleMultiplicativeUpdate",
    "PairTransition",
    "EvoformerPairBlock",
    "MSARowAttentionWithPairBias",
    "MSAColumnAttention",
    "OuterProductMean",
    "EvoformerBlock",
]


def _layer_norm(mod: nn.Module, x, name: str):
    d = x.shape[-1]
    g = mod.param(name + "_scale", nn.initializers.ones, (d,))
    b = mod.param(name + "_bias", nn.initializers.zeros, (d,))
    return fused_layer_norm_affine(x, g, b, (d,))


def _pair_bias(mod: nn.Module, z_ln, heads: int, axis_name: Optional[str],
               n_res: int, name: str = "tri_bias"):
    """Per-head attention bias projected from the (LN'd) pair rep.

    Projects on the LOCAL rows first and all-gathers the small
    (N/dap, N, heads) result (heads < D: the gather moves and the ranks
    redundantly compute D/heads-fold less than gathering the pair itself
    for an identical pointwise result).  Returns (1, H, N, N) — one bias
    group shared by every batch row, trainable through the flash path's
    dbias kernel (the grouped-G reduction sums the batch dim).
    """
    tri = nn.Dense(heads, use_bias=False, name=name)(z_ln)
    if axis_name is not None:
        tri = jax.lax.all_gather(tri, axis_name, axis=0, tiled=True)
    if tri.shape[0] != n_res or tri.shape[1] != n_res:
        raise ValueError(
            f"pair bias needs a square pair representation matching the "
            f"attended dim {n_res}; got {tri.shape[:2]}"
        )
    return tri.transpose(2, 0, 1)[None]


class GatedAttention(nn.Module):
    """OpenFold-style attention: no-bias q/k/v projections, additive pair
    bias, sigmoid gating on the attended values, output projection
    (≙ openfold_triton ``mha.py``'s fused attention surface).

    Input ``x`` (B, S, D); optional ``bias`` broadcastable to
    (B, H, S, S).  When ``bias_grad`` the flash path backprops into the
    bias with the dedicated dbias kernel.  The gate projection starts at
    sigmoid(1) (zero kernel, unit bias) and the output projection at
    zero — the reference models' residual-stability init.
    """

    heads: int
    bias_grad: bool = True

    @nn.compact
    def __call__(self, x, bias=None):
        b, s, d = x.shape
        h = self.heads
        dh = d // h
        if d % h:
            raise ValueError(f"dim {d} not divisible by heads {h}")

        def split_heads(t):
            return t.reshape(b, s, h, dh).transpose(0, 2, 1, 3)

        q = split_heads(nn.Dense(d, use_bias=False, name="q")(x))
        k = split_heads(nn.Dense(d, use_bias=False, name="k")(x))
        v = split_heads(nn.Dense(d, use_bias=False, name="v")(x))
        o = flash_attention(q, k, v, bias, bias_grad=self.bias_grad)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, d)
        gate = nn.Dense(
            d, name="gate", kernel_init=nn.initializers.zeros,
            bias_init=nn.initializers.ones,
        )(x)
        o = jax.nn.sigmoid(gate) * o
        return nn.Dense(
            d, name="out", kernel_init=nn.initializers.zeros
        )(o)


class TriangleAttention(nn.Module):
    """Triangle self-attention around the starting node on the module's
    input layout: batch = leading axial dim, attention along the second,
    bias ``b[h, j, k]`` projected from the pair itself and shared across
    the batch dim (the triangle inequality edge, AF2 suppl. Algs 13/14).

    The *ending-node* variant is this module applied to the transposed
    pair — :class:`EvoformerPairBlock` wires that (and under DAP routes
    it through the ``row_to_col`` transition so the transposed frame is
    again leading-dim sharded).

    Under DAP (``axis_name`` set) the input is (N/dap, N, D): attention
    batches over local rows directly, but the bias needs the full pair —
    so the bias is projected on the LOCAL rows first and the (N/dap, N,
    heads) result all-gathered (heads < D, and the projection FLOPs split
    across ranks; gathering the pair itself then projecting would move
    and compute D/heads-fold more for an identical pointwise result).
    """

    heads: int
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, z):
        _, n_cols, _ = z.shape
        z_ln = _layer_norm(self, z, "ln")
        tri_bias = _pair_bias(
            self, z_ln, self.heads, self.axis_name, n_cols
        )
        return GatedAttention(heads=self.heads, name="attn")(
            z_ln, bias=tri_bias
        )


class TriangleMultiplicativeUpdate(nn.Module):
    """Triangle multiplicative update (AF2 suppl. Algs 11/12).

    ``outgoing``: out[i,j] = Σ_k a[i,k]·b[j,k]; ``incoming``:
    out[i,j] = Σ_k a[k,i]·b[k,j] — with a, b gated projections of the
    LN'd pair and a final gated, LN'd output projection.

    DAP forms (leading dim sharded) are pure mesh collectives:

    - outgoing contracts each local row block against *all* rows of b →
      ``all_gather(b)`` then einsum; output rows stay local.
    - incoming contracts over the *sharded* dim k → local einsum gives a
      partial (N, N) sum, ``psum_scatter`` both reduces it and re-shards
      the rows in one collective (the reduce-scatter dual of outgoing).
    """

    mode: str  # "outgoing" | "incoming"
    hidden: Optional[int] = None
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, z):
        if self.mode not in ("outgoing", "incoming"):
            raise ValueError(f"unknown mode {self.mode!r}")
        d = z.shape[-1]
        c = self.hidden or d
        z_ln = _layer_norm(self, z, "ln_in")

        def gated_proj(name):
            p = nn.Dense(c, name=name)(z_ln)
            g = nn.Dense(
                c, name=name + "_gate", kernel_init=nn.initializers.zeros,
                bias_init=nn.initializers.ones,
            )(z_ln)
            return jax.nn.sigmoid(g) * p

        a = gated_proj("a")
        b = gated_proj("b")
        if self.mode == "outgoing":
            if self.axis_name is not None:
                b = jax.lax.all_gather(b, self.axis_name, axis=0, tiled=True)
            x = jnp.einsum("ikc,jkc->ijc", a, b)
        else:
            x = jnp.einsum("kic,kjc->ijc", a, b)
            if self.axis_name is not None:
                x = jax.lax.psum_scatter(
                    x, self.axis_name, scatter_dimension=0, tiled=True
                )
        x = _layer_norm(self, x, "ln_out")
        x = nn.Dense(d, name="out", kernel_init=nn.initializers.zeros)(x)
        gate = nn.Dense(
            d, name="gate", kernel_init=nn.initializers.zeros,
            bias_init=nn.initializers.ones,
        )(z_ln)
        return jax.nn.sigmoid(gate) * x


class PairTransition(nn.Module):
    """Per-position transition MLP (LN → expand → relu → project)."""

    ratio: int = 4

    @nn.compact
    def __call__(self, z):
        d = z.shape[-1]
        h = _layer_norm(self, z, "ln")
        h = nn.Dense(self.ratio * d, name="up")(h)
        h = jax.nn.relu(h)
        return nn.Dense(d, name="down", kernel_init=nn.initializers.zeros)(h)


class EvoformerPairBlock(nn.Module):
    """One evoformer pair-stack block under DAP.

    Residual sequence on the square pair z (N, N, D) — triangle
    multiplicative outgoing, incoming, triangle attention around the
    starting then ending node, pair transition — the openfold pair stack
    the reference's dap.py shards.  Under DAP the block stays row-sharded
    for the multiplicative updates and starting-node attention, crosses
    to the column-sharded layout (one ``row_to_col`` all-to-all) for the
    ending-node attention in its transposed frame, and crosses back.
    """

    dim: int
    heads: int
    axis_name: Optional[str] = None
    mlp_ratio: int = 4

    @nn.compact
    def __call__(self, z):
        from apex_tpu.contrib.openfold import col_to_row, row_to_col

        if z.shape[-1] != self.dim:
            raise ValueError(
                f"pair channel dim {z.shape[-1]} != configured dim {self.dim}"
            )
        ax = self.axis_name
        z = z + TriangleMultiplicativeUpdate(
            mode="outgoing", axis_name=ax, name="tri_mul_out"
        )(z)
        z = z + TriangleMultiplicativeUpdate(
            mode="incoming", axis_name=ax, name="tri_mul_in"
        )(z)
        z = z + TriangleAttention(
            heads=self.heads, axis_name=ax, name="tri_att_start"
        )(z)
        if ax is not None:
            zc = row_to_col(z, ax)
        else:
            zc = z
        zt = zc.transpose(1, 0, 2)
        zt = zt + TriangleAttention(
            heads=self.heads, axis_name=ax, name="tri_att_end"
        )(zt)
        zc = zt.transpose(1, 0, 2)
        z = col_to_row(zc, ax) if ax is not None else zc
        return z + PairTransition(ratio=self.mlp_ratio, name="transition")(z)


class MSARowAttentionWithPairBias(nn.Module):
    """MSA row-wise gated self-attention, biased by the pair rep (AF2
    suppl. Alg 7): each MSA row attends across residues with a per-head
    additive bias projected from LN(z), shared by every row.

    DAP layout: MSA (S/dap, R, c_m) sharded over its row (sequence) dim,
    pair (R/dap, R, c_z) sharded over its leading residue dim.  The bias
    is projected from the LOCAL pair rows and all-gathered as the small
    (R, R, heads) tensor — the same local-project-then-gather shape
    trick :class:`TriangleAttention` uses.
    """

    heads: int
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, m, z):
        r = m.shape[1]
        m_ln = _layer_norm(self, m, "ln_m")
        z_ln = _layer_norm(self, z, "ln_z")
        bias = _pair_bias(
            self, z_ln, self.heads, self.axis_name, r, name="pair_bias"
        )
        return GatedAttention(heads=self.heads, name="attn")(m_ln, bias=bias)


class MSAColumnAttention(nn.Module):
    """MSA column-wise gated self-attention (AF2 suppl. Alg 8): per
    residue, attend over the MSA's sequence dim.  Operates on the
    COLUMN-major layout (R_loc, S, c_m) — :class:`EvoformerBlock` crosses
    into it with the same ``row_to_col`` all-to-all the pair stack uses.
    """

    heads: int

    @nn.compact
    def __call__(self, m_col):
        m_ln = _layer_norm(self, m_col, "ln")
        return GatedAttention(heads=self.heads, name="attn")(m_ln)


class OuterProductMean(nn.Module):
    """Pair update from the MSA (AF2 suppl. Alg 10):
    o[i,j] = Linear(flatten(mean_s a[s,i] ⊗ b[s,j])).

    DAP form: the mean contracts over the SHARDED MSA row dim, so each
    rank contracts its local rows and one ``psum_scatter`` both finishes
    the sum and lands the output pair rows on their owning ranks — the
    same reduce-scatter dual the incoming triangle update uses.  The
    mean's divisor is the GLOBAL row count, recovered as
    local · ``axis_size`` (shards are equal-sized by the DAP layout
    contract) — not the local shard size.
    """

    hidden: int = 8
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, m, out_dim: int):
        s_total = m.shape[0] * (
            _compat.axis_size(self.axis_name)
            if self.axis_name is not None
            else 1
        )
        m_ln = _layer_norm(self, m, "ln")
        a = nn.Dense(self.hidden, name="a")(m_ln)
        b = nn.Dense(self.hidden, name="b")(m_ln)
        o = jnp.einsum("sic,sjd->ijcd", a, b) / s_total
        if self.axis_name is not None:
            o = jax.lax.psum_scatter(
                o, self.axis_name, scatter_dimension=0, tiled=True
            )
        o = o.reshape(o.shape[0], o.shape[1], self.hidden * self.hidden)
        return nn.Dense(
            out_dim, name="out", kernel_init=nn.initializers.zeros
        )(o)


class EvoformerBlock(nn.Module):
    """One full evoformer block (AF2 suppl. Alg 6): the MSA stack (row
    attention with pair bias, column attention, transition), the
    outer-product-mean MSA→pair communication, then the pair stack
    (:class:`EvoformerPairBlock`'s sequence).  This is the model-level
    structure ALL of the reference's openfold_triton kernels serve; under
    DAP both representations stay sharded on their leading dim and every
    cross-layout move is one collective.

    ``msa_dim``/``pair_dim`` are the channel widths.
    """

    msa_dim: int
    pair_dim: int
    heads: int
    axis_name: Optional[str] = None
    mlp_ratio: int = 4
    opm_hidden: int = 8

    @nn.compact
    def __call__(self, m, z):
        from apex_tpu.contrib.openfold import col_to_row, row_to_col

        if m.shape[-1] != self.msa_dim:
            raise ValueError(
                f"MSA channel dim {m.shape[-1]} != configured {self.msa_dim}"
            )
        if z.shape[-1] != self.pair_dim:
            raise ValueError(
                f"pair channel dim {z.shape[-1]} != configured {self.pair_dim}"
            )
        ax = self.axis_name
        # --- MSA stack -------------------------------------------------
        m = m + MSARowAttentionWithPairBias(
            heads=self.heads, axis_name=ax, name="msa_row_att"
        )(m, z)
        mc = row_to_col(m, ax) if ax is not None else m
        mt = mc.transpose(1, 0, 2)  # (R_loc, S, c_m)
        mt = mt + MSAColumnAttention(heads=self.heads, name="msa_col_att")(mt)
        mc = mt.transpose(1, 0, 2)
        m = col_to_row(mc, ax) if ax is not None else mc
        m = m + PairTransition(ratio=self.mlp_ratio, name="msa_transition")(m)
        # --- MSA -> pair communication --------------------------------
        z = z + OuterProductMean(
            hidden=self.opm_hidden, axis_name=ax,
            name="outer_product_mean",
        )(m, self.pair_dim)
        # --- pair stack ------------------------------------------------
        z = EvoformerPairBlock(
            dim=self.pair_dim, heads=self.heads, axis_name=ax,
            mlp_ratio=self.mlp_ratio, name="pair_block",
        )(z)
        return m, z
