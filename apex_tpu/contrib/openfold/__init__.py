"""OpenFold kernels + DAP helpers — ≙ ``apex/contrib/openfold_triton``
(``mha.py``, ``layer_norm.py``, ``dap.py``: Triton kernels + dynamic
axial parallelism for AlphaFold2-style training).

The reference's Triton kernels map onto pieces this framework already has
(they are re-exported below so OpenFold-shaped code finds them in one
place); DAP — sharding the pair representation's two axial dims across
devices and swapping which axis is sharded between row- and
column-attention — maps to two ``all_to_all`` helpers over a mesh axis,
the same collective Ulysses uses.
"""

from __future__ import annotations

import jax

from apex_tpu.ops.attention import flash_attention as mha  # noqa: F401
from apex_tpu.ops.layer_norm import (  # noqa: F401
    fused_layer_norm_affine as layer_norm,
)

__all__ = ["mha", "layer_norm", "scatter_rows_gather_cols", "scatter_cols_gather_rows"]


def scatter_rows_gather_cols(x, axis_name: str, row_axis: int = -3, col_axis: int = -2):
    """DAP transition: (rows sharded) → (cols sharded).

    ≙ dap.py's row↔col resharding between triangular/axial attention
    blocks: one all-to-all instead of gather+slice.
    """
    return jax.lax.all_to_all(
        x, axis_name, split_axis=col_axis % x.ndim,
        concat_axis=row_axis % x.ndim, tiled=True,
    )


def scatter_cols_gather_rows(x, axis_name: str, row_axis: int = -3, col_axis: int = -2):
    """Inverse DAP transition: (cols sharded) → (rows sharded)."""
    return jax.lax.all_to_all(
        x, axis_name, split_axis=row_axis % x.ndim,
        concat_axis=col_axis % x.ndim, tiled=True,
    )
