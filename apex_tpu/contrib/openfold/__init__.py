"""OpenFold kernels + DAP — ≙ ``apex/contrib/openfold_triton``
(``mha.py``, ``layer_norm.py``, ``dap.py``: Triton kernels + dynamic
axial parallelism for AlphaFold2-style training).

The reference's Triton kernels map onto pieces this framework already has
(re-exported below so OpenFold-shaped code finds them in one place).
DAP — sharding the pair representation's two axial dims across devices
and swapping which axis is sharded between row- and column-attention —
maps to ``all_to_all`` over a mesh axis (the same collective Ulysses
uses), exposed with the reference surface's names (``scatter`` /
``gather`` / ``row_to_col`` / ``col_to_row``) plus
:class:`DAPAxialBlock`, a pair-stack block (row attention on the
row-sharded layout, transition, column attention on the col-sharded
layout, transition back, MLP) built on those transitions.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu import _compat
from apex_tpu.ops.attention import flash_attention as mha  # noqa: F401
from apex_tpu.ops.layer_norm import (  # noqa: F401
    fused_layer_norm_affine as layer_norm,
)

__all__ = [
    "mha",
    "layer_norm",
    "scatter",
    "gather",
    "row_to_col",
    "col_to_row",
    "scatter_rows_gather_cols",
    "scatter_cols_gather_rows",
    "DAPAxialBlock",
    # evoformer pair-stack modules (openfold_triton's model-side surface)
    "GatedAttention",
    "TriangleAttention",
    "TriangleMultiplicativeUpdate",
    "PairTransition",
    "EvoformerPairBlock",
    "MSARowAttentionWithPairBias",
    "MSAColumnAttention",
    "OuterProductMean",
    "EvoformerBlock",
]


def scatter_rows_gather_cols(x, axis_name: str, row_axis: int = -3, col_axis: int = -2):
    """DAP transition: (rows sharded) → (cols sharded).

    ≙ dap.py's row↔col resharding between triangular/axial attention
    blocks: one all-to-all instead of gather+slice.
    """
    return jax.lax.all_to_all(
        x, axis_name, split_axis=col_axis % x.ndim,
        concat_axis=row_axis % x.ndim, tiled=True,
    )


def scatter_cols_gather_rows(x, axis_name: str, row_axis: int = -3, col_axis: int = -2):
    """Inverse DAP transition: (cols sharded) → (rows sharded)."""
    return jax.lax.all_to_all(
        x, axis_name, split_axis=row_axis % x.ndim,
        concat_axis=col_axis % x.ndim, tiled=True,
    )


# Reference-surface names (dap.py :: row_to_col / col_to_row / scatter /
# gather).  Directions: "row-sharded" = the R axial dim is split over the
# dap axis (each rank holds full columns of its rows).
row_to_col = scatter_rows_gather_cols
col_to_row = scatter_cols_gather_rows


def scatter(x, axis_name: str, dim: int):
    """≙ dap.py :: scatter — enter the DAP region: keep this rank's slice
    of ``dim`` (use on a replicated tensor inside shard_map)."""
    n = _compat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    dim = dim % x.ndim
    if x.shape[dim] % n:
        raise ValueError(
            f"DAP scatter: dim {dim} (size {x.shape[dim]}) is not "
            f"divisible by the {axis_name!r} axis size {n} — trailing "
            "rows would silently belong to no rank; pad the axial dim"
        )
    per = x.shape[dim] // n
    return jax.lax.dynamic_slice_in_dim(x, idx * per, per, axis=dim)


def gather(x, axis_name: str, dim: int):
    """≙ dap.py :: gather — leave the DAP region: all-gather ``dim``."""
    return jax.lax.all_gather(x, axis_name, axis=dim % x.ndim, tiled=True)


class DAPAxialBlock(nn.Module):
    """One pair-stack block under dynamic axial parallelism.

    ≙ the openfold evoformer pair-block pattern the reference's dap.py
    serves: row-wise self-attention while ROWS are sharded (each rank
    attends over its rows' full columns), ``row_to_col``, column-wise
    self-attention while COLS are sharded, ``col_to_row``, then a
    per-position transition MLP.  Pre-LN residual form throughout, all
    on the framework's fused LN + flash attention.

    Input/output: ``x`` of shape (R/dap, C, D) — row-sharded — when
    ``axis_name`` is set; (R, C, D) unsharded when ``axis_name=None``
    (the golden path; the test holds sharded == unsharded).
    """

    dim: int
    heads: int
    axis_name: Optional[str] = None
    mlp_ratio: int = 4

    def _attend(self, x, prefix):
        """Self-attention over the SECOND-to-last axis... x (B, S, D):
        batch B = the sharded axial dim, sequence S = the attended dim."""
        b, s, d = x.shape
        dh = d // self.heads
        qkv = nn.Dense(3 * d, use_bias=False, name=f"{prefix}_qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads_first(t):
            return t.reshape(b, s, self.heads, dh).transpose(0, 2, 1, 3)

        o = mha(heads_first(q), heads_first(k), heads_first(v))
        o = o.transpose(0, 2, 1, 3).reshape(b, s, d)
        return nn.Dense(d, use_bias=True, name=f"{prefix}_out")(o)

    def _ln(self, x, name):
        g = self.param(name + "_scale", nn.initializers.ones, (self.dim,))
        b = self.param(name + "_bias", nn.initializers.zeros, (self.dim,))
        return layer_norm(x, g, b, (self.dim,))

    @nn.compact
    def __call__(self, x):
        # --- row attention: rows sharded, attend along columns ---------
        h = self._ln(x, "ln_row")
        x = x + self._attend(h, "row")
        # --- transition to col-sharded ----------------------------------
        if self.axis_name is not None:
            x = row_to_col(x, self.axis_name)
        # --- col attention: cols sharded, attend along rows ------------
        h = self._ln(x, "ln_col")
        h = h.transpose(1, 0, 2)          # (C_loc, R, D): attend over R
        h = self._attend(h, "col")
        x = x + h.transpose(1, 0, 2)
        if self.axis_name is not None:
            x = col_to_row(x, self.axis_name)
        # --- per-position transition MLP --------------------------------
        h = self._ln(x, "ln_mlp")
        h = nn.Dense(self.mlp_ratio * self.dim, name="mlp_up")(h)
        h = jax.nn.gelu(h)
        return x + nn.Dense(self.dim, name="mlp_down")(h)


from apex_tpu.contrib.openfold.evoformer import (  # noqa: E402,F401
    EvoformerBlock,
    EvoformerPairBlock,
    GatedAttention,
    MSAColumnAttention,
    MSARowAttentionWithPairBias,
    OuterProductMean,
    PairTransition,
    TriangleAttention,
    TriangleMultiplicativeUpdate,
)
