"""NHWC BatchNorm with ReLU/Add fusions ("BNP") — ≙ ``apex/contrib/groupbn``
(``batch_norm.py`` :: ``BatchNorm2d_NHWC``, native ``batch_norm.cu``/``ipc.cu``).

The reference's MLPerf-ResNet BN: NHWC kernels with fused ReLU and fused
residual-add, plus ``bn_group`` — statistics all-reduced across a small
group of GPUs over CUDA IPC.  TPU-native: NHWC is the native layout, the
fusions are XLA's, and ``bn_group > 1`` maps to a ``psum`` over the ``dp``
mesh axis (the IPC/peer-memory machinery has no analog and needs none).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu import _compat
from apex_tpu import parallel_state as ps

__all__ = ["BatchNorm2d_NHWC"]


def _axis_bound(axis_name: str) -> bool:
    from apex_tpu.parallel_state import axis_is_bound

    # truly-bound check (size-1 axes included): the caller distinguishes
    # "not in shard_map" from "bn_group != axis size", and a bound size-1
    # axis must produce the latter, actionable, error
    return axis_is_bound(axis_name)


class BatchNorm2d_NHWC(nn.Module):
    """≙ BatchNorm2d_NHWC(num_features, fuse_relu=False, bn_group=1).

    ``__call__(x, z=None)``: optional ``z`` is the fused residual add
    (≙ the reference's bn_add_relu path).  ``bn_group > 1`` all-reduces
    the batch statistics over ``axis_name`` (requires the axis bound and
    its size equal to ``bn_group``, mirroring the reference's assert that
    the process group matches).
    """

    num_features: int
    eps: float = 1e-5
    momentum: float = 0.1  # torch convention: running = (1-m)*running + m*new
    fuse_relu: bool = False
    bn_group: int = 1
    axis_name: str = ps.DATA_PARALLEL_AXIS
    use_running_average: Optional[bool] = None
    dtype: Any = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, z=None, use_running_average: Optional[bool] = None):
        use_running_average = nn.merge_param(
            "use_running_average",
            self.use_running_average,
            use_running_average,
        )
        feat = self.num_features
        if x.shape[-1] != feat:
            raise ValueError(
                f"BatchNorm2d_NHWC expects channels-last with {feat} "
                f"channels, got {x.shape}"
            )
        xf = x.astype(jnp.float32)
        reduce_axes = tuple(range(x.ndim - 1))

        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((feat,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((feat,), jnp.float32)
        )

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            n_local = jnp.asarray(xf.size // feat, jnp.float32)
            s1 = jnp.sum(xf, axis=reduce_axes)
            s2 = jnp.sum(xf * xf, axis=reduce_axes)
            if self.bn_group > 1:
                if not _axis_bound(self.axis_name):
                    raise RuntimeError(
                        f"bn_group={self.bn_group} needs axis "
                        f"{self.axis_name!r} bound (run inside shard_map)"
                    )
                world = _compat.axis_size(self.axis_name)
                if world != self.bn_group:
                    raise ValueError(
                        f"bn_group ({self.bn_group}) must equal the "
                        f"{self.axis_name!r} axis size ({world})"
                    )
                n = jax.lax.psum(n_local, self.axis_name)
                s1 = jax.lax.psum(s1, self.axis_name)
                s2 = jax.lax.psum(s2, self.axis_name)
            else:
                n = n_local
            mean = s1 / n
            var = s2 / n - mean * mean
            if not self.is_initializing():
                m = self.momentum
                unbiased = var * n / jnp.maximum(n - 1.0, 1.0)
                ra_mean.value = (1.0 - m) * ra_mean.value + m * mean
                ra_var.value = (1.0 - m) * ra_var.value + m * unbiased

        scale = self.param(
            "weight", nn.initializers.ones, (feat,), self.param_dtype
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (feat,), self.param_dtype
        )
        y = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
        if z is not None:  # fused residual add (bn_add_relu)
            y = y + z.astype(jnp.float32)
        if self.fuse_relu:
            y = jax.nn.relu(y)
        return y.astype(self.dtype or x.dtype)
