"""≙ apex/contrib/clip_grad — fused clip_grad_norm_.

Same flat-buffer fused global-norm + scale as the reference's
``clip_grad_norm_`` built on ``multi_tensor_l2norm``/``multi_tensor_scale``.
"""

from apex_tpu.optimizers.clip_grad import clip_grad_norm_  # noqa: F401
