"""Halo exchange over a mesh axis — ≙ ``apex/contrib/peer_memory``
(``peer_memory.py`` :: ``PeerMemoryPool``, ``peer_halo_exchanger_1d.py`` ::
``PeerHaloExchanger1d``) and ≙ ``apex/contrib/nccl_p2p`` (raw
ncclSend/Recv halos).

The reference maintains a CUDA-IPC peer buffer pool so neighboring GPUs
can write each other's halo rows directly.  On TPU neighbor exchange IS
the hardware primitive — ``jax.lax.ppermute`` over an ICI ring — and XLA
owns buffers, so the pool disappears and only the exchange semantics
remain: each rank sends its edge rows to its neighbors and receives
theirs.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu import _compat
from apex_tpu import parallel_state as ps

__all__ = ["halo_exchange_1d", "PeerHaloExchanger1d", "PeerMemoryPool"]


def halo_exchange_1d(x, halo: int, *, axis: int = 1, axis_name: str = "dp"):
    """Pad ``x`` with ``halo`` rows from ring neighbors along ``axis``.

    x is this rank's shard, split along spatial ``axis`` (default 1 = H in
    NHWC).  Returns the shard concatenated with the received halos:
    shape grows by ``2*halo`` along ``axis``.  Edge ranks receive zeros
    (zero padding, matching conv zero-pad semantics at the true borders).
    """
    world = _compat.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)

    top = jax.lax.slice_in_dim(x, 0, halo, axis=axis)
    bottom = jax.lax.slice_in_dim(
        x, x.shape[axis] - halo, x.shape[axis], axis=axis
    )
    # bottom rows travel down (r -> r+1), top rows travel up (r -> r-1)
    down = [(i, (i + 1) % world) for i in range(world)]
    up = [(i, (i - 1) % world) for i in range(world)]
    from_above = jax.lax.ppermute(bottom, axis_name, down)
    from_below = jax.lax.ppermute(top, axis_name, up)
    # zero the wrapped-around halos at the global edges
    from_above = jnp.where(rank == 0, jnp.zeros_like(from_above), from_above)
    from_below = jnp.where(
        rank == world - 1, jnp.zeros_like(from_below), from_below
    )
    return jnp.concatenate([from_above, x, from_below], axis=axis)


class PeerHaloExchanger1d:
    """API-parity wrapper ≙ PeerHaloExchanger1d(ranks, rank_id, pool, half_halo)."""

    def __init__(
        self,
        axis_name: str = "dp",
        half_halo: int = 1,
        spatial_axis: int = 1,
    ):
        self.axis_name = axis_name
        self.half_halo = half_halo
        self.spatial_axis = spatial_axis

    def __call__(self, x):
        return halo_exchange_1d(
            x, self.half_halo, axis=self.spatial_axis, axis_name=self.axis_name
        )


class PeerMemoryPool:
    """≙ PeerMemoryPool — N/A on TPU (XLA owns device buffers; ppermute is
    the peer-transfer primitive).  Kept so ported code constructing a pool
    gets a clear answer instead of an AttributeError."""

    def __init__(self, *args, **kwargs):
        pass

    def allocate_peer_tensors(self, *args, **kwargs):
        raise NotImplementedError(
            "PeerMemoryPool has no TPU analog: XLA manages device buffers "
            "and jax.lax.ppermute performs neighbor transfers — use "
            "halo_exchange_1d / PeerHaloExchanger1d"
        )
