"""Fused Conv+Bias(+ReLU/+Add) — ≙ ``apex/contrib/conv_bias_relu``
(``conv_bias_relu.py`` :: ``ConvBiasReLU``/``ConvBias``/``ConvBiasMaskReLU``,
native cudnn-frontend runtime fusion ``conv_bias_relu.cpp``).

XLA fuses conv epilogues on TPU the way cudnn_frontend's runtime fusion
does on GPU, so these are thin functional wrappers over
``jax.lax.conv_general_dilated`` in NHWC (TPU-native layout; the reference
uses NHWC here too — its "channels_last" requirement).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["ConvBias", "ConvBiasReLU", "ConvBiasMaskReLU", "conv_bias"]


def conv_bias(x, weight, bias, *, stride=1, padding=1):
    """NHWC conv + bias.  weight: (KH, KW, Cin, Cout); bias (Cout,)."""
    from apex_tpu.amp.lists import amp_cast

    x, weight, bias = amp_cast("conv_bias_relu", x, weight, bias)
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = ((padding, padding), (padding, padding))
    # No preferred_element_type=f32: conv accumulates f32 internally on
    # TPU regardless (≙ cudnn's fp16-IO/f32-accumulate), and an explicit
    # f32 output breaks the conv transpose under bf16 inputs (the f32
    # cotangent can't enter the bf16 backward conv).
    y = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return (y + bias.astype(y.dtype)).astype(x.dtype)


def ConvBias(x, weight, bias, padding=1, stride=1):
    """≙ ConvBias.apply(x, weight, bias, padding, stride)."""
    return conv_bias(x, weight, bias, stride=stride, padding=padding)


def ConvBiasReLU(x, weight, bias, padding=1, stride=1):
    """≙ ConvBiasReLU.apply — conv+bias with fused ReLU epilogue."""
    return jax.nn.relu(conv_bias(x, weight, bias, stride=stride, padding=padding))


def ConvBiasMaskReLU(x, weight, bias, mask, padding=1, stride=1):
    """≙ ConvBiasMaskReLU.apply — conv+bias, elementwise mask, ReLU."""
    return jax.nn.relu(
        conv_bias(x, weight, bias, stride=stride, padding=padding) * mask
    )
