"""Fused gather-multiply — ≙ ``apex/contrib/index_mul_2d``
(``index_mul_2d.py``, native ``index_mul_2d_cuda.cu``).

``out = in1[idx] * in2`` with the backward scattering grads back through
the gather.  XLA fuses the gather into the multiply and autodiff produces
the scatter-add the reference hand-writes, so this is one expression.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["index_mul_2d"]


def index_mul_2d(in1, in2, idx1):
    """in1 (N, E); in2 (K, E); idx1 (K,) int → (K, E) = in1[idx1] * in2.

    ≙ index_mul_2d_cuda (fwd + the implicit scatter-add backward for
    repeated indices).
    """
    if in2.shape[0] != idx1.shape[0]:
        raise ValueError(
            f"in2 rows ({in2.shape[0]}) must match idx1 length "
            f"({idx1.shape[0]})"
        )
    from apex_tpu.amp.lists import amp_cast

    in1, in2 = amp_cast("index_mul_2d", in1, in2)
    return jnp.take(in1, idx1, axis=0) * in2
