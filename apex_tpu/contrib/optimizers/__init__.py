"""≙ apex/contrib/optimizers — ZeRO-sharded distributed fused optimizers.

``DistributedFusedAdam`` / ``DistributedFusedLamb``
(`apex/contrib/optimizers/distributed_fused_adam.py`,
``distributed_fused_lamb.py``): grads reduce-scattered over the DP axis,
shard-local fused update, params all-gathered — implemented TPU-natively in
:mod:`apex_tpu.parallel.distributed_fused_optimizers` (psum_scatter →
update shard → all_gather inside one jitted step).
"""

from apex_tpu.parallel.distributed_fused_optimizers import (  # noqa: F401
    DistributedFusedAdam,
    DistributedFusedLAMB,
)

# the reference's exact casing (apex/contrib/optimizers ::
# DistributedFusedLamb)
DistributedFusedLamb = DistributedFusedLAMB
