"""≙ apex/contrib/layer_norm — FastLayerNorm.

The reference's FastLayerNorm (`apex/contrib/layer_norm/layer_norm.py`,
``ln_fwd_cuda_kernel.cu``) is a persistent-kernel LayerNorm for a fixed
table of hidden sizes (768…65536).  The Pallas LayerNorm already tiles by
hidden size (apex_tpu/ops/pallas/layer_norm.py :: _block_rows), so the
"fast" path and the standard path are the same kernel here.
"""

from apex_tpu.normalization import FusedLayerNorm as FastLayerNorm  # noqa: F401
from apex_tpu.ops.layer_norm import fused_layer_norm_affine  # noqa: F401
