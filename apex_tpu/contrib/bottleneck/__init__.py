"""Fused ResNet bottleneck + spatial-parallel variant —
≙ ``apex/contrib/bottleneck`` (``bottleneck.py`` :: ``Bottleneck``,
``SpatialBottleneck``, native cudnn-frontend fusion ``bottleneck.cpp``;
halo machinery ``HaloExchangerPeer``/``HaloExchangerNCCL``).

``Bottleneck`` is the standard conv1x1-BN-ReLU / conv3x3-BN-ReLU /
conv1x1-BN + residual-add-ReLU block; the reference fuses it through cuDNN
v8 runtime graphs, XLA fuses it natively.  ``SpatialBottleneck`` runs the
same block with the feature map split along H across a mesh axis
(**spatial parallelism**): the 3x3 conv exchanges one halo row with ring
neighbors (:func:`apex_tpu.contrib.peer_memory.halo_exchange_1d`) and
convolves VALID over the haloed strip, which is numerically identical to
the undistributed SAME conv.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
from apex_tpu.contrib.peer_memory import halo_exchange_1d

__all__ = ["Bottleneck", "SpatialBottleneck"]


class _ConvBn(nn.Module):
    out_ch: int
    kernel: int
    stride: int = 1
    fuse_relu: bool = True
    spatial_axis_name: Optional[str] = None  # 3x3 halo path when set
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, z=None, *, train: bool = True):
        k = self.kernel
        if self.spatial_axis_name is not None and k == 3:
            # spatial-parallel 3x3: halo one row along H then VALID in H
            x = halo_exchange_1d(
                x, 1, axis=1, axis_name=self.spatial_axis_name
            )
            padding = ((0, 0), (1, 1))
        else:
            p = (k - 1) // 2
            padding = ((p, p), (p, p))
        y = nn.Conv(
            self.out_ch, (k, k), strides=(self.stride, self.stride),
            padding=padding, use_bias=False, dtype=self.dtype, name="conv",
        )(x)
        bn = BatchNorm2d_NHWC(
            self.out_ch, fuse_relu=self.fuse_relu, dtype=self.dtype, name="bn"
        )
        return bn(y, z, use_running_average=not train)


class Bottleneck(nn.Module):
    """≙ Bottleneck(in_channels, bottleneck_channels, out_channels, stride).

    NHWC throughout (the reference asserts ``explicit_nhwc`` for its fused
    path).  The final BN fuses the residual add + ReLU (bn_add_relu).
    """

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    dtype: Any = jnp.float32
    spatial_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        if self.spatial_axis_name is not None and self.stride != 1:
            raise ValueError(
                "spatial parallelism requires stride=1 (halo exchange does "
                "not support strided 3x3 convs, as in the reference)"
            )
        residual = x
        y = _ConvBn(
            self.bottleneck_channels, 1, dtype=self.dtype, name="conv1"
        )(x, train=train)
        y = _ConvBn(
            self.bottleneck_channels, 3, stride=self.stride,
            spatial_axis_name=self.spatial_axis_name, dtype=self.dtype,
            name="conv2",
        )(y, train=train)
        if self.stride != 1 or self.in_channels != self.out_channels:
            residual = _ConvBn(
                self.out_channels, 1, stride=self.stride, fuse_relu=False,
                dtype=self.dtype, name="downsample",
            )(x, train=train)
        # final 1x1 conv + BN with fused residual-add + ReLU
        return _ConvBn(
            self.out_channels, 1, fuse_relu=True, dtype=self.dtype,
            name="conv3",
        )(y, residual, train=train)


class SpatialBottleneck(Bottleneck):
    """≙ SpatialBottleneck — Bottleneck with H split over a mesh axis.

    Run inside ``shard_map`` with the input's H dim sharded over
    ``spatial_axis_name`` (default the ``dp`` axis, mirroring the
    reference's spatial_group).  Only stride-1 blocks may be split (the
    reference's halo exchange has the same restriction).
    """

    spatial_axis_name: Optional[str] = "dp"
