"""≙ ``apex/contrib/gpu_direct_storage`` (``gds.cpp`` :: cuFile tensor
I/O) — **N/A on TPU, documented.**

GDS DMA-transfers files directly into GPU memory via cuFile.  TPU hosts
stage through host RAM by architecture (no NVMe→HBM DMA path is exposed);
the idiomatic equivalent for checkpoint I/O is orbax/tensorstore async
checkpointing, which overlaps device→host transfer with training steps.
The functions below raise with that pointer rather than silently failing.
"""

from __future__ import annotations

__all__ = ["load_data", "save_data"]

_MSG = (
    "GPUDirect Storage has no TPU analog (no NVMe-to-HBM DMA path). For "
    "fast checkpoint I/O use orbax-checkpoint (async, tensorstore-backed), "
    "which overlaps device-to-host transfer with compute."
)


def load_data(*args, **kwargs):
    raise NotImplementedError(_MSG)


def save_data(*args, **kwargs):
    raise NotImplementedError(_MSG)
