"""Raw neighbor send/recv — ≙ ``apex/contrib/nccl_p2p`` (``nccl_p2p.py``,
native ``nccl_p2p_cuda.cu`` :: ``left_right_halo_exchange``).

The reference bypasses ``torch.distributed`` with raw ``ncclSend/Recv``
for halo traffic.  The TPU primitive is ``jax.lax.ppermute``; the
convenience functions below mirror the reference's call shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["left_right_halo_exchange", "halo_exchange_1d"]

from apex_tpu import _compat
from apex_tpu.contrib.peer_memory import halo_exchange_1d


def left_right_halo_exchange(
    left_output_halo, right_output_halo, axis_name: str = "dp"
):
    """Send left/right edge halos to the respective neighbors.

    ≙ nccl_p2p_cuda.left_right_halo_exchange: returns
    (left_input_halo, right_input_halo) — what the left/right neighbors
    sent this rank (zeros at the global edges).
    """
    world = _compat.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    to_left = [(i, (i - 1) % world) for i in range(world)]
    to_right = [(i, (i + 1) % world) for i in range(world)]
    # my left halo goes to my left neighbor's right input, and vice versa
    right_input_halo = jax.lax.ppermute(left_output_halo, axis_name, to_left)
    left_input_halo = jax.lax.ppermute(right_output_halo, axis_name, to_right)
    left_input_halo = jnp.where(
        rank == 0, jnp.zeros_like(left_input_halo), left_input_halo
    )
    right_input_halo = jnp.where(
        rank == world - 1, jnp.zeros_like(right_input_halo), right_input_halo
    )
    return left_input_halo, right_input_halo
