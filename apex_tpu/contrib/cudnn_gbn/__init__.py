"""Group BatchNorm (cuDNN-backend flavor) — ≙ ``apex/contrib/cudnn_gbn``
(``cudnn_gbn.py`` :: ``GroupBatchNorm2d``, native ``cudnn_gbn.cpp``/
``norm_sample.cpp``).

Functionally the same op as :mod:`apex_tpu.contrib.groupbn` (NHWC BN whose
statistics are reduced across a device group, with the BN-Add-ReLU fused
graph); the reference ships it twice because it has two native backends
(hand CUDA vs cuDNN v8 graphs).  One TPU implementation serves both —
re-exported here so either import path works.
"""

from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

__all__ = ["GroupBatchNorm2d", "BatchNorm2d_NHWC"]

GroupBatchNorm2d = BatchNorm2d_NHWC
