"""FMHA — packed/varlen flash attention ≙ ``apex/contrib/fmha``.

The reference (`apex/contrib/fmha/fmha.py :: FMHAFun`) consumes an unpadded
token-packed ``(total_tokens, 3, H, D)`` QKV with ``cu_seqlens`` prefix
offsets, running fixed-seqlen flash kernels (128–512) per batch — the MLPerf
BERT input pipeline.  On TPU, dynamic per-batch shapes defeat XLA, so the
idiomatic equivalent keeps the batch padded to ``(B, S, 3, H, D)`` and masks
padding keys inside the flash kernel via an additive bias built from
``seqlens``; the arithmetic per valid token is identical and the padded
positions are skipped by the online softmax (masked to -1e9).
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.ops.attention import fmha_qkvpacked
from apex_tpu.ops.pallas.flash_attention import MASK_VALUE

__all__ = ["fmha", "fmha_qkvpacked", "padding_bias_from_seqlens"]


def padding_bias_from_seqlens(seqlens, max_seqlen):
    """(B,) valid lengths → (B, 1, 1, S) additive key-padding bias."""
    pos = jnp.arange(max_seqlen)
    return jnp.where(
        pos[None, :] < seqlens[:, None], 0.0, MASK_VALUE
    )[:, None, None, :]


def fmha(qkv, seqlens=None, *, causal=False, dropout_p=0.0, dropout_rng=None):
    """≙ ``FMHAFun(qkv, cu_seqlens, ...)`` on a padded batch.

    qkv: (B, S, 3, H, D); seqlens: optional (B,) int valid lengths.
    Returns (B, S, H, D).  The bias masks *keys* past ``seqlens``; query
    rows past ``seqlens`` still attend (over the valid keys only) and
    yield garbage values the caller masks downstream — exactly as the
    reference's unpadded layout implies for tokens that do not exist.
    """
    bias = None
    if seqlens is not None:
        bias = padding_bias_from_seqlens(seqlens, qkv.shape[1])
    return fmha_qkvpacked(
        qkv, bias, causal=causal, dropout_p=dropout_p, dropout_rng=dropout_rng
    )
