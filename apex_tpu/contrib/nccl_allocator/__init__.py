"""≙ ``apex/contrib/nccl_allocator`` (``NCCLAllocator.cpp`` ::
``ncclMemAlloc``-backed pluggable allocator for NCCL user-buffer
registration) — **N/A on TPU, by design.**

The reference exists because NCCL ≥ 2.19 can skip internal staging copies
when communication buffers are registered with it.  On TPU, XLA owns every
device buffer and its collectives already read/write operand buffers
directly over ICI — there is nothing to register and no allocator to
plug.  ``init()`` and the ``nccl_mem`` context are provided as explicit
no-ops so ported code runs unchanged.
"""

from __future__ import annotations

import contextlib

__all__ = ["init", "nccl_mem"]


def init(*args, **kwargs) -> None:
    """No-op (XLA manages buffers; see module docstring)."""


@contextlib.contextmanager
def nccl_mem(*args, **kwargs):
    """No-op context (≙ ``with nccl_allocator.nccl_mem(): ...``)."""
    yield
