"""NHWC GroupNorm (+SiLU fusion) — ≙ ``apex/contrib/group_norm``
(``group_norm.py`` :: ``GroupNorm``, native ``apex/contrib/csrc/group_norm/*.cu``).

The reference hand-writes NHWC GroupNorm kernels (with optional fused
swish) for diffusion workloads.  On TPU the layout is already NHWC and XLA
fuses normalize+affine+SiLU into the surrounding elementwise chain, so this
is a jnp composition with f32 statistics — the kernel table
(``GN_SUPPORTED_CHANNELS``-style) is unnecessary: any channel count works.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["GroupNorm", "group_norm"]

_ACTS = {None: lambda x: x, "": lambda x: x, "silu": jax.nn.silu, "swish": jax.nn.silu}


def group_norm(
    x,
    num_groups: int,
    weight=None,
    bias=None,
    eps: float = 1e-5,
    act: Optional[str] = None,
):
    """x: (..., C) channels-last.  Stats over (spatial..., C/G) per group."""
    if act not in _ACTS:
        raise ValueError(f"act must be one of {sorted(k or '' for k in _ACTS)}")
    from apex_tpu.amp.lists import amp_cast

    x, weight, bias = amp_cast("group_norm", x, weight, bias)
    c = x.shape[-1]
    if c % num_groups:
        raise ValueError(f"channels {c} not divisible by num_groups {num_groups}")
    orig_dtype = x.dtype
    n = x.shape[0]
    # f32 statistics by design (keep_batchnorm_fp32); named scope =
    # policy-exempt for analysis' promotion lint
    with jax.named_scope("gn_f32_stats"):
        xf = x.astype(jnp.float32).reshape(
            n, -1, num_groups, c // num_groups
        )
        mean = jnp.mean(xf, axis=(1, 3), keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=(1, 3), keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + eps)
        y = y.reshape(x.shape)
        if weight is not None:
            y = y * weight.astype(jnp.float32)
        if bias is not None:
            y = y + bias.astype(jnp.float32)
    return _ACTS[act](y).astype(orig_dtype)


class GroupNorm(nn.Module):
    """≙ apex.contrib.group_norm.GroupNorm(num_groups, num_channels, eps,
    affine, act) — drop-in for torch.nn.GroupNorm plus the ``act`` fusion."""

    num_groups: int
    num_channels: int
    eps: float = 1e-5
    affine: bool = True
    act: Optional[str] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        if x.shape[-1] != self.num_channels:
            raise ValueError(
                f"expected channels-last input with {self.num_channels} "
                f"channels, got {x.shape}"
            )
        w = b = None
        if self.affine:
            w = self.param(
                "weight", nn.initializers.ones, (self.num_channels,),
                self.param_dtype,
            )
            b = self.param(
                "bias", nn.initializers.zeros, (self.num_channels,),
                self.param_dtype,
            )
        return group_norm(x, self.num_groups, w, b, self.eps, self.act)
