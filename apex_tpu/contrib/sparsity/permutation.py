"""Channel-permutation search for 2:4 sparsity — ≙ ``apex/contrib/
sparsity/permutation_lib.py`` + ``csrc/permutation_search/`` (the
"Channel Permutations for N:M Sparsity" accuracy-preserving step).

The reference searches for a permutation of a weight's input channels
that maximizes the magnitude RETAINED by the 2:4 mask: channels that
land in the same group of 4 compete for the 2 kept slots, so grouping
channels whose large entries fall on different rows preserves more
magnitude.  Its CUDA kernels accelerate a bounded-exhaustive "stripe
group" search; the documented CPU fallback is a greedy swap search —
which is what this pure-numpy implementation provides (functional
parity; the CUDA speedups exist purely to make big searches cheap).

Algorithm (greedy best-swap):

1. quality(g) = Σ_rows top2(|W|[row, channels of g]) for each group of 4.
2. For every (channel i, channel j) in different groups, the gain of
   swapping them is computable from only the two affected groups; all
   candidate gains are evaluated vectorized via a (G, 4, C) replacement-
   quality tensor.
3. Apply the best positive swap, update the two affected groups'
   entries, repeat until no swap helps (or ``max_swaps``).

Like the reference, the permutation only preserves the network function
if the producing layer's OUTPUT channels are permuted to match —
``apply_permutation`` permutes the pruned weight's input axis, and the
caller applies the same permutation to whatever feeds that axis (the
reference automates this with torch-graph propagation; a functional
param tree has no graph to walk, so the pairing is explicit here).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = [
    "search_channel_permutation",
    "permutation_retained_magnitude",
    "apply_permutation",
    "invert_permutation",
]


def _to_2d(weight, axis: int) -> np.ndarray:
    w = np.moveaxis(np.asarray(weight, np.float32), axis, -1)
    return np.abs(w.reshape(-1, w.shape[-1]))


def permutation_retained_magnitude(weight, perm, axis: int = -1) -> float:
    """Σ|w| kept by the 2:4 mask after permuting channels of ``axis``."""
    mag = _to_2d(weight, axis)[:, np.asarray(perm)]
    r, c = mag.shape
    groups = mag.reshape(r, c // 4, 4)
    top2 = np.sort(groups, axis=-1)[..., 2:]
    return float(top2.sum())


def _group_quality(mag: np.ndarray, channels: np.ndarray) -> np.ndarray:
    """(G,) retained magnitude per group; ``channels`` is (G, 4)."""
    g = mag[:, channels]                      # (R, G, 4)
    return np.sort(g, axis=-1)[..., 2:].sum(axis=(0, 2))


def _replacement_quality(mag: np.ndarray, channels: np.ndarray) -> np.ndarray:
    """(G, 4, C) quality of group g with slot s replaced by channel x."""
    r, c = mag.shape
    g_count = channels.shape[0]
    out = np.empty((g_count, 4, c), np.float32)
    for g in range(g_count):
        for s in range(4):
            keep = [channels[g, t] for t in range(4) if t != s]
            fixed = mag[:, keep]              # (R, 3)
            cand = np.concatenate(
                [np.broadcast_to(fixed[:, None, :], (r, c, 3)),
                 mag[:, :, None]], axis=-1,
            )                                  # (R, C, 4)
            out[g, s] = np.sort(cand, axis=-1)[..., 2:].sum(axis=(0, 2))
    return out


def search_channel_permutation(
    weight,
    axis: int = -1,
    max_swaps: int = 10_000,
    min_gain: float = 1e-6,
) -> Tuple[np.ndarray, float, float]:
    """Greedy best-swap search.  Returns ``(perm, before, after)`` where
    ``before``/``after`` are the retained magnitudes of the identity and
    found permutations (``after >= before`` always).
    """
    mag = _to_2d(weight, axis)
    r, c = mag.shape
    if c % 4:
        raise ValueError(f"channel count ({c}) must be divisible by 4")
    g_count = c // 4
    channels = np.arange(c).reshape(g_count, 4)
    quality = _group_quality(mag, channels)
    before = float(quality.sum())
    if g_count < 2:
        return np.arange(c), before, before

    repl = _replacement_quality(mag, channels)

    # gain of swapping (g1, s1) <-> (g2, s2):
    #   repl[g1, s1, ch(g2, s2)] + repl[g2, s2, ch(g1, s1)]
    #   - quality[g1] - quality[g2]
    def best_swap():
        ch_flat = channels.reshape(-1)                      # (G*4,)
        q_flat = np.repeat(quality, 4)                      # (G*4,)
        gain_to = repl.reshape(g_count * 4, c)[:, ch_flat]  # (G4, G4)
        gains = gain_to + gain_to.T - q_flat[:, None] - q_flat[None, :]
        # same-group swaps are no-ops; mask them
        gid = np.repeat(np.arange(g_count), 4)
        gains[gid[:, None] == gid[None, :]] = -np.inf
        idx = int(np.argmax(gains))
        a, b = divmod(idx, g_count * 4)
        return float(gains[a, b]), a, b

    swaps = 0
    while swaps < max_swaps:
        gain, a, b = best_swap()
        if gain <= min_gain:
            break
        g1, s1 = divmod(a, 4)
        g2, s2 = divmod(b, 4)
        channels[g1, s1], channels[g2, s2] = (
            channels[g2, s2], channels[g1, s1],
        )
        for g in (g1, g2):
            quality[g] = _group_quality(mag, channels[g : g + 1])[0]
            repl[g] = _replacement_quality(mag, channels[g : g + 1])[0]
        swaps += 1

    perm = channels.reshape(-1)
    after = float(quality.sum())
    return perm, before, after


def apply_permutation(weight, perm, axis: int = -1):
    """Permute ``axis`` of ``weight`` by ``perm`` (numpy or jax array in,
    same type out via take)."""
    import jax.numpy as jnp

    return jnp.take(jnp.asarray(weight), jnp.asarray(perm), axis=axis)


def invert_permutation(perm) -> np.ndarray:
    perm = np.asarray(perm)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(perm.size)
    return inv
