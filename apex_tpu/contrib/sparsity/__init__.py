"""ASP — automatic structured (2:4) sparsity — ≙ ``apex/contrib/sparsity``
(``asp.py`` :: ``ASP``, ``sparse_masklib.py`` :: ``create_mask``,
``permutation_lib.py``; native permutation-search kernels).

Functional parity, documented delta: TPUs have no 2:4 sparse tensor cores,
so the masks here buy model compression / sparse fine-tuning semantics
(mask weights, keep masks applied through optimizer steps), not a matmul
speedup.  The mask math matches the reference: for each group of 4
consecutive weights **along the matmul reduction (input) dim**, keep the
2 of largest magnitude.  Torch Linear weights are ``(out, in)`` so the
reference prunes the last axis; flax kernels are ``(in, out)`` so here the
input dim is axis ``-2`` — :func:`create_mask` takes the axis explicitly
and :class:`ASP` picks it from the leaf name.  The accuracy-preserving
channel-permutation search (``permutation_lib.py``) ships as
:mod:`.permutation` / :meth:`ASP.compute_permutations` — a greedy
best-swap search, the reference's CPU strategy minus the CUDA speedups.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.contrib.sparsity.permutation import (  # noqa: F401
    apply_permutation,
    invert_permutation,
    permutation_retained_magnitude,
    search_channel_permutation,
)

__all__ = [
    "create_mask",
    "ASP",
    "search_channel_permutation",
    "permutation_retained_magnitude",
    "apply_permutation",
    "invert_permutation",
]

PyTree = Any


def create_mask(weight, pattern: str = "m4n2_1d", axis: int = -1):
    """2:4 mask along ``axis`` — ≙ sparse_masklib.create_mask.

    Keeps the top-2 |w| in every aligned group of 4 along ``axis``
    (which must have length divisible by 4).
    """
    if pattern not in ("m4n2_1d", "m4n2"):
        raise ValueError(f"unsupported sparsity pattern {pattern!r}")
    axis = axis % weight.ndim
    w = jnp.moveaxis(weight, axis, -1)
    k = w.shape[-1]
    if k % 4:
        raise ValueError(f"pruned axis length ({k}) must be divisible by 4")
    mag = jnp.abs(w.astype(jnp.float32)).reshape(*w.shape[:-1], k // 4, 4)
    # rank within each group; keep the two largest magnitudes
    order = jnp.argsort(mag, axis=-1)  # ascending
    ranks = jnp.argsort(order, axis=-1)
    mask = (ranks >= 2).reshape(w.shape)
    return jnp.moveaxis(mask, -1, axis)


def _input_axis(path: str) -> int:
    """The matmul reduction axis by layout convention: flax 'kernel' is
    (in, out) → -2; torch-style 'weight' is (out, in) → -1."""
    return -2 if "kernel" in path else -1


def _default_allowed(path: str, leaf) -> bool:
    """Prune 2-D+ matmul weights only (the reference whitelists Linear/Conv
    weights with both dims >= 16 and skips biases/norms)."""
    if leaf.ndim < 2:
        return False
    if leaf.shape[-1] < 16 or leaf.shape[-2] < 16:
        return False
    if "kernel" not in path and "weight" not in path:
        return False
    return leaf.shape[_input_axis(path)] % 4 == 0


class ASP:
    """≙ apex.contrib.sparsity.ASP — functional-state version.

    Workflow (mirrors ``ASP.prune_trained_model(model, optimizer)``)::

        masks = ASP.compute_sparse_masks(params)     # one-time mask search
        params = ASP.apply_masks(params, masks)      # zero the pruned half
        ...
        grads = ASP.apply_masks(grads, masks)        # inside the train step
        params = ASP.apply_masks(new_params, masks)  # keep update sparse

    Non-pruned leaves carry a scalar ``True`` sentinel (not a full-size
    mask): no memory held, and ``apply_masks`` passes them through
    untouched.
    """

    @staticmethod
    def compute_sparse_masks(
        params: PyTree,
        allowed: Optional[Callable[[str, Any], bool]] = None,
        pattern: str = "m4n2_1d",
    ) -> PyTree:
        allowed = allowed or _default_allowed
        flat = jax.tree_util.tree_leaves_with_path(params)

        def mask_for(path, leaf):
            name = jax.tree_util.keystr(path)
            if allowed(name, leaf):
                return create_mask(leaf, pattern, axis=_input_axis(name))
            return jnp.asarray(True)  # scalar sentinel: leaf not pruned

        masks = [mask_for(p, l) for p, l in flat]
        treedef = jax.tree_util.tree_structure(params)
        return jax.tree_util.tree_unflatten(treedef, masks)

    @staticmethod
    def apply_masks(tree: PyTree, masks: PyTree) -> PyTree:
        return jax.tree_util.tree_map(
            lambda x, m: x if m.ndim == 0 else x * m.astype(x.dtype),
            tree,
            masks,
        )

    @staticmethod
    def prune_trained_model(params: PyTree, pattern: str = "m4n2_1d"):
        """One-shot: returns (pruned_params, masks)."""
        masks = ASP.compute_sparse_masks(params, pattern=pattern)
        return ASP.apply_masks(params, masks), masks

    @staticmethod
    def compute_permutations(
        params: PyTree,
        allowed: Optional[Callable[[str, Any], bool]] = None,
        max_swaps: int = 10_000,
    ) -> PyTree:
        """≙ permutation_lib's search step: per prunable leaf, a channel
        permutation of the input dim that the 2:4 mask will retain more
        magnitude under (greedy best-swap; ``after >= before`` always).

        Returns a pytree matching ``params`` whose prunable leaves hold
        ``{"perm": ndarray, "axis": int, "before": float, "after": float}``
        and other leaves ``None``.  Apply with
        ``apply_permutation(leaf, entry["perm"], entry["axis"])`` — and,
        to preserve the network function, apply the SAME permutation to
        the producing layer's output channels (the reference walks the
        torch graph to do this; a functional tree needs the caller to
        name the pairing).
        """
        allowed = allowed or _default_allowed
        flat = jax.tree_util.tree_leaves_with_path(params)

        def perm_for(path, leaf):
            name = jax.tree_util.keystr(path)
            if not allowed(name, leaf):
                return None
            axis = _input_axis(name)
            perm, before, after = search_channel_permutation(
                leaf, axis=axis, max_swaps=max_swaps
            )
            return {
                "perm": perm, "axis": axis,
                "before": before, "after": after,
            }

        perms = [perm_for(p, l) for p, l in flat]
        treedef = jax.tree_util.tree_structure(params)
        return jax.tree_util.tree_unflatten(treedef, perms)
