"""Shared utilities (profiling/tracing hooks)."""

from apex_tpu.utils.profiling import (
    annotate,
    nvtx_range,
    range_pop,
    range_push,
    trace,
)

__all__ = ["annotate", "nvtx_range", "range_push", "range_pop", "trace"]
