"""Shared utilities (profiling/tracing hooks).

The hooks now live in :mod:`apex_tpu.observability.trace`; this package
keeps re-exporting them (``apex_tpu.utils.trace`` is used throughout
bench.py and the tools) so callers need not care where they moved.
"""

from apex_tpu.observability.trace import (
    annotate,
    nvtx_range,
    range_pop,
    range_push,
    trace,
)

__all__ = ["annotate", "nvtx_range", "range_push", "range_pop", "trace"]
