"""DEPRECATED shim — the tracing hooks moved to
:mod:`apex_tpu.observability.trace` (where scheduled profiling windows,
the step-telemetry registry, and the metric sinks now live together;
see ``docs/observability.md``).

This module re-exports the original five names so existing imports keep
working; new code should import from ``apex_tpu.observability`` (or its
``trace`` submodule) directly.
"""

from apex_tpu.observability.trace import (  # noqa: F401
    annotate,
    nvtx_range,
    range_pop,
    range_push,
    trace,
)

__all__ = ["annotate", "nvtx_range", "range_push", "range_pop", "trace"]
