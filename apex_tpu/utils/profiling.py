"""Tracing / profiling hooks — the TPU analog of the reference's NVTX ranges.

The reference brackets its hot regions with ``torch.cuda.nvtx.range_push`` /
``range_pop`` (e.g. ``apex/parallel/distributed.py``'s allreduce regions) so
kernels group under named spans in Nsight.  The XLA equivalent is two-level:

- :func:`annotate` (``jax.named_scope``) names a region of the *traced*
  computation — the name lands in HLO metadata and therefore in the XLA
  op-profile / Perfetto trace for every kernel fused from that region.
- :func:`nvtx_range` / :func:`range_push` / :func:`range_pop` name a span on
  the *host* timeline (``jax.profiler.TraceAnnotation``), for dispatch-side
  bracketing exactly like NVTX.
- :func:`trace` wraps a block in ``jax.profiler.trace`` and writes a
  TensorBoard/Perfetto-viewable profile directory (bench.py --trace).

All hooks are zero-cost when no profiler is attached: ``named_scope`` only
adds HLO metadata at trace time and ``TraceAnnotation`` is a no-op without an
active collector — matching the survey's "build them in, they're free" rule.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, List

import jax

__all__ = ["annotate", "nvtx_range", "range_push", "range_pop", "trace"]

# module-level stack for the push/pop API (host-side spans, NVTX-style)
_RANGE_STACK: List[contextlib.AbstractContextManager] = []


def annotate(name: str):
    """Name a traced-computation region (``jax.named_scope``).

    Use inside jitted code; the name propagates into HLO metadata so the
    XLA profiler attributes fused kernels to it.
    """
    return jax.named_scope(name)


@contextlib.contextmanager
def nvtx_range(name: str) -> Iterator[None]:
    """Host-timeline span (≙ ``torch.cuda.nvtx.range`` context manager)."""
    with jax.profiler.TraceAnnotation(name):
        yield


def range_push(name: str) -> None:
    """≙ ``torch.cuda.nvtx.range_push`` — begin a host-timeline span."""
    cm = jax.profiler.TraceAnnotation(name)
    cm.__enter__()
    _RANGE_STACK.append(cm)


def range_pop() -> None:
    """≙ ``torch.cuda.nvtx.range_pop`` — end the innermost span."""
    if not _RANGE_STACK:
        raise RuntimeError("range_pop() without matching range_push()")
    _RANGE_STACK.pop().__exit__(None, None, None)


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Collect a device+host profile into ``log_dir`` (TensorBoard /
    Perfetto viewable).  Wrap a steady-state window, not compilation."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
