"""Expert parallelism — Switch-style Mixture-of-Experts over a mesh axis.

**No reference analog** (SURVEY §2.3: EP/MoE is ABSENT in the reference —
``parallel_state`` has no expert groups).  This module is the TPU-native
extension that completes the parallelism envelope (dp/tp/sp/pp/cp/ep):

- :class:`SwitchMoe` — a drop-in MoE FFN block: top-1 or top-2 router,
  fixed expert capacity (static shapes — the XLA requirement), experts
  sharded across ``expert_axis`` (Megatron's convention: the expert group
  is carved out of the data-parallel world, so no new mesh axis is
  needed), token dispatch via ``jax.lax.all_to_all``, and the Switch
  auxiliary load-balancing loss.

Dataflow per shard_map rank (T = local tokens, E = global experts,
E_l = E / ep local experts, C = capacity per expert):

    router logits (T, E) → dispatch one-hots (T, E, C)        [einsum form:
    combine weights  (T, E, C)                 Mesh-TensorFlow/GShard MoE]
    x (T, H) ──einsum──▶ (E, C, H) ──all_to_all(ep)──▶ (E_l, ep·C, H)
        ──batched expert FFN (E_l,·,H)@(E_l,H,F)──▶ (E_l, ep·C, H)
        ──all_to_all back──▶ (E, C, H) ──combine──▶ (T, H)

The one-hot dispatch keeps every shape static and lowers to MXU-friendly
einsums; overflow tokens beyond an expert's capacity are dropped (their
combine weight is zero — the standard Switch behavior) and pass through
the residual connection of the surrounding block.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu import _compat
from apex_tpu import parallel_state as ps

__all__ = [
    "MoeConfig",
    "SwitchMoe",
    "moe_dispatch_combine",
    "sync_moe_gradients",
]


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    hidden_size: int
    ffn_hidden_size: int
    num_experts: int
    top_k: int = 1  # 1 = Switch, 2 = GShard-style top-2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    # router always computes in f32 (the Switch paper's stability rule);
    # expert FFN computes in `dtype`
    dtype: Any = jnp.bfloat16
    # mesh axis the experts shard over; None = unsharded (single program).
    # "dp" is the Megatron convention (expert group ⊂ data-parallel world).
    expert_axis: Optional[str] = ps.DATA_PARALLEL_AXIS
    # True when this block runs inside the sequence-parallel region at
    # tp > 1 (each tp rank routes only its S/tp tokens): router + expert
    # params then carry tp-PARTIAL gradients and are registered for
    # allreduce_sequence_parallel_gradients' tp psum.
    sequence_parallel: bool = False
    # True under context parallelism (tokens sharded over the cp axis):
    # aux stats are pmean'd over cp with grad scale 1.0 — cp gradients
    # are synced with pmean (a data axis), not psum, so no rescale is
    # needed and no param marking happens.  Mutually exclusive with
    # sequence_parallel.
    context_parallel: bool = False

    def __post_init__(self):
        if self.top_k not in (1, 2):
            raise ValueError(f"top_k must be 1 or 2, got {self.top_k}")
        if self.sequence_parallel and self.context_parallel:
            raise ValueError(
                "sequence_parallel and context_parallel are mutually "
                "exclusive (both shard the token dimension)"
            )


def _axis_size(axis: Optional[str]) -> int:
    return 1 if axis is None else ps.bound_axis_size(axis)


def moe_dispatch_combine(router_probs, top_k, capacity, stats_axis=None,
                         stats_grad_scale=None):
    """Dispatch/combine tensors from router probabilities.

    router_probs f32 (T, E) (already softmaxed).  Returns
    ``(dispatch (T, E, C) bool-as-float, combine (T, E, C) f32, aux)``:
    position-in-expert is assigned by cumulative count in token order
    (earlier tokens win capacity — the Switch rule), ``aux`` is the
    load-balancing loss term  E · Σ_e f_e · P_e  (fraction routed ×
    mean prob).

    ``stats_axis``: mesh axis to pmean the aux statistics (f_e, P_e) over
    before forming the product — used whenever tokens are SHARDED over an
    axis (Megatron SP over tp; context parallelism over cp): aux is
    quadratic in the stats, so the mean of per-shard aux ≠ the
    global-batch aux; pmean'ing the stats first recovers exactly the
    unsharded value.

    ``stats_grad_scale``: per-rank scale applied to the aux GRADIENT
    (value unchanged, via stop_gradient).  pmean's VJP psums the
    cotangent across ranks, so each rank's aux backward carries the FULL
    E·f̄ factor on its local-path derivative.  The right scale depends on
    how the caller then syncs gradients over ``stats_axis``:

    - psum sync (Megatron SP: allreduce_sequence_parallel_gradients):
      scale 1/n, else the summed partials are n× the true gradient —
      the default (``None`` → 1/axis_size);
    - pmean sync (context parallelism treats cp as a data axis): scale
      1.0 — the 1/n of the pmean already cancels the full factor.
    """
    t, e = router_probs.shape
    # top-k expert choices per token
    _, expert_idx = jax.lax.top_k(router_probs, top_k)  # (T, K)
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # (T, K, E)

    # aux loss uses the top-1 assignment fraction (Switch definition)
    frac_routed = jnp.mean(onehot[:, 0, :], axis=0)  # (E,)
    mean_prob = jnp.mean(router_probs, axis=0)  # (E,)
    if stats_axis is not None:
        frac_routed = jax.lax.pmean(frac_routed, stats_axis)
        mean_prob = jax.lax.pmean(mean_prob, stats_axis)
        aux = e * jnp.sum(frac_routed * mean_prob)
        scale = (
            1.0 / _compat.axis_size(stats_axis)
            if stats_grad_scale is None
            else stats_grad_scale
        )
        if scale != 1.0:
            aux = aux * scale + jax.lax.stop_gradient(aux * (1.0 - scale))
    else:
        aux = e * jnp.sum(frac_routed * mean_prob)

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    # running per-expert fill counts across the K choices: a token's k-th
    # choice sees capacity consumed by ALL tokens' earlier choices and by
    # earlier tokens' k-th choice (exact GShard ordering for K <= 2)
    fill = jnp.zeros((e,), jnp.float32)
    for k in range(top_k):
        oh = onehot[:, k, :]  # (T, E)
        pos = jnp.cumsum(oh, axis=0) - oh + fill[None, :]  # (T, E)
        keep = oh * (pos < capacity)
        pos_clamped = jnp.minimum(pos, capacity - 1).astype(jnp.int32)
        pos_oh = jax.nn.one_hot(pos_clamped, capacity, dtype=jnp.float32)
        sel = keep[..., None] * pos_oh  # (T, E, C)
        dispatch = dispatch + sel
        gate = jnp.sum(router_probs * oh, axis=-1)  # (T,)
        combine = combine + sel * gate[:, None, None]
        fill = fill + jnp.sum(oh, axis=0)
    if top_k == 2:
        # renormalize the KEPT gates so they sum to 1 per token (GShard's
        # top-2 rule); a token whose both choices overflowed keeps 0
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = jnp.where(
            denom > 0.0, combine / jnp.maximum(denom, 1e-9), 0.0
        )
    return dispatch, combine, aux


def sync_moe_gradients(grads, axis: str = ps.EXPERT_PARALLEL_AXIS,
                       average: bool = True,
                       sequence_parallel_axis: Optional[str] = None):
    """Data-parallel gradient sync that understands expert sharding.

    A plain ``psum``/``pmean`` over dp (apex_tpu.parallel's DDP) is WRONG
    for an MoE model: expert weights are dp-SHARDED (rank r owns experts
    ``[r·E_l, (r+1)·E_l)``), so an element-wise allreduce would mix the
    gradients of DIFFERENT experts.  And it is also unnecessary — each
    rank's experts already saw every rank's tokens through the all_to_all
    dispatch, so their backward aggregates over the full global batch.
    This helper reduces every leaf EXCEPT those whose path contains a
    parameter named with SwitchMoe's ``expert_`` prefix.

    Scaling: the backward ``all_to_all`` already delivers to each expert
    owner the SUM over every rank's loss of that expert's gradient.  So
    for the mean global objective (``average=True``, pmean on the other
    leaves — DDP's gradient_average semantics) expert leaves are scaled
    by ``1/axis_size`` to match; for the sum objective (``average=False``,
    psum) they are left as the sum they already are.

    With tensor parallelism AND ``sequence_parallel`` (each tp rank routes
    only its S/tp tokens — set ``MoeConfig.sequence_parallel=True``), pass
    ``sequence_parallel_axis="tp"``: router/expert/LN grads are then also
    psum'd over tp via :func:`allreduce_sequence_parallel_gradients`
    (they are tp-replicated params with tp-partial gradients; without the
    reduction the replicated copies silently diverge).
    """
    from jax.tree_util import DictKey, tree_map_with_path

    reduce_ = jax.lax.pmean if average else jax.lax.psum
    world = _compat.axis_size(axis)

    def maybe_reduce(path, g):
        for k in path:
            if isinstance(k, DictKey) and str(k.key).startswith("expert_"):
                return g / world if average else g
        return reduce_(g, axis)

    grads = tree_map_with_path(maybe_reduce, grads)
    if sequence_parallel_axis is not None:
        from apex_tpu.transformer.tensor_parallel.mappings import (
            allreduce_sequence_parallel_gradients,
        )

        grads = allreduce_sequence_parallel_gradients(
            grads, sequence_parallel_axis
        )
    return grads


class SwitchMoe(nn.Module):
    """MoE FFN block (router + sharded experts + dispatch/combine).

    Input/output ``(S, B, H)`` (seq-first, matching the transformer
    stack).  Returns ``(y, aux_loss)`` — add ``cfg.aux_loss_coef * aux``
    to the training loss.  Expert weights are stored as the LOCAL shard
    ``(E_l, ...)`` when ``cfg.expert_axis`` is bound (ep-degree-invariant
    init: each rank folds its expert ids into the param key, so global
    expert e has identical weights at any ep degree).
    """

    cfg: MoeConfig

    @nn.compact
    def __call__(self, x) -> Tuple[jax.Array, jax.Array]:
        cfg = self.cfg
        s, b, h = x.shape
        if h != cfg.hidden_size:
            raise ValueError(f"hidden {h} != cfg.hidden_size {cfg.hidden_size}")
        ep = _axis_size(cfg.expert_axis)
        if cfg.num_experts % ep:
            raise ValueError(
                f"num_experts ({cfg.num_experts}) must be divisible by the "
                f"expert axis size ({ep})"
            )
        e_local = cfg.num_experts // ep
        tokens = s * b
        capacity = int(cfg.capacity_factor * tokens / cfg.num_experts + 0.5)
        capacity = max(capacity, 1)

        xt = x.reshape(tokens, h)
        # --- router (f32, replicated) ---------------------------------
        router_w = self.param(
            "router",
            nn.initializers.normal(stddev=0.02),
            (h, cfg.num_experts),
            jnp.float32,
        )
        logits = xt.astype(jnp.float32) @ router_w
        probs = jax.nn.softmax(logits, axis=-1)
        stats_axis, stats_grad_scale = None, None
        if cfg.sequence_parallel and ps.axis_is_bound(
            ps.TENSOR_PARALLEL_AXIS
        ):
            stats_axis = ps.TENSOR_PARALLEL_AXIS  # psum sync → 1/n scale
        elif cfg.context_parallel and ps.axis_is_bound(
            ps.CONTEXT_PARALLEL_AXIS
        ):
            stats_axis = ps.CONTEXT_PARALLEL_AXIS
            stats_grad_scale = 1.0  # pmean sync cancels the factor
        dispatch, combine, aux = moe_dispatch_combine(
            probs, cfg.top_k, capacity, stats_axis=stats_axis,
            stats_grad_scale=stats_grad_scale,
        )

        # --- expert weights: LOCAL shard, ep-degree-invariant init ----
        def expert_init(fan_in, fan_out):
            def init(key):
                rank = 0
                if ep > 1:
                    rank = jax.lax.axis_index(cfg.expert_axis)
                keys = jax.vmap(
                    lambda i: jax.random.fold_in(key, rank * e_local + i)
                )(jnp.arange(e_local))
                w_init = nn.initializers.normal(stddev=fan_in**-0.5)
                return jax.vmap(lambda k: w_init(k, (fan_in, fan_out)))(keys)

            return init

        # the "expert_" prefix marks dp-SHARDED parameters — the contract
        # sync_moe_gradients uses to exclude them from the dp grad psum
        w1 = self.param(
            "expert_w1", expert_init(h, cfg.ffn_hidden_size)
        ).astype(cfg.dtype)
        w2 = self.param(
            "expert_w2", expert_init(cfg.ffn_hidden_size, h)
        ).astype(cfg.dtype)
        if cfg.sequence_parallel:
            # under SP each tp rank routes a different S/tp token shard, so
            # router/expert grads are tp-partial (sum over tp = true grad)
            for name in ("router", "expert_w1", "expert_w2"):
                ps.register_sequence_parallel_param(self.path + (name,))

        # --- dispatch -> experts -> combine ---------------------------
        ex = jnp.einsum(
            "tec,th->ech", dispatch.astype(cfg.dtype), xt.astype(cfg.dtype)
        )  # (E, C, H): this rank's C capacity slots for EVERY expert
        if ep > 1:
            # tiled all_to_all, expert axis split source-rank-major:
            # (E, C, H) -> (E_l, ep*C, H) — each rank receives the slots
            # routed to ITS experts from every expert-group peer (the
            # received axis is source-rank major: peer r's block sits at
            # [r*C, (r+1)*C))
            ex = jax.lax.all_to_all(
                ex, cfg.expert_axis, split_axis=0, concat_axis=1, tiled=True
            )
        hmid = jnp.einsum("ekh,ehf->ekf", ex, w1)
        hmid = jax.nn.gelu(hmid, approximate=True)
        ey = jnp.einsum("ekf,efh->ekh", hmid, w2)  # (E_l, ep*C, H)
        if ep > 1:
            # reverse: split the source-rank-major slot axis, concat on the
            # expert axis in owner-rank order -> (E, C, H) globally
            # expert-ordered, exactly what combine expects
            ey = jax.lax.all_to_all(
                ey, cfg.expert_axis, split_axis=1, concat_axis=0, tiled=True
            )
        y = jnp.einsum(
            "tec,ech->th", combine.astype(cfg.dtype), ey
        )
        return y.reshape(s, b, h).astype(x.dtype), aux
