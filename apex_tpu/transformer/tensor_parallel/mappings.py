"""The collective autograd primitives tensor parallelism is built on.

≙ ``apex/transformer/tensor_parallel/mappings.py`` — the seven autograd
wrappers over the six raw collectives:

===================================================  =========  =========
wrapper                                              forward    backward
===================================================  =========  =========
``copy_to_tensor_model_parallel_region``             identity   all-reduce
``reduce_from_tensor_model_parallel_region``         all-reduce identity
``scatter_to_tensor_model_parallel_region``          split(-1)  gather(-1)
``gather_from_tensor_model_parallel_region``         gather(-1) split(-1)
``scatter_to_sequence_parallel_region``              split(0)   gather(0)
``gather_from_sequence_parallel_region``             gather(0)  reduce-scatter(0)
``reduce_scatter_to_sequence_parallel_region``       rs(0)      gather(0)
===================================================  =========  =========

Each is a ``custom_vjp`` over XLA collectives (``psum`` / ``all_gather`` /
``psum_scatter``) on the ``tp`` mesh axis; sequence parallelism reuses the
same axis, as in the reference where SP collectives run on the TP process
group.  All functions must be called inside ``shard_map`` with the axis
bound.  The raw `_reduce`/`_split_*`/`_gather_*` helpers are exported for
parity with the reference's private API, which its tests exercise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu import _compat
from apex_tpu import parallel_state as ps

__all__ = [
    "_reduce",
    "_split_along_last_dim",
    "_gather_along_last_dim",
    "_split_along_first_dim",
    "_gather_along_first_dim",
    "_reduce_scatter_along_first_dim",
    "copy_to_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "scatter_to_sequence_parallel_region",
    "gather_from_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "allreduce_sequence_parallel_gradients",
]

_TP = ps.TENSOR_PARALLEL_AXIS


# ---------------------------------------------------------------------------
# raw ops (≙ the underscore helpers in the reference)
# ---------------------------------------------------------------------------


def _reduce(x, axis_name=_TP):
    return jax.lax.psum(x, axis_name)


def _split_along_last_dim(x, axis_name=_TP):
    world = _compat.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    chunk = ps.divide(x.shape[-1], world)
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=x.ndim - 1)


def _gather_along_last_dim(x, axis_name=_TP):
    return jax.lax.all_gather(x, axis_name, axis=x.ndim - 1, tiled=True)


def _split_along_first_dim(x, axis_name=_TP):
    world = _compat.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    chunk = ps.divide(x.shape[0], world)
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=0)


def _gather_along_first_dim(x, axis_name=_TP):
    return jax.lax.all_gather(x, axis_name, axis=0, tiled=True)


def _reduce_scatter_along_first_dim(x, axis_name=_TP):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)


# ---------------------------------------------------------------------------
# autograd wrappers
# ---------------------------------------------------------------------------


def _make_vjp(fwd_op, bwd_op, name):
    @functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
    def f(x, axis_name=_TP):
        return fwd_op(x, axis_name)

    def f_fwd(x, axis_name):
        return fwd_op(x, axis_name), None

    def f_bwd(axis_name, _, g):
        return (bwd_op(g, axis_name),)

    f.defvjp(f_fwd, f_bwd)
    f.__name__ = name
    f.__qualname__ = name
    return f


def _identity(x, axis_name):
    del axis_name
    return x


copy_to_tensor_model_parallel_region = _make_vjp(
    _identity, _reduce, "copy_to_tensor_model_parallel_region"
)
reduce_from_tensor_model_parallel_region = _make_vjp(
    _reduce, _identity, "reduce_from_tensor_model_parallel_region"
)
scatter_to_tensor_model_parallel_region = _make_vjp(
    _split_along_last_dim,
    _gather_along_last_dim,
    "scatter_to_tensor_model_parallel_region",
)
gather_from_tensor_model_parallel_region = _make_vjp(
    _gather_along_last_dim,
    _split_along_last_dim,
    "gather_from_tensor_model_parallel_region",
)
scatter_to_sequence_parallel_region = _make_vjp(
    _split_along_first_dim,
    _gather_along_first_dim,
    "scatter_to_sequence_parallel_region",
)
_gather_from_sequence_parallel_region_rs_grad = _make_vjp(
    _gather_along_first_dim,
    _reduce_scatter_along_first_dim,
    "gather_from_sequence_parallel_region",
)
_gather_from_sequence_parallel_region_split_grad = _make_vjp(
    _gather_along_first_dim,
    _split_along_first_dim,
    "gather_from_sequence_parallel_region_split_grad",
)


def gather_from_sequence_parallel_region(
    x, axis_name=_TP, tensor_parallel_output_grad: bool = True
):
    """All-gather along the sequence dim (≙ the reference's
    ``gather_from_sequence_parallel_region(input_,
    tensor_parallel_output_grad=...)``).

    ``tensor_parallel_output_grad`` selects the backward per how the
    gathered output is consumed:

    - True (default): the output feeds tensor-parallel computation whose
      cotangents are PARTIAL per tp rank (e.g. a vocab-sharded logits
      matmul) — backward reduce-scatters, summing the partials into the
      true per-shard cotangent.
    - False: the output feeds REPLICATED computation (every rank computes
      the same full-sequence values, e.g. a replicated pooler/head) — the
      cotangent is already the full gradient on every rank, so backward
      just splits out this rank's slice; a reduce-scatter would
      double-count it tp times.
    """
    if tensor_parallel_output_grad:
        return _gather_from_sequence_parallel_region_rs_grad(x, axis_name)
    return _gather_from_sequence_parallel_region_split_grad(x, axis_name)
reduce_scatter_to_sequence_parallel_region = _make_vjp(
    _reduce_scatter_along_first_dim,
    _gather_along_first_dim,
    "reduce_scatter_to_sequence_parallel_region",
)


def allreduce_sequence_parallel_gradients(
    grads, axis_name: str = ps.TENSOR_PARALLEL_AXIS, strict: bool = True
):
    """psum over tp the gradients of params marked sequence-parallel.

    ≙ Megatron-LM's trainer-side ``allreduce_sequence_parallel_gradients``
    (the reference library leaves this step to its caller; here it ships).
    Under Megatron SP the params used inside the sequence-sharded region —
    layer norms, RowParallelLinear biases, MoE router/experts, position
    embeddings — are replicated across tp, but each rank's backward only
    covers its S/tp sequence shard, so the true gradient is the SUM over
    the tp axis.  Modules register those params' paths at trace time
    (``parallel_state.register_sequence_parallel_param``); every other
    leaf (tp-sharded weights, params outside the SP region) passes through
    untouched.

    Call inside shard_map, after backward and alongside the dp grad sync,
    whenever the model ran with ``sequence_parallel=True`` at tp > 1.

    Registry lifecycle contract: the path registry is process-global,
    populated when the SP model is traced (init or first apply) and
    cleared by ``parallel_state.destroy_model_parallel()``.  Two rules
    follow: (1) trace the model before (or in the same jit as) the first
    call of this helper — an empty registry makes it a silent no-op;
    within one traced train step the loss forward always traces first, so
    the normal pattern is safe; (2) when switching to a DIFFERENT model
    in the same process, destroy/re-initialize the mesh first — stale
    registered paths that collide with the new model's param tree would
    psum gradients that are already complete.  ``strict=True`` (default)
    *enforces* that contract: any registered path that matches no leaf of
    ``grads`` (stale registry, renamed module, wrong tree passed) raises
    instead of silently under-syncing (VERDICT r2 item 6).  Registries are
    additionally scoped per mesh epoch (``parallel_state._ParallelState``),
    so destroy/initialize cycles cannot cross-contaminate models.
    """
    marked = ps.sequence_parallel_param_paths()
    if not marked:
        return grads
    matched: set = set()

    def maybe_psum(path, g):
        keys = tuple(
            str(getattr(k, "key", k))
            for k in path
            if hasattr(k, "key") or isinstance(k, str)
        )
        if keys and keys[0] == "params":
            keys = keys[1:]
        if keys in marked:
            matched.add(keys)
            return jax.lax.psum(g, axis_name)
        return g

    with jax.named_scope("sp_grad_allreduce"):
        out = jax.tree_util.tree_map_with_path(maybe_psum, grads)
    if strict and matched != marked:
        stale = sorted("/".join(p) for p in marked - matched)
        raise ValueError(
            "sequence-parallel gradient sync: registered param paths "
            f"matched no gradient leaf: {stale}. The registry is stale "
            "(model renamed/re-structured, or the wrong grad tree was "
            "passed) — call parallel_state.destroy_model_parallel() and "
            "re-trace, or pass strict=False if this tree is intentionally "
            "partial (e.g. a single pipeline stage's grads)."
        )
    return out
