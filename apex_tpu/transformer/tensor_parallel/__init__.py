"""Tensor/sequence parallelism — ≙ apex/transformer/tensor_parallel."""

from apex_tpu.transformer.tensor_parallel.cross_entropy import (  # noqa: F401
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.data import broadcast_data  # noqa: F401
from apex_tpu.transformer.tensor_parallel.layers import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    sharded_init,
)
from apex_tpu.transformer.tensor_parallel.mappings import (  # noqa: F401
    _gather_along_first_dim,
    _gather_along_last_dim,
    _reduce,
    _reduce_scatter_along_first_dim,
    _split_along_first_dim,
    _split_along_last_dim,
    allreduce_sequence_parallel_gradients,
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.random import (  # noqa: F401
    TPURNGStatesTracker,
    checkpoint,
    get_cuda_rng_tracker,
    get_tpu_rng_tracker,
    model_parallel_cuda_manual_seed,
    model_parallel_tpu_manual_seed,
    to_per_rank_key,
)
from apex_tpu.transformer.tensor_parallel.utils import (  # noqa: F401
    VocabUtility,
    divide,
    split_tensor_along_last_dim,
)
