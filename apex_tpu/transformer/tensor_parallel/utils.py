"""Shard math — ≙ apex/transformer/tensor_parallel/utils.py +
apex/transformer/utils.py :: divide, split_tensor_along_last_dim,
VocabUtility."""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

from apex_tpu.parallel_state import divide  # noqa: F401  (re-export)

__all__ = ["divide", "split_tensor_along_last_dim", "VocabUtility"]


def split_tensor_along_last_dim(tensor, num_partitions: int):
    """≙ split_tensor_along_last_dim (contiguity is XLA's concern)."""
    last = tensor.shape[-1]
    chunk = divide(last, num_partitions)
    return tuple(
        tensor[..., i * chunk : (i + 1) * chunk] for i in range(num_partitions)
    )


class VocabUtility:
    """≙ VocabUtility: vocab range arithmetic for row-sharded embeddings."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
        per_partition_vocab_size: int, rank, world_size: int
    ) -> Tuple[int, int]:
        first = rank * per_partition_vocab_size
        return first, first + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(
        global_vocab_size: int, rank, world_size: int
    ) -> Tuple[int, int]:
        per = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per, rank, world_size
        )
