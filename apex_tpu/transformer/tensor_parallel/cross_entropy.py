"""Vocab-parallel cross entropy — no logits gather.

≙ ``apex/transformer/tensor_parallel/cross_entropy.py`` ::
``_VocabParallelCrossEntropy`` / ``vocab_parallel_cross_entropy``: the
softmax-CE over a vocab-sharded logits tensor using two scalar-per-row
collectives (max, sum-exp) plus a masked gather of the target logit —
never materializing the full vocab on one device.

Shapes: ``vocab_parallel_logits`` is ``(..., V/tp)`` (this rank's vocab
slice), ``target`` is ``(...)`` int ids in ``[0, V)``.  Loss is f32 of
shape ``(...)``; the backward rebuilds ``softmax - onehot`` locally.
``label_smoothing`` matches the reference's (smoothing spread uniformly
over the full vocab).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu import parallel_state as ps
from apex_tpu.transformer.tensor_parallel.utils import VocabUtility

__all__ = ["vocab_parallel_cross_entropy"]

_TP = ps.TENSOR_PARALLEL_AXIS


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def vocab_parallel_cross_entropy(
    vocab_parallel_logits, target, label_smoothing: float = 0.0,
    axis_name: str = _TP,
):
    loss, _ = _fwd(vocab_parallel_logits, target, label_smoothing, axis_name)
    return loss


def _world(axis_name):
    """Axis size, degrading to 1 when the axis is unbound (unsharded use —
    same fallback-and-registry-check contract as layers._tp_world)."""
    from apex_tpu.transformer.tensor_parallel.layers import _tp_world

    return _tp_world(axis_name)


def _psum(x, axis_name, world):
    return jax.lax.psum(x, axis_name) if world > 1 else x


def _pmax(x, axis_name, world):
    return jax.lax.pmax(x, axis_name) if world > 1 else x


def _partition_range(local_v, axis_name, world):
    if world == 1:
        return 0, local_v
    rank = jax.lax.axis_index(axis_name)
    return VocabUtility.vocab_range_from_per_partition_vocab_size(
        local_v, rank, world
    )


def _fwd(logits, target, smoothing, axis_name):
    world = _world(axis_name)
    lf = logits.astype(jnp.float32)
    local_v = lf.shape[-1]
    # global max over the tp group (numerical stability)
    lmax = _pmax(jnp.max(lf, axis=-1), axis_name, world)
    lf = lf - lmax[..., None]
    exp = jnp.exp(lf)
    sum_exp = _psum(jnp.sum(exp, axis=-1), axis_name, world)

    start, end = _partition_range(local_v, axis_name, world)
    in_range = (target >= start) & (target < end)
    local_idx = jnp.clip(target - start, 0, local_v - 1)
    pred = jnp.take_along_axis(lf, local_idx[..., None], axis=-1)[..., 0]
    pred = _psum(jnp.where(in_range, pred, 0.0), axis_name, world)

    log_z = jnp.log(sum_exp)
    loss = log_z - pred
    if smoothing > 0.0:
        vocab = local_v * world
        mean_logit = _psum(jnp.sum(lf, axis=-1), axis_name, world) / vocab
        # loss = (1-s)*nll + s * mean over vocab of (log_z - logit_j)
        loss = (1.0 - smoothing) * loss + smoothing * (log_z - mean_logit)
    residuals = (exp, sum_exp, in_range, local_idx)
    return loss, residuals


def _bwd(smoothing, axis_name, res, g):
    exp, sum_exp, in_range, local_idx = res
    local_v = exp.shape[-1]
    softmax = exp / sum_exp[..., None]
    onehot = jax.nn.one_hot(local_idx, local_v, dtype=jnp.float32)
    onehot = onehot * in_range[..., None]
    if smoothing > 0.0:
        vocab = local_v * _world(axis_name)
        target_dist = (1.0 - smoothing) * onehot + smoothing / vocab
    else:
        target_dist = onehot
    grad = (softmax - target_dist) * g[..., None]
    return grad, None


def _fwd_vjp(logits, target, smoothing, axis_name):
    loss, res = _fwd(logits, target, smoothing, axis_name)
    # zero-size dtype token (dtype objects are not valid residual leaves)
    return loss, (res, jnp.zeros((0,), logits.dtype))


def _bwd_vjp(smoothing, axis_name, carry, g):
    res, dtype_token = carry
    grad, _ = _bwd(smoothing, axis_name, res, g)
    return grad.astype(dtype_token.dtype), None


vocab_parallel_cross_entropy.defvjp(_fwd_vjp, _bwd_vjp)
