"""Tensor-parallel sharded layers.

≙ ``apex/transformer/tensor_parallel/layers.py`` ::
``VocabParallelEmbedding``, ``ColumnParallelLinear``, ``RowParallelLinear``
(+ ``LinearWithGradAccumulationAndAsyncCommunication``,
``set_tensor_model_parallel_attributes``, ``_initialize_affine_weight_*``).

Flax modules meant to run inside ``shard_map`` over the global mesh with
the ``tp`` axis bound.  Conventions and deltas from the reference:

- weights use the JAX layout ``(in_features, out_features)`` (the reference
  stores torch's ``(out, in)``);
- **reproducible-across-tp init**: like the reference's
  ``_initialize_affine_weight_cpu``, each shard is cut out of a
  *full-shape* initialization with the same key, so a checkpoint trained
  at tp=2 matches tp=4 initialization statistics exactly;
- ``gradient_accumulation_fusion`` (wgrad GEMM accumulating into an fp32
  main_grad — ``fused_weight_gradient_mlp_cuda``) is structural here:
  keep ``param_dtype=float32`` with bf16 ``dtype`` and the weight
  cotangent is produced directly in f32 by the backward matmul — no
  separate fused kernel exists or is needed.  The flag is accepted for
  API parity and validated, but changes nothing;
- ``no_async_tensor_model_parallel_allreduce`` — XLA overlaps the input-grad
  collective with the wgrad GEMM on its own (the hand-rolled async overlap
  in ``LinearWithGradAccumulationAndAsyncCommunication``); accepted, no-op.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu import _compat
from apex_tpu import parallel_state as ps
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.utils import VocabUtility, divide

__all__ = [
    "VocabParallelEmbedding",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "sharded_init",
]

_TP = ps.TENSOR_PARALLEL_AXIS


def _tp_world(axis_name: str) -> int:
    try:
        return _compat.axis_size(axis_name)
    except (NameError, KeyError):
        # Axis not bound.  Legitimate when running unsharded (no mesh, or
        # tp==1 outside shard_map); an error when the registry says the
        # model *is* tensor-parallel — then a typo'd/unbound axis would
        # silently compute dense math with full-shape params.
        if (
            ps.model_parallel_is_initialized()
            and axis_name == _TP
            and ps.get_tensor_model_parallel_world_size() > 1
        ):
            raise RuntimeError(
                f"tensor-parallel axis {axis_name!r} is not bound but the "
                f"mesh registry has tensor_model_parallel_size="
                f"{ps.get_tensor_model_parallel_world_size()}; run this "
                "layer inside jax.shard_map over the global mesh"
            )
        return 1


def sharded_init(
    base_init: Callable, full_shape: Tuple[int, ...], shard_axis: int,
    axis_name: str = _TP,
):
    """Initializer that cuts this rank's shard from a full-shape init.

    ≙ _initialize_affine_weight_cpu: "initialize the master weight, then
    split" — guarantees init statistics independent of the tp degree.
    """

    def init(key, shape, dtype=jnp.float32):
        world = _tp_world(axis_name)
        if world == 1:
            return base_init(key, full_shape, dtype)
        full = base_init(key, full_shape, dtype)
        rank = jax.lax.axis_index(axis_name)
        size = full_shape[shard_axis] // world
        if shape[shard_axis] != size:
            raise ValueError(
                f"local shard shape {shape} inconsistent with full shape "
                f"{full_shape} split {world}-way along axis {shard_axis}"
            )
        return jax.lax.dynamic_slice_in_dim(
            full, rank * size, size, axis=shard_axis
        )

    return init


class VocabParallelEmbedding(nn.Module):
    """Row-sharded (vocab-dim) embedding — ≙ VocabParallelEmbedding.

    Lookup masks out-of-range token ids, zeroes their rows, and all-reduces
    over tp (or reduce-scatters along the sequence dim when
    ``sequence_parallel_enabled`` — seq-first layout ``(s, ...)`` required
    then, as in Megatron).
    """

    num_embeddings: int
    embedding_dim: int
    init_method: Callable = nn.initializers.normal(stddev=0.02)
    sequence_parallel_enabled: bool = False
    param_dtype: Any = jnp.float32
    dtype: Optional[Any] = None
    axis_name: str = _TP

    @nn.compact
    def __call__(self, ids):
        world = _tp_world(self.axis_name)
        per = divide(self.num_embeddings, world)
        weight = self.param(
            "weight",
            sharded_init(
                self.init_method,
                (self.num_embeddings, self.embedding_dim),
                0,
                self.axis_name,
            ),
            (per, self.embedding_dim),
            self.param_dtype,
        )
        if world == 1:
            out = jnp.take(weight, ids, axis=0)
        else:
            rank = jax.lax.axis_index(self.axis_name)
            start, end = VocabUtility.vocab_range_from_per_partition_vocab_size(
                per, rank, world
            )
            in_range = (ids >= start) & (ids < end)
            local_ids = jnp.clip(ids - start, 0, per - 1)
            out = jnp.take(weight, local_ids, axis=0)
            out = jnp.where(in_range[..., None], out, 0.0)
            if self.sequence_parallel_enabled:
                out = reduce_scatter_to_sequence_parallel_region(
                    out, self.axis_name
                )
            else:
                out = reduce_from_tensor_model_parallel_region(
                    out, self.axis_name
                )
        if self.dtype is not None:
            out = out.astype(self.dtype)
        return out


class ColumnParallelLinear(nn.Module):
    """Y = XW + b with W column-sharded (output dim) — ≙ ColumnParallelLinear.

    fwd: SP ⇒ all-gather input along seq; else identity-with-psum-backward.
    ``gather_output`` reassembles the full output (all-gather over tp).
    ``skip_bias_add`` returns (output, bias) for downstream fusion.
    """

    input_size: int
    output_size: int
    use_bias: bool = True
    gather_output: bool = False
    sequence_parallel_enabled: bool = False
    skip_bias_add: bool = False
    init_method: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros
    gradient_accumulation_fusion: bool = False  # structural no-op (see module doc)
    no_async_tensor_model_parallel_allreduce: bool = False  # no-op
    param_dtype: Any = jnp.float32
    dtype: Optional[Any] = None
    axis_name: str = _TP

    @nn.compact
    def __call__(self, x):
        if self.gather_output and self.sequence_parallel_enabled:
            raise ValueError(
                "gather_output and sequence_parallel_enabled are mutually "
                "exclusive (reference asserts the same)"
            )
        world = _tp_world(self.axis_name)
        out_per = divide(self.output_size, world)
        weight = self.param(
            "weight",
            sharded_init(
                self.init_method,
                (self.input_size, self.output_size),
                1,
                self.axis_name,
            ),
            (self.input_size, out_per),
            self.param_dtype,
        )
        bias = (
            self.param("bias", self.bias_init, (out_per,), self.param_dtype)
            if self.use_bias
            else None
        )
        if world > 1:
            if self.sequence_parallel_enabled:
                x = gather_from_sequence_parallel_region(x, self.axis_name)
            else:
                x = copy_to_tensor_model_parallel_region(x, self.axis_name)
        cdt = self.dtype or x.dtype
        y = jnp.matmul(
            x.astype(cdt), weight.astype(cdt),
            preferred_element_type=jnp.float32,
        ).astype(cdt)
        if bias is not None and not self.skip_bias_add:
            y = y + bias.astype(cdt)
        if self.gather_output and world > 1:
            y = gather_from_tensor_model_parallel_region(y, self.axis_name)
        if self.skip_bias_add:
            return y, (bias.astype(cdt) if bias is not None else None)
        return y


class RowParallelLinear(nn.Module):
    """Y = XW + b with W row-sharded (input dim) — ≙ RowParallelLinear.

    fwd: local GEMM then all-reduce (or reduce-scatter along seq under SP).
    ``input_is_parallel``: input already carries this rank's shard of the
    last dim (the usual case after a ColumnParallelLinear).

    SP + ``skip_bias_add`` contract: the bias is registered for the
    sequence-parallel gradient psum, so the caller MUST apply the returned
    bias inside the sequence-sharded region (the Megatron
    bias-dropout-add pattern).  Applying it after a gather back to full
    sequence would double-count its gradient tp-fold.
    """

    input_size: int
    output_size: int
    use_bias: bool = True
    input_is_parallel: bool = False
    sequence_parallel_enabled: bool = False
    skip_bias_add: bool = False
    init_method: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros
    gradient_accumulation_fusion: bool = False  # structural no-op
    param_dtype: Any = jnp.float32
    dtype: Optional[Any] = None
    axis_name: str = _TP

    @nn.compact
    def __call__(self, x):
        if self.sequence_parallel_enabled and not self.input_is_parallel:
            raise ValueError(
                "sequence_parallel_enabled requires input_is_parallel "
                "(reference asserts the same)"
            )
        world = _tp_world(self.axis_name)
        in_per = divide(self.input_size, world)
        weight = self.param(
            "weight",
            sharded_init(
                self.init_method,
                (self.input_size, self.output_size),
                0,
                self.axis_name,
            ),
            (in_per, self.output_size),
            self.param_dtype,
        )
        bias = (
            self.param(
                "bias", self.bias_init, (self.output_size,), self.param_dtype
            )
            if self.use_bias
            else None
        )
        if bias is not None and self.sequence_parallel_enabled:
            # bias is added AFTER the reduce-scatter, i.e. inside the SP
            # region: tp-replicated param, per-rank S/tp-partial gradient.
            # This registration covers skip_bias_add=True as well, which
            # CONTRACTS the caller to apply the returned bias inside the
            # SP region (the Megatron bias-dropout-add convention; the
            # mirrored reference marks param.sequence_parallel there too).
            # Adding it outside the SP region (e.g. after a gather) would
            # make the psum overcount that grad tp-fold — see the class
            # docstring.
            ps.register_sequence_parallel_param(self.path + ("bias",))
        if world > 1 and not self.input_is_parallel:
            x = scatter_to_tensor_model_parallel_region(x, self.axis_name)
        cdt = self.dtype or x.dtype
        y = jnp.matmul(
            x.astype(cdt), weight.astype(cdt),
            preferred_element_type=jnp.float32,
        ).astype(cdt)
        if world > 1:
            if self.sequence_parallel_enabled:
                y = reduce_scatter_to_sequence_parallel_region(y, self.axis_name)
            else:
                y = reduce_from_tensor_model_parallel_region(y, self.axis_name)
        if bias is not None and not self.skip_bias_add:
            y = y + bias.astype(cdt)
        if self.skip_bias_add:
            return y, (bias.astype(cdt) if bias is not None else None)
        return y
