"""Batch broadcast across the tensor-parallel group.

≙ ``apex/transformer/tensor_parallel/data.py`` :: ``broadcast_data``,
``_build_key_size_numel_dictionaries``.

The reference moves the batch from tp-rank-0 to the whole group over NCCL
(each rank runs its own dataloader only on rank 0).  Under SPMD every host
feeds the same program and arrays are laid out by sharding — a broadcast
*within* the tp group is the identity (the tp axis never shards the batch).
The function therefore validates dtypes/shapes exactly like the reference
(catching the same class of bugs: ranks disagreeing about the batch
schema) and returns the data unchanged.
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax.numpy as jnp

__all__ = ["broadcast_data"]


def _check(keys: Sequence[str], data: Dict, target_dtype) -> None:
    for k in keys:
        if k not in data:
            raise KeyError(f"broadcast_data: key {k!r} missing from data")
        if data[k].dtype != target_dtype:
            raise TypeError(
                f"broadcast_data: data[{k!r}] has dtype {data[k].dtype}, "
                f"expected {target_dtype}"
            )


def broadcast_data(keys: Sequence[str], data: Dict, datatype) -> Dict:
    """≙ broadcast_data(keys, data, datatype) — validate and pass through."""
    _check(keys, data, datatype)
    return {k: data[k] for k in keys}
