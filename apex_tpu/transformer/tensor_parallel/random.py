"""Per-mode PRNG tracking + activation checkpointing.

≙ ``apex/transformer/tensor_parallel/random.py`` ::
``CudaRNGStatesTracker`` / ``get_cuda_rng_tracker`` /
``model_parallel_cuda_manual_seed`` / ``checkpoint`` / ``CheckpointFunction``.

The reference maintains a registry of CUDA RNG states (one default, one
"model-parallel" offset by the tp rank) and swaps them around regions so
that dropout inside tp-sharded layers differs per rank while replicated
regions agree; its ``checkpoint`` stashes and replays those states around
recompute.  In JAX randomness is explicit, so the tracker reduces to *key
derivation* — ``fold_in`` of the tp rank — and RNG-correct recompute is
automatic under ``jax.checkpoint`` (same keys ⇒ same dropout masks in the
replay; no state capture needed).

Seed layout follows the reference's ``model_parallel_cuda_manual_seed``:
default state = ``seed``, tensor-model-parallel state = ``seed + 2718 +
tp_rank``.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from apex_tpu import parallel_state as ps

__all__ = [
    "TPURNGStatesTracker",
    "get_tpu_rng_tracker",
    "get_cuda_rng_tracker",  # parity alias
    "model_parallel_tpu_manual_seed",
    "model_parallel_cuda_manual_seed",  # parity alias
    "checkpoint",
    "_MODEL_PARALLEL_RNG_TRACKER_NAME",
]

_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"
_DEFAULT_RNG_TRACKER_NAME = "default-rng"
_MODEL_PARALLEL_SEED_OFFSET = 2718  # the reference's magic offset


class TPURNGStatesTracker:
    """≙ CudaRNGStatesTracker — a named registry of PRNG keys.

    ``add(name, seed)`` registers a key; ``fork(name)`` returns a fresh
    subkey for that stream (advancing it), the functional analog of the
    reference's context manager that swaps the device RNG state.
    """

    def __init__(self):
        self._keys: Dict[str, jax.Array] = {}

    def reset(self):
        self._keys.clear()

    def get_states(self):
        return dict(self._keys)

    def set_states(self, states):
        self._keys = dict(states)

    def add(self, name: str, seed) -> None:
        if name in self._keys:
            raise RuntimeError(f"RNG state {name} already exists")
        self._keys[name] = jax.random.PRNGKey(seed)

    def fork(self, name: str = _MODEL_PARALLEL_RNG_TRACKER_NAME):
        """Return a fresh subkey for the named stream (advances the stream)."""
        if name not in self._keys:
            raise RuntimeError(f"RNG state {name} is not added")
        self._keys[name], sub = jax.random.split(self._keys[name])
        return sub


_TRACKER = TPURNGStatesTracker()


def get_tpu_rng_tracker() -> TPURNGStatesTracker:
    return _TRACKER


get_cuda_rng_tracker = get_tpu_rng_tracker  # parity alias


def model_parallel_tpu_manual_seed(seed: int, tp_rank: Optional[int] = None):
    """≙ model_parallel_cuda_manual_seed.

    Registers the default stream at ``seed`` and the model-parallel stream
    at ``seed + 2718 + tp_rank``.  Under SPMD the tp rank is usually folded
    in *inside* the program: pass ``tp_rank=None`` and derive per-rank keys
    with :func:`to_per_rank_key` at use sites, or pass an explicit rank for
    host-driven setups.
    """
    tracker = get_tpu_rng_tracker()
    tracker.reset()
    tracker.add(_DEFAULT_RNG_TRACKER_NAME, seed)
    offset = seed + _MODEL_PARALLEL_SEED_OFFSET
    if tp_rank is None and ps.model_parallel_is_initialized():
        if ps.get_tensor_model_parallel_world_size() > 1:
            import warnings

            warnings.warn(
                "model_parallel seed registered without a tp_rank while "
                "tensor_model_parallel_size > 1: forked keys will be "
                "IDENTICAL across tp ranks (unlike the reference's per-rank "
                "offset). Fold the rank in at use sites with "
                "to_per_rank_key(tracker.fork()), or pass tp_rank explicitly.",
                RuntimeWarning,
                stacklevel=2,
            )
    tracker.add(
        _MODEL_PARALLEL_RNG_TRACKER_NAME,
        offset + (tp_rank if tp_rank is not None else 0),
    )
    return tracker


model_parallel_cuda_manual_seed = model_parallel_tpu_manual_seed  # alias


def to_per_rank_key(key, axis_name: str = ps.TENSOR_PARALLEL_AXIS):
    """Fold the tp rank into a key (inside shard_map): the SPMD-native way
    to make dropout differ across tensor-parallel ranks."""
    return jax.random.fold_in(key, jax.lax.axis_index(axis_name))


def checkpoint(function, *args, **kwargs):
    """Activation checkpointing with RNG-correct recompute.

    ≙ tensor_parallel.random.checkpoint / CheckpointFunction.  Maps to
    ``jax.checkpoint`` (rematerialization): forward activations inside are
    discarded and recomputed in the backward; explicit PRNG keys make the
    replayed dropout identical, which is the property the reference's RNG
    stash/restore machinery exists to provide.

    ``distribute_saved_activations`` (reference: shard the stashed input
    along sequence over tp) has no direct analog — under remat nothing is
    stashed.  It is accepted both as the reference's *second positional*
    argument (``checkpoint(fn, False, *tensors)``) and as a keyword, so
    positionally-ported Megatron call sites keep working.

    Caveat of that compatibility heuristic: a *leading Python-bool
    argument of the checkpointed function itself* is indistinguishable
    from the flag and will be stripped.  If your function genuinely takes
    a leading bool, close over it (``checkpoint(partial(fn, True), x)``)
    or call ``jax.checkpoint`` directly.
    """
    kwargs.pop("distribute_saved_activations", None)
    if args and isinstance(args[0], bool):
        args = args[1:]
    return jax.checkpoint(function)(*args, **kwargs)
