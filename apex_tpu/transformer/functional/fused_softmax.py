"""FusedScaleMaskSoftmax — the attention-softmax front door.

≙ ``apex/transformer/functional/fused_softmax.py`` ::
``FusedScaleMaskSoftmax`` (dispatching to the
``scaled_upper_triang_masked_softmax`` / ``scaled_masked_softmax`` /
``scaled_softmax`` kernels with ``is_kernel_available`` heuristics).

The CUDA kernels carried hard limits (fp16/bf16 only, seq ≤ 2048,
divisibility constraints) that ``is_kernel_available`` guarded; the TPU
ops have none, so the "kernel" path is always available and the flag
surface (``scaled_masked_softmax_fusion``, ``softmax_in_fp32``) keeps its
reference meaning: ``input_in_fp16/bf16`` + ``softmax_in_fp32`` controls
whether the softmax itself runs in f32 (ours always computes the reduction
in f32; the flag controls the *output* dtype).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.scaled_softmax import (
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.transformer.enums import AttnMaskType

__all__ = ["FusedScaleMaskSoftmax"]


class FusedScaleMaskSoftmax:
    """Callable config object, matching the reference module's signature."""

    def __init__(
        self,
        input_in_fp16: bool = False,
        input_in_bf16: bool = False,
        attn_mask_type: AttnMaskType = AttnMaskType.padding,
        scaled_masked_softmax_fusion: bool = True,
        mask_func: Optional[Callable] = None,
        softmax_in_fp32: bool = True,
        scale: Optional[float] = None,
    ):
        if input_in_fp16 and input_in_bf16:
            raise RuntimeError("both fp16 and bf16 flags cannot be active")
        self.input_in_float16 = input_in_fp16 or input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale
        if self.scale is not None and not softmax_in_fp32:
            raise RuntimeError("softmax should be in fp32 when scaled")

    def is_kernel_available(self, mask, b, np_, sq, sk) -> bool:
        """≙ the reference heuristic; TPU ops have no shape limits."""
        return self.fusion

    def __call__(self, x, mask=None):
        scale = self.scale if self.scale is not None else 1.0
        if self.mask_func is not None:
            # ≙ the reference's unfused fallback: scale, apply the user's
            # mask function (e.g. additive bias), then a plain softmax.
            xs = x.astype(jnp.float32) * scale
            xs = self.mask_func(xs, mask) if mask is not None else xs
            y = jax.nn.softmax(xs, axis=-1).astype(x.dtype)
        elif self.attn_mask_type == AttnMaskType.causal:
            *lead, sq, sk = x.shape
            y = scaled_upper_triang_masked_softmax(
                x.reshape(-1, sq, sk), scale
            ).reshape(*lead, sq, sk)
        elif mask is not None:
            y = scaled_masked_softmax(x, mask, scale)
        else:
            y = scaled_softmax(x, scale)
        # Every dispatch path above already computes the reduction in f32 and
        # returns the input dtype, which is exactly the reference's
        # softmax_in_fp32 + cast-back behavior; the flag is honored by
        # construction rather than by a separate cast here.
        return y
