"""≙ apex/transformer/functional — fused softmax + fused RoPE wrappers."""

from apex_tpu.ops.rope import (  # noqa: F401
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_cached,
)
from apex_tpu.transformer.functional.fused_softmax import (  # noqa: F401
    FusedScaleMaskSoftmax,
)
