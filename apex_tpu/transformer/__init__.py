"""Model-parallel layer — ≙ apex/transformer.

- :mod:`apex_tpu.transformer.tensor_parallel` — TP/SP sharded layers,
  collective mappings, vocab-parallel CE, RNG tracking, remat checkpoint;
- :mod:`apex_tpu.transformer.pipeline_parallel` — 1F1B / interleaved
  schedules, p2p exchange, microbatch calculator;
- :mod:`apex_tpu.transformer.functional` — FusedScaleMaskSoftmax, RoPE;
- :mod:`apex_tpu.transformer.amp` — model-parallel-aware GradScaler;
- ``parallel_state`` is re-exported from the package root (the mesh
  registry replaces process-group bookkeeping).
"""

from apex_tpu import parallel_state  # noqa: F401
from apex_tpu.transformer import tensor_parallel  # noqa: F401
from apex_tpu.transformer.enums import (  # noqa: F401
    AttnMaskType,
    AttnType,
    LayerType,
    ModelType,
)
from apex_tpu.transformer.log_util import (  # noqa: F401
    get_transformer_logger,
    set_logging_level,
)

_LAZY = (
    "pipeline_parallel",
    "functional",
    "amp",
    "layers",
    "testing",
    "moe",
    "context_parallel",
)


def __getattr__(name):
    if name in _LAZY:
        import importlib

        try:
            module = importlib.import_module(f"apex_tpu.transformer.{name}")
        except ModuleNotFoundError as e:
            raise AttributeError(
                f"module 'apex_tpu.transformer' has no attribute {name!r}"
            ) from e
        globals()[name] = module
        return module
    raise AttributeError(
        f"module 'apex_tpu.transformer' has no attribute {name!r}"
    )
