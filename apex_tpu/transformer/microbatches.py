"""Microbatch calculators — ≙ apex/transformer/microbatches.py ::
``ConstantNumMicroBatches``, ``RampupBatchsizeNumMicroBatches``,
``build_num_microbatches_calculator``."""

from __future__ import annotations

from typing import Optional

__all__ = [
    "ConstantNumMicroBatches",
    "RampupBatchsizeNumMicroBatches",
    "build_num_microbatches_calculator",
]


class ConstantNumMicroBatches:
    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        micro_times_dp = micro_batch_size * data_parallel_size
        if global_batch_size % micro_times_dp != 0:
            raise ValueError(
                f"global batch size ({global_batch_size}) is not divisible by"
                f" micro batch size ({micro_batch_size}) times data parallel"
                f" size ({data_parallel_size})"
            )
        self.num_micro_batches = global_batch_size // micro_times_dp
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check=True):
        pass


class RampupBatchsizeNumMicroBatches:
    """Linear batch-size ramp: start → global over ramp_samples."""

    def __init__(
        self,
        start_batch_size: int,
        batch_size_increment: int,
        ramup_samples: int,
        global_batch_size: int,
        micro_batch_size: int,
        data_parallel_size: int,
    ):
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size
        )
        if batch_size_increment <= 0:
            raise ValueError("batch_size_increment must be positive")
        diff = global_batch_size - start_batch_size
        if diff < 0 or diff % batch_size_increment != 0:
            raise ValueError(
                "global batch size must be start batch size plus an integer "
                "number of increments"
            )
        num_increments = diff // batch_size_increment
        self.rampup_samples_per_increment = (
            ramup_samples / num_increments if num_increments > 0 else 0
        )
        self.update(0)

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check: bool = True):
        if (
            self.rampup_samples_per_increment == 0
            or consumed_samples > self.ramup_samples
        ):
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = min(
                self.start_batch_size + steps * self.batch_size_increment,
                self.global_batch_size,
            )
        if consistency_check and (
            self.current_global_batch_size
            % self.micro_batch_times_data_parallel_size
            != 0
        ):
            raise ValueError(
                f"current global batch size "
                f"({self.current_global_batch_size}) is not divisible by "
                "micro-batch-size * data-parallel-size"
            )
        self.num_micro_batches = (
            self.current_global_batch_size
            // self.micro_batch_times_data_parallel_size
        )


def build_num_microbatches_calculator(
    rank: int,
    rampup_batch_size: Optional[list],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
):
    """≙ the reference factory (rampup_batch_size = [start, incr, samples])."""
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size
        )
    if len(rampup_batch_size) != 3:
        raise ValueError(
            "rampup_batch_size must be [start_batch_size, increment, samples]"
        )
    return RampupBatchsizeNumMicroBatches(
        int(rampup_batch_size[0]),
        int(rampup_batch_size[1]),
        int(rampup_batch_size[2]),
        global_batch_size,
        micro_batch_size,
        data_parallel_size,
    )
