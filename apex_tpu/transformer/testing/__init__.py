"""In-package distributed test harness — ≙ ``apex/transformer/testing``.

The reference ships ``DistributedTestBase`` (spawns one NCCL process per
GPU), ``commons`` (seeds, separators, toy layers) and standalone
GPT/BERT fixtures for its pipeline tests.  The TPU analog is strictly
simpler: a virtual CPU mesh replaces process spawning (§4 of SURVEY.md),
and the standalone models are thin toy configs over
:mod:`apex_tpu.models`.
"""

from apex_tpu.transformer.testing.commons import (  # noqa: F401
    IdentityLayer,
    cpu_mesh,
    initialize_distributed,
    print_separator,
    set_random_seed,
)
from apex_tpu.transformer.testing.standalone_bert import (  # noqa: F401
    bert_model_provider,
)
from apex_tpu.transformer.testing.standalone_gpt import (  # noqa: F401
    gpt_model_provider,
)
