"""≙ ``apex/transformer/testing/standalone_bert.py`` — the minimal BERT
fixture the reference's pipeline tests build (``bert_model_provider``).

The real model lives in :mod:`apex_tpu.models.bert`; this provider pins a
toy configuration with deterministic shapes, sized so every parallel mode
(tp ≤ 8, pp ≤ 4, sp) divides evenly on the 8-device CPU mesh.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.models.bert import BertConfig, BertForPreTraining

__all__ = ["bert_model_provider", "TEST_CONFIG"]

TEST_CONFIG = dict(
    vocab_size=128,
    hidden_size=64,
    num_layers=4,
    num_heads=8,
    intermediate_size=128,
    max_position_embeddings=64,
    dtype=jnp.float32,
)


def bert_model_provider(
    sequence_parallel: bool = False, remat: bool = False, **overrides
) -> BertForPreTraining:
    cfg = BertConfig(
        sequence_parallel=sequence_parallel, remat=remat,
        **{**TEST_CONFIG, **overrides},
    )
    return BertForPreTraining(cfg)
