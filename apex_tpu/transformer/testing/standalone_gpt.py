"""≙ ``apex/transformer/testing/standalone_gpt.py`` — the minimal GPT
fixture (``gpt_model_provider``) over :mod:`apex_tpu.models.gpt`."""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.models.gpt import GptConfig, GptModel

__all__ = ["gpt_model_provider", "TEST_CONFIG"]

TEST_CONFIG = dict(
    vocab_size=128,
    hidden_size=64,
    num_layers=4,
    num_heads=8,
    intermediate_size=128,
    max_seq_len=64,
    dtype=jnp.float32,
)


def gpt_model_provider(
    sequence_parallel: bool = False, remat: bool = False, **overrides
) -> GptModel:
    cfg = GptConfig(
        sequence_parallel=sequence_parallel, remat=remat,
        **{**TEST_CONFIG, **overrides},
    )
    return GptModel(cfg)
