"""≙ ``apex/transformer/testing/commons.py`` (``set_random_seed``,
``print_separator``, ``initialize_distributed``, ``IdentityLayer``) and the
world-size machinery of ``distributed_test_base.py``.

Where ``DistributedTestBase`` spawns one NCCL process per GPU and skips
below 2 GPUs, :func:`cpu_mesh` gives any world size on one host — set
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` before the first
backend use (tests/conftest.py does) and every DP/TP/PP/SP/CP test runs
in CI with no hardware.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import flax.linen as nn
import jax
import numpy as np

from apex_tpu import parallel_state as ps

__all__ = [
    "set_random_seed",
    "print_separator",
    "initialize_distributed",
    "cpu_mesh",
    "IdentityLayer",
]


def set_random_seed(seed: int):
    """≙ commons.set_random_seed — returns the JAX key (keys are values,
    not global state; numpy's global RNG is seeded for host-side data)."""
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def print_separator(message: str):
    """≙ commons.print_separator."""
    print(f"\n{'-' * 31}\n{message:^31}\n{'-' * 31}", flush=True)


def initialize_distributed(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    context_parallel_size: int = 1,
    **kwargs,
):
    """≙ commons.initialize_distributed — on TPU there is no process-group
    bootstrap; this just (re)builds the global mesh and returns it.

    Distinct from :func:`apex_tpu.parallel.initialize_distributed` (the
    multi-host runtime join, which returns rank info) — same reference-
    parity name, different job; this one is a test fixture."""
    if ps.model_parallel_is_initialized():
        ps.destroy_model_parallel()
    return ps.initialize_model_parallel(
        tensor_model_parallel_size=tensor_model_parallel_size,
        pipeline_model_parallel_size=pipeline_model_parallel_size,
        context_parallel_size=context_parallel_size,
        **kwargs,
    )


@contextlib.contextmanager
def cpu_mesh(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    context_parallel_size: int = 1,
    n_devices: Optional[int] = None,
):
    """Context manager: build a mesh (over the first ``n_devices``
    devices), yield it, destroy on exit.  The standalone analog of the
    conftest fixtures, usable from scripts."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    mesh = initialize_distributed(
        tensor_model_parallel_size,
        pipeline_model_parallel_size,
        context_parallel_size,
        devices=devices,
    )
    try:
        yield mesh
    finally:
        ps.destroy_model_parallel()


class IdentityLayer(nn.Module):
    """≙ commons.IdentityLayer — a learnable tensor wrapped as a module
    (used by the reference's mapping/grad tests)."""

    shape: tuple
    scale: float = 1.0

    @nn.compact
    def __call__(self):
        w = self.param(
            "weight",
            lambda key, shape: self.scale * jax.random.normal(key, shape),
            self.shape,
        )
        return w
