"""Pipeline parallelism — ≙ apex/transformer/pipeline_parallel."""

from apex_tpu.transformer.pipeline_parallel import (  # noqa: F401
    p2p_communication,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: F401
    forward_backward_no_pipelining,
    forward_backward_pipelining_1f1b,
    forward_backward_pipelining_interleaved_1f1b,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
)
from apex_tpu.transformer.pipeline_parallel.utils import (  # noqa: F401
    get_current_global_batch_size,
    get_kth_microbatch,
    get_num_microbatches,
    listify_model,
    setup_microbatch_calculator,
    split_batch_into_microbatches,
    update_num_microbatches,
)
