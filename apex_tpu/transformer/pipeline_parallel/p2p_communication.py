"""Stage-to-stage exchange — ≙ apex/transformer/pipeline_parallel/
p2p_communication.py.

The reference builds ``torch.distributed.P2POp`` lists and
``batch_isend_irecv`` with a shape handshake (``_communicate`` /
``_communicate_shapes``).  On TPU there is no point-to-point primitive —
stage exchange is ``jax.lax.ppermute`` along the ``pp`` mesh axis inside
``shard_map``: every (sender → receiver) pair moves simultaneously over ICI,
and a rank with no inbound edge receives **zeros** (ppermute's semantics),
which replaces the reference's "first stage receives None".

Semantic shift to be aware of: these are *collectives* — every pp rank
calls the same function and gets its neighbor's value — so the reference's
send/recv pairs collapse: ``recv_forward(x)`` ≡ ``send_forward(x)`` ≡ "the
value this rank receives from the previous stage given that every rank
sends ``x``".  The shape handshake is unnecessary: shapes are static under
jit.

All functions take/return activation pytrees.
"""

from __future__ import annotations

from typing import Any

import jax

from apex_tpu import _compat
from apex_tpu import parallel_state as ps

__all__ = [
    "send_forward",
    "recv_forward",
    "send_backward",
    "recv_backward",
    "send_forward_recv_backward",
    "send_backward_recv_forward",
    "send_forward_recv_forward",
    "send_backward_recv_backward",
]

_PP = ps.PIPELINE_PARALLEL_AXIS


def _shift(tree: Any, delta: int, axis_name: str, cyclic: bool = False):
    n = _compat.axis_size(axis_name)
    if cyclic:
        perm = [(i, (i + delta) % n) for i in range(n)]
    else:
        perm = [
            (i, i + delta) for i in range(n) if 0 <= i + delta < n
        ]
    with jax.named_scope("pp_p2p_shift"):
        return jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), tree
        )


def send_forward_recv_forward(x, axis_name: str = _PP, cyclic: bool = False):
    """Every rank sends ``x`` to the next stage; returns what this rank
    receives from the previous (zeros at stage 0 unless ``cyclic``)."""
    return _shift(x, +1, axis_name, cyclic)


def send_backward_recv_backward(g, axis_name: str = _PP, cyclic: bool = False):
    """Every rank sends ``g`` to the previous stage; returns what this rank
    receives from the next (zeros at the last stage unless ``cyclic``)."""
    return _shift(g, -1, axis_name, cyclic)


# Reference-shaped aliases (see module docstring on the collective collapse).
send_forward = send_forward_recv_forward
recv_forward = send_forward_recv_forward
send_backward = send_backward_recv_backward
recv_backward = send_backward_recv_backward


def send_forward_recv_backward(output, grad, axis_name: str = _PP):
    """1F1B steady-state edge: push activations down, pull grads up.

    Returns ``(recv_activation, recv_grad)`` — two independent ppermutes
    that XLA schedules concurrently (≙ the batched isend/irecv pair)."""
    return (
        send_forward_recv_forward(output, axis_name),
        send_backward_recv_backward(grad, axis_name),
    )


def send_backward_recv_forward(grad, output, axis_name: str = _PP):
    """Mirror of :func:`send_forward_recv_backward`."""
    return (
        send_backward_recv_backward(grad, axis_name),
        send_forward_recv_forward(output, axis_name),
    )
