"""Microbatch bookkeeping — ≙ apex/transformer/pipeline_parallel/utils.py ::
``setup_microbatch_calculator``, ``get_num_microbatches``,
``get_current_global_batch_size``, ``update_num_microbatches``,
``_reconfigure_microbatch_calculator``, ``listify_model``,
``get_kth_microbatch``."""

from __future__ import annotations

from typing import Any, List, Optional

import jax

from apex_tpu.transformer.microbatches import build_num_microbatches_calculator

__all__ = [
    "setup_microbatch_calculator",
    "get_num_microbatches",
    "get_current_global_batch_size",
    "update_num_microbatches",
    "listify_model",
    "get_kth_microbatch",
    "split_batch_into_microbatches",
]

_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def setup_microbatch_calculator(
    rank: int,
    rampup_batch_size: Optional[list],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is not None:
        raise RuntimeError("num microbatches calculator is already initialized")
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size,
    )


def _reconfigure_microbatch_calculator(
    rank: int,
    rampup_batch_size: Optional[list],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size,
    )


def destroy_microbatch_calculator() -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def _calc():
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is None:
        raise RuntimeError(
            "microbatch calculator is not initialized — call "
            "setup_microbatch_calculator first"
        )
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR


def get_num_microbatches() -> int:
    return _calc().get()


def get_current_global_batch_size() -> int:
    return _calc().get_current_global_batch_size()


def update_num_microbatches(consumed_samples, consistency_check=True) -> None:
    _calc().update(consumed_samples, consistency_check)


def listify_model(model: Any) -> List[Any]:
    """≙ listify_model (interleaved schedules carry a list of chunks)."""
    if isinstance(model, list):
        return model
    return [model]


def get_kth_microbatch(batch, k: int):
    """≙ get_kth_microbatch: slice microbatch k from stacked (nm, ...) leaves."""
    return jax.tree_util.tree_map(lambda x: x[k], batch)


def split_batch_into_microbatches(batch, num_microbatches: int):
    """Reshape (global, ...) leaves into (num_microbatches, mb, ...)."""

    def f(x):
        if x.shape[0] % num_microbatches != 0:
            raise ValueError(
                f"batch dim {x.shape[0]} not divisible by num_microbatches "
                f"{num_microbatches}"
            )
        return x.reshape(num_microbatches, x.shape[0] // num_microbatches,
                         *x.shape[1:])

    return jax.tree_util.tree_map(f, batch)
