"""Pipeline fwd/bwd schedules — ≙ apex/transformer/pipeline_parallel/
schedules/ (``forward_backward_no_pipelining``,
``forward_backward_pipelining_without_interleaving`` [1F1B],
``_forward_backward_pipelining_with_interleaving`` [virtual stages],
dispatcher ``get_forward_backward_func``).

Design (TPU-native, not a translation).  The reference hand-schedules
warmup/steady/cooldown phases of explicit forward and backward calls with
NCCL p2p edges per microbatch.  Under XLA the whole pipeline is **one
traced program**: activations advance one stage per tick through
``jax.lax.ppermute`` along the ``pp`` axis (lockstep), the tick loop is a
``lax.scan``, and the backward schedule *falls out of ``jax.grad``* —
XLA reverses the scan and the ppermutes, yielding the cooldown-mirrored
grad flow without hand-scheduling.  Memory behavior equivalent to 1F1B's
bounded live-activation window comes from rematerialization: each tick's
stage compute is wrapped in ``jax.checkpoint`` (``remat=True``), so the
backward recomputes per-tick activations instead of keeping all
``nm + pp - 1`` of them live.

Uniform-stage contract (SPMD): every pp rank runs the same
``stage_fn(stage_params, x) -> y`` with activation-shaped ``x`` and ``y``
(first-stage embedding / last-stage head live inside ``stage_fn`` gated on
:func:`parallel_state.get_pipeline_model_parallel_rank`, or outside the
pipeline).  ``loss_fn(y, target) -> scalar`` is evaluated on the last
stage; it must return finite values for arbitrary finite activations (it
is traced on every stage and masked).  With ``loss_takes_params=True``
the signature becomes ``loss_fn(stage_params, y, target)`` — ≙ Megatron's
post-process rank computing the loss THROUGH the output layer: the head
(e.g. a tied unembedding) lives in the uniform per-rank param tree and
receives gradients via the loss; see ``examples/gpt/train_gpt_pp.py``.

All schedules share one signature and return ``(losses, grads)`` where
``losses`` is the per-microbatch loss vector (psum-shared across pp) and
``grads`` matches ``params`` (``None`` when ``forward_only``).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu import _compat
from apex_tpu import parallel_state as ps
from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p

__all__ = [
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_1f1b",
    "forward_backward_pipelining_with_interleaving",
    "forward_backward_pipelining_interleaved_1f1b",
    "get_forward_backward_func",
]

_PP = ps.PIPELINE_PARALLEL_AXIS

# checkpoint_name tags the "sums" named-saves policy selects.  Defined in
# infra (models import it — apex_tpu.models.{bert,gpt} tag these in their
# layers) so the model layer depends on the schedule layer, never the
# reverse.  A stage whose model carries none of these tags saves nothing
# under "sums" (= "full" behavior, same values).
SUMS_SAVE_NAMES = (
    "bert_qkv", "bert_fc1", "bert_sum_attn", "bert_sum_mlp",
    "gpt_qkv", "gpt_fc1", "gpt_sum_attn", "gpt_sum_mlp",
)


def resolve_remat_policy(name):
    """The ONE full/dots/sums -> jax.checkpoint policy resolution, shared
    by the models (BertConfig/GptConfig remat_policy) and the pipeline
    schedules' per-tick wrap.  ``None``/"full" -> recompute everything
    (policy None); "dots" -> save no-batch-dim matmul outputs; "sums" ->
    save only the :data:`SUMS_SAVE_NAMES` tags."""
    if name in (None, "full"):
        return None
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if name == "sums":
        return jax.checkpoint_policies.save_only_these_names(
            *SUMS_SAVE_NAMES
        )
    raise ValueError(f"unknown remat_policy {name!r}")


def _wrap_remat(fn, remat, remat_policy=None):
    """Per-tick stage checkpoint.  ``remat_policy``: None = recompute
    everything (min memory); "dots" = save no-batch-dim matmul outputs
    and recompute only elementwise/attention internals (the models'
    selective-recompute default — ~4/3 → ~1.0 of the fwd+bwd premium
    for a modest memory bump); "sums" = save only the checkpoint_name
    tags the BERT layers mark (qkv/fc1/residual sums — epilogue-fusion
    friendly, see BertConfig.remat_policy).  A stage whose model carries
    no tags saves nothing under "sums" (= "full" behavior, same values)."""
    if not remat:
        return fn
    if remat_policy == "dots":
        # the schedules' historical "dots" is checkpoint_dots (saves all
        # matmul outputs), intentionally broader than the models'
        # no-batch-dim variant — per-tick stages see one microbatch
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.checkpoint_dots
        )
    policy = resolve_remat_policy(remat_policy)
    if policy is None:
        return jax.checkpoint(fn)
    return jax.checkpoint(fn, policy=policy)


# ---------------------------------------------------------------------------
# no pipelining: sequential microbatches with grad accumulation
# ---------------------------------------------------------------------------


def forward_backward_no_pipelining(
    stage_fn: Callable,
    loss_fn: Callable,
    params,
    batch: Tuple[Any, Any],
    *,
    num_microbatches: int,
    axis_name: str = _PP,
    forward_only: bool = False,
    remat: bool = False,
    remat_policy=None,
    loss_takes_params: bool = False,
):
    """≙ fwd_bwd_no_pipelining.py — scan microbatches, accumulate grads."""
    inputs, targets = batch
    run = _wrap_remat(stage_fn, remat, remat_policy)
    lfn = loss_fn if loss_takes_params else (lambda p, y, t: loss_fn(y, t))

    def mean_loss(params):
        def body(carry, mb):
            x, t = mb
            loss = lfn(params, run(params, x), t)
            return carry + loss, loss

        total, losses = jax.lax.scan(
            body, jnp.zeros((), jnp.float32), (inputs, targets)
        )
        return total / num_microbatches, losses

    if forward_only:
        _, losses = mean_loss(params)
        return losses, None
    (_, losses), grads = jax.value_and_grad(mean_loss, has_aux=True)(params)
    return losses, grads


# ---------------------------------------------------------------------------
# 1F1B (non-interleaved): lockstep tick loop over the pp axis
# ---------------------------------------------------------------------------


def forward_backward_pipelining_without_interleaving(
    stage_fn: Callable,
    loss_fn: Callable,
    params,
    batch: Tuple[Any, Any],
    *,
    num_microbatches: int,
    axis_name: str = _PP,
    forward_only: bool = False,
    remat: bool = True,
    remat_policy=None,
    carry_chunk: Optional[int] = None,
    loss_takes_params: bool = False,
):
    """≙ fwd_bwd_pipelining_without_interleaving.py (1F1B).

    ``params`` are *this rank's stage* params (call inside shard_map with
    e.g. a ``P('pp', ...)``-sharded stacked tree).  ``batch = (inputs,
    targets)`` with leaves stacked ``(num_microbatches, ...)``; ``inputs``
    must be activation-shaped (consumed by stage 0).

    ``carry_chunk=K`` bounds the backward's saved scan carries for large
    grad-accumulation ``nm`` (docs/pipeline-schedules.md's measured O(nm)
    slope): the tick loop becomes a two-level scan whose outer body is
    ``jax.checkpoint``-ed, so only the ~ticks/K chunk-boundary carries are
    saved and each chunk's K inner carries are recomputed during backward
    — O(ticks/K + K) live carries (minimal at K ≈ √ticks) for one extra
    forward recompute per tick.  Ticks are padded up to a K multiple;
    padded ticks compute masked garbage exactly like bubble ticks.
    """
    inputs, targets = batch
    nm = num_microbatches
    run = _wrap_remat(stage_fn, remat, remat_policy)
    lfn = loss_fn if loss_takes_params else (lambda p, y, t: loss_fn(y, t))

    def pipeline_loss(params):
        pp = _compat.axis_size(axis_name)
        stage = jax.lax.axis_index(axis_name)
        is_first = stage == 0
        is_last = stage == pp - 1
        ticks = nm + pp - 1
        h0 = jax.tree_util.tree_map(lambda x: jnp.zeros_like(x[0]), inputs)

        def tick(carry, t):
            h_recv, losses = carry
            mb_idx = jnp.clip(t, 0, nm - 1)
            inject = jax.tree_util.tree_map(lambda x: x[mb_idx], inputs)
            x_in = jax.tree_util.tree_map(
                lambda a, b: jnp.where(is_first, a, b), inject, h_recv
            )
            y = run(params, x_in)
            out_idx = t - (pp - 1)
            valid = (out_idx >= 0) & (out_idx < nm) & is_last
            tgt = jax.tree_util.tree_map(
                lambda x: x[jnp.clip(out_idx, 0, nm - 1)], targets
            )
            loss = lfn(params, y, tgt)
            losses = losses.at[jnp.clip(out_idx, 0, nm - 1)].add(
                jnp.where(valid, loss, 0.0)
            )
            h_next = p2p.send_forward_recv_forward(y, axis_name)
            return (h_next, losses), None

        carry0 = (h0, jnp.zeros((nm,), jnp.float32))
        if carry_chunk and carry_chunk > 0:
            k = min(carry_chunk, ticks)
            n_outer = -(-ticks // k)  # ceil; padded ticks are masked no-ops
            ts = jnp.arange(n_outer * k).reshape(n_outer, k)

            @jax.checkpoint
            def outer(carry, ts_chunk):
                carry, _ = jax.lax.scan(tick, carry, ts_chunk)
                return carry, None

            (_, losses), _ = jax.lax.scan(outer, carry0, ts)
        else:
            (_, losses), _ = jax.lax.scan(tick, carry0, jnp.arange(ticks))
        # Differentiate the LOCAL loss sum (nonzero only on the last stage):
        # grads reach earlier stages through the reversed ppermutes.  Do NOT
        # psum the differentiated scalar — under check_vma=False the psum
        # transpose cannot prove the cotangent replicated and would re-psum,
        # inflating grads by pp.  The shared per-microbatch losses are
        # returned via aux (not differentiated), psum'd for reporting.
        return jnp.sum(losses) / nm, jax.lax.psum(losses, axis_name)

    if forward_only:
        _, losses = pipeline_loss(params)
        return losses, None
    (_, losses), grads = jax.value_and_grad(pipeline_loss, has_aux=True)(
        params
    )
    return losses, grads


# ---------------------------------------------------------------------------
# hand-scheduled 1F1B: explicit O(pp) stash ring, manually reversed permutes
# ---------------------------------------------------------------------------


def forward_backward_pipelining_1f1b(
    stage_fn: Callable,
    loss_fn: Callable,
    params,
    batch: Tuple[Any, Any],
    *,
    num_microbatches: int,
    axis_name: str = _PP,
    forward_only: bool = False,
    stash: str = "residuals",
    remat: bool = False,
    remat_policy=None,
    loss_takes_params: bool = False,
):
    """True 1F1B with a bounded activation window and NO dependence on
    ``jax.grad`` over the tick loop — ≙ the reference's
    ``forward_backward_pipelining_without_interleaving`` memory/compute
    point (SURVEY §3.5: ≤pp in-flight activations, no recompute).

    Where :func:`forward_backward_pipelining_without_interleaving`
    differentiates a lockstep scan (backward falls out of autodiff, at
    the price of either per-tick rematerialization or O(nm) saved scan
    carries), this schedule computes gradients INSIDE a single forward
    scan: each tick runs one stage forward AND one stage backward on
    different microbatches, per-microbatch vjp residuals live in an
    explicit ring buffer, and cotangents ride a manually reversed
    ``ppermute`` (``send_backward_recv_backward``).  Nothing about the
    loop is differentiated, so nm-proportional autodiff memory never
    exists.

    Timetable (lockstep SPMD — every rank runs the same program; bubble
    slots compute masked garbage): stage ``s`` forwards microbatch ``m``
    at tick ``m + s`` and backwards it at tick ``2(pp-1) - s + m``;
    total ticks ``nm + 2(pp-1)`` (vs ``nm + pp - 1`` per direction for
    the lockstep scan — the steady state overlaps one fwd with one bwd
    per tick exactly like the reference's 1F1B).  The in-flight window
    on stage ``s`` is ``2(pp-1-s) + 1 <= 2pp - 1``: the lockstep
    round-trip bound (the reference's asynchronous ranks reach ``pp - s``
    by backpressure instead of clock; both are O(pp), independent of nm).

    ``stash`` selects what the ring holds:

    * ``"residuals"`` (default) — the stage vjp's residuals, so backward
      replays NOTHING: the no-recompute-premium point.  Residual leaves
      that are parameter passthroughs (detected by tracer identity) are
      NOT ring-stashed — they are loop-invariant and read from a single
      copy, so ring memory is ~W x the stage's activation-derived
      residuals only.  Combine with ``remat_policy`` to bound residual
      size (policy-saved tensors + stage input become the residuals).
    * ``"input"`` — the ring holds only each microbatch's stage input;
      backward re-runs the stage forward under ``jax.vjp`` (the ~4/3
      recompute premium, minimal O(pp x |activation|) ring — strictly
      less memory than ``carry_chunk``'s O(sqrt(nm)) carries at equal
      compute).

    Same contract as the other schedules: call inside ``shard_map``,
    ``batch`` leaves stacked ``(num_microbatches, ...)``, returns
    ``(losses, grads)`` with ``losses`` psum-shared across pp.
    """
    if stash not in ("residuals", "input"):
        raise ValueError(f"unknown stash mode {stash!r}")
    inputs, targets = batch
    nm = num_microbatches
    run = _wrap_remat(stage_fn, remat, remat_policy)
    lfn = loss_fn if loss_takes_params else (lambda p, y, t: loss_fn(y, t))

    if forward_only:
        losses, _ = forward_backward_pipelining_without_interleaving(
            stage_fn, loss_fn, params, batch, num_microbatches=nm,
            axis_name=axis_name, forward_only=True, remat=False,
            loss_takes_params=loss_takes_params,
        )
        return losses, None

    pp = _compat.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    is_first = stage == 0
    is_last = stage == pp - 1
    ticks = nm + 2 * (pp - 1)
    window = 2 * (pp - 1) + 1
    tree = jax.tree_util

    h0 = tree.tree_map(lambda x: jnp.zeros_like(x[0]), inputs)

    def stage_vjp(p, x):
        return jax.vjp(lambda p_, x_: run(p_, x_), p, x)

    # Template vjp (traced once, outside the loop): fixes the residual
    # pytree structure, and partitions its leaves into parameter
    # passthroughs (loop-invariant — kept as a single closed-over copy)
    # vs activation-derived residuals (ring-stashed per in-flight mb).
    y_t, vjp_t = stage_vjp(params, h0)
    t_leaves, t_def = tree.tree_flatten(vjp_t)
    param_ids = {id(l) for l in tree.tree_leaves(params)}
    varying = [
        i for i, l in enumerate(t_leaves) if id(l) not in param_ids
    ]

    if stash == "residuals":
        ring0 = [
            jnp.zeros((window,) + t_leaves[i].shape, t_leaves[i].dtype)
            for i in varying
        ]
    else:
        ring0 = [
            jnp.zeros((window,) + l.shape, l.dtype)
            for l in tree.tree_leaves(h0)
        ]
    x_def = tree.tree_structure(h0)
    g0 = tree.tree_map(jnp.zeros_like, y_t)
    dp0 = tree.tree_map(jnp.zeros_like, params)

    def tick(carry, t):
        h_recv, g_recv, ring, dp_acc, losses = carry

        # ---- forward lane: stage s forwards microbatch t - s ----------
        mf = t - stage
        mf_c = jnp.clip(mf, 0, nm - 1)
        inject = tree.tree_map(lambda x: x[mf_c], inputs)
        x_in = tree.tree_map(
            lambda a, b: jnp.where(is_first, a, b), inject, h_recv
        )
        y, vjp_f = stage_vjp(params, x_in)
        # NOTE: the per-tick vjp treedef is NOT == t_def (each trace
        # wraps a fresh closure in the Partial's static part), but the
        # residual LEAVES line up one-to-one with the template's — that
        # is what the ring relies on, so pin it structurally.
        f_leaves, f_def = tree.tree_flatten(vjp_f)
        _check_vjp_leaf_shapes(
            f_leaves, [(l.shape, l.dtype) for l in t_leaves], "hand-1F1B"
        )
        # Explicit raise, not assert (same rationale as the helper):
        # guards a tracer-identity invariant a future JAX change could
        # break silently.
        if [
            i for i, l in enumerate(f_leaves) if id(l) not in param_ids
        ] != varying:
            raise RuntimeError(
                "hand-1F1B ring invariant violated: param-passthrough "
                "residual positions changed across ticks"
            )
        slot_f = t % window
        if stash == "residuals":
            ring = [
                r.at[slot_f].set(f_leaves[i])
                for r, i in zip(ring, varying)
            ]
        else:
            ring = [
                r.at[slot_f].set(l)
                for r, l in zip(ring, tree.tree_leaves(x_in))
            ]

        # ---- loss lane (last stage; same tick as its forward) ---------
        tgt = tree.tree_map(lambda x: x[mf_c], targets)
        (loss, (dhead, dy)) = _loss_and_head_grads(
            lfn, params, y, tgt, loss_takes_params
        )
        f_valid = (mf >= 0) & (mf < nm) & is_last
        losses = losses.at[mf_c].add(jnp.where(f_valid, loss, 0.0))
        wt = jnp.where(f_valid, 1.0 / nm, 0.0)
        # dy may be non-finite on bubble ticks (loss vjp over the garbage
        # chain) — safe, because every consumer SELECTS with where()
        # (is_last/b_valid below).  dhead is ACCUMULATED, so it needs a
        # select, not the wt multiply: NaN * 0 = NaN would poison dp_acc.
        dy = tree.tree_map(lambda g: g * wt, dy)
        if dhead is not None:
            dp_acc = tree.tree_map(
                lambda a, d: a + jnp.where(
                    f_valid, d * (1.0 / nm), jnp.zeros_like(d)
                ),
                dp_acc, dhead,
            )

        # ---- backward lane: stage s backwards mb t - 2(pp-1) + s ------
        mb = t - 2 * (pp - 1) + stage
        b_valid = (mb >= 0) & (mb < nm)
        mb_c = jnp.clip(mb, 0, nm - 1)
        slot_b = (mb_c + stage) % window  # = that mb's fwd tick mod W
        if stash == "residuals":
            # invariant (param-passthrough) positions reuse this tick's
            # own leaves — identical values every tick, never stashed
            leaves_b = list(f_leaves)
            for r, i in zip(ring, varying):
                leaves_b[i] = r[slot_b]
            vjp_b = tree.tree_unflatten(f_def, leaves_b)
        else:
            x_b = tree.tree_unflatten(x_def, [r[slot_b] for r in ring])
            _, vjp_b = stage_vjp(params, x_b)
        g_in = tree.tree_map(
            lambda a, b: jnp.where(is_last, a, b), dy, g_recv
        )
        g_in = tree.tree_map(
            lambda g: jnp.where(b_valid, g, jnp.zeros_like(g)), g_in
        )
        dp, dx = vjp_b(g_in)
        # A zero cotangent is NOT enough to null a bubble tick: a
        # never-written (zero) ring slot can make the vjp divide by a
        # stored statistic (0 * inf = NaN), so mask the OUTPUTS too.
        dp = tree.tree_map(
            lambda d: jnp.where(b_valid, d, jnp.zeros_like(d)), dp
        )
        dx = tree.tree_map(
            lambda d: jnp.where(b_valid, d, jnp.zeros_like(d)), dx
        )
        dp_acc = tree.tree_map(jnp.add, dp_acc, dp)

        # ---- edges: activations down, cotangents up -------------------
        h_next = p2p.send_forward_recv_forward(y, axis_name)
        g_next = p2p.send_backward_recv_backward(dx, axis_name)
        return (h_next, g_next, ring, dp_acc, losses), None

    carry0 = (h0, g0, ring0, dp0, jnp.zeros((nm,), jnp.float32))
    (_, _, _, grads, losses), _ = jax.lax.scan(
        tick, carry0, jnp.arange(ticks)
    )
    return jax.lax.psum(losses, axis_name), grads


def _check_vjp_leaf_shapes(f_leaves, expected_shapes, schedule_name):
    """Trace-time guard shared by the hand schedules' stash rings: the
    per-tick vjp's residual leaves must line up one-to-one with the
    template's.  Explicit raise (not assert) so it survives ``python
    -O``; free at execution time."""
    if [(l.shape, l.dtype) for l in f_leaves] != expected_shapes:
        raise RuntimeError(
            f"{schedule_name} ring invariant violated: vjp residual "
            "structure changed across ticks"
        )


def _loss_and_head_grads(lfn, params, y, tgt, loss_takes_params):
    """Loss value + its cotangents wrt (params-if-taken, y), unscaled."""
    if loss_takes_params:
        loss, dvjp = jax.vjp(lambda p, y_: lfn(p, y_, tgt), params, y)
        dhead, dy = dvjp(jnp.ones((), loss.dtype))
        return loss, (dhead, dy)
    loss, dvjp = jax.vjp(lambda y_: lfn(params, y_, tgt), y)
    (dy,) = dvjp(jnp.ones((), loss.dtype))
    return loss, (None, dy)


# ---------------------------------------------------------------------------
# hand-scheduled interleaved 1F1B: chunk-granular stash ring, three phases
# ---------------------------------------------------------------------------


def forward_backward_pipelining_interleaved_1f1b(
    stage_fn: Callable,
    loss_fn: Callable,
    params,
    batch: Tuple[Any, Any],
    *,
    num_microbatches: int,
    num_model_chunks: Optional[int] = None,
    axis_name: str = _PP,
    forward_only: bool = False,
    stash: str = "residuals",
    remat: bool = False,
    remat_policy=None,
    loss_takes_params: bool = False,
):
    """True interleaved (virtual-stage) 1F1B with an explicit chunk-stash
    ring and NO autodiff over the tick loop — ≙ the reference's
    ``_forward_backward_pipelining_with_interleaving`` memory/compute
    point (SURVEY §2.3, §3.5): bubble **(pp−1)/vpp** per direction with
    no recompute premium, in-flight stashes bounded independent of
    ``num_microbatches``.

    This extends :func:`forward_backward_pipelining_1f1b`'s machinery to
    model chunks.  ``params`` hold this rank's ``num_model_chunks`` stage
    chunks stacked on a leading axis (rank ``r`` owns virtual stages
    ``r, r+pp, …``, exactly like the lockstep interleaved schedule).  A
    tick is **chunk-granular** (1/vpp of a stage) and the program runs
    three lockstep phases so warmup/cooldown ticks never pay for a
    masked opposite-direction lane:

    * warmup — ``V−1`` fwd-only ticks (``V = pp·vpp``): the virtual pipe
      fills at one virtual stage per tick;
    * steady — ``nm·vpp + pp − V`` fwd+bwd ticks: each tick runs one
      chunk forward AND one chunk backward (on a different microbatch),
      the 1F1B overlap;
    * cooldown — ``V−1`` bwd-only ticks: the cotangent drains.

    Wall = ``(V−1)·t_f/vpp + (nm·vpp+pp−V)·(t_f+t_b)/vpp + (V−1)·t_b/vpp
    = nm·(t_f+t_b) + (pp−1)·(t_f+t_b)/vpp`` — the Megatron interleaving
    bubble exactly, vs ``2(pp−1)·(t_f+t_b)`` for the single-phase plain
    hand schedule (docs/pipeline-schedules.md has the derivation and the
    measured memory frontier).

    Timetable.  Forward: rank ``r`` runs chunk ``c`` of microbatch
    ``m = g·pp + j`` at tick ``t = g·pp·vpp + c·pp + j + r`` (Megatron's
    round-robin order — groups of ``pp`` microbatches per chunk).
    Backward mirrors at one virtual stage per tick:
    ``T_b(m,v) = T_f(m,V−1) + (V−1−v)`` for global virtual stage
    ``v = c·pp + r``, i.e. rank ``r`` backwards ``(c_b, m_b)`` at tick
    ``t`` where ``w = t + r − (V+pp−2)``, ``c_b = vpp−1 − (w mod V)//pp``,
    ``m_b = (w//V)·pp + (w mod pp)``.  Cotangents ride a **cyclic**
    reversed ppermute (rank 0 → pp−1 wraps to the previous chunk), the
    dual of the forward wrap.

    The stash ring has ``W = 2V−1`` chunk-granular slots (max in-flight
    span ``T_b−T_f = 2(V−1−v) ≤ W−1``): forward at tick ``t`` writes slot
    ``t mod W``; backward reads slot ``(t + 2·v_b + 1) mod W``.  Ring
    memory ≈ ``2V × (stage residuals / vpp) = 2pp × stage residuals`` —
    the SAME total as the plain hand schedule, and flat in ``nm``
    (matching Megatron interleaved's O(pp·vpp) in-flight chunk window).

    Chunk-param handling: the per-tick vjp is taken wrt the *sliced*
    chunk params, so residual leaves that are chunk-param passthroughs
    cannot be detected against the stacked tree by tracer identity the
    way the plain schedule does.  Instead the template trace records, for
    each passthrough residual position, WHICH chunk-param leaf flows
    through it; at backward time that position is re-materialized by
    dynamically indexing the backward tick's chunk — so weights are never
    ring-stashed.  Param-derived (non-passthrough) residuals are stashed
    per chunk, which is exactly what correctness requires (they were
    computed from that chunk's weights).

    ``stash``/``remat``/``remat_policy``/``loss_takes_params`` as in
    :func:`forward_backward_pipelining_1f1b`.  Requires
    ``num_microbatches % pp == 0`` (the reference's interleaving
    constraint).
    """
    if stash not in ("residuals", "input"):
        raise ValueError(f"unknown stash mode {stash!r}")
    inputs, targets = batch
    nm = num_microbatches
    if num_model_chunks is None:
        num_model_chunks = ps.get_virtual_pipeline_model_parallel_world_size()
    vpp = num_model_chunks
    if vpp is None or vpp < 1:
        raise ValueError("num_model_chunks (virtual pipeline size) required")
    run = _wrap_remat(stage_fn, remat, remat_policy)
    lfn = loss_fn if loss_takes_params else (lambda p, y, t: loss_fn(y, t))

    if forward_only:
        losses, _ = forward_backward_pipelining_with_interleaving(
            stage_fn, loss_fn, params, batch, num_microbatches=nm,
            num_model_chunks=vpp, axis_name=axis_name, forward_only=True,
            remat=False, loss_takes_params=loss_takes_params,
        )
        return losses, None

    pp = _compat.axis_size(axis_name)
    if nm % pp != 0:
        raise ValueError(
            f"interleaved schedule requires num_microbatches ({nm}) to "
            f"be a multiple of pipeline_parallel_size ({pp})"
        )
    stage = jax.lax.axis_index(axis_name)
    is_first = stage == 0
    is_last = stage == pp - 1
    V = pp * vpp           # virtual pipeline depth == round-robin cycle
    W = 2 * V - 1          # ring slots: max in-flight span + 1
    tree = jax.tree_util

    h0 = tree.tree_map(lambda x: jnp.zeros_like(x[0]), inputs)

    def chunk_at(idx):
        return tree.tree_map(
            lambda x: jax.lax.dynamic_index_in_dim(x, idx, 0, keepdims=False),
            params,
        )

    def stage_vjp(p, x):
        return jax.vjp(lambda p_, x_: run(p_, x_), p, x)

    # Template trace (outside the loop): pins the residual pytree
    # structure and maps each chunk-param passthrough residual position
    # to the chunk-param leaf that flows through it.
    chunk_t = tree.tree_map(lambda x: x[0], params)
    y_t, vjp_t = stage_vjp(chunk_t, h0)
    t_leaves, _ = tree.tree_flatten(vjp_t)
    cp_pos_t = {id(l): i for i, l in enumerate(tree.tree_leaves(chunk_t))}
    passthrough = {
        pos: cp_pos_t[id(l)]
        for pos, l in enumerate(t_leaves)
        if id(l) in cp_pos_t
    }
    varying = [p for p in range(len(t_leaves)) if p not in passthrough]
    t_shapes = [(l.shape, l.dtype) for l in t_leaves]

    def check_residual_contract(f_leaves, cp_leaves):
        _check_vjp_leaf_shapes(f_leaves, t_shapes, "interleaved hand-1F1B")
        # Explicit raise, not assert (same rationale as the helper):
        # guards the tracer-identity mapping the ring substitution
        # relies on.
        cp_pos = {id(l): i for i, l in enumerate(cp_leaves)}
        got = {
            pos: cp_pos[id(l)]
            for pos, l in enumerate(f_leaves)
            if id(l) in cp_pos
        }
        if got != passthrough:
            raise RuntimeError(
                "interleaved hand-1F1B ring invariant violated: "
                "chunk-param passthrough residual positions changed"
            )

    if stash == "residuals":
        ring0 = [
            jnp.zeros((W,) + t_leaves[i].shape, t_leaves[i].dtype)
            for i in varying
        ]
    else:
        ring0 = [
            jnp.zeros((W,) + l.shape, l.dtype)
            for l in tree.tree_leaves(h0)
        ]
    x_def = tree.tree_structure(h0)
    g0 = tree.tree_map(jnp.zeros_like, y_t)
    dp0 = tree.tree_map(jnp.zeros_like, params)

    def scatter_add(acc, d, idx):
        cur = jax.lax.dynamic_index_in_dim(acc, idx, 0, keepdims=False)
        return jax.lax.dynamic_update_index_in_dim(acc, cur + d, idx, 0)

    def make_tick(do_fwd, do_bwd):
        def tick(carry, t):
            h_recv, g_recv, ring, dp_acc, losses = carry
            dy = None
            f_pack = None

            if do_fwd:
                # ---- forward lane: chunk c_f of microbatch m_f ---------
                u = t - stage
                c_f = jnp.clip(jnp.mod(u, V) // pp, 0, vpp - 1)
                m_f = jnp.floor_divide(u, V) * pp + jnp.mod(u, pp)
                active_f = (u >= 0) & (u < nm * vpp)
                m_f_c = jnp.clip(m_f, 0, nm - 1)
                injecting = is_first & (c_f == 0) & active_f
                inject = tree.tree_map(lambda x: x[m_f_c], inputs)
                x_in = tree.tree_map(
                    lambda a, b: jnp.where(injecting, a, b), inject, h_recv
                )
                cp_f = chunk_at(c_f)
                y, vjp_f = stage_vjp(cp_f, x_in)
                f_leaves, f_def = tree.tree_flatten(vjp_f)
                check_residual_contract(f_leaves, tree.tree_leaves(cp_f))
                slot_f = jnp.mod(t, W)
                if stash == "residuals":
                    ring = [
                        r.at[slot_f].set(f_leaves[i])
                        for r, i in zip(ring, varying)
                    ]
                else:
                    ring = [
                        r.at[slot_f].set(l)
                        for r, l in zip(ring, tree.tree_leaves(x_in))
                    ]
                f_pack = (f_leaves, f_def)

                # ---- loss lane: last rank finishing its last chunk -----
                finishing = active_f & is_last & (c_f == vpp - 1)
                tgt = tree.tree_map(lambda x: x[m_f_c], targets)
                loss, (dhead, dy) = _loss_and_head_grads(
                    lfn, cp_f, y, tgt, loss_takes_params
                )
                losses = losses.at[m_f_c].add(
                    jnp.where(finishing, loss, 0.0)
                )
                wt = jnp.where(finishing, 1.0 / nm, 0.0)
                # dy may be non-finite on bubble ticks; every consumer
                # SELECTS with where() (finishing/active_b below).  dhead
                # is accumulated, so it needs a select, not the multiply.
                dy = tree.tree_map(lambda g: g * wt, dy)
                if dhead is not None:
                    dp_acc = tree.tree_map(
                        lambda a, d: scatter_add(
                            a,
                            jnp.where(
                                finishing, d * (1.0 / nm), jnp.zeros_like(d)
                            ),
                            c_f,
                        ),
                        dp_acc, dhead,
                    )
                h_next = p2p.send_forward_recv_forward(
                    y, axis_name, cyclic=True
                )
            else:
                h_next = h_recv

            if do_bwd:
                # ---- backward lane: mirror timetable -------------------
                w = t + stage - (V + pp - 2)
                active_b = (w >= 0) & (w < nm * vpp)
                c_b = jnp.clip(
                    vpp - 1 - jnp.mod(w, V) // pp, 0, vpp - 1
                )
                cp_b = chunk_at(c_b)
                v_b = c_b * pp + stage
                slot_b = jnp.mod(t + 2 * v_b + 1, W)
                if stash == "residuals":
                    if f_pack is not None:
                        leaves_b, f_def = list(f_pack[0]), f_pack[1]
                    else:
                        # cooldown: no forward lane this tick, so trace a
                        # dummy vjp purely for a fresh treedef — every
                        # residual leaf is substituted below, so the dummy
                        # forward is dead code and XLA DCEs it.
                        _, vjp_d = stage_vjp(cp_b, h0)
                        leaves_d, f_def = tree.tree_flatten(vjp_d)
                        check_residual_contract(
                            leaves_d, tree.tree_leaves(cp_b)
                        )
                        leaves_b = list(leaves_d)
                    # chunk-param passthroughs: re-materialize from the
                    # BACKWARD tick's chunk (never ring-stashed)
                    cpb_leaves = tree.tree_leaves(cp_b)
                    for pos, pidx in passthrough.items():
                        leaves_b[pos] = cpb_leaves[pidx]
                    for r, pos in zip(ring, varying):
                        leaves_b[pos] = r[slot_b]
                    vjp_b = tree.tree_unflatten(f_def, leaves_b)
                else:
                    x_b = tree.tree_unflatten(
                        x_def, [r[slot_b] for r in ring]
                    )
                    _, vjp_b = stage_vjp(cp_b, x_b)
                if do_fwd:
                    # rank pp−1 backwarding chunk vpp−1 consumes the dy
                    # its OWN forward lane produced this very tick
                    g_in = tree.tree_map(
                        lambda a, b: jnp.where(
                            is_last & (c_b == vpp - 1), a, b
                        ),
                        dy, g_recv,
                    )
                else:
                    g_in = g_recv
                g_in = tree.tree_map(
                    lambda g: jnp.where(active_b, g, jnp.zeros_like(g)),
                    g_in,
                )
                dp, dx = vjp_b(g_in)
                # Zero cotangent is NOT enough to null a bubble tick (a
                # zero ring slot can make the vjp emit 0*inf=NaN) — mask
                # the OUTPUTS too.
                dp = tree.tree_map(
                    lambda d: jnp.where(active_b, d, jnp.zeros_like(d)),
                    dp,
                )
                dx = tree.tree_map(
                    lambda d: jnp.where(active_b, d, jnp.zeros_like(d)),
                    dx,
                )
                dp_acc = tree.tree_map(
                    lambda a, d: scatter_add(a, d, c_b), dp_acc, dp
                )
                g_next = p2p.send_backward_recv_backward(
                    dx, axis_name, cyclic=True
                )
            else:
                g_next = g_recv

            return (h_next, g_next, ring, dp_acc, losses), None

        return tick

    total = nm * vpp + V + pp - 2
    b1 = V - 1               # warmup end: fwd-only ticks [0, b1)
    b2 = nm * vpp + pp - 1   # steady end: fwd+bwd ticks [b1, b2)
    carry = (h0, g0, ring0, dp0, jnp.zeros((nm,), jnp.float32))
    carry, _ = jax.lax.scan(
        make_tick(True, False), carry, jnp.arange(0, b1)
    )
    carry, _ = jax.lax.scan(
        make_tick(True, True), carry, jnp.arange(b1, b2)
    )
    carry, _ = jax.lax.scan(
        make_tick(False, True), carry, jnp.arange(b2, total)
    )
    _, _, _, grads, losses = carry
    return jax.lax.psum(losses, axis_name), grads


# ---------------------------------------------------------------------------
# interleaved 1F1B (virtual pipeline stages)
# ---------------------------------------------------------------------------


def forward_backward_pipelining_with_interleaving(
    stage_fn: Callable,
    loss_fn: Callable,
    params,
    batch: Tuple[Any, Any],
    *,
    num_microbatches: int,
    num_model_chunks: Optional[int] = None,
    axis_name: str = _PP,
    forward_only: bool = False,
    remat: bool = True,
    remat_policy=None,
    carry_chunk: Optional[int] = None,
    loss_takes_params: bool = False,
):
    """≙ fwd_bwd_pipelining_with_interleaving.py (virtual/interleaved 1F1B).

    ``params`` hold this rank's ``num_model_chunks`` stage chunks stacked
    on a leading axis (every leaf ``(vpp, ...)``): rank r owns virtual
    stages ``r, r+pp, ..., r+(vpp-1)·pp``.

    Each tick computes exactly ONE chunk per rank (1/vpp of a full stage),
    so a tick costs 1/vpp of a non-interleaved tick.  Microbatches are
    processed in Megatron's round-robin order — groups of ``pp``
    microbatches traverse chunk 0 on every rank, then chunk 1, ... — which
    keeps every rank busy back-to-back in steady state.  At tick ``t``,
    rank ``r`` computes, with ``u = t - r``:

        group g     = u // (pp·vpp)
        chunk c     = (u mod pp·vpp) // pp
        microbatch  = g·pp + (u mod pp)

    valid while ``0 <= u < nm·vpp``.  Total ticks = ``nm·vpp + pp - 1`` of
    duration 1/vpp stage ⇒ wall ≈ ``nm + (pp-1)/vpp`` stage-times: the
    fill/drain bubble is **(pp-1)/vpp** — the Megatron interleaving win —
    vs the non-interleaved schedule's ``pp-1``.  Routing is a uniform
    rank→rank+1 ``ppermute``: the wrap pp-1→0 lands exactly where chunk
    ``c+1`` is scheduled next tick, and rank 0 overwrites the wrapped value
    with a fresh microbatch whenever its scheduled chunk is 0.

    Like the reference schedule, requires ``num_microbatches`` to be a
    multiple of the pipeline size (SURVEY §2.3 interleaving row: Megatron
    asserts ``num_microbatches % pipeline_parallel_size == 0``).

    ``carry_chunk``: same two-level checkpointed tick scan as the
    non-interleaved schedule — more valuable here, since this schedule
    runs ``nm·vpp + pp − 1`` ticks (vpp× the carries).
    """
    inputs, targets = batch
    nm = num_microbatches
    if num_model_chunks is None:
        num_model_chunks = ps.get_virtual_pipeline_model_parallel_world_size()
    vpp = num_model_chunks
    if vpp is None or vpp < 1:
        raise ValueError("num_model_chunks (virtual pipeline size) required")
    run = _wrap_remat(stage_fn, remat, remat_policy)
    lfn = loss_fn if loss_takes_params else (lambda p, y, t: loss_fn(y, t))

    def pipeline_loss(params):
        pp = _compat.axis_size(axis_name)
        if nm % pp != 0:
            raise ValueError(
                f"interleaved schedule requires num_microbatches ({nm}) to "
                f"be a multiple of pipeline_parallel_size ({pp})"
            )
        stage = jax.lax.axis_index(axis_name)
        is_first = stage == 0
        is_last = stage == pp - 1
        cycle = pp * vpp
        ticks = nm * vpp + pp - 1
        h0 = jax.tree_util.tree_map(lambda x: jnp.zeros_like(x[0]), inputs)

        def tick(carry, t):
            h_recv, losses = carry
            u = t - stage
            w = jnp.mod(u, cycle)
            chunk = w // pp
            mb = jnp.floor_divide(u, cycle) * pp + jnp.mod(u, pp)
            active = (u >= 0) & (u < nm * vpp)
            mb_idx = jnp.clip(mb, 0, nm - 1)

            injecting = is_first & (chunk == 0) & active
            inject = jax.tree_util.tree_map(lambda x: x[mb_idx], inputs)
            x_in = jax.tree_util.tree_map(
                lambda a, b: jnp.where(injecting, a, b), inject, h_recv
            )
            chunk_params = jax.tree_util.tree_map(
                lambda x: jax.lax.dynamic_index_in_dim(
                    x, chunk, 0, keepdims=False
                ),
                params,
            )
            y = run(chunk_params, x_in)

            # loss: last virtual stage = rank pp-1 running chunk vpp-1
            finishing = is_last & (chunk == vpp - 1) & active
            tgt = jax.tree_util.tree_map(lambda x: x[mb_idx], targets)
            loss = lfn(chunk_params, y, tgt)
            losses = losses.at[mb_idx].add(jnp.where(finishing, loss, 0.0))

            h_next = p2p.send_forward_recv_forward(y, axis_name, cyclic=True)
            return (h_next, losses), None

        carry0 = (h0, jnp.zeros((nm,), jnp.float32))
        if carry_chunk and carry_chunk > 0:
            kk = min(carry_chunk, ticks)
            n_outer = -(-ticks // kk)  # padded ticks are masked no-ops
            ts = jnp.arange(n_outer * kk).reshape(n_outer, kk)

            @jax.checkpoint
            def outer(carry, ts_chunk):
                carry, _ = jax.lax.scan(tick, carry, ts_chunk)
                return carry, None

            (_, losses), _ = jax.lax.scan(outer, carry0, ts)
        else:
            (_, losses), _ = jax.lax.scan(
                tick, carry0, jnp.arange(ticks)
            )
        # local sum differentiated; psum only in aux (see 1F1B note above)
        return jnp.sum(losses) / nm, jax.lax.psum(losses, axis_name)

    if forward_only:
        _, losses = pipeline_loss(params)
        return losses, None
    (_, losses), grads = jax.value_and_grad(pipeline_loss, has_aux=True)(
        params
    )
    return losses, grads


# ---------------------------------------------------------------------------
# dispatcher
# ---------------------------------------------------------------------------


def get_forward_backward_func(
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_size: Optional[int] = None,
    hand_scheduled: bool = False,
):
    """≙ schedules/__init__.py :: get_forward_backward_func.

    ``hand_scheduled=True`` opts into the explicit-stash-ring schedules
    (no autodiff over the tick loop — the reference's 1F1B memory
    points): :func:`forward_backward_pipelining_1f1b` without virtual
    stages, :func:`forward_backward_pipelining_interleaved_1f1b` with
    them; see docs/pipeline-schedules.md for when each wins."""
    if pipeline_model_parallel_size is None and ps.model_parallel_is_initialized():
        pipeline_model_parallel_size = ps.get_pipeline_model_parallel_world_size()
    if virtual_pipeline_model_parallel_size is None and ps.model_parallel_is_initialized():
        virtual_pipeline_model_parallel_size = (
            ps.get_virtual_pipeline_model_parallel_world_size()
        )
    if (pipeline_model_parallel_size or 1) <= 1:
        return forward_backward_no_pipelining
    if virtual_pipeline_model_parallel_size is not None:
        return functools.partial(
            forward_backward_pipelining_interleaved_1f1b
            if hand_scheduled
            else forward_backward_pipelining_with_interleaving,
            num_model_chunks=virtual_pipeline_model_parallel_size,
        )
    if hand_scheduled:
        return forward_backward_pipelining_1f1b
    return forward_backward_pipelining_without_interleaving
