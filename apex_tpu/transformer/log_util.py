"""≙ apex/transformer/log_util.py :: get_transformer_logger,
set_logging_level."""

import logging

__all__ = ["get_transformer_logger", "set_logging_level"]

_BASE = "apex_tpu.transformer"


def get_transformer_logger(name: str = _BASE) -> logging.Logger:
    if not name.startswith(_BASE):
        name = f"{_BASE}.{name}"
    return logging.getLogger(name)


def set_logging_level(verbosity) -> None:
    """Set the transformer subsystem's log level (int or name)."""
    logging.getLogger(_BASE).setLevel(verbosity)
