"""≙ apex/transformer/layers — persist-LN selector.

The reference's ``layer_norm.py`` picks contrib FastLayerNorm when built
and the hidden size is in its supported table, else FusedLayerNorm.  The
TPU Pallas LayerNorm covers all sizes, so the selector is the identity.
"""

from apex_tpu.normalization import (  # noqa: F401
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
    MixedFusedRMSNorm,
)

# ≙ transformer.layers.FastLayerNorm selector — same kernel underneath here
FastLayerNorm = FusedLayerNorm
