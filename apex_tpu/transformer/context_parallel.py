"""Context parallelism — long-context attention over a mesh axis.

**No reference analog** (SURVEY §2.3: CP/ring/Ulysses are ABSENT in the
reference — its max context is bounded by one device's memory).  This
module is the TPU-native extension that makes long context first-class:

- :func:`ring_attention` — blockwise ring attention (Liu et al. 2023) over
  the ``cp`` mesh axis: q stays put, (k, v) blocks rotate ring-wise via
  ``jax.lax.ppermute`` over ICI neighbors, and per-block flash results are
  folded with the running online-softmax merge.  Sequence length scales
  linearly with the ring size at O(S_local²) compute per hop; compute and
  the permute overlap (XLA schedules the collective-permute concurrently
  with the previous block's matmuls).
- :func:`ulysses_attention` — DeepSpeed-Ulysses-style all-to-all: scatter
  heads / gather sequence (``jax.lax.all_to_all``), run ordinary (flash)
  attention on full sequences with H/cp local heads, all-to-all back.
  Cheaper than the ring when H ≥ cp and sequence fits once gathered.

Both are differentiable: Ulysses through ``all_to_all``'s transpose, the
ring through the scanned ``ppermute`` (per-hop recompute via
``jax.checkpoint`` — the standard ring-attention backward, so residual
memory stays O(S_local) per hop rather than O(S²)).

Layouts match the attention stack: q, k, v are ``(B, H, S_local, D)``
shards.  With the default ``layout="contiguous"`` rank r holds rows
``[r·S_local, (r+1)·S_local)``; with ``layout="zigzag"`` (causal
load balancing) rank r holds global chunks ``r`` and ``2cp−1−r`` — use
:func:`zigzag_split` / :func:`zigzag_merge` to convert.  Causal masking
honors global positions in both layouts.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu import _compat
from apex_tpu import parallel_state as ps

__all__ = [
    "ring_attention",
    "ulysses_attention",
    "zigzag_shard",
    "zigzag_split",
    "zigzag_merge",
]

_CP = ps.CONTEXT_PARALLEL_AXIS


def _block_attend(q, k, v, scale, *, causal=False, dropout_p=0.0,
                  dropout_rng=None, bias=None):
    """One (q-block × kv-block) flash block: returns (o (f32), lse).

    o is the block-normalized output, lse the row logsumexp — exactly the
    pair the online-softmax merge needs.  Dispatches through
    ``flash_attention_with_lse`` (its backward consumes the lse cotangent
    the merge produces): the Pallas kernel path — which never materializes
    the (S_local, S_local) score matrix in HBM — is taken on TPU when
    S_local >= 1024 (or the dispatch is forced); shorter hops use the jnp
    composition, whose transient score block XLA wins on anyway at those
    sizes (see ops.attention._pallas_eligible).  ``causal`` covers the
    ring's diagonal (self) block.
    """
    from apex_tpu.ops.attention import flash_attention_with_lse

    o, lse = flash_attention_with_lse(
        q, k, v, bias, causal=causal, scale=scale, dropout_p=dropout_p,
        dropout_rng=dropout_rng,
    )
    return o.astype(jnp.float32), lse


def _merge_block(carry, block):
    """Fold one (o, lse) block into the running online-softmax state
    ``(acc, m, l)``.  Block o is block-normalized (mass 1·β); a skipped
    block's ``lse = -inf`` folds to exactly zero weight against any
    finite running max.  THE merge for every ring layout — the max-shift
    / rescale / renormalize here is the numerically subtle core, so it
    exists exactly once."""
    acc, m, l = carry
    o_b, lse_b = block
    m_new = jnp.maximum(m, lse_b)
    alpha = jnp.exp(m - m_new)
    beta = jnp.exp(lse_b - m_new)
    l_new = l * alpha + beta
    acc_new = (
        acc * (l * alpha)[..., None] + o_b * beta[..., None]
    ) / l_new[..., None]
    return acc_new, m_new, l_new


def _skipped_block(b, h, rows, d):
    """(o, lse) of a fully-masked (causal-future) block: zero mass —
    both einsums skipped entirely."""
    return (
        jnp.zeros((b, h, rows, d), jnp.float32),
        jnp.full((b, h, rows), -jnp.inf, jnp.float32),
    )


def ring_attention(
    q,
    k,
    v,
    bias=None,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    dropout_p: float = 0.0,
    dropout_rng=None,
    layout: str = "contiguous",
    axis_name: str = _CP,
):
    """Blockwise ring attention over ``axis_name``.

    q, k, v: ``(B, H, S_local, D)`` — this rank's sequence chunk.
    Returns ``(B, H, S_local, D)`` in q's dtype, equal (within numerics)
    to full attention over the gathered sequence.

    Causal mode skips the block compute entirely for hops whose kv chunk
    lies in this rank's causal future (``lax.switch`` on the chunk order);
    the permute still runs every hop, so the ring stays in lockstep.  Note
    contiguous chunking makes causal work *imbalanced* across ranks (rank 0
    computes 1 block, rank cp-1 computes cp) — the wall-clock cost per hop
    is set by the busiest rank.  ``layout="zigzag"`` fixes that: each
    rank holds global chunks ``r`` and ``2cp−1−r`` (use
    :func:`zigzag_split` / :func:`zigzag_merge` for the layout), pairing
    a cheap early chunk with an expensive late one so every rank computes
    ~2 half-blocks per hop — halving causal ring wall on real hardware
    (Megatron-LM's cp layout).  Zigzag requires ``causal=True``.

    ``bias``: a per-rank KEY-PADDING mask of shape ``(B, 1, 1,
    S_local)`` (additive, non-trainable, MASK_VALUE-clamped) covering
    this rank's OWN kv chunk — in the rank's configured layout, so
    under ``layout="zigzag"`` its halves cover the rank's two global
    chunks (``zigzag_shard`` the global mask along its key axis).  It
    rotates around the ring with (k, v), so every hop masks the padded
    keys of whichever chunk it attends.  Variable-length long-document
    batches are the use case; each query row must keep at least one
    unmasked key globally.  Query-dependent bias shapes are rejected
    (they cannot rotate with kv; fold such terms into the model
    instead).

    ``dropout_p`` > 0 (with ``dropout_rng``) applies attention dropout
    that composes exactly with the ring merge: each (q-rank, kv-chunk)
    block draws an independent mask (``dropout_rng`` folded with
    ``rank·cp + src``), the block's PV contribution is masked +
    rescaled while its lse stays the full undropped statistic, and the
    merge weights blocks by true softmax mass — the result equals
    full-sequence attention under the block-assembled mask.  Masks
    regenerate deterministically in backward (the hop is
    ``jax.checkpoint``-ed with the same folded rng).
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if dropout_p > 0.0 and dropout_rng is None:
        raise ValueError("dropout_p > 0 requires dropout_rng")
    if bias is not None:
        if bias.ndim < 4:
            bias = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
        if bias.shape[1] != 1 or bias.shape[2] != 1:
            raise ValueError(
                "ring_attention only rotates a key-padding bias of "
                f"shape (B, 1, 1, S_local); got {bias.shape} — "
                "query-dependent bias cannot rotate with kv"
            )
        if bias.shape[-1] not in (1, k.shape[-2]):
            raise ValueError(
                f"ring_attention bias covers {bias.shape[-1]} keys but "
                f"this rank's kv chunk has {k.shape[-2]} — pass the "
                "RANK-LOCAL slice of the global mask (it rotates with "
                "kv), not the global mask itself"
            )
    if layout == "zigzag":
        if not causal:
            raise ValueError(
                "layout='zigzag' exists to balance CAUSAL ring work; "
                "non-causal rings are already balanced — use the "
                "contiguous layout"
            )
        return _ring_attention_zigzag(
            q, k, v, bias, scale, dropout_p, dropout_rng, axis_name
        )
    if layout != "contiguous":
        raise ValueError(f"unknown ring layout {layout!r}")
    world = _compat.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % world) for i in range(world)]

    @jax.checkpoint
    def hop(qf, kv, src):
        """(o, lse) for this rank's q against the kv chunk from ``src``."""
        kb, vb, bias_b = kv
        kw = {} if bias_b is None else dict(bias=bias_b)
        if dropout_p > 0.0:
            kw.update(
                dropout_p=dropout_p,
                dropout_rng=jax.random.fold_in(
                    dropout_rng, rank * world + src
                ),
            )
        if not causal:
            return _block_attend(qf, kb, vb, scale, **kw)

        def self_block(_):
            return _block_attend(qf, kb, vb, scale, causal=True, **kw)

        def past_block(_):
            return _block_attend(qf, kb, vb, scale, **kw)

        def future_block(_):
            return _skipped_block(b, h, s_local, d)

        branch = jnp.where(src == rank, 0, jnp.where(src < rank, 1, 2))
        return jax.lax.switch(branch, [self_block, past_block, future_block], None)

    # hop 0 is always the self block — no permute needed before it, and it
    # seeds the running max with a finite lse (so -inf skipped hops merge
    # to exactly zero weight)
    kv0 = (k, v, bias)
    o0, lse0 = hop(qf, kv0, rank)
    carry = (o0, lse0, jnp.ones((b, h, s_local), jnp.float32))

    def body(state, step):
        kv, carry = state
        # rotate FIRST: world-1 permutes total, none wasted on the last
        # hop; the key-padding bias rides the same rotation as (k, v)
        kv = jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), kv
        )
        src = (rank - step) % world
        carry = _merge_block(carry, hop(qf, kv, src))
        return (kv, carry), None

    if world > 1:
        (_, carry), _ = jax.lax.scan(
            body, (kv0, carry), jnp.arange(1, world)
        )
    acc, _, _ = carry
    return acc.astype(q.dtype)


def zigzag_shard(x, rank, cp: int, axis: int = 0):
    """ONE rank's zigzag shard of a GLOBAL array: the concatenation of
    global chunks ``rank`` and ``2cp−1−rank`` along ``axis`` (``rank``
    may be traced, e.g. ``jax.lax.axis_index``).  THE definition of the
    zigzag layout contract for in-shard_map use — models, examples and
    tests slice through here so the chunk math exists once; whole-array
    host-side conversion is :func:`zigzag_split` / :func:`zigzag_merge`.
    Raises unless the axis divides into ``2·cp`` chunks (a remainder
    would silently drop trailing tokens)."""
    size = x.shape[axis]
    if size % (2 * cp):
        raise ValueError(
            f"zigzag layout needs the sequence ({size}) divisible by "
            f"2*cp ({2 * cp}); a remainder would silently drop tokens"
        )
    sc = size // (2 * cp)
    lo = jax.lax.dynamic_slice_in_dim(x, rank * sc, sc, axis)
    hi = jax.lax.dynamic_slice_in_dim(x, (2 * cp - 1 - rank) * sc, sc, axis)
    return jnp.concatenate([lo, hi], axis=axis)


def zigzag_split(x, cp: int, axis: int = 2):
    """Global → zigzag layout: split ``axis`` into ``2·cp`` chunks and
    stack per-rank locals ``(cp, ..., S/cp, ...)`` where rank ``r`` holds
    the concatenation of chunks ``r`` and ``2cp−1−r``.  This pairs an
    early (cheap) causal chunk with a late (expensive) one, balancing
    causal ring work across ranks (Megatron-LM's cp layout)."""
    chunks = jnp.split(x, 2 * cp, axis=axis)
    return jnp.stack(
        [
            jnp.concatenate([chunks[r], chunks[2 * cp - 1 - r]], axis=axis)
            for r in range(cp)
        ]
    )


def zigzag_merge(locals_, cp: int, axis: int = 2):
    """Inverse of :func:`zigzag_split`: ``(cp, ..., S/cp, ...)`` stacked
    per-rank zigzag locals → the global-order array."""
    out = [None] * (2 * cp)
    for r in range(cp):
        lo, hi = jnp.split(locals_[r], 2, axis=axis)
        out[r] = lo
        out[2 * cp - 1 - r] = hi
    return jnp.concatenate(out, axis=axis)


def _ring_attention_zigzag(q, k, v, bias, scale, dropout_p, dropout_rng,
                           axis_name):
    """Causal ring attention over the zigzag layout: this rank's
    ``S_local`` rows are [global chunk ``r``; global chunk ``2cp−1−r``].

    Work per hop is balanced by construction: the lo half attends only lo
    kv halves (one half-block, skipped for future sources), the hi half
    attends every lo half (always) plus non-future hi halves — every rank
    computes ~2 half-blocks per hop instead of the contiguous layout's
    worst-rank full block, halving causal ring wall on real hardware.
    """
    world = _compat.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    if s_local % 2:
        raise ValueError("zigzag layout needs an even local sequence")
    half = s_local // 2
    qf = q.astype(jnp.float32)
    q_lo, q_hi = qf[:, :, :half], qf[:, :, half:]
    perm = [(i, (i + 1) % world) for i in range(world)]

    skip = _skipped_block(b, h, half, d)

    def _drop(src, pair):
        if dropout_p == 0.0:
            return {}
        return dict(
            dropout_p=dropout_p,
            dropout_rng=jax.random.fold_in(
                dropout_rng, (rank * world + src) * 4 + pair
            ),
        )

    @jax.checkpoint
    def hop(q_lo, q_hi, kv, src):
        k_lo, v_lo, k_hi, v_hi, b_lo, b_hi = kv
        blo = {} if b_lo is None else dict(bias=b_lo)
        bhi = {} if b_hi is None else dict(bias=b_hi)
        # lo (global chunk rank) vs lo' (global chunk src)
        lo = jax.lax.switch(
            jnp.where(src == rank, 0, jnp.where(src < rank, 1, 2)),
            [
                lambda _: _block_attend(
                    q_lo, k_lo, v_lo, scale, causal=True,
                    **blo, **_drop(src, 0)
                ),
                lambda _: _block_attend(
                    q_lo, k_lo, v_lo, scale, **blo, **_drop(src, 0)
                ),
                lambda _: skip,
            ],
            None,
        )
        # hi (chunk 2cp−1−rank) vs lo' (chunk src < cp): always past
        hi_lo = _block_attend(
            q_hi, k_lo, v_lo, scale, **blo, **_drop(src, 1)
        )
        # hi vs hi' (chunk 2cp−1−src): past iff src > rank
        hi_hi = jax.lax.switch(
            jnp.where(src == rank, 0, jnp.where(src > rank, 1, 2)),
            [
                lambda _: _block_attend(
                    q_hi, k_hi, v_hi, scale, causal=True,
                    **bhi, **_drop(src, 2)
                ),
                lambda _: _block_attend(
                    q_hi, k_hi, v_hi, scale, **bhi, **_drop(src, 2)
                ),
                lambda _: skip,
            ],
            None,
        )
        return lo, hi_lo, hi_hi

    b_lo = b_hi = None
    if bias is not None:
        # the (B, 1, 1, S_local) key-padding mask splits into the two
        # chunk halves and rotates with them; a broadcast (..., 1) mask
        # applies to both halves as-is
        if bias.shape[-1] == 1:
            b_lo = b_hi = bias
        else:
            b_lo, b_hi = bias[..., :half], bias[..., half:]
    kv0 = (
        k[:, :, :half], v[:, :, :half],
        k[:, :, half:], v[:, :, half:],
        b_lo, b_hi,
    )
    lo0, hi_lo0, hi_hi0 = hop(q_lo, q_hi, kv0, rank)
    ones = jnp.ones((b, h, half), jnp.float32)
    c_lo = (lo0[0], lo0[1], ones)
    c_hi = _merge_block((hi_lo0[0], hi_lo0[1], ones), hi_hi0)

    def body(state, step):
        kv, c_lo, c_hi = state
        kv = jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), kv
        )
        src = (rank - step) % world
        lo, hi_lo, hi_hi = hop(q_lo, q_hi, kv, src)
        c_lo = _merge_block(c_lo, lo)
        c_hi = _merge_block(_merge_block(c_hi, hi_lo), hi_hi)
        return (kv, c_lo, c_hi), None

    if world > 1:
        (_, c_lo, c_hi), _ = jax.lax.scan(
            body, (kv0, c_lo, c_hi), jnp.arange(1, world)
        )
    return jnp.concatenate([c_lo[0], c_hi[0]], axis=2).astype(q.dtype)


def ulysses_attention(
    q,
    k,
    v,
    bias=None,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    dropout_p: float = 0.0,
    dropout_rng=None,
    axis_name: str = _CP,
):
    """All-to-all (Ulysses) sequence parallelism.

    q, k, v: ``(B, H, S_local, D)`` with the FULL head count; requires
    ``H % axis_size == 0``.  all-to-all → ``(B, H/cp, S, D)`` → ordinary
    flash attention with H/cp local heads → all-to-all back to
    ``(B, H, S_local, D)``.

    ``bias``: only a head-independent key-padding bias of local shape
    ``(B, 1, 1, S_local)`` is accepted (it is all-gathered along the
    sequence to match the gathered scores); other shapes would need both
    score dims reassembled and are rejected — precompute a global bias
    and fold it into the model instead.

    ``dropout_rng`` is folded with the cp rank so each rank's H/cp head
    group draws an independent mask (statistically identical to unsharded
    dropout, not bit-identical).
    """
    from apex_tpu.ops.attention import flash_attention

    world = _compat.axis_size(axis_name)
    h = q.shape[1]
    if h % world:
        raise ValueError(
            f"ulysses_attention needs num_heads ({h}) divisible by the "
            f"axis size ({world})"
        )
    if bias is not None:
        if bias.ndim < 4:
            bias = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
        if bias.shape[1] != 1 or bias.shape[2] != 1:
            raise ValueError(
                "ulysses_attention only redistributes a key-padding bias "
                f"of shape (B, 1, 1, S_local); got {bias.shape}"
            )
        bias = jax.lax.all_gather(bias, axis_name, axis=3, tiled=True)
    if dropout_rng is not None:
        dropout_rng = jax.random.fold_in(
            dropout_rng, jax.lax.axis_index(axis_name)
        )

    def scatter_heads(x):
        # (B, H, S_local, D) -> (B, H/cp, S, D): split heads, concat seq
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    def gather_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    o = flash_attention(
        scatter_heads(q), scatter_heads(k), scatter_heads(v), bias,
        causal=causal, scale=scale, dropout_p=dropout_p,
        dropout_rng=dropout_rng,
    )
    return gather_heads(o)
