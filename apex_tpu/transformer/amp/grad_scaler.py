"""Grad scaler that agrees across the model-parallel group.

≙ ``apex/transformer/amp/grad_scaler.py`` :: ``GradScaler`` — torch's
scaler with ``found_inf`` all-reduced (MAX) over the tensor- and
pipeline-parallel groups in ``_unscale_grads_``, so every model-parallel
rank skips (or keeps) the same step even when only one shard overflowed.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu import parallel_state as ps
from apex_tpu.amp.scaler import DynamicLossScaler, LossScaleState

__all__ = ["GradScaler"]


class GradScaler(DynamicLossScaler):
    """DynamicLossScaler whose overflow flag is synchronized over the
    model-parallel axes (inside shard_map)."""

    def __init__(
        self,
        init_scale: float = 2.0**16,
        growth_factor: float = 2.0,
        backoff_factor: float = 0.5,
        growth_interval: int = 2000,
        hysteresis: int = 1,
        model_parallel_axes: Sequence[str] = (
            ps.TENSOR_PARALLEL_AXIS,
            ps.PIPELINE_PARALLEL_AXIS,
        ),
    ):
        super().__init__(
            init_scale=init_scale,
            growth_factor=growth_factor,
            backoff_factor=backoff_factor,
            growth_interval=growth_interval,
            hysteresis=hysteresis,
        )
        self.model_parallel_axes = tuple(model_parallel_axes)

    def _sync_found_inf(self, found_inf):
        for ax in self.model_parallel_axes:
            try:
                found_inf = jax.lax.pmax(found_inf, ax)
            except (NameError, KeyError):
                continue  # axis not bound (e.g. single-device tests)
        return found_inf

    def unscale(self, grads, state: LossScaleState) -> Tuple[object, jax.Array]:
        grads, found_inf = super().unscale(grads, state)
        return grads, self._sync_found_inf(found_inf)
