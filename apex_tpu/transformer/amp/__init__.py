"""≙ apex/transformer/amp — model-parallel-aware grad scaler."""

from apex_tpu.transformer.amp.grad_scaler import GradScaler  # noqa: F401
