// Host-side native ops — the TPU-native analog of the reference's host
// C++ layer (csrc/flatten_unflatten.cpp :: flatten/unflatten, and the
// input-pipeline work the reference delegates to DALI/data_prefetcher in
// examples/imagenet/main_amp.py).
//
// On TPU the *device* side belongs to XLA/Pallas, but the host side of a
// training job — assembling flat buffers for checkpoint/transfer and
// producing masked-LM batches fast enough to keep the chip fed — is
// ordinary native code.  These are the two hot host loops:
//
//  - flatten/unflatten: threaded memcpy of a tensor list into one
//    contiguous buffer (feeds single-transfer host->device uploads).
//  - mlm_mask_batch: BERT masked-LM corruption (the 80/10/10 rule) with a
//    counter-based RNG, deterministic in (seed, position).
//
// Built on demand by apex_tpu/_native/__init__.py with g++ -O3; a numpy
// fallback keeps the package importable without a toolchain.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// flatten / unflatten (≙ apex_C.flatten / apex_C.unflatten)
// ---------------------------------------------------------------------------

void apex_flatten_f32(const float** srcs, const int64_t* sizes, int64_t n,
                      float* dst, int64_t n_threads) {
  std::vector<int64_t> offsets(n + 1, 0);
  for (int64_t i = 0; i < n; ++i) offsets[i + 1] = offsets[i] + sizes[i];
  if (n_threads < 1) n_threads = 1;
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  for (int64_t t = 0; t < n_threads; ++t) {
    workers.emplace_back([&, t]() {
      for (int64_t i = t; i < n; i += n_threads) {
        std::memcpy(dst + offsets[i], srcs[i], sizes[i] * sizeof(float));
      }
    });
  }
  for (auto& w : workers) w.join();
}

void apex_unflatten_f32(const float* src, const int64_t* sizes, int64_t n,
                        float** dsts, int64_t n_threads) {
  std::vector<int64_t> offsets(n + 1, 0);
  for (int64_t i = 0; i < n; ++i) offsets[i + 1] = offsets[i] + sizes[i];
  if (n_threads < 1) n_threads = 1;
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  for (int64_t t = 0; t < n_threads; ++t) {
    workers.emplace_back([&, t]() {
      for (int64_t i = t; i < n; i += n_threads) {
        std::memcpy(dsts[i], src + offsets[i], sizes[i] * sizeof(float));
      }
    });
  }
  for (auto& w : workers) w.join();
}

// ---------------------------------------------------------------------------
// batch row gather (the data-loader hot loop: assemble a shuffled batch
// from a memory-mapped token file into one contiguous host buffer)
// ---------------------------------------------------------------------------

void apex_gather_rows(const uint8_t* base, const int64_t* offsets,
                      int64_t n_rows, int64_t row_bytes, uint8_t* dst,
                      int64_t n_threads) {
  if (n_threads < 1) n_threads = 1;
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  for (int64_t t = 0; t < n_threads; ++t) {
    workers.emplace_back([&, t]() {
      for (int64_t i = t; i < n_rows; i += n_threads) {
        std::memcpy(dst + i * row_bytes, base + offsets[i], row_bytes);
      }
    });
  }
  for (auto& w : workers) w.join();
}

// ---------------------------------------------------------------------------
// masked-LM batch corruption (the BERT phase-1 input hot loop)
// ---------------------------------------------------------------------------

// splitmix64: counter-based, so (seed, index) fully determines each draw —
// reproducible regardless of threading.
static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

static inline double u01(uint64_t bits) {
  return (bits >> 11) * (1.0 / 9007199254740992.0);  // 53-bit mantissa
}

// ids/out_ids/out_labels: length n.  Standard BERT corruption:
//   with prob mask_prob, position is "selected":
//     80%: token -> mask_id; 10%: token -> uniform random; 10%: unchanged;
//   labels = original id at selected positions, -1 elsewhere.
// special_floor: ids < special_floor (CLS/SEP/PAD) are never selected.
void apex_mlm_mask(const int32_t* ids, int64_t n, uint64_t seed,
                   double mask_prob, int32_t mask_id, int32_t vocab_size,
                   int32_t special_floor, int32_t* out_ids,
                   int32_t* out_labels, int64_t n_threads) {
  if (n_threads < 1) n_threads = 1;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  for (int64_t t = 0; t < n_threads; ++t) {
    workers.emplace_back([&, t]() {
      int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
      for (int64_t i = lo; i < hi; ++i) {
        int32_t id = ids[i];
        out_ids[i] = id;
        out_labels[i] = -1;
        if (id < special_floor) continue;
        uint64_t r0 = splitmix64(seed ^ (uint64_t)i);
        if (u01(r0) >= mask_prob) continue;
        out_labels[i] = id;
        uint64_t r1 = splitmix64(r0);
        double action = u01(r1);
        if (action < 0.8) {
          out_ids[i] = mask_id;
        } else if (action < 0.9) {
          uint64_t r2 = splitmix64(r1);
          out_ids[i] =
              special_floor +
              (int32_t)(splitmix64(r2) % (uint64_t)(vocab_size - special_floor));
        }  // else: keep original token
      }
    });
  }
  for (auto& w : workers) w.join();
}

}  // extern "C"
