"""Native host-ops loader — builds ``host_ops.cpp`` on demand (g++, cached
by source mtime) and binds it via ctypes; falls back to numpy
implementations when no toolchain is available.

≙ the reference's L0/L1 native split (``setup.py --cpp_ext`` building
``apex_C``): the device side of this framework is XLA/Pallas, but host-side
runtime work (flat-buffer assembly, input-pipeline corruption) is native
C++ exactly where the reference's is.  ``NATIVE_AVAILABLE`` tells callers
which path they got (every function is numerically identical either way —
the MLM fallback replays the same splitmix64 stream in vectorized numpy).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import List, Optional, Sequence

import numpy as np

__all__ = [
    "NATIVE_AVAILABLE",
    "available",
    "flatten_f32",
    "unflatten_f32",
    "mlm_mask_batch",
    "gather_rows",
]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "host_ops.cpp")
_LIB: Optional[ctypes.CDLL] = None
_LOAD_FAILED = False  # cache build failure: don't re-spawn g++ per call
NATIVE_AVAILABLE = False


def _build_dir() -> str:
    d = os.environ.get("APEX_TPU_NATIVE_CACHE")
    if not d:
        d = os.path.join(
            tempfile.gettempdir(), f"apex_tpu_native_{os.getuid()}"
        )
    os.makedirs(d, exist_ok=True)
    return d


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _LOAD_FAILED, NATIVE_AVAILABLE
    if _LIB is not None:
        return _LIB
    if _LOAD_FAILED:
        return None
    so = os.path.join(_build_dir(), "libapex_tpu_host.so")
    try:
        if (
            not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(_SRC)
        ):
            subprocess.run(
                [
                    "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                    "-pthread", _SRC, "-o", so,
                ],
                check=True,
                capture_output=True,
                timeout=120,
            )
        lib = ctypes.CDLL(so)
    except (OSError, subprocess.SubprocessError):
        _LOAD_FAILED = True  # the per-batch hot loops fall back instantly
        return None

    i64 = ctypes.c_int64
    lib.apex_flatten_f32.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(i64), i64,
        ctypes.c_void_p, i64,
    ]
    lib.apex_unflatten_f32.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(i64), i64,
        ctypes.POINTER(ctypes.c_void_p), i64,
    ]
    lib.apex_mlm_mask.argtypes = [
        ctypes.c_void_p, i64, ctypes.c_uint64, ctypes.c_double,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_void_p, ctypes.c_void_p, i64,
    ]
    lib.apex_gather_rows.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(i64), i64, i64, ctypes.c_void_p, i64,
    ]
    _LIB = lib
    NATIVE_AVAILABLE = True
    return lib


def available() -> bool:
    """Whether the native library is (or can be) loaded — triggers the
    lazy build.  Prefer this over reading ``NATIVE_AVAILABLE`` at import
    time, which snapshots the pre-build value."""
    return _load() is not None


def _nthreads() -> int:
    return min(8, os.cpu_count() or 1)


def flatten_f32(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """Concatenate f32 host arrays into one flat buffer (threaded memcpy).

    ≙ ``apex_C.flatten`` on the host side; pairs with a single
    host→device transfer instead of one per tensor.
    """
    arrays = [np.ascontiguousarray(a, dtype=np.float32) for a in arrays]
    total = sum(a.size for a in arrays)
    out = np.empty((total,), np.float32)
    lib = _load()
    if lib is None:
        np.concatenate([a.ravel() for a in arrays], out=out)
        return out
    n = len(arrays)
    srcs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrays])
    sizes = (ctypes.c_int64 * n)(*[a.size for a in arrays])
    lib.apex_flatten_f32(srcs, sizes, n, out.ctypes.data, _nthreads())
    return out


def unflatten_f32(
    flat: np.ndarray, like: Sequence[np.ndarray]
) -> List[np.ndarray]:
    """Split a flat f32 buffer back into arrays shaped like ``like``."""
    flat = np.ascontiguousarray(flat, dtype=np.float32)
    sizes = [int(a.size) for a in like]
    if flat.size != sum(sizes):
        raise ValueError(
            f"flat buffer has {flat.size} elements, need {sum(sizes)}"
        )
    outs = [np.empty(a.shape, np.float32) for a in like]
    lib = _load()
    if lib is None:
        off = 0
        for o, s in zip(outs, sizes):
            o.ravel()[:] = flat[off : off + s]
            off += s
        return outs
    n = len(outs)
    dsts = (ctypes.c_void_p * n)(*[o.ctypes.data for o in outs])
    csizes = (ctypes.c_int64 * n)(*sizes)
    lib.apex_unflatten_f32(flat.ctypes.data, csizes, n, dsts, _nthreads())
    return outs


def gather_rows(
    base: np.ndarray, row_starts: np.ndarray, row_elems: int
) -> np.ndarray:
    """Assemble ``out[i] = base[row_starts[i] : row_starts[i]+row_elems]``
    with a threaded native memcpy gather — the data-loader batch-assembly
    hot loop (rows of a memory-mapped token file → one contiguous batch).

    ``base``: 1-D array (typically ``np.memmap``); ``row_starts``: int64
    ELEMENT offsets into ``base``.  Returns ``(len(row_starts), row_elems)``
    in ``base.dtype``.
    """
    base = np.ascontiguousarray(base).ravel()
    starts = np.ascontiguousarray(row_starts, dtype=np.int64)
    if starts.size and (
        starts.min() < 0 or starts.max() + row_elems > base.size
    ):
        raise IndexError(
            f"row [{starts.min()}, {starts.max()} + {row_elems}) out of "
            f"bounds for base of {base.size} elements"
        )
    out = np.empty((starts.size, row_elems), base.dtype)
    lib = _load()
    if lib is None:
        for i, s in enumerate(starts):
            out[i] = base[s : s + row_elems]
        return out
    item = base.dtype.itemsize
    byte_offsets = (starts * item).astype(np.int64)
    lib.apex_gather_rows(
        base.ctypes.data,
        byte_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        starts.size, row_elems * item, out.ctypes.data, _nthreads(),
    )
    return out


def _splitmix64(x: np.ndarray) -> np.ndarray:
    x = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _u01(bits: np.ndarray) -> np.ndarray:
    return (bits >> np.uint64(11)).astype(np.float64) / 9007199254740992.0


def mlm_mask_batch(
    ids: np.ndarray,
    seed: int,
    *,
    mask_prob: float = 0.15,
    mask_id: int = 103,
    vocab_size: int = 30522,
    special_floor: int = 1000,
):
    """BERT masked-LM corruption (80/10/10) — the input-pipeline hot loop.

    ids: int32 array (any shape).  Returns (masked_ids, labels) with
    labels = -1 at unselected positions.  Deterministic in (seed,
    position) via a counter-based splitmix64 stream, so the native and
    numpy paths produce bit-identical batches.
    """
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    out_ids = np.empty_like(ids)
    labels = np.empty_like(ids)
    lib = _load()
    if lib is not None:
        lib.apex_mlm_mask(
            ids.ctypes.data, ids.size, ctypes.c_uint64(seed),
            float(mask_prob), np.int32(mask_id), np.int32(vocab_size),
            np.int32(special_floor), out_ids.ctypes.data,
            labels.ctypes.data, _nthreads(),
        )
        return out_ids, labels

    # vectorized numpy replay of the identical stream
    flat = ids.ravel()
    idx = np.arange(flat.size, dtype=np.uint64)
    r0 = _splitmix64(np.uint64(seed) ^ idx)
    selectable = flat >= special_floor
    selected = selectable & (_u01(r0) < mask_prob)
    r1 = _splitmix64(r0)
    action = _u01(r1)
    r2 = _splitmix64(r1)
    rand_tok = (
        special_floor
        + (_splitmix64(r2) % np.uint64(vocab_size - special_floor)).astype(
            np.int32
        )
    )
    out = flat.copy()
    out[selected & (action < 0.8)] = mask_id
    mid = selected & (action >= 0.8) & (action < 0.9)
    out[mid] = rand_tok[mid]
    lab = np.where(selected, flat, -1).astype(np.int32)
    out_ids[...] = out.reshape(ids.shape)
    labels[...] = lab.reshape(ids.shape)
    return out_ids, labels
