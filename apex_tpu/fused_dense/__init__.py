"""Fused dense layers — ≙ ``apex/fused_dense/fused_dense.py``.

The reference reaches for ``cublasLtMatmul`` epilogues
(``csrc/fused_dense.cpp`` :: ``linear_bias_forward``,
``linear_gelu_linear_forward``) to fold bias and GELU into the GEMM.  XLA
performs the same epilogue fusion on TPU automatically — the dot lands on
the MXU with the bias/GELU fused into its output tiling — so these are
thin, API-parity modules over a single traced expression.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = [
    "FusedDense",
    "FusedDenseGeluDense",
    "fused_dense_function",
    "fused_dense_gelu_dense_function",
]


def fused_dense_function(x, weight, bias=None):
    """GEMM + bias.  ≙ fused_dense_cuda.linear_bias_forward.

    ``weight`` uses the JAX layout ``(in, out)``.
    """
    from apex_tpu.amp.lists import amp_cast

    x, weight, bias = amp_cast("fused_dense", x, weight, bias)
    y = jnp.dot(x, weight, preferred_element_type=jnp.float32)
    if bias is not None:
        y = y + bias
    return y.astype(x.dtype)


def fused_dense_gelu_dense_function(x, weight1, bias1, weight2, bias2):
    """GEMM+bias+GELU+GEMM+bias.  ≙ linear_gelu_linear_forward.

    Uses tanh-approximate GELU, matching the reference kernel's polynomial.
    """
    from apex_tpu.amp.lists import amp_cast

    x, weight1, bias1, weight2, bias2 = amp_cast(
        "fused_dense_gelu_dense", x, weight1, bias1, weight2, bias2
    )
    h = jnp.dot(x, weight1, preferred_element_type=jnp.float32)
    if bias1 is not None:
        h = h + bias1
    h = jax.nn.gelu(h, approximate=True)
    y = jnp.dot(h.astype(x.dtype), weight2, preferred_element_type=jnp.float32)
    if bias2 is not None:
        y = y + bias2
    return y.astype(x.dtype)


class FusedDense(nn.Module):
    """≙ apex.fused_dense.FusedDense(in_features, out_features, bias=True)."""

    in_features: int
    out_features: int
    bias: bool = True
    kernel_init: Callable = nn.initializers.lecun_normal()
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param("kernel", self.kernel_init, (self.in_features, self.out_features))
        b = self.param("bias", nn.initializers.zeros, (self.out_features,)) if self.bias else None
        x = x.astype(self.dtype)
        return fused_dense_function(
            x, w.astype(self.dtype), None if b is None else b.astype(self.dtype)
        )


class FusedDenseGeluDense(nn.Module):
    """≙ apex.fused_dense.FusedDenseGeluDense (the transformer FFN shape)."""

    in_features: int
    intermediate_features: int
    out_features: int
    bias: bool = True
    kernel_init: Callable = nn.initializers.lecun_normal()
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        w1 = self.param(
            "kernel_1", self.kernel_init, (self.in_features, self.intermediate_features)
        )
        w2 = self.param(
            "kernel_2", self.kernel_init, (self.intermediate_features, self.out_features)
        )
        b1 = b2 = None
        if self.bias:
            b1 = self.param("bias_1", nn.initializers.zeros, (self.intermediate_features,))
            b2 = self.param("bias_2", nn.initializers.zeros, (self.out_features,))
        x = x.astype(self.dtype)
        cast = lambda t: None if t is None else t.astype(self.dtype)
        return fused_dense_gelu_dense_function(x, cast(w1), cast(b1), cast(w2), cast(b2))
