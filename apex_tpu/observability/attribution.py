"""Step-time attribution — where a compiled training step spends its
time, decomposed into compute / collective / host-stall fractions and
per-op-category buckets, from TWO sources that must agree:

1. **The compiled cost model** (:func:`attribute_cost_model`): every
   instruction of the optimized HLO text, costed by the shape
   arithmetic in :mod:`apex_tpu.analysis.hlo` (the repo's one HLO
   reader) and bucketed by :func:`~apex_tpu.observability.meter.
   categorize_op` into matmul / attention / norm-elementwise /
   collective / other.  Static: it knows FLOPs and bytes exactly but
   estimates time (a two-resource roofline per op), and it cannot see
   the host — its host-stall fraction is always 0.

2. **A measured profiler trace** (:func:`attribute_trace`): the
   trace-event JSON a :class:`~apex_tpu.observability.trace.
   TraceScheduler` window (or ``bench.py --trace``) already captures,
   parsed into the same buckets — per-op device events on TPU/GPU
   ("XLA Ops" tracks) or the per-thunk spans the CPU runtime emits.
   Measured: it knows time exactly, including the gaps no op accounts
   for (host stall: dispatch latency, blocked fetches, input waits).

Where both exist, disagreement IS the finding: a measured collective
fraction far above the cost model's means the overlap the schedule
promised did not happen; a large host-stall fraction means the chip is
starving, not slow.  :func:`roofline_report` turns the merged view into
a per-bucket roofline (achieved FLOP/s vs the
:mod:`~apex_tpu.observability.meter` peak table, arithmetic intensity
vs the ridge point, compute- vs bandwidth-bound verdict), and
:func:`publish_attribution` lands the fractions on the observability
board — where :class:`~apex_tpu.observability.health.
CollectiveFractionRule` / :class:`~apex_tpu.observability.health.
HostStallRule` watch them.

Surfaces: ``tools/step_profile.py`` (the workflow entry),
``tools/trace_summary.py --attribution``, and the resilient example,
which attributes any captured trace window on exit.  See
``docs/observability.md`` ("Attribution & roofline").
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Dict, List, Mapping, NamedTuple, Optional, Sequence, Tuple

from apex_tpu.observability.meter import (
    BUCKETS,
    categorize_op,
    peak_flops_for,
    peak_hbm_bandwidth_for,
    peak_ici_bandwidth_for,
)

__all__ = [
    "OpCost",
    "CostAttribution",
    "TraceAttribution",
    "RooflineRow",
    "attribute_cost_model",
    "attribute_trace",
    "attribute_trace_dir",
    "trace_step_period",
    "hlo_bucket_map",
    "roofline_report",
    "render_roofline",
    "publish_attribution",
]

#: top-level fraction keys — always sum to 1.0 (compute aggregates the
#: non-collective busy buckets)
FRACTION_KEYS = ("compute", "collective", "host_stall")


class OpCost(NamedTuple):
    """One entry-reachable instruction's modeled cost."""

    name: str
    opcode: str
    op_name: str
    bucket: str
    flops: float
    bytes: int


# ---------------------------------------------------------------------------
# source (a): the compiled cost model
# ---------------------------------------------------------------------------


class CostAttribution:
    """Bucketed FLOPs/bytes/estimated-time from optimized HLO text."""

    def __init__(self, ops: List[OpCost], peak_flops: float,
                 hbm_bw: float, ici_bw: float):
        self.ops = ops
        self.peak_flops = peak_flops
        self.hbm_bw = hbm_bw
        self.ici_bw = ici_bw
        self.buckets: Dict[str, Dict[str, float]] = {
            b: {"flops": 0.0, "bytes": 0.0, "est_time": 0.0}
            for b in BUCKETS
        }
        for op in ops:
            rec = self.buckets[op.bucket]
            rec["flops"] += op.flops
            rec["bytes"] += op.bytes
            if op.bucket == "collective":
                rec["est_time"] += op.bytes / ici_bw
            else:
                rec["est_time"] += max(
                    op.flops / peak_flops, op.bytes / hbm_bw
                )

    @property
    def total_flops(self) -> float:
        return sum(b["flops"] for b in self.buckets.values())

    @property
    def total_bytes(self) -> float:
        return sum(b["bytes"] for b in self.buckets.values())

    @property
    def est_step_time(self) -> float:
        """Roofline lower bound on the step (serial sum of per-op
        maxima — real schedules overlap, so achieved time ≥ this)."""
        return sum(b["est_time"] for b in self.buckets.values())

    def bucket_fractions(self) -> Dict[str, float]:
        """Each bucket's share of the estimated busy time."""
        total = self.est_step_time
        if total <= 0:
            return {b: 0.0 for b in BUCKETS}
        return {b: self.buckets[b]["est_time"] / total for b in BUCKETS}

    def fractions(self) -> Dict[str, float]:
        """compute/collective/host_stall (host_stall is always 0 here:
        the compiled program cannot see the host)."""
        shares = self.bucket_fractions()
        coll = shares.get("collective", 0.0)
        return {
            "compute": 1.0 - coll if self.est_step_time > 0 else 0.0,
            "collective": coll,
            "host_stall": 0.0,
        }

    def bucket_map(self) -> Dict[str, str]:
        """Instruction name → bucket — the join map the trace parser
        uses to bucket profiler rows by op metadata the trace itself
        does not carry.  Keys are the RAW instruction names (the
        ``p<i>/``/while-path prefixes :func:`attribute_cost_model`
        stamps for display are stripped; trace events use raw names)."""
        return {op.name.rsplit("/", 1)[-1]: op.bucket for op in self.ops}


def _bucket_container(instr: dict, child_costs: List[OpCost]) -> str:
    """A fusion/call's bucket: its own metadata first (XLA stamps the
    root op's path there), else the dominant-FLOPs child, else the
    dominant-bytes child."""
    own = categorize_op(instr["opcode"], instr["op_name"])
    if own != "other":
        return own
    if child_costs:
        best = max(child_costs, key=lambda c: (c.flops, c.bytes))
        if best.flops > 0 or best.bytes > 0:
            return best.bucket
    return "other"


def _walk_computation(comps, name, out: List[OpCost], seen: set,
                      label_prefix: str = "") -> Tuple[float, int]:
    """Collect entry-reachable op costs; returns (flops, bytes) of the
    computation for container accounting.  Containers:

    - ``fusion``/``call``: ONE OpCost — FLOPs summed over the interior,
      bytes = the boundary shapes only (the interior never touches
      HBM: that is the point of fusing).
    - ``while``/``conditional``: the body's ops appended individually
      (each interior fusion is its own HBM round-trip).  Bodies count
      ONCE — trip counts are not in the text, and attribution consumes
      relative shares, which a homogeneous loop body preserves.
    """
    from apex_tpu.analysis import hlo as H

    if name in seen or name not in comps:
        return 0.0, 0
    seen = seen | {name}
    flops_total, bytes_total = 0.0, 0
    for instr in comps[name]:
        opcode = instr["opcode"]
        if opcode in ("fusion", "call"):
            sub: List[OpCost] = []
            f = 0.0
            for called in instr["called"]:
                cf, _cb = _walk_computation(
                    comps, called, sub, seen, label_prefix
                )
                f += cf
            # interior ops collapse into the one fused kernel
            boundary = H.instruction_bytes(instr)
            cost = OpCost(
                label_prefix + instr["name"], opcode, instr["op_name"],
                _bucket_container(instr, sub), f, boundary,
            )
            out.append(cost)
            flops_total += f
            bytes_total += boundary
            continue
        if opcode in ("while", "conditional"):
            for called in instr["called"]:
                cf, cb = _walk_computation(
                    comps, called, out, seen,
                    label_prefix + instr["name"] + "/",
                )
                flops_total += cf
                bytes_total += cb
            continue
        if opcode.endswith("-done"):
            continue  # async pairs cost once, at -start
        f = H.instruction_flops(instr)
        b = H.instruction_bytes(instr)
        if opcode.startswith(tuple(H.COLLECTIVE_KINDS)):
            # result shape only (the wire payload); -start tuples keep
            # the result element, matching collective_summary
            shape = instr["shape"]
            if opcode.endswith("-start"):
                shape = H.async_start_result(shape)
            b = H.shape_bytes(shape)
        if f == 0.0 and b == 0:
            continue  # parameters/constants/bookkeeping: invisible
        out.append(OpCost(
            label_prefix + instr["name"], opcode, instr["op_name"],
            categorize_op(opcode, instr["op_name"]), f, b,
        ))
        flops_total += f
        bytes_total += b
    return flops_total, bytes_total


def attribute_cost_model(
    hlo_texts,
    *,
    device_kind: Optional[str] = None,
    peak_flops: Optional[float] = None,
    hbm_bw: Optional[float] = None,
    ici_bw: Optional[float] = None,
) -> CostAttribution:
    """Bucketed cost attribution of one or more optimized-HLO texts
    (pass every program a step dispatches — e.g. the resilient
    example's ``compute_grads`` + ``apply_update`` — and their costs
    merge into one step model).  Peaks default from the
    :mod:`~apex_tpu.observability.meter` table for ``device_kind``
    (default: the first visible device)."""
    from apex_tpu.analysis import hlo as H

    if isinstance(hlo_texts, str):
        hlo_texts = [hlo_texts]
    if device_kind is None:
        import jax

        device_kind = getattr(jax.devices()[0], "device_kind", "")
    peak_flops = peak_flops or peak_flops_for(device_kind)
    hbm_bw = hbm_bw or peak_hbm_bandwidth_for(device_kind)
    ici_bw = ici_bw or peak_ici_bandwidth_for(device_kind)

    ops: List[OpCost] = []
    for i, text in enumerate(hlo_texts):
        comps, entry = H.parse_computations(text)
        if entry is None:
            continue
        prefix = f"p{i}/" if len(hlo_texts) > 1 else ""
        _walk_computation(comps, entry, ops, set(), prefix)
    return CostAttribution(ops, peak_flops, hbm_bw, ici_bw)


def hlo_bucket_map(hlo_texts) -> Dict[str, str]:
    """Instruction name → bucket straight from HLO text(s) — for
    callers that only hold the text (``tools/trace_summary.py
    --attribution --hlo``).  Callers that already paid
    :func:`attribute_cost_model` should use
    :meth:`CostAttribution.bucket_map` instead of re-parsing."""
    return attribute_cost_model(
        hlo_texts, device_kind="", peak_flops=1.0, hbm_bw=1.0, ici_bw=1.0
    ).bucket_map()


# ---------------------------------------------------------------------------
# source (b): the measured profiler trace
# ---------------------------------------------------------------------------

#: trace-event names that wrap whole regions (counting them would
#: double-count every child) — same exclusions tools/trace_summary.py
#: applies
_WRAPPER_PREFIXES = ("while", "jit_", "body", "condition", "region")

#: an HLO-instruction-shaped event name: "dot.4", "fusion.123",
#: "tanh.5.clone", "all-reduce-start.1", or a bare opcode like
#: "reduce-window"
_OP_EVENT_RE = re.compile(r"^[A-Za-z][\w-]*(\.\d+)+(\.clone)?$|^[a-z][a-z-]+$")

#: bookkeeping/event names on op-bearing threads that are NOT ops
_NON_OP_NAMES = (
    "ThreadpoolListener", "ThunkExecutor", "TfrtCpu", "ParseArguments",
    "Await", "start_trace", "stop_trace", "Execute", "callback",
)

#: spans that mark "the executable was running" when no per-op events
#: exist at all (last-resort busy signal; buckets then come from the
#: cost model's weights)
_EXECUTOR_NAMES = (
    "TfrtCpuExecutable::Execute", "ThunkExecutor::Execute", "ExecuteHelper",
)


class TraceAttribution:
    """Measured per-bucket time + host-stall from trace-event JSON.

    ``bucket_ms`` sums op durations per bucket (parallel tracks may
    overlap, so the sum can exceed wall coverage — fractions normalize
    by share, not by wall).  ``span_ms`` is first-op-start to
    last-op-end; ``stall_ms`` is the part of the span no op interval
    covers (merged-union gaps): dispatch latency, host sync points,
    input waits — the time the program paid that no kernel explains.
    """

    def __init__(self, bucket_ms: Dict[str, float], span_ms: float,
                 covered_ms: float, events: int,
                 source: str = "device-ops"):
        self.bucket_ms = {b: bucket_ms.get(b, 0.0) for b in BUCKETS}
        self.span_ms = span_ms
        self.covered_ms = min(covered_ms, span_ms) if span_ms > 0 else 0.0
        self.events = events
        self.source = source

    @property
    def busy_ms(self) -> float:
        return sum(self.bucket_ms.values())

    @property
    def stall_ms(self) -> float:
        return max(0.0, self.span_ms - self.covered_ms)

    def bucket_fractions(self) -> Dict[str, float]:
        """Each bucket's share of measured busy time."""
        busy = self.busy_ms
        if busy <= 0:
            return {b: 0.0 for b in BUCKETS}
        return {b: t / busy for b, t in self.bucket_ms.items()}

    def fractions(self) -> Dict[str, float]:
        """compute / collective / host_stall, summing to 1.0: the stall
        share is measured from coverage gaps, and the busy remainder
        splits across buckets by their share of summed op time."""
        if self.span_ms <= 0:
            return {"compute": 0.0, "collective": 0.0, "host_stall": 0.0}
        stall = self.stall_ms / self.span_ms
        shares = self.bucket_fractions()
        coll = shares.get("collective", 0.0) * (1.0 - stall)
        return {
            "compute": max(0.0, 1.0 - stall - coll),
            "collective": coll,
            "host_stall": stall,
        }

    def bucket_time_fractions(self) -> Dict[str, float]:
        """Per-bucket share of the SPAN (busy shares scaled by
        1 − stall) — what the roofline uses to turn a measured step
        time into per-bucket seconds."""
        fr = self.fractions()
        busy_share = 1.0 - fr["host_stall"]
        return {
            b: s * busy_share for b, s in self.bucket_fractions().items()
        }


def _merged_coverage(intervals: List[Tuple[float, float]]) -> float:
    """Total length of the union of [start, end) intervals."""
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    total += cur_e - cur_s
    return total


def _event_is_op(name: str, hlo_map: Optional[Mapping[str, str]]) -> bool:
    if not name or name[0] in "$<" or " " in name or "::" in name:
        return False
    if name.startswith(_WRAPPER_PREFIXES) or name.isdigit():
        return False
    if any(t in name for t in _NON_OP_NAMES):
        return False
    base = name[:-6] if name.endswith(".clone") else name
    if hlo_map and (name in hlo_map or base in hlo_map):
        return True
    return bool(_OP_EVENT_RE.match(name))


def _bucket_event(name: str, hlo_map: Optional[Mapping[str, str]]) -> str:
    if hlo_map:
        hit = hlo_map.get(name) or hlo_map.get(
            name[:-6] if name.endswith(".clone") else name
        )
        if hit:
            return hit
    # heuristic: the leading token is the opcode ("dot.4"), and fused
    # kernel names carry their content ("add_multiply_fusion.78")
    lead = re.split(r"[._]", name, 1)[0]
    return categorize_op(lead, name)


def _select_op_events(
    trace: Mapping, hlo_map: Optional[Mapping[str, str]]
) -> Tuple[List[dict], str]:
    """The shared event-selection pass, in preference order:

    1. per-op events on device "XLA Ops" tracks (TPU/GPU profiles);
    2. per-op events anywhere (the CPU thunk runtime names its spans by
       HLO instruction — ``dot.4``, ``tanh.5.clone``), filtered by
       ``hlo_map`` membership or the instruction-name shape;
    3. bare executor spans (no per-op names at all).
    """
    events = trace.get("traceEvents", [])
    pnames: Dict[int, str] = {}
    tnames: Dict[Tuple[int, Optional[int]], str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pnames[e["pid"]] = e["args"].get("name", "")
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tnames[(e["pid"], e.get("tid"))] = e["args"].get("name", "")
    device_pids = {
        pid for pid, name in pnames.items()
        if "TPU" in name or "GPU" in name or "device" in name.lower()
    }
    op_tids = {
        key for key, name in tnames.items()
        if key[0] in device_pids and "Ops" in name
    }

    def _select(pred):
        out = []
        for e in events:
            if e.get("ph") != "X" or not e.get("dur"):
                continue
            if not pred(e):
                continue
            out.append(e)
        return out

    selected = _select(
        lambda e: (e.get("pid"), e.get("tid")) in op_tids
        and _event_is_op(e.get("name", ""), hlo_map)
    ) if op_tids else []
    if selected:
        return selected, "device-ops"
    selected = _select(lambda e: _event_is_op(e.get("name", ""), hlo_map))
    if selected:
        return selected, "thunk-spans"
    return _select(
        lambda e: any(x in e.get("name", "") for x in _EXECUTOR_NAMES)
    ), "executor-spans"


def trace_step_period(
    trace: Mapping, *, hlo_map: Optional[Mapping[str, str]] = None
) -> float:
    """Robust per-step seconds measured from the TRACE's own clock.

    A profiled loop dispatches the same program every step, so every
    instruction's events recur once per step: the median period between
    consecutive occurrences of the same op name IS the step time —
    immune to the host clock, and (being a median over every op's every
    period) to one-off anomalies like the profiler's first-capture
    overhead.  Returns 0.0 when no op recurs (a single-step window)."""
    selected, _src = _select_op_events(trace, hlo_map)
    by_name: Dict[str, List[float]] = {}
    for e in selected:
        by_name.setdefault(e.get("name", ""), []).append(
            float(e.get("ts", 0.0))
        )
    periods: List[float] = []
    for times in by_name.values():
        if len(times) < 2:
            continue
        times.sort()
        periods.extend(b - a for a, b in zip(times, times[1:]))
    if not periods:
        return 0.0
    periods.sort()
    return periods[len(periods) // 2] / 1e6  # us -> s


def attribute_trace(
    trace: Mapping,
    *,
    hlo_map: Optional[Mapping[str, str]] = None,
    cost_weights: Optional[Mapping[str, float]] = None,
) -> TraceAttribution:
    """Bucketed time attribution of one loaded trace-event JSON dict.

    Event selection: :func:`_select_op_events` (device "XLA Ops"
    tracks, then CPU per-thunk spans, then bare executor spans).  In
    the executor-span fallback busy/stall is still measured and the
    busy split falls back to ``cost_weights`` (the cost model's bucket
    shares) — pass them whenever available so the degraded mode stays
    attributed.
    """
    selected, source = _select_op_events(trace, hlo_map)
    bucket_ms: Dict[str, float] = {b: 0.0 for b in BUCKETS}
    intervals: List[Tuple[float, float]] = []
    tmin, tmax = float("inf"), float("-inf")
    for e in selected:
        ts, dur = float(e.get("ts", 0.0)), float(e.get("dur", 0.0))
        intervals.append((ts, ts + dur))
        tmin, tmax = min(tmin, ts), max(tmax, ts + dur)
        if source == "executor-spans":
            continue  # bucketed below from cost weights
        bucket_ms[_bucket_event(e.get("name", ""), hlo_map)] += dur / 1e3

    span_ms = (tmax - tmin) / 1e3 if tmax > tmin else 0.0
    covered_ms = _merged_coverage(intervals) / 1e3
    if source == "executor-spans" and covered_ms > 0:
        weights = dict(cost_weights or {"other": 1.0})
        wsum = sum(weights.values()) or 1.0
        for b in BUCKETS:
            bucket_ms[b] = covered_ms * weights.get(b, 0.0) / wsum
    return TraceAttribution(
        bucket_ms, span_ms, covered_ms, len(selected), source
    )


def load_trace_dir(log_dir: str) -> dict:
    """Newest ``*.trace.json.gz`` under a profile dir, parsed."""
    paths = glob.glob(
        os.path.join(log_dir, "**", "*.trace.json.gz"), recursive=True
    )
    if not paths:
        raise FileNotFoundError(f"no *.trace.json.gz under {log_dir}")
    with gzip.open(max(paths, key=os.path.getmtime), "rt") as f:
        return json.load(f)


def attribute_trace_dir(log_dir: str, **kwargs) -> TraceAttribution:
    """:func:`attribute_trace` over the newest capture in a profile
    dir (a TraceScheduler window dir or a ``--trace`` dir)."""
    return attribute_trace(load_trace_dir(log_dir), **kwargs)


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------


class RooflineRow(NamedTuple):
    bucket: str
    flops: float
    bytes: float
    time_ms: float
    achieved_tflops: float  # flops / time
    pct_peak: float  # achieved / peak
    intensity: float  # flops / byte
    bound: str  # "compute" | "bandwidth" | "comm" | "-"


def roofline_report(
    cost: CostAttribution,
    *,
    step_time_s: float,
    measured: Optional[TraceAttribution] = None,
) -> List[RooflineRow]:
    """Per-bucket roofline rows + a ``total`` row whose ``pct_peak`` is
    the step's MFU on the SAME peak table as
    :class:`~apex_tpu.observability.meter.StepMeter` (one denominator,
    by construction).  Bucket times come from the measured trace's
    shares of ``step_time_s`` when available, else from the cost
    model's estimated shares; FLOPs/bytes always come from the cost
    model (the trace cannot count them)."""
    ridge = cost.peak_flops / cost.hbm_bw  # FLOP/byte at the roof corner
    shares = (
        measured.bucket_time_fractions()
        if measured is not None and measured.busy_ms > 0
        else cost.bucket_fractions()
    )
    rows: List[RooflineRow] = []
    for b in BUCKETS:
        f = cost.buckets[b]["flops"]
        by = cost.buckets[b]["bytes"]
        t = shares.get(b, 0.0) * step_time_s
        if f == 0 and by == 0 and t == 0:
            continue
        ai = f / by if by else 0.0
        if b == "collective":
            bound = "comm"
        elif f == 0:
            bound = "bandwidth"
        else:
            bound = "compute" if ai >= ridge else "bandwidth"
        achieved = f / t if t > 0 else 0.0
        rows.append(RooflineRow(
            b, f, by, t * 1e3, achieved / 1e12,
            achieved / cost.peak_flops, ai, bound,
        ))
    total_t = step_time_s
    achieved = cost.total_flops / total_t if total_t > 0 else 0.0
    rows.append(RooflineRow(
        "total", cost.total_flops, cost.total_bytes, total_t * 1e3,
        achieved / 1e12, achieved / cost.peak_flops,
        cost.total_flops / cost.total_bytes if cost.total_bytes else 0.0,
        "-",
    ))
    return rows


def render_roofline(rows: Sequence[RooflineRow]) -> str:
    """The terminal table (ridge/bound verdicts inline)."""
    out = [
        f"{'bucket':<18} {'GFLOP':>10} {'MiB':>9} {'time_ms':>9} "
        f"{'TFLOP/s':>9} {'%peak':>7} {'FLOP/B':>8}  bound"
    ]
    for r in rows:
        out.append(
            f"{r.bucket:<18} {r.flops / 1e9:>10.2f} "
            f"{r.bytes / 2**20:>9.1f} {r.time_ms:>9.3f} "
            f"{r.achieved_tflops:>9.3f} {100 * r.pct_peak:>6.2f}% "
            f"{r.intensity:>8.1f}  {r.bound}"
        )
    return "\n".join(out)


# ---------------------------------------------------------------------------
# publication (board + Reporter sinks) — what the watchdog rules read
# ---------------------------------------------------------------------------


def publish_attribution(
    attr,
    *,
    reporter=None,
    step: int = 0,
    prefix: str = "attribution",
) -> Dict[str, float]:
    """Land an attribution's fractions on the observability board
    (``attribution/<key>_fraction``, ``attribution/bucket/<name>``) and
    — when a :class:`~apex_tpu.observability.export.Reporter` is
    passed — as bench-schema lines on its sinks.  Returns the
    fraction dict.  :class:`~apex_tpu.observability.health.
    CollectiveFractionRule` / ``HostStallRule`` read these keys."""
    from apex_tpu.observability.metrics import board

    fractions = attr.fractions() if hasattr(attr, "fractions") else dict(attr)
    records = {}
    for key in FRACTION_KEYS:
        val = float(fractions.get(key, 0.0))
        board.set(f"{prefix}/{key}_fraction", val)
        records[f"{prefix}/{key}_fraction"] = val
    if hasattr(attr, "bucket_fractions"):
        for b, share in attr.bucket_fractions().items():
            board.set(f"{prefix}/bucket/{b}", float(share))
            records[f"{prefix}/bucket/{b}"] = float(share)
    if reporter is not None:
        from apex_tpu.observability.export import bench_record

        for name, val in records.items():
            rec = bench_record(
                name, val, "fraction of step time", None, step=int(step)
            )
            for sink in reporter.sinks:
                sink.write(rec)
    return {k: records[f"{prefix}/{k}_fraction"] for k in FRACTION_KEYS}
