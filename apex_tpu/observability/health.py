"""Fleet health watchdog — declarative rules over live telemetry.

A production fleet does not fail loudly: it gets *slow* (one straggling
host gates every collective), *wasteful* (goodput decays as a flaky
guard skips), or *stale* (a hung collective stops the metric fetch
pipeline while the process looks alive).  :class:`Watchdog` rides the
``run_resilient`` observer protocol and evaluates a small declarative
rule set on a check cadence:

========================  =================================================
rule                      fires when
========================  =================================================
:class:`StragglerRule`    a host's step time z-scores above the fleet
                          (needs a :class:`~apex_tpu.observability.fleet.
                          FleetAggregator` view)
:class:`MFUFloorRule`     the live MFU sinks under a floor after warmup
:class:`GoodputFloorRule` the goodput fraction sinks under a floor
:class:`LossSpikeRule`    the fetched loss goes non-finite (critical) or
                          spikes over ``factor`` x its own EMA
:class:`NaNRateRule`      the skip rate over a sliding window exceeds a
                          budget (a NaN *storm*, not one bad batch)
:class:`StaleFetchRule`   the registry's fetched values fall further
                          behind the live step than the cadence explains
:class:`HungStepRule`     a step interval exceeds a wall-clock deadline
                          (a hung/slow collective that eventually
                          completed); :meth:`Watchdog.poll` covers the
                          still-hung case from an external thread
:class:`CollectiveFractionRule` the step-time attribution's collective
                          share exceeds a floor (comm-bound: the next
                          lever is wire format/overlap, not kernels)
:class:`HostStallRule`    the attribution's host-stall share exceeds a
                          floor (the chip is starving, not slow)
:class:`MemoryBudgetRule` the graph linter's static peak-HBM estimate
                          (``analysis/peak_hbm_bytes``) crosses the
                          deployment budget — opt-in (needs the budget)
:class:`TTFTRule`         serving time-to-first-token over its SLO
                          deadline (``serve/ttft_ms`` gauge; critical
                          past 2x) — :func:`serve_rules` only
:class:`QueueDepthRule`   the serving admission queue backs up past a
                          depth budget (``serve/queue_depth``) —
                          :func:`serve_rules` only
:class:`QueueWaitFractionRule` the TTFT attribution's queue-wait share
                          exceeds a budget (admission starved —
                          ``serve/ttft_queue_wait_fraction``) —
                          :func:`serve_rules` only
:class:`ServeFaultRule`   the serving failure ledger moved — engine
                          faults/rebuilds, poisoned quarantines
                          (critical), decode timeouts, exhausted
                          retries — :func:`serve_rules` only
========================  =================================================

Training loops use :func:`default_rules`; the serving path
(:mod:`apex_tpu.serve`) uses :func:`serve_rules` — TTFT/queue-depth
plus the substrate rules (stale fetch, hung step) — so tail-latency
regressions page the SAME health layer training uses
(``docs/serving.md``).

The fleet control plane and its canary-gated deploys emit events
through the same type without a rule class: ``fleet_*`` events come
straight from :class:`~apex_tpu.fleetctl.Fleet` (crash/preempt/eject/
scale/deploy), and the canary gate adds ``fleet_canary_fingerprint``
(old→new probe distance on a weight swap), ``fleet_canary_verdict``
(the pass/fail drift verdict — critical on fail, which also triggers
``fleet_deploy_rollback``), and ``fleet_canary_inconclusive`` (window
expired under the min-sample floor; the deploy proceeds UNPROVEN).
See :mod:`apex_tpu.observability.canary`.

The two fraction rules read the step-time attribution published by
:func:`~apex_tpu.observability.attribution.publish_attribution` —
either an object handed to ``Watchdog(attribution=...)`` or the board
keys ``attribution/collective_fraction`` /
``attribution/host_stall_fraction`` (how ``tools/step_profile.py`` and
the resilient example feed them).

Every firing emits a structured :class:`HealthEvent` to: the watchdog's
``events`` ledger, the observability board (``health/<rule>``), the
Reporter sinks (bench-schema lines with ``severity``/``message``/
``host`` extras), the flight recorder's event log, the span
recorder's health track (``Watchdog(spans=...)`` — the alert lands on
the merged timeline next to the spans that explain it), and the
``on_unhealthy`` callback — which is the escalation hook: pass a
callback that arms a :class:`~apex_tpu.observability.trace.
TraceScheduler` window and an alert turns into an on-chip profile in
the same run.  See ``docs/observability.md``.
"""

from __future__ import annotations

import collections
import time
import warnings
from typing import Any, Callable, Dict, List, NamedTuple, Optional

__all__ = [
    "HealthEvent",
    "Rule",
    "StragglerRule",
    "MFUFloorRule",
    "GoodputFloorRule",
    "LossSpikeRule",
    "NaNRateRule",
    "StaleFetchRule",
    "HungStepRule",
    "CollectiveFractionRule",
    "HostStallRule",
    "MemoryBudgetRule",
    "CheckpointStallRule",
    "InputStallRule",
    "TTFTRule",
    "QueueDepthRule",
    "QueueWaitFractionRule",
    "SpecAcceptanceRule",
    "ServeFaultRule",
    "default_rules",
    "goodput_rules",
    "serve_rules",
    "Watchdog",
]


class HealthEvent(NamedTuple):
    """One structured health finding."""

    rule: str  # e.g. "straggler", "mfu_floor"
    severity: str  # "warn" | "critical"
    step: int
    value: float  # the measurement that tripped the rule
    threshold: float  # what it was compared against
    message: str
    host: Optional[int] = None  # per-host rules name the offender

    def as_record(self) -> Dict[str, Any]:
        """Extras for a bench-schema Reporter line."""
        rec = {
            "severity": self.severity,
            "threshold": self.threshold,
            "message": self.message,
        }
        if self.host is not None:
            rec["host"] = self.host
        return rec


class Rule:
    """Base: named check + a repeat cooldown (steps) so a persistent
    condition emits on a heartbeat, not every check."""

    name = "rule"
    severity = "warn"

    def __init__(self, cooldown: int = 64):
        self.cooldown = cooldown
        self._last_fired: Optional[int] = None

    def check(self, wd: "Watchdog", step: int) -> List[HealthEvent]:
        if (
            self._last_fired is not None
            and step - self._last_fired < self.cooldown
        ):
            return []
        events = self.evaluate(wd, step)
        if events:
            self._last_fired = step
        return events

    def evaluate(self, wd: "Watchdog", step: int) -> List[HealthEvent]:
        raise NotImplementedError

    def _event(self, step, value, threshold, message, host=None):
        return [
            HealthEvent(
                self.name, self.severity, int(step), float(value),
                float(threshold), message, host,
            )
        ]


class StragglerRule(Rule):
    """A host whose step time z-scores above the rest of the fleet.

    Leave-one-out: each host is scored against the mean/std of the
    OTHER hosts — a pooled std would let one extreme outlier inflate
    the denominator and hide itself (one 4x straggler among 8 hosts
    pools to z≈2.7).  ``std`` is floored at ``rel_floor * mean`` so a
    fleet in lockstep (std ~ 0) does not turn micro-jitter into
    alerts.
    """

    name = "straggler"

    def __init__(self, zmax: float = 3.0, key: str = "train/step_time_ms",
                 rel_floor: float = 0.05, min_hosts: int = 2,
                 cooldown: int = 64):
        super().__init__(cooldown)
        self.zmax = zmax
        self.key = key
        self.rel_floor = rel_floor
        self.min_hosts = min_hosts

    def evaluate(self, wd, step):
        view = wd.fleet_view
        if view is None or view.hosts < self.min_hosts:
            return []
        if self.key not in view.names:
            return []
        vals = view.per_host(self.key)
        labels = view.labels
        events = []
        for row, v in enumerate(vals):
            if v != v:
                continue
            others = [o for j, o in enumerate(vals) if j != row and o == o]
            if len(others) < self.min_hosts - 1:
                continue
            mean = sum(others) / len(others)
            var = sum((o - mean) ** 2 for o in others) / len(others)
            std = max(var ** 0.5, self.rel_floor * abs(mean), 1e-12)
            z = (v - mean) / std
            if z > self.zmax:
                host = labels[row]
                events.extend(
                    self._event(
                        step, v, mean + self.zmax * std,
                        f"host {host} straggling: {self.key}={v:.3f} "
                        f"(fleet mean {mean:.3f}, z={z:.1f})",
                        host=host,
                    )
                )
        return events


class MFUFloorRule(Rule):
    """Live MFU under a floor once the meter window has warmed up."""

    name = "mfu_floor"

    def __init__(self, floor: float = 0.05, warmup_steps: int = 16,
                 cooldown: int = 64):
        super().__init__(cooldown)
        self.floor = floor
        self.warmup_steps = warmup_steps

    def evaluate(self, wd, step):
        meter = wd.meter
        if meter is None or meter.flops_per_step <= 0:
            return []
        if meter.steps < self.warmup_steps:
            return []
        mfu = meter.mfu
        if 0.0 < mfu < self.floor:
            return self._event(
                step, mfu, self.floor,
                f"MFU {mfu:.4f} under floor {self.floor:.4f}",
            )
        return []


class GoodputFloorRule(Rule):
    """Productive fraction under a floor after enough executed steps."""

    name = "goodput_floor"

    def __init__(self, floor: float = 0.5, min_executed: int = 20,
                 cooldown: int = 64):
        super().__init__(cooldown)
        self.floor = floor
        self.min_executed = min_executed

    def evaluate(self, wd, step):
        acct = wd.goodput
        if acct is None or acct.executed < self.min_executed:
            return []
        g = acct.goodput()
        if g < self.floor:
            return self._event(
                step, g, self.floor,
                f"goodput {g:.3f} under floor {self.floor:.3f} "
                f"(skipped={acct.skipped}, discarded={acct.discarded})",
            )
        return []


class LossSpikeRule(Rule):
    """Fetched loss non-finite (critical) or > ``factor`` x its EMA.

    The EMA folds each *newly fetched* loss value (tracked via the
    registry's ``fetched_step``), so the stale reads between cadences
    neither re-trigger nor re-teach the baseline.
    """

    name = "loss_spike"

    def __init__(self, key: str = "train/loss", factor: float = 10.0,
                 ema_beta: float = 0.9, warmup_fetches: int = 3,
                 cooldown: int = 64):
        super().__init__(cooldown)
        self.key = key
        self.factor = factor
        self.ema_beta = ema_beta
        self.warmup_fetches = warmup_fetches
        self._ema: Optional[float] = None
        self._fetches = 0
        self._last_fetched: Optional[int] = None

    def evaluate(self, wd, step):
        reg = wd.registry
        if reg is None:
            return []
        fetched = reg.fetched_step
        if fetched is None or fetched == self._last_fetched:
            return []
        value = reg.values().get(self.key)
        if value is None:
            return []
        self._last_fetched = fetched
        if value != value or value in (float("inf"), float("-inf")):
            return [
                HealthEvent(
                    self.name, "critical", int(step), float("nan"),
                    0.0, f"{self.key} non-finite at fetch {fetched}",
                )
            ]
        events = []
        if (
            self._ema is not None
            and self._fetches >= self.warmup_fetches
            and value > self.factor * self._ema
        ):
            events = self._event(
                step, value, self.factor * self._ema,
                f"{self.key}={value:.4g} spiked over {self.factor}x "
                f"EMA {self._ema:.4g}",
            )
            # a spike must not re-teach the baseline
            return events
        self._ema = (
            value if self._ema is None
            else self.ema_beta * self._ema + (1 - self.ema_beta) * value
        )
        self._fetches += 1
        return events


class NaNRateRule(Rule):
    """Skip *rate* over a sliding window — a storm, not one bad batch."""

    name = "nan_rate"

    def __init__(self, max_rate: float = 0.25, window: int = 16,
                 cooldown: int = 64):
        super().__init__(cooldown)
        self.max_rate = max_rate
        self.window = window

    def evaluate(self, wd, step):
        skips = wd.skip_window
        if len(skips) < self.window:
            return []
        recent = list(skips)[-self.window:]
        rate = sum(recent) / len(recent)
        if rate > self.max_rate:
            return self._event(
                step, rate, self.max_rate,
                f"skip rate {rate:.2f} over last {self.window} steps "
                f"exceeds {self.max_rate:.2f}",
            )
        return []


class StaleFetchRule(Rule):
    """The metric fetch pipeline wedged: fetched values lag the live
    step beyond what the double-buffered cadence explains (default
    budget: ``4 * fetch_every``)."""

    name = "stale_fetch"

    def __init__(self, max_age_steps: Optional[int] = None,
                 cooldown: int = 64):
        super().__init__(cooldown)
        self.max_age_steps = max_age_steps

    def evaluate(self, wd, step):
        reg = wd.registry
        if reg is None:
            return []
        budget = (
            self.max_age_steps
            if self.max_age_steps is not None
            else 4 * reg.fetch_every
        )
        fetched = reg.fetched_step
        age = step - (fetched if fetched is not None else wd.first_step)
        if age > budget:
            return self._event(
                step, age, budget,
                f"metric fetch {age} steps stale (budget {budget}; "
                f"fetched_step={fetched})",
            )
        return []


class HungStepRule(Rule):
    """A step interval blew through a wall-clock deadline — the shape
    of a hung collective or a wedged host that eventually recovered.
    For a step that never completes, call :meth:`Watchdog.poll` from
    outside the loop (another thread, a signal handler)."""

    name = "hung_step"
    severity = "critical"

    def __init__(self, deadline_s: float = 300.0, cooldown: int = 1):
        super().__init__(cooldown)
        self.deadline_s = deadline_s

    def evaluate(self, wd, step):
        dt = wd.last_step_seconds
        if dt is not None and dt > self.deadline_s:
            return self._event(
                step, dt, self.deadline_s,
                f"step took {dt:.1f}s (deadline {self.deadline_s:.0f}s)",
            )
        return []


class _AttributionFractionRule(Rule):
    """Base for rules over the step-time attribution fractions
    (:mod:`apex_tpu.observability.attribution`).  The fraction comes
    from ``Watchdog(attribution=...)`` — an object with
    ``fractions()`` or a plain mapping — or, failing that, the board
    key ``attribution/<key>_fraction`` that
    :func:`~apex_tpu.observability.attribution.publish_attribution`
    sets.  No attribution anywhere → the rule is silent (it cannot
    invent a decomposition)."""

    key = "collective"

    def __init__(self, max_fraction: float, cooldown: int = 64):
        super().__init__(cooldown)
        self.max_fraction = max_fraction

    def _fraction(self, wd) -> Optional[float]:
        src = getattr(wd, "attribution", None)
        if src is not None:
            fr = src.fractions() if hasattr(src, "fractions") else src
            val = fr.get(self.key)
            return float(val) if val is not None else None
        from apex_tpu.observability.metrics import board

        val = board.get(f"attribution/{self.key}_fraction")
        return float(val) if val is not None else None

    def evaluate(self, wd, step):
        frac = self._fraction(wd)
        if frac is not None and frac > self.max_fraction:
            return self._event(
                step, frac, self.max_fraction,
                f"{self.key} fraction {frac:.3f} of step time exceeds "
                f"{self.max_fraction:.3f} ({self.diagnosis})",
            )
        return []


class CollectiveFractionRule(_AttributionFractionRule):
    """Comm share of the step over a floor — the step is comm-bound:
    tune wire formats / chunked overlap (docs/comm.md) before
    kernels."""

    name = "collective_fraction"
    key = "collective"
    diagnosis = "comm-bound: next lever is wire format/overlap"

    def __init__(self, max_fraction: float = 0.35, cooldown: int = 64):
        super().__init__(max_fraction, cooldown)


class HostStallRule(_AttributionFractionRule):
    """Host-stall share of the step over a floor — the chip is
    starving (dispatch latency, blocked fetches, input waits), not
    slow; faster kernels cannot help."""

    name = "host_stall"
    key = "host_stall"
    diagnosis = "chip starving: dispatch/input path, not kernels"

    def __init__(self, max_fraction: float = 0.15, cooldown: int = 64):
        super().__init__(max_fraction, cooldown)


class TTFTRule(Rule):
    """Serving time-to-first-token over its deadline — tail latency is
    regressing at the front door.  Reads the ``serve/ttft_ms`` gauge
    the :class:`apex_tpu.serve.scheduler.ContinuousBatchingScheduler`
    publishes on every admission; like :class:`LossSpikeRule`, only a
    freshly fetched value is judged (stale reads between cadences
    neither re-trigger nor mask).  Critical at ``critical_factor`` x
    the deadline."""

    name = "ttft"

    def __init__(self, deadline_ms: float = 1000.0,
                 key: str = "serve/ttft_ms",
                 critical_factor: float = 2.0, cooldown: int = 64):
        super().__init__(cooldown)
        self.deadline_ms = deadline_ms
        self.key = key
        self.critical_factor = critical_factor
        self._last_fetched: Optional[int] = None

    def evaluate(self, wd, step):
        reg = wd.registry
        if reg is None:
            return []
        fetched = reg.fetched_step
        if fetched is None or fetched == self._last_fetched:
            return []
        value = reg.values().get(self.key)
        if value is None:
            return []
        self._last_fetched = fetched
        if value > self.deadline_ms:
            severity = (
                "critical"
                if value > self.critical_factor * self.deadline_ms
                else "warn"
            )
            return [
                HealthEvent(
                    self.name, severity, int(step), float(value),
                    float(self.deadline_ms),
                    f"TTFT {value:.1f}ms over deadline "
                    f"{self.deadline_ms:.0f}ms",
                )
            ]
        return []


class QueueDepthRule(Rule):
    """The serving admission queue backing up past a depth budget —
    arrivals outpace capacity and TTFT is about to follow.  Reads the
    ``serve/queue_depth`` gauge; sustained depth re-emits on the
    cooldown heartbeat like every rule."""

    name = "queue_depth"

    def __init__(self, max_depth: int = 16,
                 key: str = "serve/queue_depth", cooldown: int = 64):
        super().__init__(cooldown)
        self.max_depth = max_depth
        self.key = key

    def evaluate(self, wd, step):
        reg = wd.registry
        if reg is None:
            return []
        value = reg.values().get(self.key)
        if value is None:
            return []
        if value > self.max_depth:
            return self._event(
                step, value, self.max_depth,
                f"admission queue depth {value:.0f} over budget "
                f"{self.max_depth} (arrivals outpacing decode capacity)",
            )
        return []


class QueueWaitFractionRule(Rule):
    """TTFT dominated by **queue wait** — admission is starved (slots
    or pages), not the prefill program: adding compute to the decode
    path cannot help; the levers are pool size, batch slots, and
    shedding policy.  Reads the ``serve/ttft_queue_wait_fraction``
    gauge the scheduler's TTFT attribution publishes over its recent
    completion window (``docs/observability.md`` "Request tracing &
    timeline"); like :class:`TTFTRule`, only a freshly fetched value is
    judged."""

    name = "queue_wait_fraction"

    def __init__(self, max_fraction: float = 0.5,
                 key: str = "serve/ttft_queue_wait_fraction",
                 cooldown: int = 64):
        super().__init__(cooldown)
        self.max_fraction = max_fraction
        self.key = key
        self._last_fetched: Optional[int] = None

    def evaluate(self, wd, step):
        reg = wd.registry
        if reg is None:
            return []
        fetched = reg.fetched_step
        if fetched is None or fetched == self._last_fetched:
            return []
        value = reg.values().get(self.key)
        if value is None:
            return []
        self._last_fetched = fetched
        if value > self.max_fraction:
            return self._event(
                step, value, self.max_fraction,
                f"queue wait is {value:.0%} of TTFT (budget "
                f"{self.max_fraction:.0%}) — admission starved: grow "
                "the page pool / decode slots or shed earlier",
            )
        return []


class SpecAcceptanceRule(Rule):
    """Speculative-decoding acceptance rate under its floor — the
    draft model has drifted from the target (stale draft weights after
    a redeploy, a poisoned draft cache) and every rejected token is a
    wasted draft step plus a rollback.  Reads the
    ``serve/spec_accept_rate`` gauge the scheduler publishes over its
    acceptance window (``docs/serving.md`` "Speculative decoding");
    the scheduler's own degradation ladder falls back to plain decode
    below ``SpecConfig.min_accept_rate`` — this rule pages BEFORE that
    cliff so an operator can ship a better draft first.  Emits only
    when speculation actually ran (a zero-drafted window publishes
    rate 0.0 — judged only if the ``serve/spec_rounds`` counter is
    nonzero); like :class:`TTFTRule`, only a freshly fetched value is
    judged."""

    name = "spec_acceptance"

    def __init__(self, min_rate: float = 0.5,
                 key: str = "serve/spec_accept_rate",
                 cooldown: int = 64):
        super().__init__(cooldown)
        self.min_rate = min_rate
        self.key = key
        self._last_fetched: Optional[int] = None

    def evaluate(self, wd, step):
        reg = wd.registry
        if reg is None:
            return []
        fetched = reg.fetched_step
        if fetched is None or fetched == self._last_fetched:
            return []
        vals = reg.values()
        value = vals.get(self.key)
        if value is None or not vals.get("serve/spec_rounds"):
            return []
        self._last_fetched = fetched
        if value < self.min_rate:
            return self._event(
                step, value, self.min_rate,
                f"spec acceptance {value:.0%} under floor "
                f"{self.min_rate:.0%} — draft/target drift: redeploy "
                "the draft or lower k before the fallback ladder "
                "disables speculation",
            )
        return []


class ServeFaultRule(Rule):
    """The serving failure ledger moved (docs/serving.md "Failure
    semantics & degradation ladder"): engine faults and supervised
    rebuilds, poisoned-request quarantines, per-request decode
    timeouts, exhausted re-admission retries.  Each watched counter
    that increased since the last fetch emits one event carrying the
    delta — a recovered fault is WORKING AS DESIGNED but must never be
    invisible.  Poisoned quarantines page critical (non-finite logits
    mean numerics corruption upstream of the scheduler); everything
    else warns."""

    name = "serve_faults"

    #: counter -> severity when it moves
    WATCHED = (
        ("serve/engine_faults", "warn"),
        ("serve/engine_rebuilds", "warn"),
        ("serve/shed_poisoned", "critical"),
        ("serve/decode_timeouts", "warn"),
        ("serve/shed_retries_exhausted", "warn"),
        ("serve/admission_faults", "warn"),
        ("serve/kv_alloc_faults", "warn"),
    )

    def __init__(self, cooldown: int = 0):
        super().__init__(cooldown)
        self._last: Dict[str, float] = {}
        self._last_fetched: Optional[int] = None

    def evaluate(self, wd, step):
        reg = wd.registry
        if reg is None:
            return []
        fetched = reg.fetched_step
        if fetched is None or fetched == self._last_fetched:
            return []
        self._last_fetched = fetched
        values = reg.values()
        events = []
        for key, severity in self.WATCHED:
            value = values.get(key)
            if value is None:
                continue
            prev = self._last.get(key, 0.0)
            self._last[key] = float(value)
            delta = float(value) - prev
            if delta <= 0:
                continue
            events.append(
                HealthEvent(
                    self.name, severity, int(step), float(value),
                    prev,
                    f"{key} advanced by {delta:.0f} (now {value:.0f}) — "
                    "a fault was absorbed by the serving recovery "
                    "machinery; check the span timeline for the "
                    "retrying/shed chains",
                )
            )
        return events


class MemoryBudgetRule(Rule):
    """The static peak-HBM estimate published by the graph linter
    (``analysis/peak_hbm_bytes`` — :func:`apex_tpu.analysis.memory
    .publish_peak`, also republished when a program recompiles
    mid-run) crosses the deployment's budget: critical when over it
    (the NEXT recompile OOMs), warn when inside ``warn_fraction`` of
    it (one batch-size bump from the cliff).  Budget-less
    construction is an error — a watchdog cannot guess how much HBM
    the deployment reserved, which is why this rule is opt-in rather
    than in :func:`default_rules`."""

    name = "memory_budget"
    severity = "critical"

    def __init__(self, budget_bytes: int, warn_fraction: float = 0.9,
                 key: str = "analysis/peak_hbm_bytes",
                 cooldown: int = 512):
        if not budget_bytes or budget_bytes <= 0:
            raise ValueError("MemoryBudgetRule needs a positive budget")
        super().__init__(cooldown)
        self.budget_bytes = int(budget_bytes)
        self.warn_fraction = warn_fraction
        self.key = key

    def evaluate(self, wd, step):
        from apex_tpu.observability.metrics import board

        peak = board.get(self.key)
        if peak is None:
            return []
        peak = float(peak)
        mib = 1 << 20
        if peak > self.budget_bytes:
            return self._event(
                step, peak, self.budget_bytes,
                f"static peak HBM {peak / mib:.1f} MiB exceeds the "
                f"{self.budget_bytes / mib:.1f} MiB budget — the next "
                "(re)compile OOMs; see tools/shard_report.py for the "
                "per-buffer attribution",
            )
        if peak > self.warn_fraction * self.budget_bytes:
            ev = self._event(
                step, peak, self.warn_fraction * self.budget_bytes,
                f"static peak HBM {peak / mib:.1f} MiB is inside "
                f"{1 - self.warn_fraction:.0%} of the "
                f"{self.budget_bytes / mib:.1f} MiB budget",
            )
            return [ev[0]._replace(severity="warn")]
        return []


class CheckpointStallRule(Rule):
    """The checkpoint engine's step-path stall fraction
    (``goodput/ckpt/stall_frac``, published by
    :class:`apex_tpu.goodput.AsyncCheckpointEngine` on every save —
    snapshot + enqueue wait over wall time, background write time
    excluded) crosses the overhead budget.  The default 1% is the
    GOODPUT acceptance bar (docs/goodput.md): above it the "zero
    stall" contract is broken — typically the writer falling behind
    the save cadence, so the bounded queue's backpressure has reached
    the step path.  Critical at 2x the budget."""

    name = "ckpt_stall"
    severity = "warn"

    def __init__(self, max_fraction: float = 0.01, cooldown: int = 128):
        super().__init__(cooldown)
        self.max_fraction = max_fraction

    def evaluate(self, wd, step):
        from apex_tpu.observability.metrics import board

        frac = board.get("goodput/ckpt/stall_frac")
        if frac is None or float(frac) <= self.max_fraction:
            return []
        frac = float(frac)
        ev = self._event(
            step, frac, self.max_fraction,
            f"checkpoint stall fraction {frac:.4f} over the "
            f"{self.max_fraction:.2%} budget — the background writer "
            "is not keeping up with the save cadence (backpressure "
            "reached the step path); lengthen save_interval_steps or "
            "speed up storage",
        )
        if frac > 2 * self.max_fraction:
            return [ev[0]._replace(severity="critical")]
        return ev


class InputStallRule(Rule):
    """The input pipeline's stall fraction
    (``data/input_stall_fraction``, published by
    :class:`apex_tpu.data.DevicePrefetcher` — consumer time blocked on
    an empty prefetch queue over wall time) crosses ``max_fraction``:
    the chip is data-starved.  Cross-check against the attribution
    layer's host-stall bucket (``attribution/host_stall_fraction`` — docs/
    observability.md "Attribution & roofline"): input stall without
    host stall means the gap is hidden by dispatch depth; both high
    means the loader genuinely gates the step."""

    name = "input_stall"
    severity = "warn"

    def __init__(self, max_fraction: float = 0.15, cooldown: int = 128):
        super().__init__(cooldown)
        self.max_fraction = max_fraction

    def evaluate(self, wd, step):
        from apex_tpu.observability.metrics import board

        frac = board.get("data/input_stall_fraction")
        if frac is None or float(frac) <= self.max_fraction:
            return []
        frac = float(frac)
        # the key publish_attribution actually writes (attribution.py)
        host_stall = board.get("attribution/host_stall_fraction")
        xref = (
            f" (attribution host-stall bucket reads {float(host_stall):.3f})"
            if host_stall is not None else ""
        )
        return self._event(
            step, frac, self.max_fraction,
            f"input-stall fraction {frac:.3f} over {self.max_fraction:.2f}"
            f" — the step consumer is blocking on the prefetch queue; "
            f"raise the prefetch depth or feed from faster storage{xref}",
        )


def goodput_rules(floor: float = 0.99, **overrides) -> List[Rule]:
    """The preemptible-fleet rule set (docs/goodput.md): the goodput
    floor at the deployment bar (default 99% — the storm-drill
    acceptance number), checkpoint stall over budget, input
    starvation, plus the substrate rules.  Same override convention as
    :func:`default_rules`."""
    specs = {
        "goodput_floor": GoodputFloorRule,
        "ckpt_stall": CheckpointStallRule,
        "input_stall": InputStallRule,
        "stale_fetch": StaleFetchRule,
        "hung_step": HungStepRule,
    }
    unknown = set(overrides) - set(specs)
    if unknown:
        raise ValueError(f"unknown goodput health rules: {sorted(unknown)}")
    # merge, not setdefault: goodput_rules(floor=0.999,
    # goodput_floor={"cooldown": 64}) must keep the explicit floor (an
    # override dict that names "floor" itself still wins)
    overrides["goodput_floor"] = {
        "floor": floor, **overrides.get("goodput_floor", {})
    }
    return [cls(**overrides.get(name, {})) for name, cls in specs.items()]


def serve_rules(**overrides) -> List[Rule]:
    """The serving-path rule set (``docs/serving.md``): TTFT deadline,
    queue-depth budget, queue-wait-fraction attribution, plus the
    substrate rules that apply to any long-running device loop (stale
    fetch, hung step).  Same override convention as
    :func:`default_rules`, e.g.
    ``serve_rules(ttft={"deadline_ms": 250.0})``."""
    specs = {
        "ttft": TTFTRule,
        "queue_depth": QueueDepthRule,
        "queue_wait_fraction": QueueWaitFractionRule,
        "spec_acceptance": SpecAcceptanceRule,
        "serve_faults": ServeFaultRule,
        "stale_fetch": StaleFetchRule,
        "hung_step": HungStepRule,
    }
    unknown = set(overrides) - set(specs)
    if unknown:
        raise ValueError(f"unknown serve health rules: {sorted(unknown)}")
    return [cls(**overrides.get(name, {})) for name, cls in specs.items()]


def default_rules(**overrides) -> List[Rule]:
    """The standard rule set; keyword args override a rule's kwargs by
    name, e.g. ``default_rules(straggler={"zmax": 2.5})``."""
    specs = {
        "straggler": StragglerRule,
        "mfu_floor": MFUFloorRule,
        "goodput_floor": GoodputFloorRule,
        "loss_spike": LossSpikeRule,
        "nan_rate": NaNRateRule,
        "stale_fetch": StaleFetchRule,
        "hung_step": HungStepRule,
        "collective_fraction": CollectiveFractionRule,
        "host_stall": HostStallRule,
    }
    unknown = set(overrides) - set(specs)
    if unknown:
        raise ValueError(f"unknown health rules: {sorted(unknown)}")
    return [cls(**overrides.get(name, {})) for name, cls in specs.items()]


class Watchdog:
    """Evaluate health rules on a cadence; emit structured events.

    Implements the ``run_resilient`` observer protocol, so wiring is
    one entry in the observer fan-out::

        wd = Watchdog(registry=reg, meter=meter, goodput=acct,
                      reporter=reporter, flight=recorder,
                      on_unhealthy=lambda ev: tracer.arm(ev.step + 1, 3))
        run_resilient(..., observer=ObserverFanout([acct, wd]))

    A broken rule must not kill training: rule exceptions are caught,
    warned once per rule, and the rule is disabled for the run.
    """

    def __init__(
        self,
        rules: Optional[List[Rule]] = None,
        *,
        registry=None,
        meter=None,
        goodput=None,
        fleet=None,
        reporter=None,
        flight=None,
        spans=None,
        attribution=None,
        on_unhealthy: Optional[Callable[[HealthEvent], Any]] = None,
        check_every: int = 8,
        window: int = 64,
        clock: Callable[[], float] = time.monotonic,
    ):
        if check_every < 1:
            raise ValueError("check_every must be >= 1")
        self.rules = list(rules) if rules is not None else default_rules()
        self.registry = registry
        self.meter = meter
        self.goodput = goodput
        self.fleet = fleet
        #: step-time attribution source for the fraction rules: an
        #: object with ``fractions()`` (Cost/TraceAttribution) or a
        #: plain mapping; None → rules fall back to the board keys
        self.attribution = attribution
        self.reporter = reporter
        self.flight = flight
        #: optional :class:`~apex_tpu.observability.spans.SpanRecorder`
        #: — every firing lands on its health track, so the merged
        #: timeline shows the alert next to the spans that explain it
        self.spans = spans
        self.on_unhealthy = on_unhealthy
        self.check_every = check_every
        self.events: List[HealthEvent] = []
        self.skip_window: collections.deque = collections.deque(
            maxlen=window
        )
        self.first_step = 0
        self._seen_step = False
        self._step = 0
        self._clock = clock
        self._last_tick: Optional[float] = None
        self.last_step_seconds: Optional[float] = None
        self._broken: set = set()

    @property
    def fleet_view(self):
        return self.fleet.view() if self.fleet is not None else None

    # -- observer protocol -------------------------------------------------
    def on_step(self, step: int, skipped: bool = False, info=None) -> None:
        step = int(step)
        if not self._seen_step:
            self.first_step = step
            self._seen_step = True
        self._step = step
        now = self._clock()
        if self._last_tick is not None:
            self.last_step_seconds = now - self._last_tick
        self._last_tick = now
        self.skip_window.append(bool(skipped))
        if step % self.check_every == 0:
            self.check(step)

    def on_rollback(self, step, anchor, skips=0, discarded=None) -> None:
        # the replay re-executes the window; a stale skip history would
        # double-count the streak the rollback just handled
        self.skip_window.clear()

    def on_resume(self, step: int) -> None:
        self.first_step = int(step)

    # -- evaluation --------------------------------------------------------
    def check(self, step: Optional[int] = None) -> List[HealthEvent]:
        """Run every rule now; returns (and emits) new events."""
        step = self._step if step is None else int(step)
        fired: List[HealthEvent] = []
        for rule in self.rules:
            if rule.name in self._broken:
                continue
            try:
                fired.extend(rule.check(self, step))
            except Exception as e:  # a telemetry bug must not kill training
                self._broken.add(rule.name)
                warnings.warn(
                    f"health rule {rule.name!r} raised "
                    f"{type(e).__name__}: {e} — disabled for this run",
                    RuntimeWarning,
                )
        for event in fired:
            self._emit(event)
        return fired

    def poll(self) -> List[HealthEvent]:
        """External deadline check — call from a monitor thread or a
        dump path to catch a step that is hung *right now* (the in-loop
        rules only see completed intervals).

        Honors the rule's cooldown and broken-set exactly like
        :meth:`check`: the step counter does not advance during a hang,
        so a once-per-second monitor loop emits ONE event per hung
        step, not one per poll.
        """
        fired: List[HealthEvent] = []
        if self._last_tick is not None:
            waiting = self._clock() - self._last_tick
            for rule in self.rules:
                if not isinstance(rule, HungStepRule):
                    continue
                if rule.name in self._broken:
                    continue
                if (
                    rule._last_fired is not None
                    and self._step - rule._last_fired < rule.cooldown
                ):
                    continue
                if waiting > rule.deadline_s:
                    rule._last_fired = self._step
                    fired.extend(
                        rule._event(
                            self._step, waiting, rule.deadline_s,
                            f"step {self._step + 1} hung for "
                            f"{waiting:.1f}s (deadline "
                            f"{rule.deadline_s:.0f}s)",
                        )
                    )
        for event in fired:
            self._emit(event)
        return fired

    # -- emission ----------------------------------------------------------
    def _emit(self, event: HealthEvent) -> None:
        self.events.append(event)
        from apex_tpu.observability.metrics import board

        board.set(f"health/{event.rule}", event.value)
        if self.reporter is not None:
            from apex_tpu.observability.export import bench_record

            rec = bench_record(
                f"health/{event.rule}", event.value, "", None,
                step=event.step, **event.as_record(),
            )
            for sink in self.reporter.sinks:
                sink.write(rec)
        if self.flight is not None:
            self.flight.note_health(event)
        if self.spans is not None:
            self.spans.note_health(event)
        if self.on_unhealthy is not None:
            try:
                self.on_unhealthy(event)
            except Exception as e:
                warnings.warn(
                    f"on_unhealthy callback raised {type(e).__name__}: {e}",
                    RuntimeWarning,
                )
