"""Structured metric export — one ``report()`` API, three sinks.

Every record is the ``bench.py`` metric-line schema::

    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

plus a ``"step"`` key on telemetry lines, so a live ``--metrics-out``
JSONL and the ``BENCH_*.json`` trajectory artifacts are the same
language — a regression is a diff between two JSONL files, not prose.

Sinks:

- :class:`JSONLSink` — one JSON object per line (the canonical form);
- :class:`CSVSink` — spreadsheet-friendly, columns fixed by the first
  record;
- :class:`TensorBoardSink` — real ``events.out.tfevents.*`` scalar
  files, written directly (TFRecord framing + masked CRC32C + a
  hand-encoded ``Event`` proto), because this environment must not grow
  a tensorboard/tensorflow dependency.  Any TensorBoard install reads
  the output.

:class:`Reporter` fans one step's values out to every sink, pulling
from the attached sources (:class:`~apex_tpu.observability.metrics.
MetricRegistry`, :class:`~apex_tpu.observability.meter.StepMeter`,
:class:`~apex_tpu.observability.meter.GoodputAccountant`, and the
module :data:`~apex_tpu.observability.metrics.board`).
"""

from __future__ import annotations

import csv
import json
import os
import struct
import time
from typing import Any, Dict, IO, Iterable, Mapping, Optional, Union

__all__ = [
    "bench_record",
    "JSONLSink",
    "CSVSink",
    "TensorBoardSink",
    "Reporter",
]


def bench_record(
    metric: str,
    value,
    unit: str = "",
    vs_baseline=None,
    **extra,
) -> Dict[str, Any]:
    """A record in the bench.py line schema; ``extra`` keys (``step``,
    ...) append after the four contract keys."""
    rec: Dict[str, Any] = {
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": vs_baseline,
    }
    rec.update(extra)
    return rec


class _FileSink:
    """Shared open/close plumbing: path or open file object."""

    def __init__(self, target: Union[str, os.PathLike, IO], mode: str = "a"):
        if hasattr(target, "write"):
            self._f, self._owns = target, False
        else:
            path = os.fspath(target)
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            self._f, self._owns = open(path, mode), True

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        if self._owns:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class JSONLSink(_FileSink):
    """One JSON object per line, flushed per write (a killed run keeps
    every completed line — the property resume debugging relies on).

    Opens in APPEND mode deliberately: a preempted job relaunched on
    the same ``--metrics-out`` path continues its telemetry stream the
    way its checkpoints continue training (and the ``BENCH_all_*``
    artifacts accrete lines the same way).  Consumers wanting "this
    run only" should take the last matching record, as
    ``tools/verify_tier1.sh`` does.

    Non-finite floats (a NaN grad norm on a skipped step, an untouched
    min/max seed at ±inf) are written as JSON ``null`` — bare ``NaN``
    is invalid JSON that jq/JS parsers reject wholesale, and in the
    bench schema null already means "no measurement"."""

    def write(self, record: Mapping[str, Any]) -> None:
        clean = {
            k: (None if isinstance(v, float) and (v != v or v in (
                float("inf"), float("-inf"))) else v)
            for k, v in record.items()
        }
        self._f.write(json.dumps(clean, allow_nan=False) + "\n")
        self._f.flush()


class CSVSink(_FileSink):
    """Columns are the FIRST record's keys; later extras are dropped
    and missing keys left blank (csv needs a stable header).

    Unlike :class:`JSONLSink` this TRUNCATES an existing path: a CSV
    cannot tolerate a second header row mid-file or a column set fixed
    by some earlier run's first record."""

    def __init__(self, target):
        super().__init__(target, mode="w")
        self._writer: Optional[csv.DictWriter] = None

    def write(self, record: Mapping[str, Any]) -> None:
        if self._writer is None:
            self._writer = csv.DictWriter(
                self._f, fieldnames=list(record), extrasaction="ignore"
            )
            self._writer.writeheader()
        self._writer.writerow(
            {k: record.get(k, "") for k in self._writer.fieldnames}
        )
        self._f.flush()


# -- TensorBoard event encoding (no tensorflow/tensorboard dependency) ------

_CRC_TABLE = None


def _crc32c(data: bytes) -> int:
    """CRC32C (Castagnoli) — the TFRecord checksum.  Table built once;
    called only on the report cadence, so pure Python is fine."""
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            table.append(crc)
        _CRC_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


def _pb_bytes(field: int, payload: bytes) -> bytes:
    return _pb_varint_tag(field, 2) + _pb_varint(len(payload)) + payload


def _pb_varint_tag(field: int, wire: int) -> bytes:
    return _pb_varint(field << 3 | wire)


def _pb_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _event_proto(
    wall_time: float, step: int, scalars: Mapping[str, float] = (),
    file_version: Optional[str] = None,
) -> bytes:
    # Event{1: double wall_time, 2: int64 step, 3: string file_version,
    #       5: Summary{repeated 1: Value{1: string tag,
    #                                    2: float simple_value}}}
    ev = _pb_varint_tag(1, 1) + struct.pack("<d", wall_time)
    ev += _pb_varint_tag(2, 0) + _pb_varint(step & 0xFFFFFFFFFFFFFFFF)
    if file_version is not None:
        ev += _pb_bytes(3, file_version.encode())
    if scalars:
        summary = b""
        for tag, value in scalars.items():
            val = _pb_bytes(1, tag.encode())
            val += _pb_varint_tag(2, 5) + struct.pack("<f", float(value))
            summary += _pb_bytes(1, val)
        ev += _pb_bytes(5, summary)
    return ev


class TensorBoardSink:
    """Scalar summaries into ``logdir/events.out.tfevents.<ts>.<pid>``.

    ``write`` takes a bench-schema record: non-numeric values are
    skipped (TensorBoard scalars are floats), the ``step`` key (default
    0) becomes the global step, and the metric name becomes the tag.
    """

    def __init__(self, logdir: Union[str, os.PathLike]):
        os.makedirs(os.fspath(logdir), exist_ok=True)
        self.path = os.path.join(
            os.fspath(logdir),
            f"events.out.tfevents.{int(time.time())}.{os.getpid()}",
        )
        self._f = open(self.path, "ab")
        self._record(_event_proto(time.time(), 0, file_version="brain.Event:2"))

    def _record(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))
        self._f.flush()

    def add_scalars(self, step: int, scalars: Mapping[str, float]) -> None:
        numeric = {
            k: float(v)
            for k, v in scalars.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        if numeric:
            self._record(_event_proto(time.time(), int(step), numeric))

    def write(self, record: Mapping[str, Any]) -> None:
        value = record.get("value")
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return
        self.add_scalars(
            int(record.get("step", 0) or 0), {record["metric"]: value}
        )

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Reporter:
    """Fan one step's telemetry out to every sink.

    ``report(step)`` merges, in order (later wins on key collisions):
    the registry's latest fetched values, the step meter summary, the
    goodput summary, the board snapshot, then ``extra`` — and writes
    one bench-schema record per metric to each sink.  Units come from
    the registry where declared.
    """

    def __init__(
        self,
        sinks: Iterable,
        *,
        registry=None,
        meter=None,
        goodput=None,
        include_board: bool = True,
    ):
        self.sinks = list(sinks)
        self.registry = registry
        self.meter = meter
        self.goodput = goodput
        self.include_board = include_board

    _UNITS = {
        "train/step_time_ms": "ms",
        "train/tokens_per_sec": "tokens/s",
        "train/mfu": "MFU",
        "train/goodput": "fraction (productive/executed)",
    }

    def collect(self) -> Dict[str, Any]:
        values: Dict[str, Any] = {}
        if self.registry is not None:
            values.update(self.registry.values())
        if self.meter is not None:
            values.update(self.meter.summary())
        if self.goodput is not None:
            values.update(self.goodput.summary())
        if self.include_board:
            from apex_tpu.observability.metrics import board

            values.update(board.snapshot())
        return values

    def report(
        self, step: int, extra: Optional[Mapping[str, Any]] = None
    ) -> Dict[str, Any]:
        values = self.collect()
        if extra:
            values.update(extra)
        for name, value in values.items():
            unit = self._UNITS.get(name, "")
            if not unit and self.registry is not None:
                unit = self.registry.unit(name)
            rec = bench_record(name, value, unit, None, step=int(step))
            for sink in self.sinks:
                sink.write(rec)
        return values

    def close(self) -> None:
        for sink in self.sinks:
            sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
