"""Structured metric export — one ``report()`` API, three sinks.

Every record is the ``bench.py`` metric-line schema::

    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

plus a ``"step"`` key on telemetry lines, so a live ``--metrics-out``
JSONL and the ``BENCH_*.json`` trajectory artifacts are the same
language — a regression is a diff between two JSONL files, not prose.

Sinks:

- :class:`JSONLSink` — one JSON object per line (the canonical form);
- :class:`CSVSink` — spreadsheet-friendly, columns fixed by the first
  record;
- :class:`TensorBoardSink` — real ``events.out.tfevents.*`` scalar
  files, written directly (TFRecord framing + masked CRC32C + a
  hand-encoded ``Event`` proto), because this environment must not grow
  a tensorboard/tensorflow dependency.  Any TensorBoard install reads
  the output.

:class:`Reporter` fans one step's values out to every sink, pulling
from the attached sources (:class:`~apex_tpu.observability.metrics.
MetricRegistry`, :class:`~apex_tpu.observability.meter.StepMeter`,
:class:`~apex_tpu.observability.meter.GoodputAccountant`, and the
module :data:`~apex_tpu.observability.metrics.board`).
"""

from __future__ import annotations

import csv
import json
import os
import struct
import time
from typing import Any, Dict, IO, Iterable, Mapping, Optional, Union

__all__ = [
    "bench_record",
    "JSONLSink",
    "CSVSink",
    "TensorBoardSink",
    "TimelineSink",
    "flight_entries",
    "flight_counters",
    "Reporter",
]


def bench_record(
    metric: str,
    value,
    unit: str = "",
    vs_baseline=None,
    **extra,
) -> Dict[str, Any]:
    """A record in the bench.py line schema; ``extra`` keys (``step``,
    ...) append after the four contract keys."""
    rec: Dict[str, Any] = {
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": vs_baseline,
    }
    rec.update(extra)
    return rec


class _FileSink:
    """Shared open/close plumbing: path or open file object."""

    def __init__(self, target: Union[str, os.PathLike, IO], mode: str = "a"):
        if hasattr(target, "write"):
            self._f, self._owns = target, False
        else:
            path = os.fspath(target)
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            self._f, self._owns = open(path, mode), True

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        if self._owns:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class JSONLSink(_FileSink):
    """One JSON object per line, flushed per write (a killed run keeps
    every completed line — the property resume debugging relies on).

    Opens in APPEND mode deliberately: a preempted job relaunched on
    the same ``--metrics-out`` path continues its telemetry stream the
    way its checkpoints continue training (and the ``BENCH_all_*``
    artifacts accrete lines the same way).  Consumers wanting "this
    run only" should take the last matching record, as
    ``tools/verify_tier1.sh`` does.

    Non-finite floats (a NaN grad norm on a skipped step, an untouched
    min/max seed at ±inf) are written as JSON ``null`` — bare ``NaN``
    is invalid JSON that jq/JS parsers reject wholesale, and in the
    bench schema null already means "no measurement"."""

    def write(self, record: Mapping[str, Any]) -> None:
        clean = {
            k: (None if isinstance(v, float) and (v != v or v in (
                float("inf"), float("-inf"))) else v)
            for k, v in record.items()
        }
        self._f.write(json.dumps(clean, allow_nan=False) + "\n")
        self._f.flush()


class CSVSink(_FileSink):
    """Columns are the FIRST record's keys; later extras are dropped
    and missing keys left blank (csv needs a stable header).

    Unlike :class:`JSONLSink` this TRUNCATES an existing path: a CSV
    cannot tolerate a second header row mid-file or a column set fixed
    by some earlier run's first record."""

    def __init__(self, target):
        super().__init__(target, mode="w")
        self._writer: Optional[csv.DictWriter] = None

    def write(self, record: Mapping[str, Any]) -> None:
        if self._writer is None:
            self._writer = csv.DictWriter(
                self._f, fieldnames=list(record), extrasaction="ignore"
            )
            self._writer.writeheader()
        self._writer.writerow(
            {k: record.get(k, "") for k in self._writer.fieldnames}
        )
        self._f.flush()


# -- TensorBoard event encoding (no tensorflow/tensorboard dependency) ------

_CRC_TABLE = None


def _crc32c(data: bytes) -> int:
    """CRC32C (Castagnoli) — the TFRecord checksum.  Table built once;
    called only on the report cadence, so pure Python is fine."""
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            table.append(crc)
        _CRC_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


def _pb_bytes(field: int, payload: bytes) -> bytes:
    return _pb_varint_tag(field, 2) + _pb_varint(len(payload)) + payload


def _pb_varint_tag(field: int, wire: int) -> bytes:
    return _pb_varint(field << 3 | wire)


def _pb_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _event_proto(
    wall_time: float, step: int, scalars: Mapping[str, float] = (),
    file_version: Optional[str] = None,
) -> bytes:
    # Event{1: double wall_time, 2: int64 step, 3: string file_version,
    #       5: Summary{repeated 1: Value{1: string tag,
    #                                    2: float simple_value}}}
    ev = _pb_varint_tag(1, 1) + struct.pack("<d", wall_time)
    ev += _pb_varint_tag(2, 0) + _pb_varint(step & 0xFFFFFFFFFFFFFFFF)
    if file_version is not None:
        ev += _pb_bytes(3, file_version.encode())
    if scalars:
        summary = b""
        for tag, value in scalars.items():
            val = _pb_bytes(1, tag.encode())
            val += _pb_varint_tag(2, 5) + struct.pack("<f", float(value))
            summary += _pb_bytes(1, val)
        ev += _pb_bytes(5, summary)
    return ev


class TensorBoardSink:
    """Scalar summaries into ``logdir/events.out.tfevents.<ts>.<pid>``.

    ``write`` takes a bench-schema record: non-numeric values are
    skipped (TensorBoard scalars are floats), the ``step`` key (default
    0) becomes the global step, and the metric name becomes the tag.
    """

    def __init__(self, logdir: Union[str, os.PathLike]):
        os.makedirs(os.fspath(logdir), exist_ok=True)
        self.path = os.path.join(
            os.fspath(logdir),
            f"events.out.tfevents.{int(time.time())}.{os.getpid()}",
        )
        self._f = open(self.path, "ab")
        self._record(_event_proto(time.time(), 0, file_version="brain.Event:2"))

    def _record(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))
        self._f.flush()

    def add_scalars(self, step: int, scalars: Mapping[str, float]) -> None:
        numeric = {
            k: float(v)
            for k, v in scalars.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        if numeric:
            self._record(_event_proto(time.time(), int(step), numeric))

    def write(self, record: Mapping[str, Any]) -> None:
        value = record.get("value")
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return
        self.add_scalars(
            int(record.get("step", 0) or 0), {record["metric"]: value}
        )

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- Chrome-trace-event timeline (Perfetto-viewable) ------------------------


def _is_nonfinite_sample(v) -> bool:
    """A frame-metric value the counter track cannot render: the
    ``json_safe`` string encodings, or a live non-finite float."""
    if isinstance(v, str):
        return v in ("NaN", "Infinity", "-Infinity")
    if isinstance(v, float):
        return v != v or v in (float("inf"), float("-inf"))
    return False


def flight_entries(dump: Mapping[str, Any]) -> list:
    """SpanRecorder-format entries from a flight-recorder dump —
    frames become ``train/step`` spans, the event log becomes instants
    (timestamps are already epoch seconds: ``FlightRecorder`` clocks
    ``time.time``).  Non-finite frame metrics — the crash evidence the
    dump's ``json_safe`` encoding deliberately preserves — become
    marker instants, since a counter track cannot render them and
    silently ending the track one frame early would hide exactly the
    value the flight recorder kept.  ``tools/timeline.py`` and
    ``tools/flight_view.py --timeline`` feed the result to
    :meth:`TimelineSink.add_spans`."""
    entries = []
    prev_t = None
    for fr in dump.get("frames", []):
        t = fr.get("t")
        if isinstance(prev_t, (int, float)) and isinstance(t, (int, float)):
            args = {"step": fr.get("step"),
                    "skipped": bool(fr.get("skipped"))}
            if fr.get("replay"):
                args["replay"] = True
            entries.append({
                "name": "train/step", "track": "train",
                "t0": prev_t, "t1": t, "args": args,
            })
        prev_t = t
        if isinstance(t, (int, float)):
            for name, v in (fr.get("metrics") or {}).items():
                if _is_nonfinite_sample(v):
                    entries.append({
                        "name": f"{name} = {v}", "track": "health",
                        "t": t,
                        "args": {"metric": name, "value": str(v),
                                 "step": fr.get("step")},
                    })
    for ev in dump.get("events", []):
        t = ev.get("t")
        if not isinstance(t, (int, float)):
            continue
        kind = ev.get("kind", "event")
        name = (
            f"health/{ev.get('rule', '?')}" if kind == "health"
            else f"train/{kind}"
        )
        track = "health" if kind == "health" else "train"
        args = {k: v for k, v in ev.items()
                if k not in ("seq", "t", "kind") and v is not None}
        entries.append({
            "name": name, "track": track, "t": t, "args": args,
        })
    return entries


def flight_counters(dump: Mapping[str, Any]) -> list:
    """``(name, t_epoch_s, value)`` counter samples from a flight
    dump's per-frame metrics — one Perfetto counter track per metric."""
    out = []
    for fr in dump.get("frames", []):
        t = fr.get("t")
        metrics = fr.get("metrics") or {}
        if not isinstance(t, (int, float)):
            continue
        for name, v in metrics.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out.append((name, t, float(v)))
    return out


class TimelineSink:
    """Chrome-trace-event JSON (the format ``ui.perfetto.dev`` and
    ``chrome://tracing`` open) — the merged-timeline sink beside
    JSONL/CSV/TensorBoard.

    Two input surfaces:

    - :meth:`add_spans` takes :class:`~apex_tpu.observability.spans.
      SpanRecorder` entries (spans → ``"X"`` complete events, instants
      → ``"i"``) together with the recorder dump's **wall-clock
      anchor**, converting monotonic timestamps to epoch microseconds —
      which is what lets artifacts from different processes/hosts merge
      onto one timeline.  Each ``track`` becomes its own named thread
      row; a span's ``lane`` (e.g. a request id) becomes a sub-row.
    - :meth:`write` takes a bench-schema record (the
      :class:`Reporter` sink protocol) and emits a ``"C"`` counter
      event, so live metric lines render as counter tracks under the
      spans.

    Events buffer in memory and the JSON object is written at
    :meth:`close` (the trace format is one document, not a line
    stream).  ``tools/timeline.py`` and ``tools/flight_view.py
    --timeline`` are the CLI surfaces.
    """

    def __init__(self, target: Union[str, os.PathLike, IO], *,
                 pid: int = 1, process_name: Optional[str] = None,
                 other_data: Optional[Mapping[str, Any]] = None):
        if hasattr(target, "write"):
            self._f, self._owns = target, False
        else:
            path = os.fspath(target)
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            self._f, self._owns = open(path, "w"), True
        self.pid = int(pid)
        self._events: list = []
        self._tids: Dict[Any, int] = {}
        self._procs: set = set()
        self._other: Dict[str, Any] = dict(other_data or {})
        self._closed = False
        if process_name is not None:
            self._name_process(self.pid, process_name)

    def _name_process(self, pid: int, name: str) -> None:
        if pid not in self._procs:
            self._procs.add(pid)
            self._events.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": name},
            })

    def _tid(self, pid: int, track: str, lane=None) -> int:
        key = (pid, track, lane)
        tid = self._tids.get(key)
        if tid is None:
            tid = len(self._tids) + 1
            self._tids[key] = tid
            label = track if lane is None else f"{track} [{lane}]"
            self._events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": label},
            })
            # keep tracks grouped by name, lanes in creation order
            self._events.append({
                "ph": "M", "name": "thread_sort_index", "pid": pid,
                "tid": tid, "args": {"sort_index": tid},
            })
        return tid

    @staticmethod
    def _to_epoch_us(t: float, anchor: Optional[Mapping[str, Any]]) -> float:
        """Epoch microseconds for timestamp ``t`` — monotonic seconds
        when ``anchor`` carries the process's monotonic→epoch offset,
        already-epoch seconds when ``anchor`` is None (flight frames)."""
        if anchor:
            t = float(t) - float(anchor["monotonic"]) + float(
                anchor["epoch"]
            )
        return float(t) * 1e6

    def add_spans(
        self,
        entries: Iterable[Mapping[str, Any]],
        *,
        anchor: Optional[Mapping[str, Any]] = None,
        pid: Optional[int] = None,
        process_name: Optional[str] = None,
    ) -> int:
        """Append SpanRecorder-format entries; returns the event count
        added.  Pass each source file's own ``anchor`` (and a distinct
        ``pid``/``process_name`` per host) when merging."""
        pid = self.pid if pid is None else int(pid)
        if process_name is not None:
            self._name_process(pid, process_name)
        n = 0
        for e in entries:
            track = e.get("track", "events")
            tid = self._tid(pid, track, e.get("lane"))
            args = dict(e.get("args") or {})
            if "t0" in e:
                ts = self._to_epoch_us(e["t0"], anchor)
                dur = max(
                    0.0,
                    self._to_epoch_us(e["t1"], anchor) - ts,
                )
                self._events.append({
                    "name": e.get("name", "?"), "cat": track, "ph": "X",
                    "ts": ts, "dur": dur, "pid": pid, "tid": tid,
                    "args": args,
                })
            else:
                self._events.append({
                    "name": e.get("name", "?"), "cat": track, "ph": "i",
                    "ts": self._to_epoch_us(e.get("t", 0.0), anchor),
                    "s": "t", "pid": pid, "tid": tid, "args": args,
                })
            n += 1
        return n

    def counter(self, name: str, t_epoch_s: float, value: float,
                *, pid: Optional[int] = None) -> None:
        """One counter sample (epoch seconds) — renders as a counter
        track."""
        self._events.append({
            "name": name, "ph": "C",
            "ts": float(t_epoch_s) * 1e6,
            "pid": self.pid if pid is None else int(pid),
            "tid": 0, "args": {"value": float(value)},
        })

    def write(self, record: Mapping[str, Any]) -> None:
        """Reporter sink protocol: numeric bench-schema records become
        counter samples stamped with the wall clock at write time."""
        value = record.get("value")
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return
        v = float(value)
        if v != v or v in (float("inf"), float("-inf")):
            return  # counter tracks are numeric; non-finite has no bar
        self.counter(record["metric"], time.time(), v)

    def flush(self) -> None:
        pass  # events buffer until close — the trace is one document

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._events.sort(
            key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0))
        )
        # span args may carry forensic non-finites (a NaN health value)
        # — encode them the flight-dump way, strict JSON throughout
        from apex_tpu.observability.flight import json_safe

        json.dump(
            json_safe({
                "traceEvents": self._events,
                "displayTimeUnit": "ms",
                "otherData": self._other,
            }),
            self._f,
            allow_nan=False,
        )
        self._f.write("\n")
        self._f.flush()
        if self._owns:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Reporter:
    """Fan one step's telemetry out to every sink.

    ``report(step)`` merges, in order (later wins on key collisions):
    the registry's latest fetched values, the step meter summary, the
    goodput summary, the board snapshot, then ``extra`` — and writes
    one bench-schema record per metric to each sink.  Units come from
    the registry where declared.
    """

    def __init__(
        self,
        sinks: Iterable,
        *,
        registry=None,
        meter=None,
        goodput=None,
        include_board: bool = True,
    ):
        self.sinks = list(sinks)
        self.registry = registry
        self.meter = meter
        self.goodput = goodput
        self.include_board = include_board

    _UNITS = {
        "train/step_time_ms": "ms",
        "train/tokens_per_sec": "tokens/s",
        "train/mfu": "MFU",
        "train/goodput": "fraction (productive/executed)",
    }

    def collect(self) -> Dict[str, Any]:
        values: Dict[str, Any] = {}
        if self.registry is not None:
            values.update(self.registry.values())
        if self.meter is not None:
            values.update(self.meter.summary())
        if self.goodput is not None:
            values.update(self.goodput.summary())
        if self.include_board:
            from apex_tpu.observability.metrics import board

            values.update(board.snapshot())
        return values

    def report(
        self, step: int, extra: Optional[Mapping[str, Any]] = None
    ) -> Dict[str, Any]:
        values = self.collect()
        if extra:
            values.update(extra)
        for name, value in values.items():
            unit = self._UNITS.get(name, "")
            if not unit and self.registry is not None:
                unit = self.registry.unit(name)
            rec = bench_record(name, value, unit, None, step=int(step))
            for sink in self.sinks:
                sink.write(rec)
        return values

    def close(self) -> None:
        for sink in self.sinks:
            sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
