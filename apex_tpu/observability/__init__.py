"""Unified step telemetry — the shared reporting spine of apex_tpu.

One subsystem every layer reports into, so "what is my MFU, step time,
comm volume, and goodput right now" is a query, not an archaeology
session over bench logs:

- :mod:`apex_tpu.observability.metrics` —
  :class:`~apex_tpu.observability.metrics.MetricRegistry`: device-side
  counters/gauges accumulated INSIDE the jitted step and fetched
  asynchronously on a cadence (no per-step host sync; <1% step-time
  overhead, asserted in tests), plus the host-side
  :data:`~apex_tpu.observability.metrics.board` that
  ``apex_tpu.parallel.comm`` publishes wire-byte/collective gauges to.
- :mod:`apex_tpu.observability.meter` —
  :class:`~apex_tpu.observability.meter.StepMeter` (wall-clock step
  time, tokens/s, model-FLOPs MFU — the same FLOP/peak model as
  ``bench.py``) and :class:`~apex_tpu.observability.meter.
  GoodputAccountant` (productive vs. skipped/rolled-back/replayed
  steps, fed by ``run_resilient`` observer events).
- :mod:`apex_tpu.observability.export` — JSONL (bench.py line schema),
  CSV, and TensorBoard-event sinks behind one
  :class:`~apex_tpu.observability.export.Reporter` ``report()`` API.
- :mod:`apex_tpu.observability.trace` — NVTX-style annotation hooks
  (absorbing ``apex_tpu/utils/profiling.py``) plus
  :class:`~apex_tpu.observability.trace.TraceScheduler`: "profile
  steps N..N+K to this dir" via ``APEX_TPU_TRACE_STEPS``, no script
  edits.

See ``docs/observability.md`` for the full tour.
"""

from apex_tpu.observability.export import (  # noqa: F401
    CSVSink,
    JSONLSink,
    Reporter,
    TensorBoardSink,
    bench_record,
)
from apex_tpu.observability.meter import (  # noqa: F401
    GoodputAccountant,
    StepMeter,
    chip_peak_flops,
    total_peak_flops,
    transformer_train_flops,
)
from apex_tpu.observability.metrics import (  # noqa: F401
    Board,
    MetricRegistry,
    board,
)
# NOTE: the trace() context manager is deliberately NOT re-exported
# here — it would shadow the `apex_tpu.observability.trace` SUBMODULE
# attribute on the package.  Reach it as `observability.trace.trace`
# or via the long-standing `apex_tpu.utils.trace` alias.
from apex_tpu.observability import trace  # noqa: F401
from apex_tpu.observability.trace import (  # noqa: F401
    TraceScheduler,
    annotate,
    nvtx_range,
    range_pop,
    range_push,
)

__all__ = [
    "MetricRegistry",
    "Board",
    "board",
    "StepMeter",
    "GoodputAccountant",
    "chip_peak_flops",
    "total_peak_flops",
    "transformer_train_flops",
    "Reporter",
    "JSONLSink",
    "CSVSink",
    "TensorBoardSink",
    "bench_record",
    "TraceScheduler",
    "annotate",
    "nvtx_range",
    "range_push",
    "range_pop",
    "trace",  # the submodule (holding the trace() context manager)
]
