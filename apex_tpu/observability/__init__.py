"""Unified step telemetry — the shared reporting spine of apex_tpu.

One subsystem every layer reports into, so "what is my MFU, step time,
comm volume, and goodput right now" is a query, not an archaeology
session over bench logs:

- :mod:`apex_tpu.observability.metrics` —
  :class:`~apex_tpu.observability.metrics.MetricRegistry`: device-side
  counters/gauges accumulated INSIDE the jitted step and fetched
  asynchronously on a cadence (no per-step host sync; <1% step-time
  overhead, asserted in tests), plus the host-side
  :data:`~apex_tpu.observability.metrics.board` that
  ``apex_tpu.parallel.comm`` publishes wire-byte/collective gauges to.
- :mod:`apex_tpu.observability.meter` —
  :class:`~apex_tpu.observability.meter.StepMeter` (wall-clock step
  time, tokens/s, model-FLOPs MFU — the same FLOP/peak model as
  ``bench.py``) and :class:`~apex_tpu.observability.meter.
  GoodputAccountant` (productive vs. skipped/rolled-back/replayed
  steps, fed by ``run_resilient`` observer events).
- :mod:`apex_tpu.observability.export` — JSONL (bench.py line schema),
  CSV, and TensorBoard-event sinks behind one
  :class:`~apex_tpu.observability.export.Reporter` ``report()`` API.
- :mod:`apex_tpu.observability.trace` — NVTX-style annotation hooks
  (absorbing ``apex_tpu/utils/profiling.py``) plus
  :class:`~apex_tpu.observability.trace.TraceScheduler`: "profile
  steps N..N+K to this dir" via ``APEX_TPU_TRACE_STEPS``, no script
  edits.
- :mod:`apex_tpu.observability.spans` —
  :class:`~apex_tpu.observability.spans.SpanRecorder`: ring-buffered
  structured spans with monotonic timestamps anchored once to wall
  clock — per-request serve lifecycles (``queued → admitted →
  prefill → decode[i] → done|shed(reason)``) with engine-iteration
  correlation ids, per-step train spans from the ``run_resilient``
  observer protocol, health events and profiler-window markers —
  merged into one Perfetto timeline by
  :class:`~apex_tpu.observability.export.TimelineSink` /
  ``tools/timeline.py``.
- :mod:`apex_tpu.observability.flight` —
  :class:`~apex_tpu.observability.flight.FlightRecorder`: a ring
  buffer of the last N steps' telemetry + event log, dumped
  atomically to ``flight_<ts>.json`` on crash / skip-budget
  exhaustion / SIGTERM (armed by ``APEX_TPU_FLIGHT=N[:DIR]`` or
  ``run_resilient(flight=...)``); ``tools/flight_view.py`` renders
  the postmortem.
- :mod:`apex_tpu.observability.fleet` —
  :class:`~apex_tpu.observability.fleet.FleetAggregator`: every
  host's metric row gathered through ONE jitted collective on the
  registry's cadence (no per-step host sync) into per-host columns
  + min/median/max rollups on host 0's board.
- :mod:`apex_tpu.observability.health` —
  :class:`~apex_tpu.observability.health.Watchdog`: declarative
  rules (straggler z-score, MFU/goodput floors, loss spike, NaN
  rate, stale fetch, hung step, comm/host-stall fraction floors)
  emitting structured
  :class:`~apex_tpu.observability.health.HealthEvent` s to the
  sinks/flight recorder, with ``on_unhealthy`` escalation (e.g.
  arm a trace window — alert→profile in one run).
- :mod:`apex_tpu.observability.ometrics` — the live ops plane: a
  dependency-free OpenMetrics exporter over the registry/board key
  vocabulary (validated injective name mapping), host-side
  :class:`~apex_tpu.observability.ometrics.Histogram` s, and a stdlib
  ``http.server`` :class:`~apex_tpu.observability.ometrics.OpsServer`
  serving ``GET /metrics`` from cached values (never a blocking
  fetch) — armed by ``--ops-port`` / ``APEX_TPU_OPS_PORT``.
- :mod:`apex_tpu.observability.slo` — declarative SLOs (TTFT latency,
  goodput, shed rate) with Google-SRE multi-window multi-burn-rate
  alerting; a firing is a normal
  :class:`~apex_tpu.observability.health.HealthEvent`, so an SLO page
  lands on the same merged timeline as the request spans that blew
  the budget.
- :mod:`apex_tpu.observability.canary` — canary analysis for fleet
  deploys: golden-probe model fingerprints (seeded probe prompts,
  greedy streams + prefill-logits bytes hashed blake2b — a single
  flipped weight bit flips the digest) and
  :class:`~apex_tpu.observability.canary.CanaryAnalyzer` statistical
  drift verdicts (one-sided Mann–Whitney U / exact binomial tails
  with a min-sample honesty floor), driving the fleet's canary-gated
  rolling updates with auto-halt + rollback
  (``tools/canary_drill.py``).
- :mod:`apex_tpu.observability.memstats` — live device-memory
  watermarks (``device.memory_stats()`` behind a provider interface,
  fake provider on CPU) cross-checked against the static analyzer's
  peak-HBM predictions (drift names the program), with an
  OOM-forensics hook that drains the watermark history into the
  flight recorder on allocation failure.
- :mod:`apex_tpu.observability.attribution` — step-time attribution
  and roofline analysis: the compiled cost model (per-op FLOPs/bytes
  bucketed matmul/attention/norm-elementwise/collective/other via
  ``analysis/hlo.py``) cross-checked against measured profiler trace
  windows, reduced to compute/collective/host-stall fractions and a
  per-bucket roofline (``tools/step_profile.py``,
  ``tools/bench_diff.py`` ride it).

See ``docs/observability.md`` for the full tour.
"""

from apex_tpu.observability.fleet import (  # noqa: F401
    FleetAggregator,
    FleetView,
)
from apex_tpu.observability.flight import (  # noqa: F401
    FlightRecorder,
    parse_flight_spec,
)
from apex_tpu.observability.health import (  # noqa: F401
    CheckpointStallRule,
    CollectiveFractionRule,
    HealthEvent,
    HostStallRule,
    InputStallRule,
    MemoryBudgetRule,
    QueueDepthRule,
    QueueWaitFractionRule,
    ServeFaultRule,
    SpecAcceptanceRule,
    TTFTRule,
    Watchdog,
    default_rules,
    goodput_rules,
    serve_rules,
)
from apex_tpu.observability.canary import (  # noqa: F401
    CanaryAnalyzer,
    CanaryConfig,
    CanaryController,
    CanaryVerdict,
    GoldenProbeSet,
    binom_tail,
    fingerprint_distance,
    mann_whitney_p,
    model_fingerprint,
)
from apex_tpu.observability.spans import (  # noqa: F401
    SpanRecorder,
    monotonic_to_epoch,
    wall_clock_anchor,
)
from apex_tpu.observability.attribution import (  # noqa: F401
    CostAttribution,
    TraceAttribution,
    attribute_cost_model,
    attribute_trace,
    attribute_trace_dir,
    hlo_bucket_map,
    publish_attribution,
    roofline_report,
)
from apex_tpu.observability.export import (  # noqa: F401
    CSVSink,
    JSONLSink,
    Reporter,
    TensorBoardSink,
    TimelineSink,
    bench_record,
)
from apex_tpu.observability.meter import (  # noqa: F401
    BUCKETS,
    GoodputAccountant,
    StepMeter,
    categorize_op,
    chip_peak_flops,
    peak_flops_for,
    peak_hbm_bandwidth_for,
    total_peak_flops,
    transformer_train_flops,
)
from apex_tpu.observability.metrics import (  # noqa: F401
    Board,
    MetricRegistry,
    board,
)
from apex_tpu.observability.locks import (  # noqa: F401
    TrackedLock,
    lock_order_graph,
    reset_sanitizer,
    sanitizer_report,
)
from apex_tpu.observability.locks import arm as locksan_arm  # noqa: F401
from apex_tpu.observability.locks import armed as locksan_armed  # noqa: F401
from apex_tpu.observability.locks import (  # noqa: F401
    attach_flight as locksan_attach_flight,
)
from apex_tpu.observability.ometrics import (  # noqa: F401
    Histogram,
    OpsServer,
    metric_name,
    parse_exposition,
)
from apex_tpu.observability.slo import (  # noqa: F401
    SLO,
    BurnRateTracker,
    CounterRatioSLO,
    LatencySLO,
    SLORule,
    Window,
    fleet_slo_rules,
    serve_slo_rules,
)
from apex_tpu.observability.memstats import (  # noqa: F401
    DeviceMemoryProvider,
    FakeMemoryProvider,
    MemStatsMonitor,
    MemStatsRule,
    oom_forensics,
)
# NOTE: the trace() context manager is deliberately NOT re-exported
# here — it would shadow the `apex_tpu.observability.trace` SUBMODULE
# attribute on the package.  Reach it as `observability.trace.trace`
# or via the long-standing `apex_tpu.utils.trace` alias.
from apex_tpu.observability import trace  # noqa: F401
from apex_tpu.observability.trace import (  # noqa: F401
    TraceScheduler,
    annotate,
    nvtx_range,
    range_pop,
    range_push,
)

__all__ = [
    "MetricRegistry",
    "Board",
    "board",
    "FlightRecorder",
    "parse_flight_spec",
    "FleetAggregator",
    "FleetView",
    "Watchdog",
    "HealthEvent",
    "default_rules",
    "goodput_rules",
    "serve_rules",
    "CheckpointStallRule",
    "CollectiveFractionRule",
    "HostStallRule",
    "InputStallRule",
    "MemoryBudgetRule",
    "TTFTRule",
    "QueueDepthRule",
    "QueueWaitFractionRule",
    "ServeFaultRule",
    "SpecAcceptanceRule",
    "SpanRecorder",
    "wall_clock_anchor",
    "monotonic_to_epoch",
    "CanaryAnalyzer",
    "CanaryConfig",
    "CanaryController",
    "CanaryVerdict",
    "GoldenProbeSet",
    "model_fingerprint",
    "fingerprint_distance",
    "mann_whitney_p",
    "binom_tail",
    "TrackedLock",
    "lock_order_graph",
    "sanitizer_report",
    "reset_sanitizer",
    "locksan_arm",
    "locksan_armed",
    "locksan_attach_flight",
    "OpsServer",
    "Histogram",
    "metric_name",
    "parse_exposition",
    "SLO",
    "CounterRatioSLO",
    "LatencySLO",
    "BurnRateTracker",
    "SLORule",
    "Window",
    "serve_slo_rules",
    "fleet_slo_rules",
    "MemStatsMonitor",
    "MemStatsRule",
    "DeviceMemoryProvider",
    "FakeMemoryProvider",
    "oom_forensics",
    "StepMeter",
    "GoodputAccountant",
    "BUCKETS",
    "categorize_op",
    "chip_peak_flops",
    "peak_flops_for",
    "peak_hbm_bandwidth_for",
    "total_peak_flops",
    "transformer_train_flops",
    "CostAttribution",
    "TraceAttribution",
    "attribute_cost_model",
    "attribute_trace",
    "attribute_trace_dir",
    "hlo_bucket_map",
    "publish_attribution",
    "roofline_report",
    "Reporter",
    "JSONLSink",
    "CSVSink",
    "TensorBoardSink",
    "TimelineSink",
    "bench_record",
    "TraceScheduler",
    "annotate",
    "nvtx_range",
    "range_push",
    "range_pop",
    "trace",  # the submodule (holding the trace() context manager)
]
