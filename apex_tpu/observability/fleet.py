"""Cross-host fleet aggregation — every host's telemetry on one board.

On a multi-host topology each process owns its own
:class:`~apex_tpu.observability.metrics.MetricRegistry` and
:class:`~apex_tpu.observability.meter.StepMeter`: host 0's JSONL shows
host 0's numbers, and the straggler dragging the pod runs invisibly on
host 5.  :class:`FleetAggregator` folds every participant's metric row
through ONE jitted all-gather (:func:`apex_tpu.parallel.comm
.all_gather_rows` — the comm engine's collective, so it shows up in
``collective_summary`` like any other wire traffic) into a
``(hosts, n_metrics)`` matrix of **per-host columns**, then publishes
min/median/max rollups on host 0's board.

The cadence discipline matches the registry exactly — **no per-step
host sync**:

- ``observe(step, values)`` on an off-cadence step is one tuple
  assignment (no device contact);
- on the cadence (``every`` — align it with the registry's
  ``fetch_every``) the newest row is placed on the mesh, the jitted
  gather is *dispatched* (async), and the gather started one cadence
  earlier is materialized — so the fleet view is at most
  ``2 * every`` steps stale and the host never blocks between
  cadences.

Participants are the rows of the mesh axis: on a real pod each
process's row rides its own devices
(``jax.make_array_from_callback`` fills only addressable shards, so
each host contributes its own values); on the single-process CPU test
mesh every device carries the same host row, and tests inject skewed
rows directly via :meth:`FleetAggregator.gather_rows` to simulate a
straggling host.  See ``docs/observability.md``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, NamedTuple, Optional, Tuple

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import parallel_state as ps

__all__ = ["FleetView", "FleetAggregator"]


class FleetView(NamedTuple):
    """One materialized fleet snapshot: per-host columns + rollups.

    ``host_ids`` labels each row with its real process index on a
    multi-process fleet (rows are collapsed to one per host before the
    view is built); None means row index == host label (the
    single-process simulation, one row per mesh-axis participant).
    """

    step: int
    names: Tuple[str, ...]
    rows: Any  # np.ndarray (hosts, n_metrics)
    host_ids: Optional[Tuple[int, ...]] = None

    @property
    def hosts(self) -> int:
        return int(self.rows.shape[0])

    @property
    def labels(self) -> Tuple[int, ...]:
        """The host label of each row."""
        if self.host_ids is not None:
            return tuple(self.host_ids)
        return tuple(range(self.hosts))

    def per_host(self, name: str) -> List[float]:
        """``name``'s value on every host (row order = :attr:`labels`)."""
        i = self.names.index(name)
        return [float(v) for v in self.rows[:, i]]

    def rollup(self, name: str) -> Dict[str, float]:
        vals = sorted(self.per_host(name))
        return {
            "min": vals[0],
            "median": vals[len(vals) // 2],
            "max": vals[-1],
        }

    def as_dict(self) -> Dict[str, Any]:
        """Board-shaped flat dict: ``fleet/<name>/host<i>`` columns +
        ``fleet/<name>/{min,median,max}`` rollups."""
        out: Dict[str, Any] = {"fleet/step": self.step}
        labels = self.labels
        for name in self.names:
            vals = self.per_host(name)
            for label, v in zip(labels, vals):
                out[f"fleet/{name}/host{label}"] = v
            roll = self.rollup(name)
            for k, v in roll.items():
                out[f"fleet/{name}/{k}"] = v
        return out


class FleetAggregator:
    """Gather each participant's metric row into per-host columns.

    >>> agg = FleetAggregator(("train/step_time_ms", "train/mfu"),
    ...                       every=32)
    >>> # per step, on the host (cheap off-cadence):
    >>> agg.observe(step, {**registry.values(), **meter.summary()})
    >>> view = agg.view()           # latest materialized FleetView
    >>> view.per_host("train/step_time_ms")

    ``names`` fixes the row layout (every host must declare the same
    names in the same order — they are SPMD programs of one job).
    Missing values observe as NaN, which survives the gather and reads
    back as "this host had no measurement".
    """

    def __init__(
        self,
        names,
        *,
        mesh=None,
        axis: str = ps.DATA_PARALLEL_AXIS,
        every: int = 32,
        publish: bool = True,
    ):
        if every < 1:
            raise ValueError("every must be >= 1")
        self.names = tuple(names)
        if not self.names:
            raise ValueError("need at least one metric name")
        self.n = len(self.names)
        self.mesh = mesh if mesh is not None else ps.get_mesh()
        self.axis = axis
        self.world = self.mesh.shape[axis]
        self.every = every
        self.publish = publish
        self._sharding = NamedSharding(self.mesh, P(axis))
        self._row_host = self._axis_row_hosts()
        self._gather = self._build_gather()
        self._pending: Optional[Tuple[int, Dict[str, float]]] = None
        self._inflight: Optional[Tuple[int, Any]] = None
        self._view: Optional[FleetView] = None

    def _axis_row_hosts(self) -> List[int]:
        """The owning process of each position along the axis — the map
        that collapses per-device rows into per-host columns on a real
        pod (each host's row rides ALL its devices on the axis, so the
        raw gather duplicates it ``devices_per_host`` times; scoring
        duplicated rows would dilute the straggler z-score and label
        device indices as hosts)."""
        try:
            axes = list(self.mesh.axis_names)
            devs = np.moveaxis(
                np.asarray(self.mesh.devices), axes.index(self.axis), 0
            ).reshape(self.world, -1)
            return [int(d.process_index) for d in devs[:, 0]]
        except Exception:
            return list(range(self.world))

    # -- the collective ----------------------------------------------------
    def _build_gather(self):
        from apex_tpu.parallel import comm

        axis = self.axis

        def inner(local):  # (1, n) — this participant's row
            return comm.all_gather_rows(local[0], axis)

        fn = jax.shard_map(
            inner, mesh=self.mesh, in_specs=P(axis), out_specs=P(),
            check_vma=False,
        )
        return jax.jit(fn)

    def _place_rows(self, row: np.ndarray):
        """A ``(world, n)`` array sharded one row per participant, each
        process filling only ITS addressable shards with ITS row —
        single- and multi-process uniformly."""

        def fill(index):
            rows = len(range(*index[0].indices(self.world)))
            return np.ascontiguousarray(
                np.broadcast_to(row, (rows, self.n))
            )

        return jax.make_array_from_callback(
            (self.world, self.n), self._sharding, fill
        )

    def gather_rows(self, rows) -> np.ndarray:
        """Run the jitted gather on a prepared ``(world, n)`` matrix and
        block for the result — the synchronous path tests (and offline
        analysis) use to inject per-host skew; production goes through
        :meth:`observe`'s async double buffer."""
        rows = np.asarray(rows, np.float32)
        if rows.shape != (self.world, self.n):
            raise ValueError(
                f"rows must be ({self.world}, {self.n}), got {rows.shape}"
            )
        placed = jax.device_put(rows, self._sharding)
        return np.asarray(self._gather(placed))

    # -- cadence / double buffer ------------------------------------------
    def observe(self, step: int, values: Mapping[str, Any]) -> None:
        """Stash this step's host-local values; gather on the cadence.

        Off-cadence: one tuple assignment.  On-cadence: dispatch the
        gather (async) and materialize the previous one.
        """
        self._pending = (int(step), dict(values))
        if step % self.every == 0:
            self._rotate()

    def _row(self, values: Mapping[str, Any]) -> np.ndarray:
        return np.asarray(
            [float(values.get(name, float("nan"))) for name in self.names],
            np.float32,
        )

    def _rotate(self) -> None:
        if self._inflight is not None:
            self._materialize(self._inflight)
            self._inflight = None
        if self._pending is not None:
            step, values = self._pending
            self._pending = None
            result = self._gather(self._place_rows(self._row(values)))
            copy = getattr(result, "copy_to_host_async", None)
            if copy is not None:
                copy()
            self._inflight = (step, result)

    def _materialize(self, stash) -> None:
        step, result = stash
        self._view = self._collapse(step, np.asarray(result))
        self._publish(self._view)

    def _collapse(self, step: int, rows: np.ndarray) -> FleetView:
        """One row per HOST.  Single-process (every row owned by
        process 0 — the test/simulation topology where each device
        stands in for a host) keeps the raw per-participant rows;
        multi-process keeps the first row of each owning process and
        labels rows with real process indices, so straggler events
        name hosts and ``fleet/*/host<i>`` columns mean host ``i``."""
        distinct = sorted(set(self._row_host))
        if len(distinct) <= 1:
            return FleetView(step, self.names, rows)
        first_row = {}
        for j, host in enumerate(self._row_host):
            first_row.setdefault(host, j)
        keep = [first_row[h] for h in distinct]
        return FleetView(step, self.names, rows[keep], tuple(distinct))

    def _publish(self, view: FleetView) -> None:
        """Columns + rollups onto the board — host 0 only (the host
        whose Reporter feeds the job-level JSONL/dashboard)."""
        if not self.publish:
            return
        from apex_tpu.parallel import multihost

        if multihost.host_id() != 0:
            return
        from apex_tpu.observability.metrics import board

        for key, value in view.as_dict().items():
            board.set(key, value)

    def view(self) -> Optional[FleetView]:
        """Latest materialized fleet view (no device contact; at most
        ``2 * every`` steps stale), or None before the first cadence."""
        return self._view

    def fetch(self) -> Optional[FleetView]:
        """Force-drain both buffers (blocks) — shutdown/dump path."""
        if self._inflight is not None:
            self._materialize(self._inflight)
            self._inflight = None
        if self._pending is not None:
            step, values = self._pending
            self._pending = None
            result = self._gather(self._place_rows(self._row(values)))
            self._materialize((step, result))
        return self._view
