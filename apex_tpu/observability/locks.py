"""TrackedLock — the runtime lock-order sanitizer (``APEX_TPU_LOCKSAN``).

The static half of the concurrency story
(:mod:`apex_tpu.analysis.concurrency`) proves lock DISCIPLINE — shared
attributes mutate under the class's lock.  This module validates the
dynamic half the static pass cannot see: lock ORDER.  Two locks
acquired in opposite nesting orders on two threads deadlock the first
time the schedules interleave badly — which on a quiet CI box may be
never, and in a preemption storm may be always.

:class:`TrackedLock` is a drop-in ``threading.Lock`` (context manager,
``acquire``/``release``) that always tracks cheap diagnostics —
:attr:`holder` (the owning thread's name) and :attr:`acquires` — so
surfaces like ``AsyncCheckpointEngine.close()`` can NAME the stuck
phase instead of hanging silently.  When the sanitizer is armed
(``APEX_TPU_LOCKSAN=1``, or :func:`arm` in tests) every acquisition is
also recorded into a per-thread held-stack and a global **lock-order
graph**: acquiring ``B`` while holding ``A`` adds edge ``A -> B``.  A
new edge that closes a cycle is a potential deadlock and reports
LOUDLY — a ``RuntimeWarning``, a board gauge (``locksan/cycles``), and
a ``locksan_cycle`` event on any attached flight recorder
(:func:`attach_flight` — ``run_resilient`` attaches its armed
recorder).

Armed paths in CI: the goodput drill (real checkpoint-writer thread;
the drill artifact records :func:`sanitizer_report` and the GOODPUT
gate asserts zero cycles) and the ``--ops-port`` train/serve paths
(the ``OpsServer`` scrape lock) — set ``APEX_TPU_LOCKSAN=1`` and every
TrackedLock in the process participates.  Unarmed, the overhead is one
env check (cached) per acquire.

See docs/analysis.md "Concurrency & replay-purity passes" and
docs/observability.md.
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Dict, List, Optional, Set

__all__ = [
    "ENV_LOCKSAN",
    "TrackedLock",
    "arm",
    "armed",
    "attach_flight",
    "lock_order_graph",
    "cycles",
    "sanitizer_report",
    "reset_sanitizer",
]

ENV_LOCKSAN = "APEX_TPU_LOCKSAN"


class _Sanitizer:
    """Process-global lock-order bookkeeping (armed-only)."""

    def __init__(self):
        self._mu = threading.Lock()
        #: lock name -> set of lock names acquired while holding it
        self._edges: Dict[str, Set[str]] = {}
        #: name -> acquire count (every TrackedLock seen while armed)
        self._counts: Dict[str, int] = {}
        self._cycles: List[dict] = []
        self._cycle_keys: Set[frozenset] = set()
        self._held = threading.local()
        self._flight = None
        self._armed: Optional[bool] = None  # None = read env lazily

    def armed(self) -> bool:
        if self._armed is None:
            self._armed = os.environ.get(ENV_LOCKSAN, "") == "1"
        return self._armed

    def arm(self, on: Optional[bool]) -> None:
        """True/False force the state; None re-reads the env."""
        self._armed = on

    def attach_flight(self, flight) -> None:
        self._flight = flight

    def _stack(self) -> List[str]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    # -- recording ---------------------------------------------------------
    def on_acquire(self, name: str) -> None:
        stack = self._stack()
        new_cycles = []
        with self._mu:
            self._counts[name] = self._counts.get(name, 0) + 1
            for prev in stack:
                if prev == name:  # reentrant re-acquire, not an edge
                    continue
                succ = self._edges.setdefault(prev, set())
                if name not in succ:
                    succ.add(name)
                    path = self._find_cycle(name, prev)
                    if path is not None:
                        key = frozenset(path)
                        if key not in self._cycle_keys:
                            self._cycle_keys.add(key)
                            record = {
                                "cycle": path,
                                "closing_edge": [prev, name],
                                "thread": threading.current_thread().name,
                            }
                            self._cycles.append(record)
                            new_cycles.append(record)
        stack.append(name)
        for record in new_cycles:
            self._report_cycle(record)

    def on_release(self, name: str) -> None:
        stack = self._stack()
        # remove the most recent occurrence (locks usually release LIFO
        # but the API does not require it)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    def _find_cycle(self, start: str, target: str):
        """DFS ``start -> ... -> target`` through the edge set (caller
        holds ``_mu``); the found path + the just-added closing edge
        ``target -> start`` is the cycle."""
        seen = {start}
        path = [start]

        def dfs(node: str) -> bool:
            for nxt in sorted(self._edges.get(node, ())):
                if nxt == target:
                    path.append(nxt)
                    return True
                if nxt not in seen:
                    seen.add(nxt)
                    path.append(nxt)
                    if dfs(nxt):
                        return True
                    path.pop()
            return False

        return path if dfs(start) else None

    def _report_cycle(self, record: dict) -> None:
        chain = " -> ".join(record["cycle"] + [record["cycle"][0]])
        warnings.warn(
            f"LOCKSAN: lock-order cycle {chain} (edge "
            f"{record['closing_edge'][0]} -> {record['closing_edge'][1]}"
            f" closed it on thread '{record['thread']}') — two threads "
            "taking these locks in opposite orders can deadlock",
            RuntimeWarning,
            stacklevel=4,
        )
        try:
            from apex_tpu.observability.metrics import board

            board.set("locksan/cycles", len(self._cycles))
        except ImportError:  # pragma: no cover - partial install
            pass
        if self._flight is not None:
            try:
                self._flight.note("locksan_cycle", **record)
            except Exception:  # the report must never kill the holder
                pass

    # -- reporting ---------------------------------------------------------
    def graph(self) -> Dict[str, list]:
        with self._mu:
            return {a: sorted(bs) for a, bs in sorted(self._edges.items())}

    def cycles(self) -> List[dict]:
        with self._mu:
            return [dict(c) for c in self._cycles]

    def report(self) -> dict:
        with self._mu:
            return {
                "armed": self.armed(),
                "locks": dict(sorted(self._counts.items())),
                "edges": [
                    [a, b]
                    for a, bs in sorted(self._edges.items())
                    for b in sorted(bs)
                ],
                "cycles": [dict(c) for c in self._cycles],
            }

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._counts.clear()
            self._cycles.clear()
            self._cycle_keys.clear()
        self._held = threading.local()


_SAN = _Sanitizer()


class TrackedLock:
    """Drop-in ``threading.Lock`` with sanitizer hooks and diagnostics.

    ``name`` keys the lock-order graph — give every lock a stable,
    human-readable name (``"ckpt.stats"``, ``"ops.scrape"``).
    ``reentrant=True`` wraps an ``RLock`` for the rare owner-recursive
    path; re-acquiring a held reentrant lock adds no graph edge.

    :attr:`holder` / :attr:`acquires` are best-effort diagnostics
    (written only by the owning thread between acquire and release) —
    what ``AsyncCheckpointEngine.close()`` prints when the writer
    wedges.
    """

    def __init__(self, name: str, *, reentrant: bool = False):
        self._lock = threading.RLock() if reentrant else threading.Lock()
        self.name = str(name)
        self._holder: Optional[str] = None
        self._acquires = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._holder = threading.current_thread().name
            self._acquires += 1
            if _SAN.armed():
                _SAN.on_acquire(self.name)
        return got

    def release(self) -> None:
        if _SAN.armed():
            _SAN.on_release(self.name)
        self._holder = None
        self._lock.release()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    @property
    def holder(self) -> Optional[str]:
        """Thread name currently holding the lock (None when free)."""
        return self._holder

    @property
    def acquires(self) -> int:
        """Total successful acquisitions (diagnostic counter)."""
        return self._acquires

    def locked(self) -> bool:
        return self._holder is not None

    def __repr__(self):
        state = f"held by {self._holder}" if self._holder else "free"
        return f"TrackedLock({self.name!r}, {state}, " \
               f"acquires={self._acquires})"


def armed() -> bool:
    """Whether the sanitizer records acquisitions (env or :func:`arm`)."""
    return _SAN.armed()


def arm(on: Optional[bool] = True) -> None:
    """Force the sanitizer on/off for this process (tests, drills);
    ``arm(None)`` reverts to the ``APEX_TPU_LOCKSAN`` env check."""
    _SAN.arm(on)


def attach_flight(flight) -> None:
    """Route cycle reports onto a flight recorder's event log
    (``locksan_cycle`` events) — ``run_resilient`` attaches its armed
    recorder so a potential deadlock lands in the crash dump."""
    _SAN.attach_flight(flight)


def lock_order_graph() -> Dict[str, list]:
    """``{lock: [locks acquired while holding it]}`` observed so far."""
    return _SAN.graph()


def cycles() -> List[dict]:
    """Distinct lock-order cycles detected (each a potential deadlock)."""
    return _SAN.cycles()


def sanitizer_report() -> dict:
    """The artifact section the goodput drill records: armed flag,
    per-lock acquire counts, the edge list, and any cycles."""
    return _SAN.report()


def reset_sanitizer() -> None:
    """Clear graph/counters/cycles (test isolation)."""
    _SAN.reset()
