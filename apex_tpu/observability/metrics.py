"""Device-side metric registry — step telemetry without host syncs.

The reference stack's training scripts print loss/grad-norm by pulling
device scalars to the host every step — a forced ``device→host`` sync
that serializes dispatch and, over this environment's remote TPU
tunnel, costs more than the step itself.  :class:`MetricRegistry`
splits the problem the functional-JAX way:

- **inside the jitted step** the metrics live in a small pytree of f32
  scalars threaded through the step like any other state
  (``state = registry.update(state, {...})``).  Counters add, gauges
  replace, ``min``/``max`` fold — a handful of scalar ops fused into
  the step program, far below the <1% overhead budget
  (``tests/test_observability.py`` asserts it).
- **on the host** :meth:`MetricRegistry.observe` is called once per
  step with the *device* state.  It only stashes the array references
  (JAX dispatch is async — holding an array does not sync).  Every
  ``fetch_every`` steps it starts an **async** device→host copy of the
  newest state and materializes the copy started one cadence earlier,
  so a value is at most ``2 * fetch_every`` steps stale and the host
  never blocks on the device between fetches.

Host-side-only values (wall-clock timings, static config) go on the
module-level :data:`board` — a plain gauge dictionary with no device
involvement — which ``apex_tpu.parallel.comm`` uses to publish the
wire-byte/collective-count plan of every gradient sync at trace time.

See ``docs/observability.md``.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Mapping, Optional

import jax
import jax.numpy as jnp

__all__ = ["MetricRegistry", "Board", "board"]

_KINDS = ("counter", "gauge", "min", "max")


class MetricRegistry:
    """Declare metrics, accumulate them in-jit, fetch them on a cadence.

    >>> reg = MetricRegistry(fetch_every=32)
    >>> reg.gauge("train/loss")
    >>> reg.counter("train/skips")
    >>> state = reg.init()                      # pytree of f32 scalars
    >>> # ... inside the jitted step:
    >>> #   state = reg.update(state, {"train/loss": loss, ...})
    >>> # ... on the host, once per step:
    >>> #   reg.observe(step, state)
    >>> reg.fetch()                             # force-drain at the end
    >>> reg.values()                            # {name: float}

    ``update`` raises ``KeyError`` on an undeclared name — a typo'd
    metric must fail at trace time, not vanish silently.
    """

    def __init__(self, *, fetch_every: int = 32):
        if fetch_every < 1:
            raise ValueError("fetch_every must be >= 1")
        from apex_tpu.observability.ometrics import ExportNamespace

        self.fetch_every = fetch_every
        self._kinds: Dict[str, str] = {}
        self._units: Dict[str, str] = {}
        # every declared key must round-trip through the OpenMetrics
        # name mapping without collisions — a key an --ops-port scrape
        # cannot represent fails HERE, at declare time
        self._export = ExportNamespace()
        self._values: Dict[str, float] = {}
        self._fetched_step: Optional[int] = None
        # double buffer: _pending is the newest observed device state,
        # _inflight the one whose async host copy is already running
        self._pending = None  # (step, state)
        self._inflight = None  # (step, state)
        self._timings: Dict[str, Dict[str, float]] = {}

    # -- declaration -------------------------------------------------------
    def _declare(self, name: str, kind: str, unit: str) -> None:
        assert kind in _KINDS
        prev = self._kinds.get(name)
        if prev is not None and prev != kind:
            raise ValueError(
                f"metric {name!r} already declared as {prev!r}"
            )
        # ValueError on an exporter-illegal key or a post-mangling
        # collision with an existing key (idempotent on re-declares)
        self._export.declare(name, kind)
        self._kinds[name] = kind
        self._units[name] = unit

    def counter(self, name: str, unit: str = "count") -> None:
        """A monotonically accumulating value (``update`` adds)."""
        self._declare(name, "counter", unit)

    def gauge(self, name: str, unit: str = "") -> None:
        """A point-in-time value (``update`` replaces)."""
        self._declare(name, "gauge", unit)

    def minimum(self, name: str, unit: str = "") -> None:
        self._declare(name, "min", unit)

    def maximum(self, name: str, unit: str = "") -> None:
        self._declare(name, "max", unit)

    def unit(self, name: str) -> str:
        return self._units.get(name, "")

    def kind(self, name: str) -> str:
        """``"counter" | "gauge" | "min" | "max"`` for a declared
        metric (the OpenMetrics exporter's type source)."""
        return self._kinds[name]

    @property
    def names(self):
        return tuple(self._kinds)

    # -- device side -------------------------------------------------------
    def init(self) -> Dict[str, jax.Array]:
        """Fresh device state: one f32 scalar per declared metric
        (``min``/``max`` seed at ±inf)."""
        out = {}
        for name, kind in self._kinds.items():
            if kind == "min":
                out[name] = jnp.asarray(jnp.inf, jnp.float32)
            elif kind == "max":
                out[name] = jnp.asarray(-jnp.inf, jnp.float32)
            else:
                out[name] = jnp.zeros((), jnp.float32)
        return out

    def update(
        self, state: Mapping[str, Any], values: Mapping[str, Any]
    ) -> Dict[str, jax.Array]:
        """Fold ``values`` into ``state`` — call INSIDE the jitted step.

        Everything is cast to an f32 scalar; booleans count as 0/1 so a
        skip flag feeds a counter directly.
        """
        out = dict(state)
        for name, value in values.items():
            kind = self._kinds.get(name)
            if kind is None:
                raise KeyError(
                    f"metric {name!r} not declared on this registry "
                    f"(have {sorted(self._kinds)})"
                )
            v = jnp.asarray(value, jnp.float32)
            if kind == "counter":
                out[name] = out[name] + v
            elif kind == "min":
                out[name] = jnp.minimum(out[name], v)
            elif kind == "max":
                out[name] = jnp.maximum(out[name], v)
            else:
                out[name] = v
        return out

    # -- host side ---------------------------------------------------------
    def observe(self, step: int, state: Mapping[str, Any]) -> None:
        """Stash the step's device state; fetch on the cadence.

        Called once per step with CONCRETE arrays (outside jit).  Cheap
        on off-cadence steps: one tuple assignment, no device contact.
        """
        self._pending = (int(step), dict(state))
        if step % self.fetch_every == 0:
            self._rotate()

    def _rotate(self) -> None:
        if self._inflight is not None:
            self._materialize(self._inflight)
            self._inflight = None
        if self._pending is not None:
            step, state = self._pending
            for v in state.values():
                copy = getattr(v, "copy_to_host_async", None)
                if copy is not None:
                    copy()
            self._inflight = (step, state)
            self._pending = None

    def _materialize(self, stash) -> None:
        step, state = stash
        for name, v in state.items():
            self._values[name] = float(v)
        self._fetched_step = step

    def fetch(self) -> Dict[str, float]:
        """Force-drain both buffers (blocks) and return the values —
        call at checkpoints / shutdown, not per step.

        The pending stash (the NEWEST observed state) is flushed in a
        ``finally``: even when materializing the in-flight copy raises
        (a device buffer poisoned by the failure being debugged), the
        newest values still land — the flight recorder's last frame
        must never be one cadence stale because an OLDER fetch died.
        """
        inflight, self._inflight = self._inflight, None
        pending, self._pending = self._pending, None
        try:
            if inflight is not None:
                self._materialize(inflight)
        finally:
            if pending is not None:
                self._materialize(pending)
        return dict(self._values)

    def close(self) -> Dict[str, float]:
        """Best-effort drain for exception paths: like :meth:`fetch`
        but NEVER raises — per-value failures keep the previous value
        so a partially poisoned state still yields its healthy scalars
        (the dump path of :class:`~apex_tpu.observability.flight.
        FlightRecorder` relies on this)."""
        for stash in (self._inflight, self._pending):
            if stash is None:
                continue
            step, state = stash
            landed = False
            for name, v in state.items():
                try:
                    self._values[name] = float(v)
                    landed = True
                except Exception:
                    pass
            # only claim the stash's freshness if something from it
            # actually materialized — a fully poisoned stash must not
            # stamp cadence-old values with the crash step in the dump
            if landed:
                self._fetched_step = step
        self._inflight = self._pending = None
        return dict(self._values)

    def values(self) -> Dict[str, float]:
        """Latest fetched values (no device contact; possibly stale by
        up to ``2 * fetch_every`` steps)."""
        return dict(self._values)

    @property
    def fetched_step(self) -> Optional[int]:
        """The step the current :meth:`values` were captured at."""
        return self._fetched_step

    # -- host-side timings -------------------------------------------------
    @contextlib.contextmanager
    def timing(self, name: str):
        """Host-side duration stat: ``with reg.timing("io/save"): ...``
        accumulates {count, total_s, last_s} — wall clock, never device
        time (use :mod:`apex_tpu.observability.trace` for that)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            rec = self._timings.setdefault(
                name, {"count": 0.0, "total_s": 0.0, "last_s": 0.0}
            )
            rec["count"] += 1.0
            rec["total_s"] += dt
            rec["last_s"] = dt

    def timings(self) -> Dict[str, Dict[str, float]]:
        return {k: dict(v) for k, v in self._timings.items()}


class Board:
    """Host-side gauge board: module-level, no device state.

    The escape hatch for values produced where no registry is in scope
    — ``apex_tpu.parallel.comm`` publishes each gradient sync's planned
    wire bytes / collective count here at trace time.  Values are plain
    Python scalars or short strings.
    """

    def __init__(self):
        self._values: Dict[str, Any] = {}

    def set(self, name: str, value) -> None:
        self._values[name] = value

    def get(self, name: str, default=None):
        return self._values.get(name, default)

    def snapshot(self) -> Dict[str, Any]:
        return dict(self._values)

    def clear(self) -> None:
        self._values.clear()


#: The process-wide board (cleared by tests via ``board.clear()``).
board = Board()
