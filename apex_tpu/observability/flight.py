"""Flight recorder — crash forensics for training runs.

When a run dies — NaN budget exhausted, preemption mid-rollback, a hung
collective — the telemetry that explains *why* usually dies with it:
the :class:`~apex_tpu.observability.metrics.MetricRegistry` values live
in process memory and the JSONL reporter only writes on its cadence.
:class:`FlightRecorder` is the black box: a bounded ring of the last
``capacity`` steps' host-side telemetry (fetched metrics, skip flags,
step times) plus an event log (rollbacks, resumes, retries, preemption,
health events), dumped **atomically** to ``flight_<ts>.json`` when the
run ends badly.

Armed three ways:

- **explicitly** — construct one, attach sources, pass it to
  :func:`apex_tpu.resilience.run_resilient` via ``flight=`` (the
  resilient example does this; ``--flight N[:DIR]``);
- **by env** — ``APEX_TPU_FLIGHT=N[:DIR]`` arms a recorder inside any
  ``run_resilient`` loop with no code changes (the
  :class:`~apex_tpu.observability.trace.TraceScheduler` pattern);
- **standalone** — ``bench.py --flight`` records every emitted metric
  line and dumps on an unhandled exception.

``run_resilient`` dumps on unhandled exceptions (which covers
skip-budget exhaustion — the ``max_rollbacks`` ``RuntimeError``) and on
SIGTERM/preemption.  The dump drains the registry's async fetch
buffers first (:meth:`MetricRegistry.close` — best-effort, never
raises), so the final frame carries the guard/scaler state *at death*,
not one fetch cadence earlier.

Recording is host-side only: a frame copies the registry's cached
values (a dict copy — no device contact) and never forces a device
sync.  Rollback replays that rewind the step counter are recorded
as-is with a ``replay`` mark — the ring keeps both passes, ordered by
a monotonic ``seq``, which is exactly what a postmortem wants to see
(``tools/flight_view.py`` renders the timeline).

See ``docs/observability.md``.
"""

from __future__ import annotations

import collections
import json
import math
import os
import time
from typing import Any, Dict, List, Mapping, Optional, Tuple

__all__ = [
    "ENV_FLIGHT",
    "DEFAULT_FLIGHT_DIR",
    "DEFAULT_CAPACITY",
    "parse_flight_spec",
    "json_safe",
    "FlightRecorder",
]

ENV_FLIGHT = "APEX_TPU_FLIGHT"
DEFAULT_FLIGHT_DIR = "/tmp/apex_tpu_flight"
DEFAULT_CAPACITY = 64


def parse_flight_spec(spec: str) -> Tuple[int, Optional[str]]:
    """``(capacity, dir_override)`` from an ``APEX_TPU_FLIGHT`` value.

    Accepted: ``"N"`` (ring of N steps) optionally followed by
    ``:DIR``; ``"0"`` means disabled (callers treat it as unarmed).
    """
    spec = spec.strip()
    dir_override = None
    if ":" in spec:
        head, dir_override = spec.split(":", 1)
        spec, dir_override = head.strip(), dir_override.strip()
    try:
        capacity = int(spec)
    except ValueError:
        raise ValueError(
            f"bad {ENV_FLIGHT} spec {spec!r}; want 'N' or 'N:DIR'"
        )
    if capacity < 0:
        raise ValueError(f"flight capacity must be >= 0, got {capacity}")
    return capacity, dir_override


def json_safe(value):
    """Make ``value`` JSON-serializable without destroying forensics:
    non-finite floats become the strings ``"NaN"`` / ``"Infinity"`` /
    ``"-Infinity"`` (a NaN loss IS the evidence — ``null`` would erase
    it, a bare NaN token is invalid JSON).  The ONE non-finite encoding
    shared by every observability artifact: flight dumps, span dumps
    (:mod:`~apex_tpu.observability.spans`), Perfetto timelines
    (:class:`~apex_tpu.observability.export.TimelineSink`), and the
    ``tools/serve_bench.py`` acceptance JSON."""
    if isinstance(value, float):
        if math.isnan(value):
            return "NaN"
        if math.isinf(value):
            return "Infinity" if value > 0 else "-Infinity"
        return value
    if isinstance(value, dict):
        return {str(k): json_safe(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [json_safe(v) for v in value]
    if isinstance(value, (str, int, bool)) or value is None:
        return value
    try:
        return json_safe(float(value))
    except Exception:
        return repr(value)


class FlightRecorder:
    """Ring buffer of recent telemetry + event log, dumped on failure.

    Implements the ``run_resilient`` observer protocol (``on_step`` /
    ``on_rollback`` / ``on_resume`` / ``on_preempt`` / ``on_retry``),
    so arming it is just adding it to the observer fan-out — the runner
    does that automatically when ``flight=`` is given or
    ``APEX_TPU_FLIGHT`` is set.

    ``registry`` / ``meter`` / ``goodput`` enrich frames and the dump;
    attach them late via :meth:`attach` when the recorder is created
    before the training program (the env-armed path).
    """

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        directory: Optional[str] = None,
        *,
        registry=None,
        meter=None,
        goodput=None,
        include_board: bool = True,
        run: Optional[Mapping[str, Any]] = None,
        _clock=time.time,
    ):
        if capacity < 1:
            raise ValueError("flight capacity must be >= 1")
        self.capacity = int(capacity)
        self.directory = directory or os.environ.get(
            ENV_FLIGHT + "_DIR", DEFAULT_FLIGHT_DIR
        )
        self.registry = registry
        self.meter = meter
        self.goodput = goodput
        self.include_board = include_board
        self.run = dict(run or {})
        self._clock = _clock
        self._frames: collections.deque = collections.deque(maxlen=capacity)
        # events are rarer than frames but must survive longer — a
        # rollback 200 steps ago still explains a dump; bound anyway
        self._events: collections.deque = collections.deque(
            maxlen=max(4 * capacity, 256)
        )
        self._seq = 0
        self._prev_step: Optional[int] = None
        self.dumps: List[str] = []

    @classmethod
    def from_env(cls, spec: Optional[str] = None, **kwargs):
        """A recorder armed by ``APEX_TPU_FLIGHT=N[:DIR]``, or ``None``
        when the env is unset/empty/``0`` (the unarmed no-op path)."""
        spec = spec if spec is not None else os.environ.get(ENV_FLIGHT)
        if not spec:
            return None
        capacity, dir_override = parse_flight_spec(spec)
        if capacity == 0:
            return None
        if dir_override:
            kwargs["directory"] = dir_override
        return cls(capacity, **kwargs)

    def attach(self, *, registry=None, meter=None, goodput=None) -> None:
        """Late-bind telemetry sources (env-armed recorders exist before
        the training program does)."""
        if registry is not None:
            self.registry = registry
        if meter is not None:
            self.meter = meter
        if goodput is not None:
            self.goodput = goodput

    # -- recording ---------------------------------------------------------
    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq - 1

    def on_step(self, step: int, skipped: bool = False, info=None) -> None:
        """Record one step frame — host-side dict copies only, never a
        device sync (the registry's cached values may be a cadence
        stale; :meth:`dump` drains the fresh ones)."""
        step = int(step)
        frame: Dict[str, Any] = {
            "seq": self._next_seq(),
            "step": step,
            "t": self._clock(),
            "skipped": bool(skipped),
        }
        if self._prev_step is not None and step <= self._prev_step:
            # a rollback replay rewound the counter: keep recording —
            # both passes are evidence — but mark the frame so the
            # timeline renders the rewind instead of hiding it
            frame["replay"] = True
        self._prev_step = step
        if self.registry is not None:
            frame["metrics"] = self.registry.values()
            frame["fetched_step"] = self.registry.fetched_step
        if self.meter is not None:
            frame["step_time_ms"] = self.meter.step_time * 1e3
        self._frames.append(frame)

    def note(self, kind: str, **data) -> None:
        """Append an event (rollback, retry, health, ...) to the log."""
        self._events.append(
            {"seq": self._next_seq(), "t": self._clock(), "kind": kind,
             **data}
        )

    # observer protocol (events)
    def on_rollback(
        self, step: int, anchor: int, skips: int = 0,
        discarded: Optional[int] = None,
    ) -> None:
        self.note(
            "rollback", step=int(step), anchor=int(anchor),
            skips=int(skips),
            discarded=None if discarded is None else int(discarded),
        )
        # the replay restarts below the anchor; reset the rewind marker
        # baseline so the FIRST replayed frame carries the replay mark
        # relative to the pre-rollback position (kept as-is: on_step
        # compares against the real previous step)

    def on_resume(self, step: int) -> None:
        self.note("resume", step=int(step))

    def on_preempt(self, step: int) -> None:
        self.note("preempt", step=int(step))

    def on_retry(self, what: str = "", attempt: int = 0, error=None) -> None:
        self.note(
            "retry", what=str(what), attempt=int(attempt),
            error=None if error is None else f"{type(error).__name__}: {error}",
        )

    def on_checkpoint(self, step, info=None) -> None:
        """A checkpoint event — the enqueue (``info=None``) or a
        completed async-engine phase (``info`` = the engine's event
        record: write/finalize timings, ok flag).  A postmortem wants
        these next to the step frames: "did the state at death ever
        reach disk" is the first question."""
        data = dict(info) if info else {"phase": "enqueue"}
        data.pop("step", None)
        self.note(
            "checkpoint", step=-1 if step is None else int(step), **data
        )

    def note_health(self, event) -> None:
        """Record a :class:`apex_tpu.observability.health.HealthEvent`."""
        self.note(
            "health", rule=event.rule, severity=event.severity,
            step=int(event.step), value=event.value,
            threshold=event.threshold, message=event.message,
            host=event.host,
        )

    # -- introspection -----------------------------------------------------
    @property
    def frames(self) -> List[Dict[str, Any]]:
        return list(self._frames)

    @property
    def events(self) -> List[Dict[str, Any]]:
        return list(self._events)

    # -- the dump ----------------------------------------------------------
    def dump(self, reason: str, directory: Optional[str] = None) -> str:
        """Write the black box to ``flight_<ts>.json`` atomically
        (tmp + ``os.replace`` — a reader never sees a torn file) and
        return the path.

        Drains the registry's async buffers first via
        :meth:`MetricRegistry.close` (best-effort — a poisoned device
        buffer must not lose the dump) and appends a ``final`` frame
        with the freshest values, so the last state the dump shows is
        the state at death, not one fetch cadence earlier.
        """
        final: Dict[str, Any] = {"t": self._clock()}
        if self.registry is not None:
            final["metrics"] = self.registry.close()
            final["fetched_step"] = self.registry.fetched_step
        if self.meter is not None:
            final["meter"] = self.meter.summary()
        host = {"id": 0, "count": 1}
        try:
            from apex_tpu.parallel import multihost

            host = {"id": multihost.host_id(), "count": multihost.host_count()}
        except Exception:
            pass
        # the per-process monotonic→epoch anchor (captured once in
        # observability.spans): lets tools/timeline.py line this dump
        # up against span records from the same or other processes
        try:
            from apex_tpu.observability.spans import wall_clock_anchor

            anchor = wall_clock_anchor()
        except Exception:
            anchor = None
        payload: Dict[str, Any] = {
            "version": 1,
            "reason": str(reason),
            "wall_time": self._clock(),
            "anchor": anchor,
            "host": host,
            "capacity": self.capacity,
            "run": self.run,
            "frames": self.frames,
            "final": final,
            "events": self.events,
        }
        if self.goodput is not None:
            payload["goodput"] = self.goodput.snapshot()
        if self.include_board:
            from apex_tpu.observability.metrics import board

            payload["board"] = board.snapshot()
        directory = directory or self.directory
        os.makedirs(directory, exist_ok=True)
        ts = time.strftime("%Y%m%d_%H%M%S")
        path = os.path.join(
            directory, f"flight_{ts}_{os.getpid()}_{self._seq}.json"
        )
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(json_safe(payload), f, indent=1, allow_nan=False)
            f.write("\n")
        os.replace(tmp, path)
        self.dumps.append(path)
        return path
