"""Tracing hooks + scheduled on-chip profiling windows.

The annotation half (moved here from ``apex_tpu/utils/profiling.py``,
which remains as a deprecation shim) is the TPU analog of the
reference's NVTX ranges:

- :func:`annotate` (``jax.named_scope``) names a region of the *traced*
  computation — the name lands in HLO metadata and therefore in the XLA
  op-profile / Perfetto trace for every kernel fused from that region.
- :func:`nvtx_range` / :func:`range_push` / :func:`range_pop` name a
  span on the *host* timeline (``jax.profiler.TraceAnnotation``), for
  dispatch-side bracketing exactly like NVTX.
- :func:`trace` wraps a block in ``jax.profiler.trace`` and writes a
  TensorBoard/Perfetto-viewable profile directory (bench.py --trace).

All hooks are zero-cost when no profiler is attached: ``named_scope``
only adds HLO metadata at trace time and ``TraceAnnotation`` is a no-op
without an active collector.

The scheduling half is new: :class:`TraceScheduler` captures a profile
of steps ``N..M`` of a *running* job without editing the training
script — set ::

    APEX_TPU_TRACE_STEPS="1200+3"            # steps 1200..1202
    APEX_TPU_TRACE_STEPS="1200..1205"        # explicit end (inclusive)
    APEX_TPU_TRACE_STEPS="1200+3:/tmp/prof"  # dir override inline
    APEX_TPU_TRACE_DIR=/tmp/prof             # dir the windows land in

and call ``scheduler.on_step(step)`` at the top of each step (the
resilient example and ``run_resilient`` consumers already do).  Each
window writes ``<dir>/steps_<start>_<end>/`` — the layout
``tools/trace_summary.py`` discovers — so a flaky-tunnel on-chip
session can arm a capture via env alone and pick the artifact up later.
"""

from __future__ import annotations

import contextlib
import os
import re
from typing import Iterator, List, Optional, Tuple

import jax

__all__ = [
    "annotate",
    "nvtx_range",
    "range_push",
    "range_pop",
    "trace",
    "parse_trace_spec",
    "window_dir",
    "TraceScheduler",
    "ENV_TRACE_STEPS",
    "ENV_TRACE_DIR",
]

ENV_TRACE_STEPS = "APEX_TPU_TRACE_STEPS"
ENV_TRACE_DIR = "APEX_TPU_TRACE_DIR"
DEFAULT_TRACE_DIR = "/tmp/apex_tpu_trace"

# module-level stack for the push/pop API (host-side spans, NVTX-style)
_RANGE_STACK: List[contextlib.AbstractContextManager] = []


def annotate(name: str):
    """Name a traced-computation region (``jax.named_scope``).

    Use inside jitted code; the name propagates into HLO metadata so the
    XLA profiler attributes fused kernels to it.
    """
    return jax.named_scope(name)


@contextlib.contextmanager
def nvtx_range(name: str) -> Iterator[None]:
    """Host-timeline span (≙ ``torch.cuda.nvtx.range`` context manager)."""
    with jax.profiler.TraceAnnotation(name):
        yield


def range_push(name: str) -> None:
    """≙ ``torch.cuda.nvtx.range_push`` — begin a host-timeline span."""
    cm = jax.profiler.TraceAnnotation(name)
    cm.__enter__()
    _RANGE_STACK.append(cm)


def range_pop() -> None:
    """≙ ``torch.cuda.nvtx.range_pop`` — end the innermost span."""
    if not _RANGE_STACK:
        raise RuntimeError("range_pop() without matching range_push()")
    _RANGE_STACK.pop().__exit__(None, None, None)


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Collect a device+host profile into ``log_dir`` (TensorBoard /
    Perfetto viewable).  Wrap a steady-state window, not compilation."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def parse_trace_spec(spec: str) -> Tuple[int, int, Optional[str]]:
    """``(start, end_inclusive, dir_override)`` from a spec string.

    Accepted: ``"N"`` (one step), ``"N+K"`` (K steps from N),
    ``"N..M"`` (inclusive), each optionally followed by ``:DIR``.
    """
    spec = spec.strip()
    dir_override = None
    m = re.match(r"^([^:]+):(.+)$", spec)
    if m:
        spec, dir_override = m.group(1).strip(), m.group(2).strip()
    m = re.match(r"^(\d+)\s*(?:(\+|\.\.)\s*(\d+))?$", spec)
    if not m:
        raise ValueError(
            f"bad {ENV_TRACE_STEPS} spec {spec!r}; want 'N', 'N+K', "
            "or 'N..M' (optionally ':DIR')"
        )
    start = int(m.group(1))
    if m.group(2) is None:
        end = start
    elif m.group(2) == "+":
        k = int(m.group(3))
        if k < 1:
            raise ValueError(f"window length must be >= 1, got {k}")
        end = start + k - 1
    else:
        end = int(m.group(3))
    if end < start:
        raise ValueError(f"trace window ends ({end}) before it starts ({start})")
    return start, end, dir_override


def window_dir(base_dir: str, start: int, end: int) -> str:
    """The per-window directory layout trace_summary.py discovers."""
    return os.path.join(base_dir, f"steps_{start:06d}_{end:06d}")


class TraceScheduler:
    """Arm a profile window on a step schedule — env-driven by default.

    >>> sched = TraceScheduler()        # reads APEX_TPU_TRACE_STEPS
    >>> for step in range(num_steps):
    ...     sched.on_step(step)         # starts/stops the window
    ...     run_one_step()
    >>> sched.stop()                    # safety net past the last step

    With no spec configured every call is a cheap no-op.  The profiler
    collects from the ``on_step(start)`` call until the
    ``on_step(end + 1)`` call, i.e. steps ``start..end`` inclusive.
    A step that moves BACKWARD mid-window (a resilience rollback
    replaying from a checkpoint) aborts the capture and re-arms: the
    partial file would mix the restore with replayed earlier steps, so
    the window is taken cleanly on the replay pass instead (the latest
    file in the window dir is the good one — what trace_summary reads).
    A capture only ever begins at exactly ``start`` — a resume or
    replay that lands INSIDE the window would produce a partial capture
    mislabeled with the full range, so it never triggers (re-arm with a
    reachable window instead).
    """

    def __init__(
        self,
        spec: Optional[str] = None,
        base_dir: Optional[str] = None,
        *,
        spans=None,
        _start_fn=None,
        _stop_fn=None,
    ):
        #: optional :class:`~apex_tpu.observability.spans.SpanRecorder`
        #: — each captured window records a ``trace/window`` span, so
        #: on-chip profile artifacts locate themselves on the merged
        #: timeline (``tools/timeline.py``)
        self.spans = spans
        self._capture_t0 = None
        spec = spec if spec is not None else os.environ.get(ENV_TRACE_STEPS)
        self.start = self.end = None
        dir_override = None
        if spec:
            self.start, self.end, dir_override = parse_trace_spec(spec)
        self.base_dir = (
            dir_override
            or base_dir
            or os.environ.get(ENV_TRACE_DIR, DEFAULT_TRACE_DIR)
        )
        self.log_dir = (
            window_dir(self.base_dir, self.start, self.end)
            if self.start is not None
            else None
        )
        self._tracing = False
        self._done = False
        self._prev_step = None
        # injectable for tests; default to the real profiler
        self._start_fn = _start_fn or jax.profiler.start_trace
        self._stop_fn = _stop_fn or jax.profiler.stop_trace

    @property
    def active(self) -> bool:
        """True when a window is configured and not yet captured."""
        return self.start is not None and not self._done

    def arm(self, start: int, length: int = 1,
            base_dir: Optional[str] = None) -> None:
        """(Re-)arm a window of ``length`` steps from ``start`` at
        runtime — the escalation hook a health ``on_unhealthy``
        callback uses to turn an alert into an on-chip profile in the
        same run (``docs/observability.md``).  An in-flight capture is
        closed first; a window already armed for a *future* start is
        left alone (first alert wins — re-arming per repeated alert
        would keep pushing the window out of reach)."""
        if length < 1:
            raise ValueError(f"window length must be >= 1, got {length}")
        if self.active and (
            self._prev_step is None or self.start > self._prev_step
        ):
            return
        if self._tracing:
            self._abort("rearm")
        self.start, self.end = int(start), int(start) + length - 1
        if base_dir is not None:
            self.base_dir = base_dir
        self.log_dir = window_dir(self.base_dir, self.start, self.end)
        self._done = False

    @property
    def tracing(self) -> bool:
        return self._tracing

    def on_step(self, step: int) -> None:
        """Call at the TOP of every step (before dispatching its work)."""
        if not self.active:
            return
        rewound = self._prev_step is not None and step <= self._prev_step
        self._prev_step = step
        if self._tracing:
            if rewound:
                # rollback replay mid-window: abort and re-arm — the
                # replay pass recaptures the window cleanly
                self._abort("rollback")
            elif step > self.end:
                self._finish()
        # only ever start at exactly `start`: beginning mid-window (a
        # resume or a rollback anchor inside the window) would write a
        # partial capture under a dir named for the full range
        if not self._tracing and not self._done and step == self.start:
            os.makedirs(self.log_dir, exist_ok=True)
            self._start_fn(self.log_dir)
            self._tracing = True
            if self.spans is not None:
                self._capture_t0 = self.spans.now()

    def _abort(self, reason: str) -> None:
        """Close an in-flight capture WITHOUT marking the window done
        (it re-arms).  The partial artifacts exist on disk, so the
        window span is still recorded — marked ``aborted`` so the
        timeline says how far they cover."""
        self._stop_fn()
        self._tracing = False
        if self.spans is not None and self._capture_t0 is not None:
            self.spans.trace_window(
                self.start, self.end, self._capture_t0,
                self.spans.now(), log_dir=self.log_dir, aborted=reason,
            )
            self._capture_t0 = None

    def _finish(self) -> None:
        self._stop_fn()
        self._tracing = False
        self._done = True
        if self.spans is not None and self._capture_t0 is not None:
            self.spans.trace_window(
                self.start, self.end, self._capture_t0,
                self.spans.now(), log_dir=self.log_dir,
            )
            self._capture_t0 = None

    def stop(self) -> None:
        """Close an in-flight window (end of training / teardown)."""
        if self._tracing:
            self._finish()
