"""Structured span recorder — the per-request / per-step causal record.

The aggregate telemetry (:mod:`~apex_tpu.observability.metrics`) says
*that* a TTFT deadline was missed or a step was slow; it cannot say
*why* — queue wait vs prefill vs decode-batch contention, a rollback
replay vs a hung collective.  :class:`SpanRecorder` is the missing
causal layer: a low-overhead ring buffer of **spans** (named intervals)
and **instants** (point events) on a handful of stable tracks, merged
into one Perfetto-viewable timeline by
:class:`~apex_tpu.observability.export.TimelineSink` and
``tools/timeline.py``.

Design rules:

- **low overhead** — recording is one dict append into a bounded
  ``deque``; no formatting, no IO, no device contact.  A ``None``
  recorder costs one ``is not None`` check at every hook site.
- **monotonic time, anchored once** — every timestamp is
  ``time.monotonic()``; the process's monotonic→epoch offset is
  captured ONCE (:func:`wall_clock_anchor`) and written into span
  dump headers, flight dumps, and serve_bench artifacts, so timelines
  from different hosts/processes align when merged (each file carries
  its own anchor; the merge tool converts to epoch microseconds).
- **a stable event vocabulary** — serve requests walk
  ``queued → admitted → prefill → decode[i] → done | shed(reason)``
  with a validated ``retrying`` recovery phase between faults and
  re-admission (driven from the
  :class:`~apex_tpu.serve.scheduler.Request` runtime
  ledger); training steps, rollbacks, resumes, retries, checkpoints
  and preemption come from the ``run_resilient`` observer protocol;
  :class:`~apex_tpu.observability.health.HealthEvent` s and
  :class:`~apex_tpu.observability.trace.TraceScheduler` windows land
  on their own tracks.
- **correlation ids** — every serve-request span carries the request
  id as its ``lane``; the engine numbers its decode iterations
  (``InferenceEngine.decode_iters``) and each request's decode span
  records the ``first_iter``/``last_iter`` it rode, so a blown TTFT
  links to the exact engine batch iterations responsible.
- **out-of-order events are rejected loudly** — the request lifecycle
  is a state machine; an illegal transition (``decode`` before
  ``prefill``, a second terminal event, time running backwards within
  a request) raises ``ValueError`` instead of recording garbage that a
  postmortem would trust.

Armed three ways, mirroring the flight recorder: explicitly
(``SpanRecorder()`` handed to the scheduler / observer fan-out), by env
(``APEX_TPU_SPANS=N[:DIR]`` inside any ``run_resilient`` loop), or by
tools (``tools/serve_bench.py --spans``).  See
``docs/observability.md`` ("Request tracing & timeline").
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "ENV_SPANS",
    "DEFAULT_SPANS_DIR",
    "DEFAULT_CAPACITY",
    "TRACK_REQUESTS",
    "TRACK_ENGINE",
    "TRACK_TRAIN",
    "TRACK_HEALTH",
    "TRACK_TRACE",
    "REQ_QUEUED",
    "REQ_ROUTED",
    "REQ_PREFILL",
    "REQ_DECODE",
    "REQ_RETRYING",
    "REQ_DONE",
    "REQ_SHED",
    "REQ_TERMINAL",
    "wall_clock_anchor",
    "monotonic_to_epoch",
    "SpanRecorder",
]

ENV_SPANS = "APEX_TPU_SPANS"
DEFAULT_SPANS_DIR = "/tmp/apex_tpu_spans"
DEFAULT_CAPACITY = 4096

# -- track names (one Perfetto track per source) ----------------------------
TRACK_REQUESTS = "serve/requests"
TRACK_ENGINE = "serve/engine"
TRACK_TRAIN = "train"
TRACK_HEALTH = "health"
TRACK_TRACE = "trace"

# -- request lifecycle vocabulary -------------------------------------------
REQ_QUEUED = "queued"
#: fleet routing phase (``apex_tpu.fleetctl``): the request is in the
#: router's hands between replicas — on first submission (the router
#: picks a replica before the replica queues it) and on every
#: re-route after a drain handoff, replica crash, or preemption (the
#: span's ``replica`` arg names the destination)
REQ_ROUTED = "routed"
REQ_PREFILL = "prefill"
REQ_DECODE = "decode"
REQ_RETRYING = "retrying"
REQ_DONE = "done"
REQ_SHED = "shed"
REQ_TERMINAL = frozenset({REQ_DONE, REQ_SHED})

#: legal lifecycle transitions — anything else is an out-of-order event
#: and raises.  ``queued → prefill`` is the admission edge (the
#: recorder emits a ``req/admitted`` instant on it); a request can be
#: shed from any live phase but can never leave a terminal one.
#: ``retrying`` is the fault-recovery phase (docs/serving.md "Failure
#: semantics"): a prefill/decode fault sends the request back through
#: bounded re-admission with its pages and generated prefix retained —
#: it can only re-enter through ``prefill``/``decode`` or be shed; it
#: can never complete straight from ``retrying`` (``retrying → done``
#: would claim tokens no decode produced), and a terminal ``shed``
#: can never be re-admitted (``shed → decode`` raises — recovery must
#: go through an explicit re-submission, a NEW request id).
#: ``routed`` is the fleet-router phase: it brackets the hop between
#: replicas (first submission, drain handoff, crash/preempt
#: evacuation).  A routed request can only be queued on its target
#: replica or shed by the router; ``queued``/``retrying`` can re-enter
#: ``routed`` (a re-route), but a request mid-``prefill``/``decode``
#: cannot — it must pass through ``retrying`` first (the re-route IS a
#: fault recovery and must be charged against the retry budget).
_REQ_TRANSITIONS: Dict[Optional[str], frozenset] = {
    None: frozenset({REQ_QUEUED, REQ_ROUTED}),
    REQ_ROUTED: frozenset({REQ_QUEUED, REQ_SHED}),
    REQ_QUEUED: frozenset({REQ_PREFILL, REQ_SHED, REQ_ROUTED}),
    REQ_PREFILL: frozenset({REQ_DECODE, REQ_DONE, REQ_SHED, REQ_RETRYING}),
    REQ_DECODE: frozenset({REQ_DONE, REQ_SHED, REQ_RETRYING}),
    REQ_RETRYING: frozenset({REQ_PREFILL, REQ_DECODE, REQ_SHED, REQ_ROUTED}),
}


_ANCHOR: Optional[Dict[str, float]] = None


def wall_clock_anchor() -> Dict[str, Any]:
    """The process's monotonic→epoch anchor, captured ONCE.

    ``epoch - monotonic`` is the offset that converts any
    ``time.monotonic()`` timestamp taken in this process to wall-clock
    epoch seconds.  Capturing it once (instead of stamping every event
    with ``time.time()``) keeps recording cheap and makes every
    artifact from one process share one consistent offset — the
    property multi-host merge relies on.
    """
    global _ANCHOR
    if _ANCHOR is None:
        m = time.monotonic()
        e = time.time()
        _ANCHOR = {"monotonic": m, "epoch": e, "pid": os.getpid()}
    return dict(_ANCHOR)


def monotonic_to_epoch(t: float) -> float:
    """Epoch seconds for a ``time.monotonic()`` timestamp ``t``."""
    a = wall_clock_anchor()
    return float(t) - a["monotonic"] + a["epoch"]


def parse_spans_spec(spec: str) -> Tuple[int, Optional[str]]:
    """``(capacity, dir_override)`` from an ``APEX_TPU_SPANS`` value —
    the ``"N"`` / ``"N:DIR"`` grammar the flight recorder uses."""
    from apex_tpu.observability.flight import ENV_FLIGHT, parse_flight_spec

    try:
        return parse_flight_spec(spec)
    except ValueError as e:
        # same grammar, right env name in the error
        raise ValueError(str(e).replace(ENV_FLIGHT, ENV_SPANS)) from None


class SpanRecorder:
    """Bounded ring of spans + instants with a request state machine.

    Generic surface::

        rec.span("engine/decode", t0, t1, track=TRACK_ENGINE, iter=7)
        rec.instant("train/rollback", t, track=TRACK_TRAIN, step=120)

    Request lifecycle surface (validated)::

        rec.request_event(rid, REQ_QUEUED, t_submit, prompt_tokens=16)
        rec.request_event(rid, REQ_PREFILL, t_admit, bucket=32)
        rec.request_event(rid, REQ_DECODE, t_first, ttft_ms=..., ...)
        rec.request_event(rid, REQ_DONE, t_done, tokens=8)

    Each lifecycle event *closes* the previous phase as a span named
    ``req/<phase>`` on :data:`TRACK_REQUESTS` (lane = request id) —
    args given at the phase's open and close merge onto that span —
    and terminal events additionally emit a ``req/done`` / ``req/shed``
    instant carrying the terminal args (``reason=...`` for sheds).

    Implements the ``run_resilient`` observer protocol (``on_step`` /
    ``on_rollback`` / ``on_resume`` / ``on_preempt`` / ``on_retry`` /
    ``on_checkpoint``) so training runs record per-step spans by adding
    the recorder to the observer fan-out — or by env,
    ``APEX_TPU_SPANS=N[:DIR]`` (see :meth:`from_env`).
    """

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        directory: Optional[str] = None,
        *,
        run: Optional[Dict[str, Any]] = None,
        clock=time.monotonic,
    ):
        if capacity < 1:
            raise ValueError("span capacity must be >= 1")
        self.capacity = int(capacity)
        self.directory = directory or os.environ.get(
            ENV_SPANS + "_DIR", DEFAULT_SPANS_DIR
        )
        self.run = dict(run or {})
        self.clock = clock
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._seq = 0
        self._appended = 0
        # rid -> (state, t_opened, open_args)
        self._open_req: Dict[Any, Tuple[str, float, Dict[str, Any]]] = {}
        # observer-bridge state
        self._step_tick: Optional[float] = None
        self._prev_step: Optional[int] = None
        #: True while a canary deploy window is open — the only time a
        #: ``canary=...`` routing annotation is legal
        self._deploy_window = False
        self.dumps: List[str] = []

    @classmethod
    def from_env(cls, spec: Optional[str] = None, **kwargs):
        """A recorder armed by ``APEX_TPU_SPANS=N[:DIR]``, or ``None``
        when the env is unset/empty/``0``."""
        spec = spec if spec is not None else os.environ.get(ENV_SPANS)
        if not spec:
            return None
        capacity, dir_override = parse_spans_spec(spec)
        if capacity == 0:
            return None
        if dir_override:
            kwargs["directory"] = dir_override
        return cls(capacity, **kwargs)

    # -- core recording ----------------------------------------------------
    def now(self) -> float:
        return self.clock()

    def _append(self, entry: Dict[str, Any]) -> None:
        entry["seq"] = self._seq
        self._seq += 1
        self._appended += 1
        self._ring.append(entry)

    def span(
        self,
        name: str,
        t0: float,
        t1: float,
        *,
        track: str = TRACK_TRAIN,
        lane=None,
        **args,
    ) -> None:
        """Record a completed interval.  ``t1 < t0`` raises — a span
        that ends before it starts is corrupt evidence, not data."""
        t0, t1 = float(t0), float(t1)
        if t1 < t0:
            raise ValueError(
                f"span {name!r} ends before it starts: t0={t0} t1={t1}"
            )
        entry: Dict[str, Any] = {
            "name": name, "track": track, "t0": t0, "t1": t1,
        }
        if lane is not None:
            entry["lane"] = lane
        if args:
            entry["args"] = args
        self._append(entry)

    def instant(
        self, name: str, t: float, *, track: str = TRACK_TRAIN,
        lane=None, **args,
    ) -> None:
        """Record a point event."""
        entry: Dict[str, Any] = {"name": name, "track": track,
                                 "t": float(t)}
        if lane is not None:
            entry["lane"] = lane
        if args:
            entry["args"] = args
        self._append(entry)

    # -- request lifecycle -------------------------------------------------
    def request_event(self, rid, state: str, t: Optional[float] = None,
                      **args) -> None:
        """Advance request ``rid``'s lifecycle to ``state`` at time
        ``t`` (defaults to :meth:`now`).  Illegal transitions and
        backwards timestamps raise ``ValueError`` loudly."""
        t = self.now() if t is None else float(t)
        if "canary" in args:
            # the canary routing annotation is part of the exposure
            # PROOF (timeline --json re-derives the bound from these
            # spans), so it is validated like a state transition: only
            # a routing hop can carry it, and only while a deploy
            # window is open — a canary tag outside a window would be
            # unfalsifiable noise
            if state != REQ_ROUTED:
                raise ValueError(
                    f"canary annotation on {state!r} event for "
                    f"rid={rid}: only {REQ_ROUTED!r} hops carry it"
                )
            if not self._deploy_window:
                raise ValueError(
                    f"canary annotation for rid={rid} outside a "
                    f"deploy window (begin_deploy_window not open)"
                )
        cur = self._open_req.get(rid)
        cur_state = cur[0] if cur is not None else None
        allowed = _REQ_TRANSITIONS.get(cur_state, frozenset())
        if state not in allowed:
            raise ValueError(
                f"out-of-order request event: rid={rid} "
                f"{cur_state!r} -> {state!r} "
                f"(allowed: {sorted(allowed) or 'none — terminal'})"
            )
        if cur is not None:
            _, t_open, open_args = cur
            if t < t_open:
                raise ValueError(
                    f"out-of-order request timestamp: rid={rid} "
                    f"{state!r} at t={t} before {cur_state!r} opened "
                    f"at t={t_open}"
                )
            merged = dict(open_args)
            merged.update(args)
            self.span(
                f"req/{cur_state}", t_open, t,
                track=TRACK_REQUESTS, lane=rid, **merged,
            )
            if cur_state == REQ_QUEUED and state == REQ_PREFILL:
                # the admission edge — keep the vocabulary's explicit
                # "admitted" marker without a separate scheduler call
                self.instant(
                    "req/admitted", t, track=TRACK_REQUESTS, lane=rid
                )
        if state in REQ_TERMINAL:
            self.instant(
                f"req/{state}", t, track=TRACK_REQUESTS, lane=rid, **args
            )
            self._open_req.pop(rid, None)
        else:
            self._open_req[rid] = (state, t, dict(args))

    @property
    def open_requests(self) -> Dict[Any, str]:
        """``{rid: current_phase}`` for requests not yet terminal."""
        return {rid: st for rid, (st, _, _) in self._open_req.items()}

    # -- canary deploy windows ---------------------------------------------
    def begin_deploy_window(self, t: Optional[float] = None, *,
                            canary: str, frac: float) -> None:
        """Open a canary deploy window: emits a
        ``fleet/deploy_window_open`` instant on :data:`TRACK_HEALTH`
        carrying the canary replica's name + its router load-share
        ceiling, and arms the ``canary`` routing-annotation validator.
        ``tools/timeline.py --json`` pairs open/close markers into
        windows and re-proves the exposure bound per-request from the
        annotated ``req/routed`` spans inside them."""
        if self._deploy_window:
            raise RuntimeError(
                "begin_deploy_window: a deploy window is already open "
                "(one canary at a time per recorder)"
            )
        self._deploy_window = True
        self.instant(
            "fleet/deploy_window_open",
            self.now() if t is None else float(t),
            track=TRACK_HEALTH, canary=str(canary), frac=float(frac),
        )

    def end_deploy_window(self, t: Optional[float] = None, *,
                          verdict: str) -> None:
        """Close the open deploy window with its verdict (``"pass"`` /
        ``"fail"`` / ``"inconclusive"``)."""
        if not self._deploy_window:
            raise RuntimeError(
                "end_deploy_window: no deploy window is open"
            )
        self._deploy_window = False
        self.instant(
            "fleet/deploy_window_close",
            self.now() if t is None else float(t),
            track=TRACK_HEALTH, verdict=str(verdict),
        )

    @property
    def deploy_window_open(self) -> bool:
        return self._deploy_window

    # -- run_resilient observer bridge -------------------------------------
    def on_step(self, step: int, skipped: bool = False, info=None) -> None:
        """One ``train/step`` span per completed step interval (the
        first call only sets the baseline tick — the recorder cannot
        know when step 0 started)."""
        now = self.now()
        step = int(step)
        if self._step_tick is not None:
            span_args: Dict[str, Any] = {
                "step": step, "skipped": bool(skipped),
            }
            if self._prev_step is not None and step <= self._prev_step:
                # a rollback replay rewound the counter — mark it, the
                # timeline must render the rewind, not hide it
                span_args["replay"] = True
            self.span(
                "train/step", self._step_tick, now,
                track=TRACK_TRAIN, **span_args,
            )
        self._step_tick = now
        self._prev_step = step

    def on_rollback(self, step: int, anchor: int, skips: int = 0,
                    discarded: Optional[int] = None) -> None:
        self.instant(
            "train/rollback", self.now(), track=TRACK_TRAIN,
            step=int(step), anchor=int(anchor), skips=int(skips),
            discarded=None if discarded is None else int(discarded),
        )

    def on_resume(self, step: int) -> None:
        self.instant(
            "train/resume", self.now(), track=TRACK_TRAIN, step=int(step)
        )

    def on_preempt(self, step: int) -> None:
        self.instant(
            "train/preempt", self.now(), track=TRACK_TRAIN, step=int(step)
        )

    def on_retry(self, what: str = "", attempt: int = 0, error=None) -> None:
        self.instant(
            "train/retry", self.now(), track=TRACK_TRAIN,
            what=str(what), attempt=int(attempt),
            error=None if error is None else
            f"{type(error).__name__}: {error}",
        )

    def on_checkpoint(self, step: int, info=None) -> None:
        """A checkpoint event.  Bare (``info=None``): the enqueue
        instant, as before.  With ``info`` (an async-engine phase
        record — ``run_resilient`` forwards
        :meth:`apex_tpu.goodput.AsyncCheckpointEngine.drain_events`):
        the completed phase lands as a real interval on the train
        track — ``ckpt/snapshot`` + ``ckpt/write`` for a background
        write, ``ckpt/finalize`` for a drain barrier — so the Perfetto
        timeline shows checkpoint I/O overlapping the steps it ran
        under."""
        step = -1 if step is None else int(step)
        if info is None:
            self.instant(
                "train/checkpoint", self.now(), track=TRACK_TRAIN,
                step=step,
            )
            return
        phase = info.get("phase", "write")
        if phase == "write":
            s0, s1 = info.get("snapshot_t0"), info.get("snapshot_t1")
            if s0 is not None and s1 is not None:
                self.span(
                    "ckpt/snapshot", s0, s1, track=TRACK_TRAIN, step=step,
                )
            self.span(
                "ckpt/write", info["t0"], info["t1"], track=TRACK_TRAIN,
                step=step, ok=bool(info.get("ok", True)),
            )
        else:
            self.span(
                f"ckpt/{phase}", info["t0"], info["t1"],
                track=TRACK_TRAIN, step=step,
            )

    def note_health(self, event) -> None:
        """Record a :class:`~apex_tpu.observability.health.HealthEvent`
        on the health track (same shape the flight recorder logs)."""
        self.instant(
            f"health/{event.rule}", self.now(), track=TRACK_HEALTH,
            severity=event.severity, step=int(event.step),
            value=event.value, threshold=event.threshold,
            message=event.message, host=event.host,
        )

    def trace_window(self, start_step: int, end_step: int,
                     t0: float, t1: float,
                     log_dir: Optional[str] = None,
                     aborted: Optional[str] = None) -> None:
        """A :class:`~apex_tpu.observability.trace.TraceScheduler`
        profiler window — so on-chip profile artifacts locate
        themselves on the same timeline.  ``aborted`` names why a
        capture was closed early (a rollback rewind, a watchdog
        re-arm): the partial artifacts still exist in ``log_dir`` and
        the span says exactly how far they cover."""
        args: Dict[str, Any] = {
            "start_step": int(start_step), "end_step": int(end_step),
            "log_dir": log_dir,
        }
        if aborted is not None:
            args["aborted"] = str(aborted)
        self.span("trace/window", t0, t1, track=TRACK_TRACE, **args)

    # -- introspection / export --------------------------------------------
    @property
    def dropped(self) -> int:
        """Entries the ring evicted (0 means the record is complete)."""
        return self._appended - len(self._ring)

    def snapshot(self) -> List[Dict[str, Any]]:
        return [dict(e) for e in self._ring]

    def header(self) -> Dict[str, Any]:
        host = {"id": 0, "count": 1}
        try:
            from apex_tpu.parallel import multihost

            host = {"id": multihost.host_id(),
                    "count": multihost.host_count()}
        except Exception:
            pass
        return {
            "version": 1,
            "kind": "apex_tpu_spans",
            "anchor": wall_clock_anchor(),
            "host": host,
            "capacity": self.capacity,
            "dropped": self.dropped,
            "run": self.run,
        }

    def dump(self, reason: str = "", path: Optional[str] = None,
             directory: Optional[str] = None) -> str:
        """Write the span record atomically (tmp + ``os.replace``) and
        return the path.  ``path`` names the file exactly; otherwise a
        ``spans_<ts>_<pid>.json`` lands in ``directory`` (default: the
        recorder's)."""
        if path is None:
            directory = directory or self.directory
            os.makedirs(directory, exist_ok=True)
            ts = time.strftime("%Y%m%d_%H%M%S")
            path = os.path.join(
                directory, f"spans_{ts}_{os.getpid()}_{self._seq}.json"
            )
        else:
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
        payload = dict(self.header())
        payload["reason"] = str(reason)
        payload["open_requests"] = {
            str(rid): st for rid, st in self.open_requests.items()
        }
        payload["spans"] = self.snapshot()
        # the flight recorder's non-finite encoding ("NaN"/"Infinity"
        # strings): a NaN health value is evidence, and a bare NaN
        # token is invalid JSON
        from apex_tpu.observability.flight import json_safe

        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(json_safe(payload), f, indent=1, allow_nan=False)
            f.write("\n")
        os.replace(tmp, path)
        self.dumps.append(path)
        return path
