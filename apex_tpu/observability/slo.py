"""SLO objects + multi-window multi-burn-rate alerting.

The health rules landed so far are point-in-time thresholds: "TTFT over
its deadline right now", "queue depth over budget right now".  A
production pager does not work that way — it pages on **error-budget
burn**: with an objective of 99.9% good events, the budget is 0.1% of
traffic per period, and the *burn rate* is how many times faster than
sustainable the service is currently spending it (burn 1.0 = exactly
exhausting the budget over the period; burn 14.4 over a 0.1% budget =
the classic "1h window eats 2% of a 30-day budget" page).  The Google
SRE workbook's refinement — fire only when BOTH a short and a long
window exceed the factor — is what keeps a 10-second blip from paging
while a sustained storm pages in minutes:

- the **long** window proves the burn is sustained (a blip dilutes);
- the **short** window proves it is *still happening* (alerts stop
  quickly after recovery instead of riding the long tail).

Pieces:

- :class:`SLO` / :class:`CounterRatioSLO` / :class:`LatencySLO` —
  declarative objectives over cumulative good/total event counts.
  Counter SLOs read registry counters (``serve/completed`` vs
  completed+shed); latency SLOs read a host-side
  :class:`~apex_tpu.observability.ometrics.Histogram`'s cumulative
  buckets (good = observations ≤ the threshold bound — the classic
  Prometheus-histogram SLI).
- :class:`BurnRateTracker` — a bounded deque of ``(t, good, total)``
  cumulative samples recorded on the evaluation cadence;
  :meth:`burn_rate` computes the windowed error rate / error budget.
  A window reports ``None`` until its samples span at least half the
  window (cold-start honesty: extrapolating a 2-second-old process
  onto a 1-hour window manufactures pages).
- :class:`SLORule` — a :class:`~apex_tpu.observability.health.Rule`,
  so SLO alerting rides the EXISTING Watchdog machinery: a firing
  emits a structured :class:`~apex_tpu.observability.health
  .HealthEvent` to the board (``health/slo_<name>``), the Reporter
  sinks, the flight recorder, and the span recorder's health track —
  which is the point: an SLO page lands on the same merged timeline as
  the request spans that blew it (``tools/timeline.py``).
- :func:`serve_slo_rules` — the serving objective set (TTFT latency,
  request goodput, deadline-shed rate) ready to append to a serving
  watchdog's rules.

Evaluation happens on the watchdog's check cadence; counter sources
read the registry's *cached* values (fresh within the registry's
``2 × fetch_every`` contract), so a wedged fetch pipeline decays burn
toward 0 — which :class:`~apex_tpu.observability.health.StaleFetchRule`
already alerts on.  See ``docs/observability.md`` ("Live ops plane").
"""

from __future__ import annotations

import collections
import time
from typing import (
    Deque, Dict, Iterable, List, Mapping, NamedTuple, Optional, Tuple,
)

from apex_tpu.observability.health import HealthEvent, Rule

__all__ = [
    "Window",
    "DEFAULT_WINDOWS",
    "SLO",
    "CounterRatioSLO",
    "LatencySLO",
    "BurnRateTracker",
    "SLORule",
    "serve_slo_rules",
    "FLEET_TERMINAL_SHED_KEYS",
    "fleet_slo_rules",
    "burn_rate_drill",
]


class Window(NamedTuple):
    """One multi-window burn-rate alert condition: fire when the burn
    over BOTH ``short_s`` and ``long_s`` exceeds ``factor``."""

    short_s: float
    long_s: float
    factor: float
    severity: str = "critical"


#: the Google SRE workbook's recommended pair for a 30-day budget:
#: page on 5m/1h at 14.4x (2% of budget in an hour), ticket on
#: 30m/6h at 6x (5% in six hours)
DEFAULT_WINDOWS = (
    Window(300.0, 3600.0, 14.4, "critical"),
    Window(1800.0, 21600.0, 6.0, "warn"),
)


class SLO:
    """Base: a named objective over cumulative good/total counts.

    ``objective`` is the target good fraction (0.999 = "99.9% of
    events good"); the error budget is ``1 - objective``.
    Subclasses implement :meth:`counts`.
    """

    def __init__(self, name: str, objective: float, description: str = ""):
        if not 0.0 < objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {objective}"
            )
        self.name = str(name)
        self.objective = float(objective)
        self.description = description

    @property
    def error_budget(self) -> float:
        return 1.0 - self.objective

    def counts(self, values: Mapping[str, float]) -> Optional[
        Tuple[float, float]
    ]:
        """Cumulative ``(good, total)`` event counts, or ``None`` when
        the source has no data yet.  ``values`` is the registry's
        cached value mapping (latency SLOs ignore it — their histogram
        is bound at construction)."""
        raise NotImplementedError


class CounterRatioSLO(SLO):
    """Good/total from registry counters (each side a sum of keys).

    >>> CounterRatioSLO("goodput", 0.95,
    ...                 good_keys=("serve/completed",),
    ...                 total_keys=("serve/completed", "serve/shed"))
    """

    def __init__(self, name: str, objective: float, *,
                 good_keys: Iterable[str], total_keys: Iterable[str],
                 description: str = ""):
        super().__init__(name, objective, description)
        self.good_keys = tuple(good_keys)
        self.total_keys = tuple(total_keys)
        if not self.good_keys or not self.total_keys:
            raise ValueError("good_keys and total_keys must be non-empty")

    def counts(self, values):
        if not any(k in values for k in self.total_keys):
            return None
        good = sum(float(values.get(k, 0.0)) for k in self.good_keys)
        total = sum(float(values.get(k, 0.0)) for k in self.total_keys)
        return good, total


class LatencySLO(SLO):
    """Good = observations at or under ``threshold`` on a histogram.

    The threshold should sit ON a bucket bound
    (:meth:`~apex_tpu.observability.ometrics.Histogram.count_le`
    truncates to the nearest lower bound otherwise — conservative, but
    an avoidable distortion)."""

    def __init__(self, name: str, objective: float, *,
                 histogram, threshold: float, description: str = ""):
        super().__init__(name, objective, description)
        self.histogram = histogram
        self.threshold = float(threshold)

    def counts(self, values):
        total = self.histogram.count
        if total == 0:
            return None
        return float(self.histogram.count_le(self.threshold)), float(total)


class BurnRateTracker:
    """Windowed burn rates over cumulative ``(t, good, total)``
    samples.

    ``observe`` records one sample (monotonic seconds); retention is
    bounded in BOTH dimensions — trimmed to ``horizon_s`` at the old
    end, and **decimated** at the new end: a sample arriving within
    ``min_interval_s`` of the previous one *replaces* it (cumulative
    counts make the newest value strictly more informative), so a
    per-iteration evaluation cadence against a multi-hour window
    cannot grow the deque past ``~horizon_s / min_interval_s``
    entries.  :meth:`burn_rate` anchors at the newest sample old
    enough to cover the window (or the oldest available) and returns
    ``bad_delta / total_delta / error_budget`` — ``None`` when the
    data spans less than ``min_coverage`` of the window, when no
    events arrived in it, or when fewer than two samples exist.
    """

    def __init__(self, objective: float, horizon_s: float, *,
                 min_coverage: float = 0.5,
                 min_interval_s: Optional[float] = None):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1): {objective}")
        self.objective = float(objective)
        self.horizon_s = float(horizon_s)
        self.min_coverage = float(min_coverage)
        self.min_interval_s = float(
            min_interval_s if min_interval_s is not None
            else horizon_s / 4096.0
        )
        self._samples: Deque[Tuple[float, float, float]] = (
            collections.deque()
        )

    def observe(self, good: float, total: float, t: float) -> None:
        sample = (float(t), float(good), float(total))
        if (
            len(self._samples) >= 2
            and t - self._samples[-2][0] < self.min_interval_s
        ):
            # decimate: the previous sample is closer than the floor to
            # the one before it — supersede it (never the FIRST sample:
            # it anchors cold-start coverage)
            self._samples[-1] = sample
        else:
            self._samples.append(sample)
        cutoff = t - self.horizon_s
        # keep one sample at/just before the horizon: it anchors the
        # full-length window
        while len(self._samples) > 2 and self._samples[1][0] <= cutoff:
            self._samples.popleft()

    @property
    def samples(self) -> List[Tuple[float, float, float]]:
        return list(self._samples)

    def burn_rate(self, window_s: float,
                  now: Optional[float] = None) -> Optional[float]:
        if len(self._samples) < 2:
            return None
        t1, good1, total1 = self._samples[-1]
        now = t1 if now is None else float(now)
        cutoff = now - float(window_s)
        anchor = self._samples[0]
        for s in self._samples:
            if s[0] <= cutoff:
                anchor = s
            else:
                break
        t0, good0, total0 = anchor
        span = t1 - t0
        if span <= 0 or span < self.min_coverage * float(window_s):
            return None
        d_total = total1 - total0
        if d_total <= 0:
            return None
        d_bad = d_total - (good1 - good0)
        error_rate = max(0.0, d_bad / d_total)
        return error_rate / (1.0 - self.objective)


class SLORule(Rule):
    """Watchdog rule: evaluate one SLO's burn against its windows.

    On each check it samples the SLO's cumulative counts (registry
    counters via ``wd.registry.values()`` — or ``values_fn`` for
    drills/tests — latency SLOs from their bound histogram), records
    them on the tracker, and fires the FIRST window whose short AND
    long burns both exceed its factor.  The emitted
    :class:`HealthEvent` carries the short-window burn as its value,
    the window's factor as its threshold, and a message naming the
    SLO, both windows, and the error budget — then rides the normal
    Watchdog emission fan-out (board / sinks / flight / spans /
    ``on_unhealthy``).
    """

    def __init__(self, slo: SLO, windows: Iterable[Window] = DEFAULT_WINDOWS,
                 *, cooldown: int = 64, values_fn=None,
                 clock=time.monotonic):
        super().__init__(cooldown)
        self.slo = slo
        self.windows = tuple(windows)
        if not self.windows:
            raise ValueError("SLORule needs at least one window")
        for w in self.windows:
            if w.short_s >= w.long_s:
                raise ValueError(
                    f"window short_s must be < long_s: {w}"
                )
        self.name = f"slo_{slo.name}"
        self.values_fn = values_fn
        self._clock = clock
        horizon = max(w.long_s for w in self.windows)
        # sample-count bound: per-iteration checks against multi-hour
        # windows must not hoard samples — keep ≥8 per short window
        self.tracker = BurnRateTracker(
            slo.objective, horizon,
            min_interval_s=min(w.short_s for w in self.windows) / 8.0,
        )

    def _values(self, wd) -> Mapping[str, float]:
        if self.values_fn is not None:
            return self.values_fn()
        reg = getattr(wd, "registry", None)
        return reg.values() if reg is not None else {}

    def evaluate(self, wd, step) -> List[HealthEvent]:
        counts = self.slo.counts(self._values(wd))
        if counts is None:
            return []
        now = self._clock()
        self.tracker.observe(counts[0], counts[1], now)
        for w in self.windows:
            short = self.tracker.burn_rate(w.short_s, now)
            if short is None or short < w.factor:
                continue
            long = self.tracker.burn_rate(w.long_s, now)
            if long is None or long < w.factor:
                continue
            budget = self.slo.error_budget
            return [HealthEvent(
                self.name, w.severity, int(step), float(short),
                float(w.factor),
                f"SLO {self.slo.name!r} (objective "
                f"{self.slo.objective:.4g}, budget {budget:.4g}) "
                f"burning {short:.1f}x over {w.short_s:.0f}s AND "
                f"{long:.1f}x over {w.long_s:.0f}s "
                f"(page factor {w.factor:g})",
            )]
        return []


def serve_slo_rules(
    *,
    ttft_histogram=None,
    ttft_threshold_ms: Optional[float] = None,
    ttft_objective: float = 0.9,
    goodput_objective: float = 0.95,
    deadline_shed_objective: float = 0.99,
    windows: Iterable[Window] = DEFAULT_WINDOWS,
    cooldown: int = 64,
    clock=time.monotonic,
) -> List[SLORule]:
    """The serving SLO set (``docs/serving.md``):

    - ``ttft`` — fraction of admitted requests whose TTFT lands at or
      under ``ttft_threshold_ms`` (needs the scheduler's
      ``ttft_hist``; skipped when either piece is missing);
    - ``goodput`` — completed / (completed + shed) requests;
    - ``deadline_shed`` — requests NOT shed for a blown queue deadline
      (``serve/shed_deadline``) — operationally distinct from goodput:
      this one means demand is exceeding the latency budget, not just
      capacity.
    """
    rules: List[SLORule] = []
    if ttft_histogram is not None and ttft_threshold_ms is not None:
        rules.append(SLORule(
            LatencySLO(
                "ttft", ttft_objective, histogram=ttft_histogram,
                threshold=ttft_threshold_ms,
                description="TTFT under the serving deadline",
            ),
            windows, cooldown=cooldown, clock=clock,
        ))
    rules.append(SLORule(
        CounterRatioSLO(
            "goodput", goodput_objective,
            good_keys=("serve/completed",),
            total_keys=("serve/completed", "serve/shed"),
            description="requests completed vs offered",
        ),
        windows, cooldown=cooldown, clock=clock,
    ))
    rules.append(SLORule(
        CounterRatioSLO(
            "deadline_shed", deadline_shed_objective,
            good_keys=("serve/completed", "serve/shed_growth_victim",
                       "serve/shed_pool_exhausted", "serve/shed_oversize"),
            total_keys=("serve/completed", "serve/shed"),
            description="requests not shed for a blown TTFT deadline",
        ),
        windows, cooldown=cooldown, clock=clock,
    ))
    return rules


#: the TERMINAL shed ledger keys — every ``serve/shed_<reason>``
#: counter EXCEPT ``rerouted``, which is a hop (the request continues
#: on another replica), not an outcome.  Deliberately a literal:
#: ``tests/test_fleetctl.py`` pins it against
#: ``apex_tpu.serve.scheduler.SHED_REASONS`` so a new shed reason
#: cannot silently leak out of (or into) the fleet SLO denominators.
FLEET_TERMINAL_SHED_KEYS = (
    "serve/shed_deadline",
    "serve/shed_growth_victim",
    "serve/shed_pool_exhausted",
    "serve/shed_oversize",
    "serve/shed_poisoned",
    "serve/shed_queue_full",
    "serve/shed_retries_exhausted",
    "serve/shed_draining",
)


def fleet_slo_rules(
    *,
    ttft_histogram=None,
    ttft_threshold_ms: Optional[float] = None,
    ttft_objective: float = 0.9,
    goodput_objective: float = 0.95,
    deploy_loss_objective: float = 0.999,
    windows: Iterable[Window] = DEFAULT_WINDOWS,
    cooldown: int = 64,
    values_fn=None,
    clock=time.monotonic,
) -> List[SLORule]:
    """The FLEET-level SLO set (docs/serving.md "Fleet operations"),
    evaluated over counters aggregated ACROSS replicas (``values_fn``
    is typically ``Fleet.aggregate_values`` — per-replica registries
    fetched and their ``serve/*`` counters summed).

    The per-replica ``serve_slo_rules`` denominators use the rolled-up
    ``serve/shed`` counter; at fleet level that would be a LIE — a
    re-routed request appears as ``shed(rerouted)`` on its source
    replica while completing on its destination, so the fleet rules
    sum the terminal reasons explicitly
    (:data:`FLEET_TERMINAL_SHED_KEYS`):

    - ``fleet_ttft`` — end-to-end TTFT (original ``submitted_at``
      preserved across re-routes) under threshold, from the fleet-wide
      histogram when one is supplied;
    - ``fleet_goodput`` — completed vs terminally resolved across the
      whole fleet, through any churn;
    - ``fleet_deploy_loss`` — requests NOT terminally shed as
      ``draining``: a zero-downtime rolling update must keep this
      budget untouched (drains re-route; only a handoff-less or
      refused drain sheds ``draining``).
    """
    rules: List[SLORule] = []
    if ttft_histogram is not None and ttft_threshold_ms is not None:
        rules.append(SLORule(
            LatencySLO(
                "fleet_ttft", ttft_objective, histogram=ttft_histogram,
                threshold=ttft_threshold_ms,
                description="end-to-end TTFT across the fleet",
            ),
            windows, cooldown=cooldown, values_fn=values_fn, clock=clock,
        ))
    total_keys = ("serve/completed",) + FLEET_TERMINAL_SHED_KEYS
    rules.append(SLORule(
        CounterRatioSLO(
            "fleet_goodput", goodput_objective,
            good_keys=("serve/completed",),
            total_keys=total_keys,
            description="fleet requests completed vs terminally "
                        "resolved (re-routes are hops, not outcomes)",
        ),
        windows, cooldown=cooldown, values_fn=values_fn, clock=clock,
    ))
    rules.append(SLORule(
        CounterRatioSLO(
            "fleet_deploy_loss", deploy_loss_objective,
            good_keys=("serve/completed",) + tuple(
                k for k in FLEET_TERMINAL_SHED_KEYS
                if k != "serve/shed_draining"
            ),
            total_keys=total_keys,
            description="requests not lost to a drain (rolling "
                        "updates must re-route, not shed)",
        ),
        windows, cooldown=cooldown, values_fn=values_fn, clock=clock,
    ))
    return rules


def burn_rate_drill() -> int:
    """The canonical burn-rate fixture: a 50%-error-rate storm against
    a 90% objective (burn 5x) sampled every 60s for six minutes,
    judged by a single (60s, 240s, 2x) window.  The short window is
    covered at the second sample and the long window at half coverage
    by t=120s — exactly ONE alert fires (the cooldown holds the rest).

    Deterministic by construction (synthetic clock, fixed counts):
    ``bench.py --config serve`` emits the fired count as the
    ``slo_alerts_fired`` row, so the burn-rate path's behavior is
    pinned into the bench_diff golden stream and can never regress
    silently; ``tests/test_slo.py`` asserts the same number against
    the hand-checked math.
    """
    t = {"now": 0.0}
    counts = {"good": 0.0, "total": 0.0}
    rule = SLORule(
        CounterRatioSLO(
            "drill", 0.9, good_keys=("good",), total_keys=("total",)
        ),
        windows=(Window(60.0, 240.0, 2.0, "critical"),),
        values_fn=lambda: dict(counts),
        clock=lambda: t["now"],
    )

    class _Wd:  # the minimal Watchdog surface a rule touches
        registry = None

    fired: List[HealthEvent] = []
    for minute in range(7):
        t["now"] = 60.0 * minute
        fired.extend(rule.check(_Wd(), minute))
        counts["good"] += 50.0
        counts["total"] += 100.0
    return len(fired)
