"""Live device-memory telemetry — the runtime half of the HBM story.

The static analyzer (:mod:`apex_tpu.analysis.memory`) predicts a peak
HBM number per compiled program and the serve engine gates its BUILD on
it — but until now nothing ever checked the prediction against what the
device actually allocates.  This module closes the loop:

- :class:`DeviceMemoryProvider` wraps ``device.memory_stats()`` (real
  on TPU/GPU; the CPU backend reports nothing and the provider
  degrades to an empty view — tier-1 uses :class:`FakeMemoryProvider`
  instead, scripted or seeded from the analyzer's own static peaks).
- :class:`MemStatsMonitor` samples the provider on the observation
  cadence, publishes per-device watermark gauges to the board
  (``memstats/<dev>/bytes_in_use`` / ``peak_bytes_in_use`` /
  ``bytes_limit`` — live on any ``--ops-port`` scrape) and keeps a
  bounded watermark history.
- :meth:`MemStatsMonitor.crosscheck` reconciles the live peak against
  the static predictions already on the board
  (``serve/hbm/<program>/peak_hbm_bytes`` from the engine build,
  ``analysis/peak_hbm_bytes`` from the graph linter): drift beyond
  tolerance in EITHER direction is a finding **naming the program**
  whose prediction governs — never a silent pass.  The expectation is
  ``max`` over program peaks (programs share the weights and pool on
  one device), and the tolerance is deliberately loose: the estimate
  is a model, the point is catching the 2x of a dropped donation or a
  pool that silently doubled, not the last 2%.
- :class:`MemStatsRule` runs sample + crosscheck inside the existing
  :class:`~apex_tpu.observability.health.Watchdog`, so drift pages the
  same health layer as everything else.
- :func:`oom_forensics` / :meth:`MemStatsMonitor.on_allocation_failure`
  — the black-box hook: when an allocation fails
  (``RESOURCE_EXHAUSTED``), the watermark history drains into the
  flight recorder as an ``oom`` event before the exception propagates,
  so the postmortem shows the climb, not just the cliff.

See ``docs/observability.md`` ("Live ops plane") and
``docs/analysis.md`` (the static side).
"""

from __future__ import annotations

import collections
import contextlib
import re
import time
from typing import Any, Deque, Dict, List, Mapping, Optional

__all__ = [
    "DeviceMemoryProvider",
    "FakeMemoryProvider",
    "default_provider",
    "static_peaks_from_board",
    "MemStatsMonitor",
    "MemStatsRule",
    "oom_forensics",
]

#: the stat keys a provider reports per device (floats, bytes)
STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")

_STATIC_PEAK_RE = re.compile(r"^serve/hbm/(?P<program>.+)/peak_hbm_bytes$")


class DeviceMemoryProvider:
    """``device.memory_stats()`` across the local devices.

    ``stats()`` returns ``{"device<i>": {bytes_in_use,
    peak_bytes_in_use, bytes_limit}}`` — empty when no backend device
    reports memory stats (the CPU backend), which is the documented
    degradation: callers fall back to a :class:`FakeMemoryProvider`
    or simply record nothing.
    """

    kind = "device"

    def stats(self) -> Dict[str, Dict[str, float]]:
        import jax

        out: Dict[str, Dict[str, float]] = {}
        for i, d in enumerate(jax.local_devices()):
            getter = getattr(d, "memory_stats", None)
            ms = None
            if getter is not None:
                try:
                    ms = getter()
                except Exception:
                    ms = None
            if not ms:
                continue
            in_use = float(ms.get("bytes_in_use", 0.0))
            out[f"device{i}"] = {
                "bytes_in_use": in_use,
                "peak_bytes_in_use": float(
                    ms.get("peak_bytes_in_use", in_use)
                ),
                "bytes_limit": float(ms.get("bytes_limit", 0.0)),
            }
        return out

    @property
    def available(self) -> bool:
        return bool(self.stats())


class FakeMemoryProvider:
    """Scripted provider for CPU tier-1 and planted-drift CI checks.

    >>> fake = FakeMemoryProvider(limit_bytes=1 << 30)
    >>> fake.set_usage(bytes_in_use=100 << 20)       # peak tracks max
    >>> fake.stats()["device0"]["peak_bytes_in_use"]
    104857600.0
    """

    kind = "fake"

    def __init__(self, devices: int = 1, limit_bytes: float = 0.0):
        if devices < 1:
            raise ValueError("need at least one fake device")
        self._stats = {
            f"device{i}": {
                "bytes_in_use": 0.0,
                "peak_bytes_in_use": 0.0,
                "bytes_limit": float(limit_bytes),
            }
            for i in range(devices)
        }

    @classmethod
    def from_static(cls, static_peaks: Mapping[str, float], *,
                    scale: float = 1.0, limit_factor: float = 4.0,
                    devices: int = 1) -> "FakeMemoryProvider":
        """A fake whose live peak is ``scale`` x the largest static
        prediction — ``scale=1.0`` reconciles cleanly, ``scale=2.0``
        is the planted drift the CI gate must flag."""
        if not static_peaks:
            raise ValueError("from_static needs at least one static peak")
        peak = float(max(static_peaks.values())) * float(scale)
        fake = cls(devices=devices,
                   limit_bytes=max(peak, 1.0) * float(limit_factor))
        for i in range(devices):
            fake.set_usage(device=i, bytes_in_use=peak)
        return fake

    def set_usage(self, *, device: int = 0, bytes_in_use: float,
                  peak: Optional[float] = None) -> None:
        s = self._stats[f"device{device}"]
        s["bytes_in_use"] = float(bytes_in_use)
        s["peak_bytes_in_use"] = float(
            peak if peak is not None
            else max(s["peak_bytes_in_use"], bytes_in_use)
        )

    def stats(self) -> Dict[str, Dict[str, float]]:
        return {dev: dict(s) for dev, s in self._stats.items()}

    @property
    def available(self) -> bool:
        return True


def default_provider() -> Optional[DeviceMemoryProvider]:
    """The real provider when the backend reports memory stats, else
    ``None`` (CPU) — callers pick their fake explicitly."""
    p = DeviceMemoryProvider()
    return p if p.available else None


def static_peaks_from_board(board=None) -> Dict[str, float]:
    """Harvest the static peak-HBM predictions already published to the
    board: one entry per serve step program
    (``serve/hbm/<program>/peak_hbm_bytes`` — the engine build), plus
    the graph linter's whole-step ``analysis/peak_hbm_bytes`` under the
    program name ``"analysis"``."""
    if board is None:
        from apex_tpu.observability.metrics import board as board_

        board = board_
    out: Dict[str, float] = {}
    for key, value in board.snapshot().items():
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        m = _STATIC_PEAK_RE.match(key)
        if m:
            out[m.group("program")] = float(value)
        elif key == "analysis/peak_hbm_bytes":
            out["analysis"] = float(value)
    return out


class MemStatsMonitor:
    """Sample a provider, publish watermark gauges, keep history,
    reconcile against the static analyzer.

    ``sample()`` is host-side and cheap (one ``memory_stats()`` call
    per device, dict copies); run it on the observation cadence or
    hand it to an :class:`~apex_tpu.observability.ometrics.OpsServer`
    as its ``collect`` hook so every scrape carries fresh watermarks.
    """

    def __init__(self, provider, *, history: int = 256,
                 prefix: str = "memstats", clock=time.monotonic):
        if provider is None:
            raise ValueError(
                "MemStatsMonitor needs a provider — use "
                "default_provider() and fall back to a "
                "FakeMemoryProvider on CPU"
            )
        self.provider = provider
        self.prefix = prefix
        self._clock = clock
        self._history: Deque[Dict[str, Any]] = collections.deque(
            maxlen=history
        )
        self.samples = 0

    # -- sampling ---------------------------------------------------------
    def sample(self, step: Optional[int] = None) -> Dict[str, Dict[str, float]]:
        from apex_tpu.observability.metrics import board

        stats = self.provider.stats()
        frame: Dict[str, Any] = {"t": self._clock(), "devices": stats}
        if step is not None:
            frame["step"] = int(step)
        self._history.append(frame)
        self.samples += 1
        for dev, s in stats.items():
            for key in STAT_KEYS:
                board.set(f"{self.prefix}/{dev}/{key}", s[key])
        board.set(f"{self.prefix}/samples", self.samples)
        return stats

    def watermarks(self) -> List[Dict[str, Any]]:
        """The watermark history (oldest first) — what the OOM hook
        drains into the flight recorder."""
        return [dict(f) for f in self._history]

    def live_peaks(self) -> Dict[str, float]:
        """Per-device high-water mark over the recorded history."""
        peaks: Dict[str, float] = {}
        for frame in self._history:
            for dev, s in frame["devices"].items():
                peaks[dev] = max(
                    peaks.get(dev, 0.0), s["peak_bytes_in_use"]
                )
        return peaks

    # -- the static-vs-live reconciliation --------------------------------
    def crosscheck(self, static_peaks: Optional[Mapping[str, float]] = None,
                   *, tolerance: float = 0.25) -> List[Dict[str, Any]]:
        """Reconcile live watermarks against static predictions.

        Returns drift findings (empty = reconciled).  The expected live
        peak is the MAX over program predictions; a device whose
        watermark exceeds it by more than ``tolerance`` means the
        analyzer **under**-predicted (the dangerous direction: the
        budget gate is lying), a watermark under it by more than
        ``tolerance`` means it **over**-predicted (the estimate drifted
        from the program actually running).  Either way the finding
        names the governing program.  With no static predictions or no
        samples the result is ``[]`` and ``memstats/crosscheck`` on
        the board says ``-1`` ("no basis") — distinguishable from a
        clean ``0``.
        """
        from apex_tpu.observability.metrics import board

        static = dict(
            static_peaks if static_peaks is not None
            else static_peaks_from_board()
        )
        live = self.live_peaks()
        if not static or not live:
            board.set(f"{self.prefix}/crosscheck", -1.0)
            return []
        program, expected = max(static.items(), key=lambda kv: kv[1])
        findings: List[Dict[str, Any]] = []
        worst = 1.0
        for dev, peak in sorted(live.items()):
            if expected <= 0:
                continue
            ratio = peak / expected
            if abs(ratio - 1.0) > max(abs(worst - 1.0), 0.0):
                worst = ratio
            if ratio > 1.0 + tolerance:
                direction = "static-under-predicts"
            elif ratio < 1.0 - tolerance:
                direction = "static-over-predicts"
            else:
                continue
            mib = 1 << 20
            findings.append({
                "rule": "memstats-drift",
                "device": dev,
                "program": program,
                "live_peak_bytes": int(peak),
                "static_peak_bytes": int(expected),
                "ratio": ratio,
                "direction": direction,
                "tolerance": tolerance,
                "message": (
                    f"{dev} live HBM watermark {peak / mib:.1f} MiB vs "
                    f"static peak {expected / mib:.1f} MiB for program "
                    f"{program!r} ({ratio:.2f}x, tolerance "
                    f"±{tolerance:.0%}) — {direction}"
                ),
            })
        board.set(f"{self.prefix}/crosscheck", float(len(findings)))
        board.set(f"{self.prefix}/crosscheck_ratio", worst)
        return findings

    # -- OOM forensics -----------------------------------------------------
    def on_allocation_failure(self, error=None, *, flight=None,
                              spans=None) -> Dict[str, Any]:
        """Drain the watermark history for the black box.  Safe to call
        from an exception handler: records to the flight recorder's
        event log (``kind="oom"``), the span recorder's health track,
        and the board — none of which touch the device — and returns
        the payload for callers without either recorder."""
        from apex_tpu.observability.metrics import board

        payload: Dict[str, Any] = {
            "error": None if error is None
            else f"{type(error).__name__}: {error}",
            "live_peaks": self.live_peaks(),
            "watermarks": self.watermarks(),
            "provider": getattr(self.provider, "kind", "?"),
        }
        board.set(f"{self.prefix}/oom", 1.0)
        if flight is not None:
            flight.note("oom", **payload)
        if spans is not None:
            spans.instant(
                "health/oom", spans.now(), track="health",
                error=payload["error"],
                live_peaks=payload["live_peaks"],
            )
        return payload


def _looks_like_oom(error: BaseException) -> bool:
    if isinstance(error, MemoryError):
        return True
    text = f"{type(error).__name__}: {error}"
    return (
        "RESOURCE_EXHAUSTED" in text
        or "Out of memory" in text
        or "out of memory" in text
    )


@contextlib.contextmanager
def oom_forensics(monitor: MemStatsMonitor, *, flight=None, spans=None):
    """Wrap an allocation-prone region: an OOM-shaped exception
    (``RESOURCE_EXHAUSTED`` / ``MemoryError``) takes one final
    watermark sample and drains the history into the flight recorder
    before re-raising — every other exception passes through
    untouched."""
    try:
        yield monitor
    except BaseException as e:
        if _looks_like_oom(e):
            try:
                monitor.sample()
            except Exception:
                pass  # the provider may be the thing that is dying
            monitor.on_allocation_failure(e, flight=flight, spans=spans)
        raise


class MemStatsRule:
    """Watchdog rule: sample + crosscheck on the check cadence.

    Drift findings become :class:`~apex_tpu.observability.health
    .HealthEvent` s (critical past ``2 × tolerance``, warn inside it),
    so they ride the normal emission fan-out — board, sinks, flight
    recorder, span timeline.  Subclassing deferred to composition: the
    health module stays import-light, so this mirrors the
    :class:`~apex_tpu.observability.health.Rule` surface instead of
    importing it at module scope.
    """

    severity = "warn"

    def __init__(self, monitor: MemStatsMonitor, *,
                 static_peaks: Optional[Mapping[str, float]] = None,
                 tolerance: float = 0.25, cooldown: int = 64):
        self.monitor = monitor
        self.static_peaks = static_peaks
        self.tolerance = tolerance
        self.cooldown = cooldown
        self.name = "memstats_drift"
        self._last_fired: Optional[int] = None

    def check(self, wd, step: int) -> List[Any]:
        # the sample must run EVERY check (the watermark history is the
        # OOM forensics record); only the alerting honors the cooldown
        self.monitor.sample(step)
        if (
            self._last_fired is not None
            and step - self._last_fired < self.cooldown
        ):
            return []
        events = self.evaluate(wd, step)
        if events:
            self._last_fired = step
        return events

    def evaluate(self, wd, step: int) -> List[Any]:
        from apex_tpu.observability.health import HealthEvent

        findings = self.monitor.crosscheck(
            self.static_peaks, tolerance=self.tolerance
        )
        events = []
        for f in findings:
            severity = (
                "critical"
                if abs(f["ratio"] - 1.0) > 2 * self.tolerance
                else "warn"
            )
            events.append(HealthEvent(
                self.name, severity, int(step), float(f["ratio"]),
                1.0 + self.tolerance, f["message"],
            ))
        return events
