"""Step meters: wall-clock step time, tokens/s, MFU, and goodput.

:class:`StepMeter` answers "how fast is this run right now" from the
host side — mark each completed step with :meth:`StepMeter.tick` and
read step time (median over a sliding window, robust to the dispatch
hiccups a remote TPU tunnel injects), tokens/s, and model-FLOPs
utilization.  The FLOP/peak model is the SAME one ``bench.py`` /
``tools/mfu_sweep.py`` use for the headline (per-chip dense bf16 peak
by device kind; 6·N·T for transformer training), moved here so live
telemetry and the benchmark artifacts can never disagree on the
denominator.

:class:`GoodputAccountant` answers "how much of that speed is real
progress".  It is fed by :func:`apex_tpu.resilience.run_resilient`'s
``observer`` events (accepted/skipped steps, rollbacks with their
discarded work, checkpoint retries, resume replay) and reduces them to
one number::

    goodput = (accepted - discarded_by_rollback) / executed_steps

which is exactly the "productive steps / all steps paid for" ratio a
capacity dashboard wants.  See ``docs/observability.md``.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

__all__ = [
    "PEAK_BF16_FLOPS",
    "PEAK_HBM_GBPS",
    "PEAK_ICI_GBPS",
    "BUCKETS",
    "VMEM_BYTES",
    "peak_flops_for",
    "peak_hbm_bandwidth_for",
    "peak_ici_bandwidth_for",
    "vmem_bytes_for",
    "categorize_op",
    "chip_peak_flops",
    "total_peak_flops",
    "transformer_train_flops",
    "StepMeter",
    "GoodputAccountant",
    "percentile",
]


def percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an already-sorted sequence — the
    ONE implementation behind the serving TTFT/latency statistics on
    every surface (scheduler gauges, ``tools/serve_bench.py``
    artifacts), so the two can never disagree on the same data.
    Returns NaN on an empty sequence ("no measurement", the bench
    schema's null)."""
    if not sorted_vals:
        return float("nan")
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]

#: Per-chip dense bf16 peak FLOP/s by device kind (public specs) — the
#: single source bench.py's MFU headline, live telemetry, and the
#: roofline (``observability.attribution``) share.
PEAK_BF16_FLOPS = {
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,  # v6e (Trillium)
}

#: Per-chip HBM bandwidth (bytes/s, public specs) — the roofline's
#: bandwidth ceiling and the ridge-point denominator.
PEAK_HBM_GBPS = {
    "TPU v5 lite": 819e9,  # v5e
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v5": 2765e9,
    "TPU v4": 1228e9,
    "TPU v6 lite": 1640e9,  # v6e (Trillium)
}

#: Per-chip ICI bandwidth (bytes/s per link direction, public specs) —
#: the cost model's collective-time denominator.
PEAK_ICI_GBPS = {
    "TPU v5 lite": 200e9,  # v5e: 4x 100 GB/s links bidir, ~200 usable
    "TPU v5e": 200e9,
    "TPU v5p": 600e9,
    "TPU v5": 600e9,
    "TPU v4": 300e9,
    "TPU v6 lite": 400e9,
}

#: Unknown device kinds (CPU, new chips) fall back conservatively.
DEFAULT_PEAK_FLOPS = 197e12
DEFAULT_HBM_GBPS = 819e9
DEFAULT_ICI_GBPS = 100e9


def _lookup(table: Dict[str, float], device_kind: str, default: float) -> float:
    for key, val in table.items():
        if device_kind.startswith(key):
            return val
    return default


def peak_flops_for(device_kind: str) -> float:
    """Dense bf16 peak FLOP/s for a device-kind STRING — the one
    denominator StepMeter MFU, bench.py headlines, and the roofline
    share (conservative default for unknown kinds: an MFU from it is a
    floor, not a lie)."""
    return _lookup(PEAK_BF16_FLOPS, device_kind, DEFAULT_PEAK_FLOPS)


def peak_hbm_bandwidth_for(device_kind: str) -> float:
    """HBM bytes/s for a device-kind string (roofline ceiling)."""
    return _lookup(PEAK_HBM_GBPS, device_kind, DEFAULT_HBM_GBPS)


def peak_ici_bandwidth_for(device_kind: str) -> float:
    """Interconnect bytes/s for a device-kind string (cost-model
    collective-time denominator)."""
    return _lookup(PEAK_ICI_GBPS, device_kind, DEFAULT_ICI_GBPS)


def chip_peak_flops(device) -> float:
    """Dense bf16 peak FLOP/s of one device object (delegates to
    :func:`peak_flops_for` on its ``device_kind``)."""
    return peak_flops_for(getattr(device, "device_kind", ""))


#: Per-core VMEM bytes by device kind — the kernel static analyzer's
#: (``apex_tpu.analysis.kernels``) overflow budget, kept in the same
#: home as the FLOP/bandwidth peaks so every cost model shares one
#: hardware table.  TPU generations to date all carry ~16 MiB of
#: vector memory per core (the pallas guide's "~16 MB/core"); the
#: conservative default means an overflow verdict on an unknown chip
#: is a floor, not a lie.
VMEM_BYTES = {
    "TPU v5 lite": 16 * 1024 * 1024,  # v5e
    "TPU v5e": 16 * 1024 * 1024,
    "TPU v5p": 16 * 1024 * 1024,
    "TPU v5": 16 * 1024 * 1024,
    "TPU v4": 16 * 1024 * 1024,
    "TPU v6 lite": 32 * 1024 * 1024,  # v6e (Trillium)
}

DEFAULT_VMEM_BYTES = 16 * 1024 * 1024


def vmem_bytes_for(device_kind: str) -> int:
    """Per-core VMEM budget for a device-kind string (the
    kernel-vmem-overflow gate's denominator)."""
    return int(_lookup(VMEM_BYTES, device_kind, DEFAULT_VMEM_BYTES))


# ---------------------------------------------------------------------------
# the bucket model: one op-category vocabulary for attribution/roofline
# ---------------------------------------------------------------------------

#: The op-category buckets step-time attribution decomposes into — the
#: shared vocabulary of the cost model, the trace parser, the roofline
#: table, and the watchdog's fraction rules.
BUCKETS = ("matmul", "attention", "norm_elementwise", "collective", "other")

_ATTENTION_HINTS = (
    "attention", "attn", "flash", "mha", "multihead", "softmax_xent",
)
#: "conv_general"/"convolution" (jax's conv_general_dilated), never a
#: bare "conv": dtype casts print as convert/convert_element_type and
#: must fall through to the elementwise branch, not inflate matmul
_MATMUL_HINTS = (
    "dot_general", "einsum", "conv_general", "convolution", "conv2d",
    "matmul", "dense", "gemm", "dot",
)
_NORM_ELEMENTWISE_HINTS = (
    "norm", "softmax", "gelu", "relu", "tanh", "sigmoid", "logistic",
    "dropout", "bias", "residual", "add", "mul", "rope", "rotary",
    "scale", "mean", "var", "rsqrt", "exp", "erf",
)
_ELEMENTWISE_OPCODES = frozenset((
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "power", "negate", "abs", "compare", "select", "clamp", "convert",
    "exponential", "log", "tanh", "logistic", "sqrt", "rsqrt", "sine",
    "cosine", "erf", "reduce", "reduce-window", "map", "broadcast",
    "iota", "floor", "ceil", "sign", "and", "or", "xor", "not",
))


def categorize_op(opcode: str, op_name: str = "") -> str:
    """Bucket one op into :data:`BUCKETS` from its HLO opcode and
    ``op_name`` metadata (the jax source path — named scopes land
    there, so a ``dot`` inside ``named_scope("flash_attention")``
    buckets as attention, which is what a roofline wants: the
    attention bucket owns its matmuls).

    Priority: collective > attention > matmul > norm-elementwise >
    other.  Works on trace-event names too: pass the event name as
    ``op_name`` with its leading token as ``opcode`` (fused kernels
    print like ``add_multiply_fusion.78``, carrying their content in
    the name).
    """
    opcode = (opcode or "").lower()
    name = (op_name or "").lower()
    if opcode.startswith(
        ("all-reduce", "all-gather", "all-to-all", "reduce-scatter",
         "collective-permute", "collective-broadcast")
    ) or any(
        k in name
        for k in ("all-reduce", "all_reduce", "all-gather", "all_gather",
                  "reduce-scatter", "reduce_scatter", "all-to-all",
                  "all_to_all", "collective-permute", "psum")
    ):
        return "collective"
    if any(k in name for k in _ATTENTION_HINTS):
        return "attention"
    if opcode in ("dot", "convolution") or any(
        k in name for k in _MATMUL_HINTS
    ):
        return "matmul"
    if opcode in _ELEMENTWISE_OPCODES or any(
        k in name for k in _NORM_ELEMENTWISE_HINTS
    ):
        return "norm_elementwise"
    return "other"


def total_peak_flops(devices=None) -> float:
    """Summed peak over ``devices`` (default: all visible devices)."""
    if devices is None:
        import jax

        devices = jax.devices()
    return sum(chip_peak_flops(d) for d in devices)


def transformer_train_flops(n_params: int, tokens: int) -> float:
    """The 6·N·T training-FLOPs model (BASELINE.md's MFU contract)."""
    return 6.0 * float(n_params) * float(tokens)


class StepMeter:
    """Wall-clock step meter: tick once per completed step.

    The first :meth:`tick` only arms the clock (it closes no interval);
    step time is the median of the last ``window`` intervals, so a
    single stalled dispatch does not poison the rate.  ``peak_flops``
    defaults lazily to the visible devices' summed peak — pass it
    explicitly when metering a sub-mesh.
    """

    def __init__(
        self,
        *,
        tokens_per_step: float = 0.0,
        flops_per_step: float = 0.0,
        peak_flops: Optional[float] = None,
        window: int = 32,
        clock: Callable[[], float] = time.perf_counter,
    ):
        if window < 1:
            raise ValueError("window must be >= 1")
        self.tokens_per_step = float(tokens_per_step)
        self.flops_per_step = float(flops_per_step)
        self._peak_flops = peak_flops
        self._window = window
        self._clock = clock
        self._last: Optional[float] = None
        self._times: list = []
        self.steps = 0  # completed (timed) intervals

    @property
    def peak_flops(self) -> float:
        if self._peak_flops is None:
            self._peak_flops = total_peak_flops()
        return self._peak_flops

    def tick(self) -> Optional[float]:
        """Mark a step boundary; returns the closed interval in seconds
        (None on the arming call)."""
        now = self._clock()
        if self._last is None:
            self._last = now
            return None
        dt = now - self._last
        self._last = now
        self._times.append(dt)
        if len(self._times) > self._window:
            self._times.pop(0)
        self.steps += 1
        return dt

    @property
    def step_time(self) -> float:
        """Median step seconds over the window (0.0 before any tick)."""
        if not self._times:
            return 0.0
        s = sorted(self._times)
        return s[len(s) // 2]

    @property
    def tokens_per_sec(self) -> float:
        t = self.step_time
        return self.tokens_per_step / t if t > 0 else 0.0

    @property
    def mfu(self) -> float:
        t = self.step_time
        if t <= 0 or self.flops_per_step <= 0:
            return 0.0
        return self.flops_per_step / (t * self.peak_flops)

    def summary(self) -> Dict[str, float]:
        return {
            "train/step": float(self.steps),
            "train/step_time_ms": self.step_time * 1e3,
            "train/tokens_per_sec": self.tokens_per_sec,
            "train/mfu": self.mfu,
        }


class GoodputAccountant:
    """Productive-work ledger over ``run_resilient`` observer events.

    Implements the observer protocol (every method optional on other
    observers): ``on_step`` / ``on_rollback`` / ``on_retry`` /
    ``on_resume`` / ``on_preempt``.  Counting rules:

    - an accepted step is *provisionally* productive;
    - a skipped step is executed-but-wasted;
    - a rollback discards the accepted-but-unsaved steps behind it —
      ``run_resilient`` passes the exact count (it tracks accepted
      steps against actual save results); when an older caller omits
      it, the fallback ``(step - anchor) - skips`` over-charges spans
      containing skip streaks broken by accepted steps, never
      under-charges;
    - a resume after restart only bumps ``resumes`` — work before the
      restart was paid for by a previous process, so charging it here
      would double-count across the job's lifetime.
    """

    def __init__(self):
        self.accepted = 0
        self.skipped = 0
        self.discarded = 0  # accepted steps a rollback threw away
        self.rollbacks = 0
        self.retries = 0
        self.resumes = 0
        self.preempted = False

    # -- observer protocol -------------------------------------------------
    def on_step(self, step: int, skipped: bool, info=None) -> None:
        if skipped:
            self.skipped += 1
        else:
            self.accepted += 1

    def on_rollback(
        self,
        step: int,
        anchor: int,
        skips: int = 0,
        discarded: Optional[int] = None,
    ) -> None:
        self.rollbacks += 1
        if discarded is None:
            # legacy fallback: the replay span minus the final skip
            # streak (an upper bound when the span holds earlier,
            # broken skip streaks)
            discarded = max(0, (step - anchor) - skips)
        self.discarded += discarded

    def on_retry(self, what: str = "", attempt: int = 0, error=None) -> None:
        self.retries += 1

    def on_resume(self, step: int) -> None:
        self.resumes += 1

    def on_preempt(self, step: int) -> None:
        self.preempted = True

    # -- ledger ------------------------------------------------------------
    @property
    def executed(self) -> int:
        return self.accepted + self.skipped

    @property
    def productive(self) -> int:
        return max(0, self.accepted - self.discarded)

    def goodput(self) -> float:
        """Productive fraction of executed steps (1.0 before any work —
        an idle job has wasted nothing yet)."""
        if self.executed == 0:
            return 1.0
        return self.productive / self.executed

    def snapshot(self) -> Dict[str, Any]:
        """The full ledger as plain values — monotonic event counts +
        the derived fractions.  The stable read API for consumers that
        would otherwise reach into fields (the flight recorder's dump,
        fleet aggregation rows, the resilient example's final goodput
        line): one place to keep key names honest."""
        return {
            "accepted": self.accepted,
            "skipped": self.skipped,
            "discarded": self.discarded,
            "rollbacks": self.rollbacks,
            "retries": self.retries,
            "resumes": self.resumes,
            "preempted": self.preempted,
            "executed": self.executed,
            "productive": self.productive,
            "goodput": self.goodput(),
        }

    def summary(self) -> Dict[str, float]:
        return {
            "train/goodput": self.goodput(),
            "train/steps_accepted": float(self.accepted),
            "train/steps_skipped": float(self.skipped),
            "train/steps_discarded": float(self.discarded),
            "train/rollbacks": float(self.rollbacks),
            "train/retries": float(self.retries),
            "train/resumes": float(self.resumes),
        }
