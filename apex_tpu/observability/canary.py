"""Canary analysis for fleet deploys — fingerprints + drift verdicts.

A rolling deploy that ships regressed, corrupted, or miscompiled
weights rolls out fleet-wide with a clean verdict unless something
observes whether the *new weights* are any good.  This module is that
observer, in two independent halves:

**Golden-probe fingerprints** (bit-level identity).  A fixed seeded
probe-prompt set (:class:`GoldenProbeSet`) is run greedily through an
engine after every ``rebuild()``/redeploy and the token streams —
plus the prefill logits bytes, so even a corruption too small to flip
an argmax is visible — are hashed (blake2b) into a model fingerprint
(:func:`model_fingerprint`).  Same-weights rebuilds must match
bit-exactly (the supervised-recovery rebuild path gets this check for
free: rebuild determinism is already pinned by the serve stack);
an INTENTIONAL weight update records the old→new
:func:`fingerprint_distance` on the board instead of failing.  A
single-bit weight corruption flips the digest.

**Statistical drift verdicts** (distribution-level health).  The
:class:`CanaryAnalyzer` compares the canary replica's windowed metric
distributions (TTFT samples, per-slot decode progress, per-reason
terminal shed rates, poisoned-slot counts, speculative accept rate)
against the incumbent pool using nonparametric tests — a one-sided
Mann–Whitney U for continuous channels, a binomial tail against the
pooled incumbent rate for event channels — with a **min-sample
honesty floor**: below the floor a channel returns NO verdict (never
"pass"), the same cold-start honesty as
:class:`~apex_tpu.observability.slo.BurnRateTracker`'s half-coverage
rule.  Verdicts land as
:class:`~apex_tpu.observability.health.HealthEvent` s on the shared
timeline (``fleet_canary_*`` rules).

The fleet integration (:meth:`apex_tpu.fleetctl.Fleet.
start_rolling_update` with a :class:`CanaryConfig`) makes the first
updated replica the canary, holds its router load share at
``canary_frac`` until the verdict passes, and on a failed verdict
halts the deploy, rebuilds the canary back to the incumbent weights,
and bumps ``fleet/deploys_rolled_back`` — bad-weight exposure is
provably bounded by the canary fraction (``tools/canary_drill.py``
re-proves the bound from the span dump).  See docs/serving.md
("Canary deploys") and docs/observability.md ("Canary analysis").
"""

from __future__ import annotations

import hashlib
import math
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

__all__ = [
    "GoldenProbeSet",
    "model_fingerprint",
    "fingerprint_distance",
    "mann_whitney_p",
    "binom_tail",
    "CanaryVerdict",
    "CanaryAnalyzer",
    "CanaryConfig",
    "CanaryController",
]

#: shed reasons the drift analyzer treats as weight-health channels —
#: ``draining`` is deploy machinery (the canary itself drains twice on
#: a rollback) and would self-trigger; ``rerouted`` is a hop, not a
#: terminal, and never appears in ``scheduler.shed`` anyway
DRIFT_SHED_REASONS = (
    "deadline", "growth_victim", "pool_exhausted", "oversize",
    "poisoned", "queue_full", "retries_exhausted",
)


# ---------------------------------------------------------------------------
# golden-probe fingerprints
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class GoldenProbeSet:
    """A fixed, seeded probe-prompt set — the model's identity quiz.

    The prompts are data, not randomness at probe time: two probes of
    the same weights ask the same questions, so the fingerprint is a
    pure function of the weights (+ the compiled programs, whose
    rebuild determinism the serve stack already pins).
    """

    prompts: Tuple[Tuple[int, ...], ...]
    max_new_tokens: int = 8

    @classmethod
    def generate(cls, vocab: int, *, n_probes: int = 4,
                 prompt_len: int = 8, max_new_tokens: int = 8,
                 seed: int = 0xCA9A) -> "GoldenProbeSet":
        """Deterministic probe prompts from a seed (no live RNG state:
        the set is reproducible from ``(vocab, n_probes, prompt_len,
        seed)`` alone)."""
        import numpy as np

        rs = np.random.RandomState(seed)
        prompts = tuple(
            tuple(int(t) for t in rs.randint(1, vocab, size=prompt_len))
            for _ in range(n_probes)
        )
        return cls(prompts=prompts, max_new_tokens=int(max_new_tokens))

    def total_tokens(self) -> int:
        return sum(len(p) for p in self.prompts) + \
            len(self.prompts) * self.max_new_tokens


def model_fingerprint(engine, probes: GoldenProbeSet) -> Dict[str, object]:
    """Run every probe greedily through ``engine`` and hash the token
    streams + prefill logits bytes (blake2b) into a fingerprint.

    The token streams alone would miss a corruption too small to flip
    any argmax; the prefill last-logits bytes make the digest
    sensitive to a SINGLE flipped weight bit.  Returns ``{"digest",
    "streams", "finite", "tokens"}`` — ``finite`` is False when any
    probe tripped the engine's in-step non-finite screen (NaN-poisoned
    weights fingerprint honestly instead of crashing the probe).
    """
    h = hashlib.blake2b(digest_size=16)
    streams: List[List[int]] = []
    finite = True
    for prompt in probes.prompts:
        toks, logits_bytes, ok = engine.probe_stream(
            list(prompt), probes.max_new_tokens
        )
        finite = finite and ok
        h.update(logits_bytes)
        h.update(b"".join(int(t).to_bytes(4, "little", signed=True)
                          for t in toks))
        h.update(b"\x00")  # probe separator
        streams.append(list(toks))
    return {
        "digest": h.hexdigest(),
        "streams": streams,
        "finite": finite,
        "tokens": sum(len(s) for s in streams),
    }


def fingerprint_distance(old: Dict[str, object],
                         new: Dict[str, object]) -> Dict[str, object]:
    """Token-level distance between two fingerprints: the fraction of
    stream positions that differ (0.0 = bit-exact, 1.0 = fully
    divergent), plus which probe/position diverged first — the number
    an INTENTIONAL weight update records on the board instead of
    failing the deploy."""
    if old["digest"] == new["digest"]:
        return {"distance": 0.0, "streams_differing": 0,
                "first_divergence": None, "match": True}
    total = differ = 0
    streams_differing = 0
    first: Optional[Tuple[int, int]] = None
    for pi, (a, b) in enumerate(zip(old["streams"], new["streams"])):
        stream_differs = False
        for ti in range(max(len(a), len(b))):
            total += 1
            ta = a[ti] if ti < len(a) else None
            tb = b[ti] if ti < len(b) else None
            if ta != tb:
                differ += 1
                stream_differs = True
                if first is None:
                    first = (pi, ti)
        if stream_differs:
            streams_differing += 1
    # digests differ but every token matched: the logits bytes moved
    # (a sub-argmax corruption) — report the smallest nonzero distance
    distance = (differ / total) if total else 0.0
    if distance == 0.0:
        distance = 1.0 / (total + 1) if total else 1.0
    return {"distance": distance, "streams_differing": streams_differing,
            "first_divergence": first, "match": False}


# ---------------------------------------------------------------------------
# nonparametric tests (dependency-free: no scipy)
# ---------------------------------------------------------------------------


def _norm_sf(z: float) -> float:
    """P[Z >= z] for a standard normal."""
    return 0.5 * math.erfc(z / math.sqrt(2.0))


def mann_whitney_p(canary: Sequence[float], incumbent: Sequence[float],
                   *, worse: str = "greater") -> float:
    """One-sided Mann–Whitney U p-value for "the canary's distribution
    is WORSE than the incumbent's" — ``worse="greater"`` means higher
    values are worse (TTFT), ``worse="less"`` means lower values are
    worse (per-slot decode progress).  Normal approximation with tie
    correction and continuity correction; all-tied samples return 1.0
    (identical distributions are not drift)."""
    if worse not in ("greater", "less"):
        raise ValueError(f"worse must be 'greater'/'less', got {worse!r}")
    n1, n2 = len(canary), len(incumbent)
    if n1 == 0 or n2 == 0:
        return 1.0
    pooled = [(float(v), 0) for v in canary] + \
        [(float(v), 1) for v in incumbent]
    pooled.sort(key=lambda p: p[0])
    # average ranks over ties
    n = n1 + n2
    ranks = [0.0] * n
    tie_term = 0.0
    i = 0
    while i < n:
        j = i
        while j + 1 < n and pooled[j + 1][0] == pooled[i][0]:
            j += 1
        avg = (i + j) / 2.0 + 1.0
        for k in range(i, j + 1):
            ranks[k] = avg
        t = j - i + 1
        if t > 1:
            tie_term += t ** 3 - t
        i = j + 1
    r_canary = sum(r for r, (_, side) in zip(ranks, pooled) if side == 0)
    u_canary = r_canary - n1 * (n1 + 1) / 2.0
    mean = n1 * n2 / 2.0
    var = (n1 * n2 / 12.0) * ((n + 1) - tie_term / (n * (n - 1)))
    if var <= 0.0:
        return 1.0  # every observation tied — no evidence of drift
    sigma = math.sqrt(var)
    if worse == "greater":
        # large U (canary ranks high) = canary worse
        z = (u_canary - mean - 0.5) / sigma
        return _norm_sf(z)
    z = (u_canary - mean + 0.5) / sigma
    return 1.0 - _norm_sf(z)


def binom_tail(k: int, n: int, p: float) -> float:
    """P[Bin(n, p) >= k], exactly, in log space (lgamma)."""
    k, n = int(k), int(n)
    if k <= 0:
        return 1.0
    if n <= 0 or k > n:
        return 0.0 if k > n else 1.0
    if p <= 0.0:
        return 0.0
    if p >= 1.0:
        return 1.0
    log_p, log_q = math.log(p), math.log1p(-p)
    total = 0.0
    lg_n1 = math.lgamma(n + 1)
    for i in range(k, n + 1):
        log_c = lg_n1 - math.lgamma(i + 1) - math.lgamma(n - i + 1)
        total += math.exp(log_c + i * log_p + (n - i) * log_q)
    return min(total, 1.0)


# ---------------------------------------------------------------------------
# the analyzer
# ---------------------------------------------------------------------------


class CanaryVerdict(NamedTuple):
    #: "pass" | "fail" | "no_verdict" — no_verdict means the honesty
    #: floor was not met on ANY channel; it is NOT a pass
    status: str
    #: per-channel evidence: metric, kind, sample counts, p, verdict
    checks: Tuple[Dict[str, object], ...]

    @property
    def failed(self) -> Tuple[Dict[str, object], ...]:
        return tuple(c for c in self.checks if c["verdict"] == "fail")


class CanaryAnalyzer:
    """Canary-vs-incumbent drift verdicts over named metric channels.

    Two channel kinds:

    - **samples** (:meth:`add_samples`): continuous observations
      (TTFT ms, per-slot tokens per tick) judged by a one-sided
      Mann–Whitney U in the channel's ``worse`` direction;
    - **events** (:meth:`add_events`): bad-event counts out of a total
      (per-reason sheds / terminals, spec rejects / drafts) judged by
      an exact binomial tail against the pooled incumbent rate
      (add-half smoothed).

    The **min-sample honesty floor**: a samples channel needs
    ``min_samples`` observations ON EACH SIDE, an events channel needs
    ``min_event_total`` trials on each side — below the floor the
    channel's verdict is ``None`` and contributes nothing, and an
    analyzer whose every channel is below floor returns
    ``"no_verdict"``, never ``"pass"`` (the BurnRateTracker
    half-coverage rule, applied to deploys).  A fail additionally
    requires ``min_events`` bad canary events (one unlucky request is
    an anecdote, not a regression).
    """

    def __init__(self, *, min_samples: int = 16, alpha: float = 1e-3,
                 min_events: int = 4, min_event_total: int = 8):
        if not (0.0 < alpha < 1.0):
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        self.min_samples = int(min_samples)
        self.alpha = float(alpha)
        self.min_events = int(min_events)
        self.min_event_total = int(min_event_total)
        # metric -> {"canary": [...], "incumbent": [...], "worse": str}
        self._samples: Dict[str, Dict[str, object]] = {}
        # metric -> {"canary": [bad, total], "incumbent": [bad, total]}
        self._events: Dict[str, Dict[str, List[float]]] = {}

    @staticmethod
    def _side(side: str) -> str:
        if side not in ("canary", "incumbent"):
            raise ValueError(
                f"side must be 'canary'/'incumbent', got {side!r}"
            )
        return side

    def add_samples(self, side: str, metric: str,
                    values: Sequence[float], *,
                    worse: str = "greater") -> None:
        side = self._side(side)
        if worse not in ("greater", "less"):
            raise ValueError(
                f"channel {metric!r}: worse={worse!r} is not "
                f"'greater' or 'less'"
            )
        ch = self._samples.setdefault(
            metric, {"canary": [], "incumbent": [], "worse": worse}
        )
        if ch["worse"] != worse:
            raise ValueError(
                f"channel {metric!r} direction changed: "
                f"{ch['worse']!r} -> {worse!r}"
            )
        ch[side].extend(float(v) for v in values)

    def add_events(self, side: str, metric: str, bad: float,
                   total: float) -> None:
        side = self._side(side)
        if bad < 0 or total < 0 or bad > total:
            raise ValueError(
                f"channel {metric!r}: bad={bad} total={total} is not a "
                f"count of bad events out of a total"
            )
        ch = self._events.setdefault(
            metric, {"canary": [0.0, 0.0], "incumbent": [0.0, 0.0]}
        )
        ch[side][0] += float(bad)
        ch[side][1] += float(total)

    def verdict(self) -> CanaryVerdict:
        checks: List[Dict[str, object]] = []
        for metric in sorted(self._samples):
            ch = self._samples[metric]
            can, inc = ch["canary"], ch["incumbent"]
            check = {
                "metric": metric, "kind": "samples",
                "worse": ch["worse"],
                "n_canary": len(can), "n_incumbent": len(inc),
                "p": None, "verdict": None,
            }
            if len(can) >= self.min_samples and \
                    len(inc) >= self.min_samples:
                p = mann_whitney_p(can, inc, worse=ch["worse"])
                check["p"] = p
                check["verdict"] = "fail" if p < self.alpha else "pass"
            checks.append(check)
        for metric in sorted(self._events):
            ch = self._events[metric]
            bad_c, tot_c = ch["canary"]
            bad_i, tot_i = ch["incumbent"]
            check = {
                "metric": metric, "kind": "events",
                "bad_canary": bad_c, "n_canary": tot_c,
                "bad_incumbent": bad_i, "n_incumbent": tot_i,
                "p": None, "verdict": None,
            }
            if tot_c >= self.min_event_total and \
                    tot_i >= self.min_event_total:
                # pooled incumbent rate, add-half smoothed (a 0-count
                # incumbent never claims the bad rate is exactly 0)
                p_hat = (bad_i + 0.5) / (tot_i + 1.0)
                p = binom_tail(int(round(bad_c)), int(round(tot_c)),
                               p_hat)
                check["p"] = p
                check["verdict"] = (
                    "fail"
                    if p < self.alpha and bad_c >= self.min_events
                    else "pass"
                )
            checks.append(check)
        if any(c["verdict"] == "fail" for c in checks):
            status = "fail"
        elif any(c["verdict"] == "pass" for c in checks):
            status = "pass"
        else:
            status = "no_verdict"
        return CanaryVerdict(status=status, checks=tuple(checks))


# ---------------------------------------------------------------------------
# fleet-facing configuration + controller
# ---------------------------------------------------------------------------


@dataclass
class CanaryConfig:
    """Canary-gating knobs for :meth:`~apex_tpu.fleetctl.Fleet.
    start_rolling_update`.

    ``frac`` is the router load-share ceiling while the verdict is
    out (the provable bad-weight exposure bound).  ``soak_ticks`` is
    the minimum window before a statistical PASS is accepted (a fail
    halts immediately); ``max_window_ticks`` bounds the wait — at
    expiry a floor-starved window closes ``inconclusive`` (warned,
    deploy proceeds) rather than blocking the fleet forever.
    """

    frac: float = 0.25
    probes: Optional[GoldenProbeSet] = None
    min_samples: int = 16
    alpha: float = 1e-3
    min_events: int = 4
    min_event_total: int = 8
    soak_ticks: int = 240
    max_window_ticks: int = 600

    def __post_init__(self):
        if not (0.0 < self.frac < 1.0):
            raise ValueError(
                f"canary_frac must be in (0, 1), got {self.frac}"
            )
        if self.max_window_ticks < self.soak_ticks:
            raise ValueError(
                f"max_window_ticks {self.max_window_ticks} < "
                f"soak_ticks {self.soak_ticks}"
            )


class CanaryController:
    """Windowed canary-vs-incumbent observation over live replicas.

    Opened by the fleet when the canary returns to service: baselines
    every replica's ledgers (completion index, terminal-shed index,
    token counter, spec counters), then :meth:`observe` once per fleet
    tick collects the per-tick continuous channel and
    :meth:`verdict` folds everything since the baseline through a
    fresh :class:`CanaryAnalyzer`.  Replicas that die mid-window keep
    contributing the samples they produced while alive (their ledgers
    persist) — the verdict never reads beyond what actually happened.
    """

    def __init__(self, canary, incumbents, config: CanaryConfig):
        self.canary = canary
        self.incumbents = list(incumbents)
        self.cfg = config
        self._base: Dict[str, Dict[str, object]] = {}
        self._last_tokens: Dict[str, int] = {}
        self._open_tokens: Dict[str, int] = {}
        self._tick_samples: Dict[str, List[float]] = {
            "canary": [], "incumbent": [],
        }
        for rep in [self.canary] + self.incumbents:
            self._base[rep.name] = self._snapshot(rep)
            self._last_tokens[rep.name] = rep.sched._tokens_out
            self._open_tokens[rep.name] = rep.sched._tokens_out

    @staticmethod
    def _snapshot(rep) -> Dict[str, object]:
        spec = (0.0, 0.0)
        if rep.engine.spec is not None and rep.registry is not None:
            vals = rep.registry.fetch()
            spec = (float(vals.get("serve/spec_drafted", 0.0)),
                    float(vals.get("serve/spec_accepted", 0.0)))
        return {
            "completed": len(rep.sched.completed),
            "shed": len(rep.sched.shed),
            "spec": spec,
        }

    def _sides(self):
        return (("canary", [self.canary]),
                ("incumbent", self.incumbents))

    def observe(self) -> None:
        """Per-tick channel: tokens emitted per RUNNING slot this tick
        — load-independent decode progress (a throttled/stalled decode
        shows up here even when every token is eventually produced)."""
        for side, reps in self._sides():
            for rep in reps:
                cur = rep.sched._tokens_out
                delta = cur - self._last_tokens[rep.name]
                self._last_tokens[rep.name] = cur
                running = len(rep.sched.running)
                if running > 0:
                    self._tick_samples[side].append(delta / running)

    def token_exposure(self) -> Tuple[int, int]:
        """``(canary_tokens, total_tokens)`` emitted since the window
        opened — the bad-token half of the exposure bound."""
        canary = total = 0
        for side, reps in self._sides():
            for rep in reps:
                d = rep.sched._tokens_out - self._open_tokens[rep.name]
                total += d
                if side == "canary":
                    canary += d
        return canary, total

    def analyzer(self) -> CanaryAnalyzer:
        cfg = self.cfg
        an = CanaryAnalyzer(
            min_samples=cfg.min_samples, alpha=cfg.alpha,
            min_events=cfg.min_events,
            min_event_total=cfg.min_event_total,
        )
        for side, reps in self._sides():
            ttfts: List[float] = []
            shed_by_reason = {r: 0 for r in DRIFT_SHED_REASONS}
            terminals = 0
            spec_drafted = spec_accepted = 0.0
            for rep in reps:
                base = self._base[rep.name]
                done = rep.sched.completed[base["completed"]:]
                shed = rep.sched.shed[base["shed"]:]
                ttfts.extend(
                    r.ttft_ms for r in done if r.ttft_ms is not None
                )
                terminals += len(done) + len(shed)
                for r in shed:
                    if r.shed_reason in shed_by_reason:
                        shed_by_reason[r.shed_reason] += 1
                if rep.engine.spec is not None and \
                        rep.registry is not None:
                    vals = rep.registry.fetch()
                    d0, a0 = base["spec"]
                    spec_drafted += \
                        float(vals.get("serve/spec_drafted", 0.0)) - d0
                    spec_accepted += \
                        float(vals.get("serve/spec_accepted", 0.0)) - a0
            an.add_samples(side, "ttft_ms", ttfts, worse="greater")
            an.add_samples(side, "tokens_per_slot_tick",
                           self._tick_samples[side], worse="less")
            for reason, n in shed_by_reason.items():
                an.add_events(side, f"shed_{reason}", n, terminals)
            if spec_drafted > 0:
                an.add_events(side, "spec_reject",
                              spec_drafted - spec_accepted, spec_drafted)
        return an

    def verdict(self) -> CanaryVerdict:
        return self.analyzer().verdict()
