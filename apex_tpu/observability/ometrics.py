"""OpenMetrics exporter — the live half of the telemetry spine.

Everything the observability stack has landed so far is post-hoc: JSONL
artifacts read after the run, flight dumps read after the death,
timelines assembled offline.  A production deployment needs the
complement — *live* state queryable while the process handles traffic.
This module is that surface, dependency-free by construction:

- :func:`metric_name` — the documented, deterministic mapping from the
  board/registry key vocabulary (``serve/ttft_queue_wait_fraction``,
  ``guard/skipped``, ``memstats/device0/bytes_in_use``) to legal
  OpenMetrics metric names (``apex_tpu_serve_ttft_queue_wait_fraction``
  …).  The mapping is structural (slashes/dashes/dots → ``_``,
  lowercase, ``apex_tpu_`` prefix) and *injective over the declared
  vocabulary*: :class:`ExportNamespace` rejects any new key whose
  mangled name — or reserved sample names (``<name>_total`` for
  counters) — collides with an existing key's, and
  :class:`~apex_tpu.observability.metrics.MetricRegistry` runs every
  declaration through it, so a key that cannot round-trip through the
  exporter fails at declare time, not scrape time.
- :func:`render` — one OpenMetrics exposition
  (``# TYPE``/``# UNIT``/``# HELP`` metadata, counter ``_total``
  samples, histogram ``_bucket``/``_count``/``_sum`` with cumulative
  ``le`` buckets, ``# EOF`` terminator) over any mix of metric
  registries, host-side :class:`Histogram` s, and the module board.
  ``# HELP`` carries the ORIGINAL key, so the mapping documents itself
  in the scrape.
- :class:`Histogram` — a host-side bucket accumulator (the registry's
  device-side kinds are scalar by design; latency distributions live on
  the host where the timestamps are taken).  The serve scheduler
  publishes its TTFT distribution through one, and
  :class:`~apex_tpu.observability.slo.LatencySLO` reads good/total
  event counts straight off its cumulative buckets — the classic
  Prometheus-histogram SLI.
- :class:`OpsServer` — a stdlib ``http.server`` thread serving
  ``GET /metrics``.  A scrape renders from the registry's *cached*
  values (:meth:`MetricRegistry.values` — no device contact, no
  blocking fetch), so scraping under load rides the same <1%-overhead
  contract the registry itself is pinned to
  (``tests/test_ometrics.py``).
- :func:`parse_exposition` — a strict validating parser for the subset
  this module emits, used by the conformance tests and the
  ``verify_tier1.sh`` OPS gate so "OpenMetrics-valid" is a checked
  claim, not an adjective.

Armed via ``--ops-port`` on ``tools/serve_bench.py`` and
``examples/simple/resilient/train_resilient.py``, or the
``APEX_TPU_OPS_PORT`` env (:meth:`OpsServer.from_env`).  See
``docs/observability.md`` ("Live ops plane").
"""

from __future__ import annotations

import bisect
import math
import os
import re
import threading
import time
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

from apex_tpu.observability.locks import TrackedLock

__all__ = [
    "ENV_OPS_PORT",
    "ops_port_from_env",
    "CONTENT_TYPE",
    "DEFAULT_LATENCY_BUCKETS_MS",
    "metric_name",
    "ExportNamespace",
    "Histogram",
    "render",
    "parse_exposition",
    "OpsServer",
]

ENV_OPS_PORT = "APEX_TPU_OPS_PORT"

#: the OpenMetrics 1.0 content type every ``/metrics`` response carries
CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

#: default latency buckets (milliseconds) — spans sub-ms CPU smoke runs
#: to multi-second tail blowups; SLO thresholds should land ON a bound
#: (``Histogram.count_le`` truncates to the nearest lower bound)
DEFAULT_LATENCY_BUCKETS_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
    500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)

def ops_port_from_env(spec: Optional[str] = None) -> Optional[int]:
    """The ONE ``APEX_TPU_OPS_PORT`` parsing convention (``0`` =
    OS-assigned, unset/empty = disabled) — tools resolve their
    ``--ops-port`` default through this so the arming grammar cannot
    drift per surface."""
    spec = spec if spec is not None else os.environ.get(ENV_OPS_PORT)
    if spec is None or str(spec).strip() == "":
        return None
    return int(str(spec).strip())


_PREFIX = "apex_tpu_"
_LEGAL_NAME = re.compile(r"^[a-z_][a-z0-9_]*$")
#: characters that become ``_`` (everything else non-alphanumeric is
#: dropped — and the injectivity check catches any resulting collision)
_SEPARATORS = frozenset("/-. :")


def metric_name(key: str) -> str:
    """The OpenMetrics metric name for a board/registry ``key``.

    Deterministic and purely structural: lowercase, separators
    (``/ - . :`` and spaces) to ``_``, other non-``[a-z0-9_]``
    characters dropped, runs of ``_`` collapsed, ``apex_tpu_``
    prefixed.  Raises ``ValueError`` when nothing legal survives —
    injectivity over a *set* of keys is :class:`ExportNamespace`'s job.
    """
    out = []
    for ch in str(key):
        if ch.isascii() and ch.isalnum():
            out.append(ch.lower())
        elif ch in _SEPARATORS or ch == "_":
            out.append("_")
        # anything else: dropped (collision check guards the fallout)
    name = re.sub(r"__+", "_", "".join(out)).strip("_")
    if not name or not _LEGAL_NAME.match(name):
        raise ValueError(
            f"key {key!r} cannot be mapped to a legal OpenMetrics "
            f"metric name (got {name!r} after mangling)"
        )
    return _PREFIX + name


def _reserved_samples(family: str, kind: str) -> Tuple[str, ...]:
    """Every sample name a family of ``kind`` will emit (the collision
    surface: a counter ``x`` exposes ``x_total``, so a gauge named
    ``x_total`` must be rejected)."""
    if kind == "counter":
        return (family, family + "_total")
    if kind == "histogram":
        return (family, family + "_bucket", family + "_count",
                family + "_sum")
    return (family,)


class ExportNamespace:
    """Injectivity guard for the key→metric-name mapping.

    ``declare(key, kind)`` returns the family name, is idempotent for a
    re-declared ``(key, kind)``, and raises ``ValueError`` when the key
    is unmappable or any of its reserved sample names collides with a
    DIFFERENT key's — the registry-level validation that keeps the
    whole board vocabulary round-trippable through the exporter.
    """

    def __init__(self):
        self._families: Dict[str, Tuple[str, str]] = {}  # family -> (key, kind)
        self._samples: Dict[str, str] = {}  # sample name -> family

    def declare(self, key: str, kind: str = "gauge") -> str:
        # min/max registry kinds export as gauges
        kind = "gauge" if kind in ("min", "max") else kind
        family = metric_name(key)
        prev = self._families.get(family)
        if prev is not None:
            if prev == (key, kind):
                return family
            raise ValueError(
                f"key {key!r} ({kind}) mangles to {family!r} which is "
                f"already taken by key {prev[0]!r} ({prev[1]}) — the "
                "OpenMetrics mapping must stay injective; rename the key"
            )
        for sample in _reserved_samples(family, kind):
            owner = self._samples.get(sample)
            if owner is not None and owner != family:
                raise ValueError(
                    f"key {key!r} ({kind}) would emit sample "
                    f"{sample!r} which collides with family {owner!r} "
                    f"(key {self._families[owner][0]!r}) — rename the key"
                )
        self._families[family] = (key, kind)
        for sample in _reserved_samples(family, kind):
            self._samples[sample] = family
        return family

    @property
    def families(self) -> Dict[str, Tuple[str, str]]:
        return dict(self._families)


class Histogram:
    """Host-side cumulative-bucket histogram (OpenMetrics semantics).

    ``buckets`` are the finite upper bounds (``le`` is inclusive); the
    ``+Inf`` bucket is implicit.  ``observe`` is a bisect + two adds —
    cheap enough for per-request call sites.  ``count_le(bound)``
    returns the cumulative count at the nearest bucket bound ≤
    ``bound`` (exact when the bound IS a bucket edge — put SLO
    thresholds on edges), which is what
    :class:`~apex_tpu.observability.slo.LatencySLO` uses as its
    good-event count.
    """

    def __init__(self, key: str, buckets: Iterable[float],
                 unit: str = "", help: str = ""):
        bounds = tuple(float(b) for b in buckets)
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            raise ValueError(f"bucket bounds must increase: {bounds}")
        if any(math.isnan(b) or math.isinf(b) for b in bounds):
            raise ValueError("bucket bounds must be finite (+Inf is implicit)")
        self.key = str(key)
        self.unit = str(unit)
        self.help = str(help)
        # fail unmappable names at construction, not at scrape
        metric_name(self.key)
        self._bounds = bounds
        self._counts = [0] * len(bounds)
        self._inf = 0
        self._sum = 0.0
        self._count = 0
        # observe() runs on the serving thread while a scrape renders
        # on the HTTP thread: without the lock a scrape could see a
        # bucket incremented but _count not yet — an exposition whose
        # _count disagrees with the +Inf bucket, which strict parsers
        # (including parse_exposition in the CI gate) reject
        self._lock = threading.Lock()

    def observe(self, value) -> None:
        v = float(value)
        i = bisect.bisect_left(self._bounds, v)
        with self._lock:
            if i < len(self._bounds):
                self._counts[i] += 1
            else:
                self._inf += 1
            self._sum += v
            self._count += 1

    def _consistent_view(self) -> Tuple[List[Tuple[float, int]], int, float]:
        """``(cumulative, count, sum)`` captured under ONE lock — the
        render/snapshot source, so ``_count`` always equals the
        ``+Inf`` bucket in anything emitted."""
        with self._lock:
            counts = list(self._counts)
            inf, count, total = self._inf, self._count, self._sum
        out, running = [], 0
        for b, c in zip(self._bounds, counts):
            running += c
            out.append((b, running))
        out.append((math.inf, running + inf))
        return out, count, total

    @property
    def bounds(self) -> Tuple[float, ...]:
        return self._bounds

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative(self) -> List[Tuple[float, int]]:
        """``[(le_bound, cumulative_count), ...]`` ending at ``+Inf``."""
        return self._consistent_view()[0]

    def count_le(self, bound: float) -> int:
        """Observations ≤ the nearest bucket bound ≤ ``bound`` (0 when
        ``bound`` sits under the first bucket).  Conservative by
        construction: a threshold between bounds under-counts good
        events rather than inventing them."""
        i = bisect.bisect_right(self._bounds, float(bound)) - 1
        if i < 0:
            return 0
        with self._lock:
            return sum(self._counts[: i + 1])

    def snapshot(self) -> Dict[str, Any]:
        cumulative, count, total = self._consistent_view()
        return {
            "key": self.key,
            "unit": self.unit,
            "count": count,
            "sum": total,
            "buckets": [
                {"le": ("+Inf" if math.isinf(b) else b), "count": c}
                for b, c in cumulative
            ],
        }


# -- exposition rendering ---------------------------------------------------


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(text: str) -> str:
    return (
        text.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')
    )


def _fmt(v) -> str:
    v = float(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _fmt_le(bound: float) -> str:
    return "+Inf" if math.isinf(bound) else _fmt(bound)


class _Family:
    def __init__(self, name, kind, unit="", help=""):
        self.name, self.kind, self.unit, self.help = name, kind, unit, help
        self.lines: List[str] = []

    def render(self) -> List[str]:
        out = [f"# TYPE {self.name} {self.kind}"]
        # a UNIT line requires the name to end with the unit suffix —
        # emit it only when the vocabulary already follows the
        # convention (serve/ttft_ms etc.); the mapping itself never
        # rewrites names to force it
        if self.unit and self.name.endswith("_" + self.unit):
            out.append(f"# UNIT {self.name} {self.unit}")
        if self.help:
            out.append(f"# HELP {self.name} {_escape_help(self.help)}")
        out.extend(self.lines)
        return out


def _unit_token(unit: str) -> str:
    """Unit metadata must itself be a legal name token; anything else
    (e.g. the registry's descriptive ``"fraction (…)"`` strings) is
    dropped from metadata rather than corrupting the exposition."""
    unit = (unit or "").strip().lower()
    return unit if re.match(r"^[a-z][a-z0-9_]*$", unit) else ""


def render(
    registries: Iterable[Any] = (),
    histograms: Iterable[Histogram] = (),
    board: Optional[Mapping[str, Any]] = None,
) -> str:
    """One OpenMetrics exposition over the given sources.

    - ``registries``: :class:`~apex_tpu.observability.metrics.
      MetricRegistry` objects — declared kinds/units, **cached** values
      only (:meth:`values` — never a blocking fetch).
    - ``histograms``: :class:`Histogram` objects.
    - ``board``: a key→value mapping (pass ``board.snapshot()``);
      numeric values export as gauges, strings are skipped (the board
      holds config strings like ``serve/kv_wire`` that have no sample
      representation).

    Name collisions across sources resolve first-wins in the order
    above (a registry value is fresher than a board echo of it) —
    *within* a registry the :class:`ExportNamespace` validation already
    made collisions impossible.
    """
    families: Dict[str, _Family] = {}
    taken: set = set()

    def claim(name: str, kind: str) -> bool:
        reserved = _reserved_samples(name, kind)
        if name in families or any(s in taken for s in reserved):
            return False
        taken.update(reserved)
        return True

    for reg in registries:
        values = reg.values()
        for key in reg.names:
            kind = reg.kind(key)
            kind = "gauge" if kind in ("min", "max") else kind
            if key not in values:
                continue  # declared but never fetched: no sample yet
            name = metric_name(key)
            if not claim(name, kind):
                continue
            fam = families[name] = _Family(
                name, kind, _unit_token(reg.unit(key)),
                f"board key {key!r}",
            )
            sample = name + "_total" if kind == "counter" else name
            fam.lines.append(f"{sample} {_fmt(values[key])}")

    for hist in histograms:
        name = metric_name(hist.key)
        if not claim(name, "histogram"):
            continue
        fam = families[name] = _Family(
            name, "histogram", _unit_token(hist.unit),
            hist.help or f"board key {hist.key!r}",
        )
        # one consistent view: buckets, _count and _sum must agree even
        # while another thread observes mid-render
        cumulative, count, total = hist._consistent_view()
        for bound, cum in cumulative:
            fam.lines.append(
                f'{name}_bucket{{le="{_fmt_le(bound)}"}} {cum}'
            )
        fam.lines.append(f"{name}_count {count}")
        fam.lines.append(f"{name}_sum {_fmt(total)}")

    if board:
        for key in sorted(board):
            value = board[key]
            if isinstance(value, bool) or not isinstance(
                value, (int, float)
            ):
                continue
            try:
                name = metric_name(key)
            except ValueError:
                continue  # an unmappable ad-hoc board key: skip, not crash
            if not claim(name, "gauge"):
                continue
            fam = families[name] = _Family(
                name, "gauge", help=f"board key {key!r}"
            )
            fam.lines.append(f"{name} {_fmt(value)}")

    lines: List[str] = []
    for name in sorted(families):
        lines.extend(families[name].render())
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


# -- strict validating parser (tests + the CI OPS gate) ---------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r" (?P<value>\S+)(?: (?P<ts>\S+))?$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_value(tok: str) -> float:
    if tok == "+Inf":
        return math.inf
    if tok == "-Inf":
        return -math.inf
    if tok == "NaN":
        return math.nan
    return float(tok)


def parse_exposition(text: str) -> Dict[str, Dict[str, Any]]:
    """Parse + validate an OpenMetrics exposition (the subset
    :func:`render` emits).  Returns ``{family: {"type", "unit",
    "help", "samples": [(sample_name, labels, value)], "value"}}``
    (``value`` is the bare sample for gauge/counter families).

    Raises ``ValueError`` on: a missing/misplaced ``# EOF``, samples
    before their ``# TYPE``, metadata after samples of the same family,
    a counter sample not named ``<family>_total``, a ``# UNIT`` that is
    not a suffix of the name, histogram buckets whose ``le`` bounds are
    not strictly increasing / cumulative counts decreasing / missing
    ``+Inf`` / ``_count`` disagreeing with the ``+Inf`` bucket.
    This is the checker the conformance tests and the
    ``verify_tier1.sh`` OPS gate run over a live scrape.
    """
    lines = text.split("\n")
    if lines and lines[-1] == "":
        lines.pop()
    if not lines or lines[-1] != "# EOF":
        raise ValueError("exposition does not end with '# EOF'")
    lines.pop()

    families: Dict[str, Dict[str, Any]] = {}

    def family_for_sample(sample: str) -> Optional[str]:
        for suffix in ("_total", "_bucket", "_count", "_sum", ""):
            base = sample[: len(sample) - len(suffix)] if suffix else sample
            if suffix and not sample.endswith(suffix):
                continue
            if base in families:
                return base
        return None

    for i, line in enumerate(lines, 1):
        if line.startswith("# "):
            parts = line[2:].split(" ", 2)
            if len(parts) < 2:
                raise ValueError(f"line {i}: bad metadata line {line!r}")
            keyword, name = parts[0], parts[1]
            rest = parts[2] if len(parts) > 2 else ""
            if keyword == "EOF":
                raise ValueError(f"line {i}: '# EOF' before the end")
            if keyword == "TYPE":
                if name in families:
                    raise ValueError(f"line {i}: duplicate TYPE for {name}")
                families[name] = {
                    "type": rest, "unit": "", "help": "", "samples": [],
                }
            elif keyword in ("UNIT", "HELP"):
                fam = families.get(name)
                if fam is None:
                    raise ValueError(
                        f"line {i}: {keyword} for undeclared family {name}"
                    )
                if fam["samples"]:
                    raise ValueError(
                        f"line {i}: {keyword} after samples of {name}"
                    )
                if keyword == "UNIT":
                    if not name.endswith("_" + rest):
                        raise ValueError(
                            f"line {i}: unit {rest!r} is not a suffix "
                            f"of {name!r}"
                        )
                    fam["unit"] = rest
                else:
                    fam["help"] = rest
            else:
                raise ValueError(f"line {i}: unknown metadata {keyword!r}")
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {i}: unparseable sample line {line!r}")
        sample = m.group("name")
        base = family_for_sample(sample)
        if base is None:
            raise ValueError(
                f"line {i}: sample {sample!r} before any matching # TYPE"
            )
        labels = dict(
            (k, v.replace('\\"', '"').replace("\\n", "\n")
             .replace("\\\\", "\\"))
            for k, v in _LABEL_RE.findall(m.group("labels") or "")
        )
        value = _parse_value(m.group("value"))
        fam = families[base]
        kind = fam["type"]
        if kind == "counter":
            if sample != base + "_total":
                raise ValueError(
                    f"line {i}: counter sample must be {base}_total, "
                    f"got {sample!r}"
                )
            if value < 0:
                raise ValueError(f"line {i}: negative counter {value}")
        elif kind == "gauge":
            if sample != base:
                raise ValueError(
                    f"line {i}: gauge sample {sample!r} != family {base!r}"
                )
        elif kind == "histogram":
            if sample == base + "_bucket" and "le" not in labels:
                raise ValueError(f"line {i}: bucket without an le label")
        fam["samples"].append((sample, labels, value))

    for name, fam in families.items():
        if fam["type"] == "histogram":
            buckets = [
                (_parse_value(labels["le"]), value)
                for sample, labels, value in fam["samples"]
                if sample == name + "_bucket"
            ]
            if not buckets or not math.isinf(buckets[-1][0]):
                raise ValueError(
                    f"{name}: histogram must end with an le=\"+Inf\" bucket"
                )
            for (b1, c1), (b2, c2) in zip(buckets, buckets[1:]):
                if not b2 > b1:
                    raise ValueError(
                        f"{name}: le bounds not increasing ({b1} -> {b2})"
                    )
                if c2 < c1:
                    raise ValueError(
                        f"{name}: cumulative counts decreasing "
                        f"({c1} -> {c2} at le={_fmt_le(b2)})"
                    )
            counts = [
                value for sample, _l, value in fam["samples"]
                if sample == name + "_count"
            ]
            if counts and counts[0] != buckets[-1][1]:
                raise ValueError(
                    f"{name}: _count {counts[0]} != +Inf bucket "
                    f"{buckets[-1][1]}"
                )
        else:
            bare = [
                value for sample, _l, value in fam["samples"]
                if not sample.endswith(("_bucket",))
            ]
            fam["value"] = bare[0] if bare else None
    return families


# -- the HTTP endpoint ------------------------------------------------------


class OpsServer:
    """Serve ``GET /metrics`` from a daemon thread (stdlib only).

    >>> srv = OpsServer(registries=[reg], histograms=[hist],
    ...                 port=0).start()        # port 0 = OS-assigned
    >>> srv.url                                 # http://127.0.0.1:PORT/metrics
    >>> srv.stop()

    A scrape calls the optional ``collect`` hook (e.g.
    :meth:`~apex_tpu.observability.memstats.MemStatsMonitor.sample`),
    then renders the sources' **cached** values — no device contact, no
    blocking registry fetch; freshness is the registry's own
    ``2 × fetch_every`` contract.  Scrape count and duration publish to
    the board (``ops/scrapes``, ``ops/scrape_ms``) so the exporter
    observes itself.

    ``port=0`` binds an OS-assigned ephemeral port; :attr:`bound_port`
    (and the updated :attr:`port` / :attr:`url`) expose it after
    :meth:`start` — how N fleet replicas in ONE process each export
    ``/metrics`` without a port collision.  ``name=`` namespaces the
    self-observation board keys (``ops/<name>/scrapes``, ...): without
    it, N servers in one process would silently overwrite each other's
    gauges on the shared board.
    """

    def __init__(
        self,
        *,
        registries: Iterable[Any] = (),
        histograms: Iterable[Histogram] = (),
        include_board: bool = True,
        collect=None,
        host: str = "127.0.0.1",
        port: int = 0,
        name: Optional[str] = None,
    ):
        self.registries = list(registries)
        self.histograms = list(histograms)
        self.include_board = include_board
        self.collect = collect
        self.host = host
        self.port = int(port)
        self.name = name
        self.scrapes = 0
        self.last_scrape_ms: Optional[float] = None
        # scrape() runs on ThreadingHTTPServer handler threads while
        # tests/boards read the counters from the main thread
        self._lock = TrackedLock("ops.scrape")
        self._server = None
        self._thread = None

    def _board_key(self, leaf: str) -> str:
        return (
            f"ops/{self.name}/{leaf}" if self.name else f"ops/{leaf}"
        )

    @property
    def bound_port(self) -> Optional[int]:
        """The OS-assigned port after :meth:`start` (None before — a
        requested ``port=0`` is a *wish*, not an address)."""
        if self._server is None:
            return None
        return self._server.server_address[1]

    @classmethod
    def from_env(cls, spec: Optional[str] = None, **kwargs):
        """An UNSTARTED server armed by ``APEX_TPU_OPS_PORT=PORT``
        (``0`` = OS-assigned), or ``None`` when the env is unset/empty
        — the flight-recorder arming convention."""
        port = ops_port_from_env(spec)
        if port is None:
            return None
        return cls(port=port, **kwargs)

    def add_source(self, *, registry=None, histogram=None) -> None:
        """Late-bind a source (schedulers and their histograms usually
        exist only after the server that should export them)."""
        if registry is not None:
            self.registries.append(registry)
        if histogram is not None:
            self.histograms.append(histogram)

    def scrape(self) -> str:
        """One in-process exposition (the exact text ``/metrics``
        serves)."""
        t0 = time.perf_counter()
        if self.collect is not None:
            self.collect()
        board_snapshot = None
        if self.include_board:
            from apex_tpu.observability.metrics import board

            board_snapshot = board.snapshot()
        text = render(self.registries, self.histograms, board_snapshot)
        with self._lock:
            self.scrapes += 1
            self.last_scrape_ms = 1e3 * (time.perf_counter() - t0)
            scrapes, scrape_ms = self.scrapes, self.last_scrape_ms
        if self.include_board:
            from apex_tpu.observability.metrics import board

            board.set(self._board_key("scrapes"), scrapes)
            board.set(self._board_key("scrape_ms"), scrape_ms)
        return text

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def start(self) -> "OpsServer":
        import http.server

        ops = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_error(404, "only /metrics is served")
                    return
                try:
                    body = ops.scrape().encode("utf-8")
                except Exception as e:  # pragma: no cover - defensive
                    self.send_error(500, f"scrape failed: {e}")
                    return
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet: scrapes are routine
                pass

        self._server = http.server.ThreadingHTTPServer(
            (self.host, self.port), Handler
        )
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="apex-tpu-ops",
            daemon=True,
        )
        self._thread.start()
        from apex_tpu.observability.metrics import board

        board.set(self._board_key("port"), self.port)
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
