"""Fleet router — the one front door over N replicas.

Requests enter the fleet at the router's **door** (an unbounded fleet
queue — per-replica backpressure still applies at each replica's own
bounded admission queue) and are dispatched once per fleet tick to the
least-loaded LIVE replica (deterministic: load = queued + running,
ties break on replica name).  Every dispatch records the validated
``routed`` span phase (:data:`~apex_tpu.observability.spans.
REQ_ROUTED`) carrying the destination replica — the timeline shows
exactly which replica each request (and each re-route) landed on.

The router is also the fleet's re-admission path: a replica draining
for a preemption or rolling deploy hands its never-admitted queue to
:meth:`Router.reroute` (the ``scheduler.drain(handoff=)`` hook), and a
crashed replica's evacuated requests arrive the same way.  A re-routed
request is reset to prompt-only — pages are replica-local, the
destination re-prefills — while its original ``submitted_at``,
accumulated queue-wait, and SHARED retry budget ride along unchanged.

Chaos: the ``fleet.router`` site faults a whole dispatch tick (the
transient routing error) — requests stay at the door and go out on the
next tick; nothing is lost, the ``fleet/router_faults`` counter says
it happened.
"""

from __future__ import annotations

import collections
from typing import Any, Deque, Dict, Iterable, List, Optional

from apex_tpu.observability.spans import REQ_ROUTED
from apex_tpu.resilience import chaos
from apex_tpu.serve.scheduler import QUEUED, Request

from apex_tpu.fleetctl.replica import LIVE, EngineReplica

__all__ = ["Router", "aggregate_expositions"]


class Router:
    """Least-loaded dispatch + re-routing over a replica set.

    ``count`` is the fleet's counter hook (``callable(name, n=1)``) so
    router traffic lands on the fleet ledger without the router owning
    a registry.
    """

    def __init__(self, *, clock, spans=None, count=None):
        self.clock = clock
        self.spans = spans
        self._count = count if count is not None else (lambda name, n=1: None)
        self.door: Deque[Request] = collections.deque()
        #: dispatch ticks lost to an injected ``fleet.router`` fault
        self.faulted_ticks = 0
        # canary hold: while set, the named replica's share of routed
        # requests is capped at `frac` (the deploy exposure bound)
        self._canary_name: Optional[str] = None
        self._canary_frac = 0.0
        self.window_routed = 0
        self.window_canary = 0

    # -- canary hold -------------------------------------------------------
    def set_canary(self, name: str, frac: float) -> None:
        """Open a canary hold: until :meth:`clear_canary`, replica
        ``name`` receives at most ``frac`` of the window's dispatches
        (enforced per-request, counted from zero at the hold's open) —
        THE mechanism behind the deploy's provable bad-weight exposure
        bound.  Every canary dispatch is additionally annotated
        ``canary=True`` on its validated ``routed`` span, so the bound
        is re-provable from the span dump alone."""
        self._canary_name = str(name)
        self._canary_frac = float(frac)
        self.window_routed = 0
        self.window_canary = 0

    def clear_canary(self) -> Dict[str, Any]:
        """Close the hold; returns the window's routing tallies."""
        stats = {
            "canary": self._canary_name,
            "frac": self._canary_frac,
            "routed": self.window_routed,
            "canary_routed": self.window_canary,
        }
        self._canary_name = None
        self._canary_frac = 0.0
        return stats

    def _canary_admissible(self) -> bool:
        """Would one more canary dispatch keep the window share within
        the hold?  ``(canary + 1) <= frac * (routed + 1)`` — the +1s
        make the very first dispatches honest (0/0 is not "under")."""
        return (self.window_canary + 1) <= \
            self._canary_frac * (self.window_routed + 1)

    # -- intake ------------------------------------------------------------
    def submit(self, req: Request) -> Request:
        """A NEW request enters the fleet (dispatched next tick)."""
        self._count("fleet/submitted")
        self.door.append(req)
        return req

    def reroute(self, req: Request) -> bool:
        """Re-admit a request another replica gave up (drain handoff /
        crash evacuation): reset it to prompt-only — the pages were
        already freed to their OWN pool by the shedding scheduler, the
        generated prefix is untrusted without them — and queue it at
        the door.  ``submitted_at`` (end-to-end TTFT), accumulated
        ``queue_blocked_s``, any clamp, and the consumed ``retries``
        budget are deliberately PRESERVED.  Always accepts (the door
        is the fleet's unbounded holding area); the bool return is the
        ``drain(handoff=)`` contract."""
        assert not req.pages, (
            f"re-routed request {req.rid} still holds pages — they are "
            f"replica-local and must be freed by the source scheduler"
        )
        req.tokens = []
        req.ctx_len = 0
        req.status = QUEUED
        req.admitted_at = None
        req.first_token_at = None
        req.blocked_since = None
        req.first_decode_iter = None
        req.last_decode_iter = None
        # prefix-cache state is replica-local too: the source's hit
        # (borrowed pages, skipped positions) means nothing on the
        # destination — it probes its OWN cache afresh
        req.prefill_pos = 0
        req.cache_hit_tokens = 0
        req.cache_hit_pages = 0
        req.cache_probed = False
        req.prefill_started_at = None
        self._count("fleet/rerouted")
        self.door.append(req)
        return True

    # -- dispatch ----------------------------------------------------------
    @staticmethod
    def pick(replicas: Iterable[EngineReplica],
             prompt=None) -> Optional[EngineReplica]:
        """The routing policy: least-loaded LIVE replica WITH queue
        headroom, name as the deterministic tie-break.  A replica
        whose bounded admission queue is already full is not a routing
        candidate — force-feeding it would convert fleet-survivable
        backpressure into terminal ``shed(queue_full)``; when every
        replica is saturated the door holds the traffic (that is the
        queue-depth pressure the autoscaler scales out on).

        **Prefix affinity**: with a ``prompt``, candidates whose
        prefix cache already holds part of it are preferred — deepest
        hit first (the probe is a non-touching
        :meth:`~apex_tpu.serve.cache.PrefixCache.peek_tokens`, so
        routing does not mutate any replica's LRU order), then the
        same (depth, name) deterministic tie-break.  Replicas without
        a cache probe as 0, so a cacheless fleet routes exactly as
        before."""
        live = [
            r for r in replicas
            if r.state == LIVE and (
                r.sched.max_queue_depth is None
                or len(r.sched.queue) < r.sched.max_queue_depth
            )
        ]
        if not live:
            return None
        if prompt:
            best = min(
                live,
                key=lambda r: (-Router.peek_cached(r, prompt),
                               r.depth, r.name),
            )
            if Router.peek_cached(best, prompt) > 0:
                return best
        return min(live, key=lambda r: (r.depth, r.name))

    @staticmethod
    def peek_cached(rep: EngineReplica, prompt) -> int:
        """Prompt tokens ``rep``'s prefix cache would cover (0 when the
        replica runs without a cache)."""
        prefix = rep.sched.prefix
        return prefix.peek_tokens(prompt) if prefix is not None else 0

    def dispatch(self, replicas: List[EngineReplica], tick: int) -> int:
        """Route everything at the door to live replicas (one fleet
        tick).  Returns the number dispatched; 0 when the ``fleet.
        router`` chaos site faults this tick or no replica is live —
        either way the door RETAINS its requests for the next tick."""
        # chaos BEFORE the empty-door fast path: a fault scheduled at
        # this tick must fire (and be ledgered) even when there is
        # nothing to route — a drill asserting "every spec'd site
        # fired" must not depend on door occupancy at the fault tick
        if chaos.active(chaos.FLEET_ROUTER, tick) is not None:
            self._count("fleet/router_faults")
            self.faulted_ticks += 1
            return 0
        if not self.door:
            return 0
        dispatched = 0
        for _ in range(len(self.door)):
            req = self.door[0]
            target = self.pick(replicas, prompt=req.prompt)
            is_canary = (
                self._canary_name is not None
                and target is not None
                and target.name == self._canary_name
            )
            if is_canary and not self._canary_admissible():
                # the hold: re-pick from the non-canary pool; if no
                # incumbent can take it, the request WAITS at the door
                # — holding is what makes the exposure bound provable
                # (the door's depth is the autoscaler's scale-out
                # signal, and an inconclusive window expires, so a
                # canary-only fleet cannot deadlock here)
                target = self.pick(
                    [r for r in replicas
                     if r.name != self._canary_name],
                    prompt=req.prompt,
                )
                is_canary = False
            if target is None:
                break
            self.door.popleft()
            if self.peek_cached(target, req.prompt) > 0:
                self._count("fleet/prefix_affinity_hits")
            now = self.clock()
            span_args: Dict[str, Any] = {"replica": target.name}
            if self._canary_name is not None:
                self.window_routed += 1
                if is_canary:
                    self.window_canary += 1
                    self._count("fleet/canary/routed")
                    # validated annotation: legal only on a routed hop
                    # inside an open deploy window (spans.py enforces)
                    span_args["canary"] = True
            if self.spans is not None:
                # the validated `routed` phase: opened here with the
                # destination, closed by the target's own `queued`
                # event — the hop is on the timeline, replica named
                self.spans.request_event(
                    req.rid, REQ_ROUTED, now, **span_args,
                )
            self._count("fleet/routed")
            target.sched.submit(req)
            dispatched += 1
        return dispatched


def aggregate_expositions(texts: Iterable[str]) -> Dict[str, Any]:
    """Fold N per-replica OpenMetrics expositions (each replica's
    :meth:`~apex_tpu.observability.ometrics.OpsServer.scrape`) into a
    fleet view: counters SUM across replicas, gauges are kept
    per-source (summing a queue-depth gauge is meaningful, summing a
    page-size gauge is not — the caller picks its aggregation).
    Every input is parsed through the validating
    :func:`~apex_tpu.observability.ometrics.parse_exposition`, so a
    malformed replica exposition fails the aggregation loudly."""
    from apex_tpu.observability.ometrics import parse_exposition

    counters: Dict[str, float] = {}
    gauges: Dict[str, List[float]] = {}
    sources = 0
    for text in texts:
        sources += 1
        for family, fam in parse_exposition(text).items():
            value = fam.get("value")
            if value is None:
                continue
            if fam.get("type") == "counter":
                counters[family] = counters.get(family, 0.0) + float(value)
            elif fam.get("type") == "gauge":
                gauges.setdefault(family, []).append(float(value))
    return {"sources": sources, "counters": counters, "gauges": gauges}
