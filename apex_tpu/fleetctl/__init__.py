"""apex_tpu.fleetctl — the fleet control plane (docs/serving.md).

Multi-replica serving that survives replica death, preemption storms,
and rolling deploys: in-process :class:`EngineReplica`\\ s (each its
own engine/scheduler/pool/registry) behind one :class:`Router`, with
burn-rate :class:`Autoscaler` capacity control and a deterministic
:class:`Fleet` tick loop drillable on a virtual clock
(``tools/fleet_drill.py``).  Rolling deploys can be canary-gated
(``start_rolling_update(..., canary=CanaryConfig(...))``): golden-probe
fingerprints + statistical drift verdicts with auto-halt and rollback
(:mod:`apex_tpu.observability.canary`, ``tools/canary_drill.py``).
"""

from apex_tpu.observability.canary import CanaryConfig  # noqa: F401

from apex_tpu.fleetctl.autoscale import Autoscaler, AutoscalerConfig
from apex_tpu.fleetctl.fleet import Fleet, declare_fleet_metrics
from apex_tpu.fleetctl.replica import (
    DEAD,
    DRAINING,
    EJECTED,
    LIVE,
    EngineReplica,
)
from apex_tpu.fleetctl.router import Router, aggregate_expositions

__all__ = [
    "LIVE",
    "DRAINING",
    "EJECTED",
    "DEAD",
    "EngineReplica",
    "Router",
    "aggregate_expositions",
    "Autoscaler",
    "AutoscalerConfig",
    "Fleet",
    "declare_fleet_metrics",
    "CanaryConfig",
]
