"""Burn-rate-driven autoscaling for the replica fleet.

The :class:`Autoscaler` turns the PR 11 SLO machinery into a capacity
controller: on an evaluation cadence it samples the FLEET-WIDE TTFT
SLI (every live replica's ``ttft_hist`` folded into one cumulative
good/total pair — observations at or under ``ttft_threshold_ms`` are
good) into a :class:`~apex_tpu.observability.slo.BurnRateTracker`, and

- **scales OUT** on a fast burn — the short-window burn rate at or
  over ``out_factor`` means the fleet is eating its TTFT error budget
  faster than sustainable NOW — or on raw queue pressure (mean live
  depth at or over ``queue_high``: a traffic spike shows up in queue
  depth before the TTFTs it will blow are even measurable);
- **scales IN** on sustained headroom — ``headroom_evals``
  consecutive evaluations with mean depth at or under ``queue_low``
  and no burn signal, and only above ``min_replicas``;
- is **cooldown-bounded** (``cooldown_ticks`` between decisions) so a
  single storm cannot flap the fleet.

The autoscaler only DECIDES; the :class:`~apex_tpu.fleetctl.fleet.
Fleet` executes (spawn / drain-and-retire) and stamps every executed
decision as a ``fleet_scale_out`` / ``fleet_scale_in``
:class:`~apex_tpu.observability.health.HealthEvent` on the shared
span timeline — a capacity change is a health-relevant act and must
be visible next to the request chains it affected.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, List, Optional, Tuple

from apex_tpu.observability.health import HealthEvent
from apex_tpu.observability.slo import BurnRateTracker

__all__ = ["AutoscalerConfig", "Autoscaler"]

SCALE_OUT = "out"
SCALE_IN = "in"


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Scaling policy knobs (see the module docstring)."""

    min_replicas: int = 1
    max_replicas: int = 6
    #: a TTFT at or under this is a good event for the burn-rate SLI
    ttft_threshold_ms: float = 100.0
    #: the SLI objective (fraction of TTFTs under threshold)
    objective: float = 0.9
    #: burn-rate windows (seconds, on the fleet clock)
    short_window_s: float = 0.5
    long_window_s: float = 4.0
    #: short-window burn at/over this pages a scale-out
    out_factor: float = 3.0
    #: mean live-replica depth (queued+running) at/over this is spike
    #: pressure — scale out without waiting for TTFTs to complete
    queue_high: float = 8.0
    #: mean depth at/under this counts toward headroom
    queue_low: float = 1.0
    #: consecutive headroom evaluations before a scale-in
    headroom_evals: int = 3
    #: minimum fleet ticks between two executed decisions
    cooldown_ticks: int = 16
    #: evaluate every N fleet ticks
    eval_every: int = 4

    def __post_init__(self):
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}"
            )


class Autoscaler:
    """Decide ``"out"`` / ``"in"`` / ``None`` per evaluation tick."""

    def __init__(self, config: Optional[AutoscalerConfig] = None, *,
                 clock=None):
        self.config = config or AutoscalerConfig()
        c = self.config
        self.clock = clock
        self.tracker = BurnRateTracker(
            c.objective, c.long_window_s,
            min_interval_s=c.short_window_s / 8.0,
        )
        self._headroom = 0
        self._last_decision_tick: Optional[int] = None
        #: every decision this scaler made, in order (the drill's
        #: ">=1 out AND >=1 in" acceptance reads it)
        self.decisions: List[HealthEvent] = []

    # -- signals -----------------------------------------------------------
    def fleet_sli(self, replicas: Iterable) -> Tuple[float, float]:
        """Cumulative fleet ``(good, total)`` TTFT events: every live
        replica's histogram folded together.  Dead replicas drop out —
        their history must not keep diluting (or inflating) the burn
        after they stopped taking traffic."""
        good = total = 0.0
        for rep in replicas:
            hist = rep.sched.ttft_hist
            total += float(hist.count)
            good += float(hist.count_le(self.config.ttft_threshold_ms))
        return good, total

    def _in_cooldown(self, tick: int) -> bool:
        return (
            self._last_decision_tick is not None
            and tick - self._last_decision_tick < self.config.cooldown_ticks
        )

    # -- the decision ------------------------------------------------------
    def evaluate(self, live_replicas: List, tick: int) -> Optional[
        HealthEvent
    ]:
        """One evaluation: sample the SLI, judge burn + queue
        pressure, return the decision as a ``fleet_scale_out`` /
        ``fleet_scale_in`` :class:`HealthEvent` (or ``None``).  The
        tracker SAMPLES every call even in cooldown — a cooldown mutes
        the actuator, not the measurement."""
        c = self.config
        if tick % c.eval_every != 0 or not live_replicas:
            return None
        now = self.clock() if self.clock is not None else float(tick)
        good, total = self.fleet_sli(live_replicas)
        if total > 0:
            self.tracker.observe(good, total, now)
        burn = self.tracker.burn_rate(c.short_window_s, now)
        depth = (
            sum(r.depth for r in live_replicas) / len(live_replicas)
        )

        n = len(live_replicas)
        event: Optional[HealthEvent] = None
        burning = burn is not None and burn >= c.out_factor
        if burning or depth >= c.queue_high:
            self._headroom = 0
            if n < c.max_replicas and not self._in_cooldown(tick):
                value, threshold = (
                    (burn, c.out_factor) if burning
                    else (depth, c.queue_high)
                )
                event = HealthEvent(
                    "fleet_scale_out", "warn", int(tick), float(value),
                    float(threshold),
                    f"scale out {n} -> {n + 1}: "
                    + (f"TTFT burn {burn:.1f}x over "
                       f"{c.short_window_s:g}s (page factor "
                       f"{c.out_factor:g})" if burning
                       else f"mean queue depth {depth:.1f} >= "
                            f"{c.queue_high:g}"),
                )
        elif depth <= c.queue_low and (burn is None or burn < 1.0):
            self._headroom += 1
            if (
                self._headroom >= c.headroom_evals
                and n > c.min_replicas
                and not self._in_cooldown(tick)
            ):
                event = HealthEvent(
                    "fleet_scale_in", "info", int(tick), float(depth),
                    float(c.queue_low),
                    f"scale in {n} -> {n - 1}: mean depth "
                    f"{depth:.2f} <= {c.queue_low:g} for "
                    f"{self._headroom} evaluations, burn "
                    f"{'n/a' if burn is None else f'{burn:.2f}x'}",
                )
        else:
            self._headroom = 0

        if event is not None:
            self._last_decision_tick = tick
            self._headroom = 0
            self.decisions.append(event)
        return event
