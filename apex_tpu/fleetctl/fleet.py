"""The fleet control plane: N replicas, one door, failure as input.

:class:`Fleet` composes the pieces — :class:`~apex_tpu.fleetctl.
replica.EngineReplica` (engine + scheduler + own pool/registry),
:class:`~apex_tpu.fleetctl.router.Router` (least-loaded dispatch +
re-routing), :class:`~apex_tpu.fleetctl.autoscale.Autoscaler`
(burn-rate capacity control) — into one deterministic tick loop
(:meth:`Fleet.step`), drillable on a virtual clock:

1. chaos: the ``fleet.replica_crash`` / ``fleet.preempt`` sites fire
   against the tick index — a crash evacuates the victim NOW (running
   work through the shared retry budget, queue re-routed with pages
   dropped and prompts kept), a preempt notice starts a graceful
   drain (running work finishes over the grace ticks, never-admitted
   work re-routes immediately);
2. the rolling-update state machine advances (drain one replica at a
   time — never the last live one — rebuild with the new weights
   through the supervised path, re-admit);
3. the router dispatches the door (``fleet.router`` chaos can fault a
   whole tick — requests wait);
4. every live/draining replica takes one scheduler iteration; drains
   that emptied are sealed (pool re-proven empty) and dispatched on
   their reason (preempt/scale-in → dead, deploy → redeploy);
5. health: a replica whose progress counter froze for ``hung_ticks``
   with work pending is EJECTED (evacuated, re-routable later via
   :meth:`rejoin`); an optional per-replica goodput burn page ejects
   the same way;
6. the autoscaler evaluates; executed decisions spawn or drain-retire
   a replica and land as ``fleet_scale_out``/``fleet_scale_in``
   health instants on the shared span timeline.

Fleet **goodput** is accounted across churn: a request counts exactly
once fleet-wide (``completed`` on whichever replica finished it, a
terminal ``shed`` wherever it truly ended) — re-routes are ledgered
per-replica as ``shed(rerouted)`` but are NOT terminals.  See
docs/serving.md ("Fleet operations").
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from apex_tpu.observability.health import HealthEvent
from apex_tpu.observability.metrics import MetricRegistry
from apex_tpu.observability.slo import BurnRateTracker
from apex_tpu.resilience import chaos
from apex_tpu.serve.scheduler import Request

from apex_tpu.fleetctl.replica import (
    DEAD,
    DRAINING,
    EJECTED,
    LIVE,
    EngineReplica,
)
from apex_tpu.fleetctl.router import Router, aggregate_expositions

__all__ = ["declare_fleet_metrics", "Fleet"]


def declare_fleet_metrics(registry) -> None:
    """Declare the fleet ledger on a registry (idempotent)."""
    for c in ("fleet/submitted", "fleet/routed", "fleet/rerouted",
              "fleet/prefix_affinity_hits",
              "fleet/router_faults", "fleet/replica_crashes",
              "fleet/preempts", "fleet/ejections", "fleet/rejoins",
              "fleet/scale_out", "fleet/scale_in", "fleet/deploys",
              "fleet/deploys_rolled_back", "fleet/spawned",
              "fleet/canary/probes", "fleet/canary/routed",
              "fleet/canary/verdict_pass", "fleet/canary/verdict_fail"):
        registry.counter(c)
    for g in ("fleet/replicas_live", "fleet/door_depth",
              "fleet/canary/fingerprint_distance",
              "fleet/canary/detect_ticks", "fleet/canary/exposure_frac"):
        registry.gauge(g)


class Fleet:
    """N in-process replicas behind one router, one tick at a time.

    ``replica_factory(name)`` builds a fresh :class:`EngineReplica`
    (its own engine, pool, registry) wired to the SHARED fleet clock
    and span recorder — that wiring is the factory's contract; the
    fleet only names and owns the result.
    """

    def __init__(self, replica_factory: Callable[[str], EngineReplica],
                 *, replicas: int = 2, clock=time.monotonic, spans=None,
                 autoscaler=None, registry: Optional[MetricRegistry] = None,
                 hung_ticks: int = 200,
                 eject_burn_factor: Optional[float] = None,
                 eject_burn_window_s: float = 2.0,
                 eject_objective: float = 0.8):
        self.clock = clock
        self.spans = spans
        self.registry = (
            registry if registry is not None
            else MetricRegistry(fetch_every=1)
        )
        declare_fleet_metrics(self.registry)
        self._mstate = self.registry.init()
        self.router = Router(clock=clock, spans=spans, count=self._count)
        self.replica_factory = replica_factory
        self.replicas: List[EngineReplica] = []
        self._next_id = 0
        self.tick = 0
        self.autoscaler = autoscaler
        self.hung_ticks = int(hung_ticks)
        self._progress: Dict[str, tuple] = {}  # name -> (tick, counter)
        self.eject_burn_factor = eject_burn_factor
        self._eject_trackers: Dict[str, BurnRateTracker] = {}
        self._eject_burn_window_s = float(eject_burn_window_s)
        self._eject_objective = float(eject_objective)
        #: the in-progress rolling update, or None
        self.deploy: Optional[Dict[str, object]] = None
        #: canary window observer for the in-progress deploy, or None
        self._canary_ctl = None
        #: completed rolling updates, newest last
        self.deploy_history: List[Dict[str, object]] = []
        self.health_events: List[HealthEvent] = []
        for _ in range(int(replicas)):
            self._spawn()

    # -- plumbing ----------------------------------------------------------
    def _count(self, name: str, n: float = 1.0) -> None:
        self._mstate = self.registry.update(self._mstate, {name: n})

    def _gauge(self, name: str, value: float) -> None:
        self._mstate = self.registry.update(
            self._mstate, {name: float(value)}
        )

    def _note(self, event: HealthEvent) -> None:
        self.health_events.append(event)
        if self.spans is not None:
            self.spans.note_health(event)

    def _spawn(self) -> EngineReplica:
        name = f"r{self._next_id}"
        self._next_id += 1
        rep = self.replica_factory(name)
        rep.name = name
        self.replicas.append(rep)
        self._count("fleet/spawned")
        if self.deploy is not None:
            phase = self.deploy.get("phase", "rolling")
            if phase == "rolling":
                # born mid-deploy: the factory built it with the OLD
                # weights — swap in the deploy's params before it takes
                # any traffic, or the "rolling update complete" claim
                # would be false for the newest replica
                rep.redeploy(
                    self.deploy["params"],
                    self.deploy.get("draft_params"),
                )
                self.deploy["updated"].append(name)
            elif phase in ("canary_pending", "canary"):
                # born before the canary verdict: it KEEPS the
                # incumbent weights the factory built it with (the
                # exposure bound says at most the canary serves the
                # unproven weights) and queues for the rolling phase
                # so a PASS still updates it
                self.deploy["remaining"].append(name)
            # phase == "rollback": incumbent weights, and the deploy
            # is being unwound — nothing to do
        if self.eject_burn_factor is not None:
            self._eject_trackers[name] = BurnRateTracker(
                self._eject_objective, self._eject_burn_window_s,
            )
        return rep

    def replica(self, name: str) -> EngineReplica:
        for rep in self.replicas:
            if rep.name == name:
                return rep
        raise KeyError(f"no replica named {name!r}")

    @property
    def live(self) -> List[EngineReplica]:
        return [r for r in self.replicas if r.state == LIVE]

    @property
    def pending(self) -> bool:
        """Work anywhere in the fleet: at the door, on a live or
        draining replica, or a rolling update still in progress."""
        if self.door_depth:
            return True
        if self.deploy is not None:
            return True
        return any(
            r.sched.pending for r in self.replicas
            if r.state in (LIVE, DRAINING)
        )

    @property
    def door_depth(self) -> int:
        return len(self.router.door)

    # -- intake ------------------------------------------------------------
    def submit(self, req: Request) -> Request:
        return self.router.submit(req)

    # -- failure handling --------------------------------------------------
    def _evacuate_to_router(self, rep: EngineReplica, cause: str) -> int:
        moved = 0
        for req in rep.evacuate(cause):
            self.router.reroute(req)
            moved += 1
        return moved

    def crash(self, rep: EngineReplica, cause: str = "replica_crash") -> int:
        """Kill a replica NOW (the ``fleet.replica_crash`` path): its
        work evacuates through the shared retry budget and the replica
        is dead.  Returns how many requests moved to the router."""
        self._count("fleet/replica_crashes")
        moved = self._evacuate_to_router(rep, cause)
        rep.state = DEAD
        self._note(HealthEvent(
            "fleet_replica_crash", "critical", self.tick, float(moved),
            0.0,
            f"replica {rep.name} crashed ({cause}); {moved} requests "
            f"re-routed, {len(self.live)} replicas live",
        ))
        return moved

    def preempt(self, rep: EngineReplica) -> None:
        """Deliver a preempt notice (the ``fleet.preempt`` path): the
        replica drains gracefully — never-admitted work re-routes NOW,
        running work finishes over the following ticks (the grace
        period) — then leaves the fleet."""
        self._count("fleet/preempts")
        rerouted = rep.begin_drain(self.router.reroute, reason="preempt")
        self._note(HealthEvent(
            "fleet_preempt", "warn", self.tick, float(rerouted), 0.0,
            f"replica {rep.name} preempted: draining, {rerouted} "
            f"queued requests re-routed",
        ))

    def eject(self, rep: EngineReplica, cause: str) -> int:
        """Health-based ejection (burn-rate page, hung iteration):
        evacuate like a crash, but keep the replica for a possible
        :meth:`rejoin` once the operator (or a drill) clears it."""
        self._count("fleet/ejections")
        moved = self._evacuate_to_router(rep, cause)
        rep.state = EJECTED
        self._note(HealthEvent(
            "fleet_eject", "critical", self.tick, float(moved), 0.0,
            f"replica {rep.name} ejected ({cause}); {moved} requests "
            f"re-routed",
        ))
        return moved

    def rejoin(self, rep: EngineReplica) -> None:
        """Re-admit an ejected replica to the routing set."""
        if rep.state != EJECTED:
            raise RuntimeError(
                f"replica {rep.name} cannot rejoin from {rep.state!r}"
            )
        self._count("fleet/rejoins")
        rep.state = LIVE
        rep.end_cause = None
        self._progress.pop(rep.name, None)
        self._note(HealthEvent(
            "fleet_rejoin", "info", self.tick, 0.0, 0.0,
            f"replica {rep.name} rejoined the fleet",
        ))

    # -- health detection --------------------------------------------------
    def _check_hung(self, rep: EngineReplica) -> bool:
        """A live replica with pending work whose progress counter has
        not moved for ``hung_ticks`` is wedged — eject it."""
        if not rep.sched.pending:
            self._progress.pop(rep.name, None)
            return False
        seen = self._progress.get(rep.name)
        now = rep.progress
        if seen is None or seen[1] != now:
            self._progress[rep.name] = (self.tick, now)
            return False
        if self.tick - seen[0] >= self.hung_ticks:
            self.eject(rep, "hung")
            return True
        return False

    def _check_burn(self, rep: EngineReplica) -> bool:
        """Optional per-replica goodput burn page → ejection."""
        if self.eject_burn_factor is None:
            return False
        tracker = self._eject_trackers.get(rep.name)
        if tracker is None:
            return False
        good, total = rep.goodput_counts()
        if total <= 0:
            return False
        now = self.clock()
        tracker.observe(good, total, now)
        burn = tracker.burn_rate(self._eject_burn_window_s / 2.0, now)
        if burn is not None and burn >= self.eject_burn_factor:
            self.eject(rep, f"burn_rate:{burn:.1f}x")
            return True
        return False

    # -- rolling update ----------------------------------------------------
    def start_rolling_update(self, params, draft_params=None, *,
                             canary=None) -> None:
        """Begin a zero-downtime deploy of ``params``: replicas drain
        ONE AT A TIME (never the last live one — the fleet keeps
        serving throughout), rebuild through the supervised path, and
        re-admit.  Advanced by :meth:`step`; done when
        :attr:`deploy` is None again.  ``draft_params`` ships a
        refreshed speculative draft on the same deploy — every updated
        replica carries it through its redeploy (self-draft replicas
        re-alias the new target weights automatically).

        ``canary`` (a :class:`~apex_tpu.observability.canary.
        CanaryConfig`) gates the deploy: the FIRST updated replica
        becomes the canary — golden-probe fingerprinted before and
        after the weight swap (old→new distance on the board) — and
        the router holds its load share at ``canary.frac`` while a
        :class:`~apex_tpu.observability.canary.CanaryController`
        compares its windowed metric distributions against the
        incumbent pool.  The deploy proceeds to the remaining replicas
        only on a PASS verdict; a FAIL halts it, drains the canary,
        rebuilds it back to the captured incumbent weights, and bumps
        ``fleet/deploys_rolled_back`` — bad-weight exposure is bounded
        by the canary fraction, re-provable from the span dump."""
        if self.deploy is not None:
            raise RuntimeError("a rolling update is already in progress")
        self.deploy = {
            "params": params,
            "draft_params": draft_params,
            "remaining": [r.name for r in self.live],
            "current": None,
            "updated": [],
            "started_tick": self.tick,
            "draining_shed_before": self.shed_count("draining"),
            "phase": "rolling" if canary is None else "canary_pending",
        }
        if canary is not None:
            from apex_tpu.observability.canary import CanaryConfig

            if not isinstance(canary, CanaryConfig):
                raise TypeError(
                    f"canary must be a CanaryConfig, got {type(canary)}"
                )
            self.deploy["canary_cfg"] = canary
            self.deploy["canary"] = {"frac": canary.frac}
        self._canary_ctl = None

    def _advance_deploy(self) -> None:
        d = self.deploy
        if d is None:
            return
        phase = d.get("phase", "rolling")
        if phase == "canary":
            self._canary_tick()
            return
        if phase == "rollback":
            return  # the canary's rollback drain completes in the loop
        if d["current"] is not None:
            return  # the per-replica drain completes in the step loop
        while d["remaining"]:
            name = d["remaining"][0]
            rep = self.replica(name)
            if rep.state != LIVE:
                # crashed/preempted away mid-deploy: nothing to update
                d["remaining"].pop(0)
                continue
            if len(self.live) <= 1 and (
                rep.sched.pending or self.door_depth
            ):
                # zero-downtime invariant: never drain the LAST live
                # replica out from under traffic — wait for a
                # scale-out (still allowed mid-deploy) or for the
                # traffic to clear.  A lone IDLE replica with an empty
                # door swaps instantly instead: the drain seals and
                # redeploys on this same tick, before any request can
                # be routed at it.
                return
            d["remaining"].pop(0)
            d["current"] = name
            rep.begin_drain(self.router.reroute, reason="deploy")
            return
        # everything updated — seal the deploy
        d["finished_tick"] = self.tick
        d["draining_shed_after"] = self.shed_count("draining")
        d["lost_requests"] = (
            d["draining_shed_after"] - d["draining_shed_before"]
        )
        self._strip_deploy_weights(d)
        self.deploy_history.append(d)
        self.deploy = None
        self._canary_ctl = None
        self._count("fleet/deploys")
        self._note(HealthEvent(
            "fleet_deploy", "info", self.tick, float(d["lost_requests"]),
            0.0,
            f"rolling update complete: {len(d['updated'])} replicas "
            f"over ticks {d['started_tick']}..{d['finished_tick']}, "
            f"{d['lost_requests']} requests lost to draining",
        ))

    @staticmethod
    def _strip_deploy_weights(d: Dict[str, object]) -> None:
        """Drop the weight trees (and the config object) before a
        deploy record enters :attr:`deploy_history` — the history is
        part of the drill artifact and must stay JSON-sized."""
        for key in ("params", "draft_params", "incumbent_params",
                    "incumbent_draft", "canary_cfg"):
            d.pop(key, None)

    def _seal_drain(self, rep: EngineReplica) -> None:
        report = rep.finish_drain()
        reason = rep.drain_reason
        d = self.deploy
        if reason == "deploy" and d is not None and d["current"] == rep.name:
            if d.get("phase") == "canary_pending":
                self._promote_canary(rep)
            else:
                rep.redeploy(d["params"], d.get("draft_params"))
                d["updated"].append(rep.name)
                d["current"] = None
        elif reason == "canary_rollback" and d is not None:
            self._finish_rollback(rep)
        else:
            rep.state = DEAD
            rep.end_cause = reason
        assert report["pool_in_use"] == 0

    # -- canary gating -----------------------------------------------------
    def _promote_canary(self, rep: EngineReplica) -> None:
        """The drained first replica becomes the canary: capture the
        incumbent weights for a possible rollback, fingerprint the old
        and new weights across the swap (the distance is recorded, not
        judged — an intentional update SHOULD move it), open the
        router hold + deploy window, and baseline the controller."""
        from apex_tpu.observability.canary import (
            CanaryController,
            fingerprint_distance,
        )

        d = self.deploy
        cfg = d["canary_cfg"]
        # the raw incumbent params object: redeploy() assigns it back
        # verbatim (no re-quantization), so a rollback is bit-exact
        d["incumbent_params"] = rep.engine.params
        d["incumbent_draft"] = None
        if rep.engine.spec is not None and \
                rep.engine.draft_params is not rep.engine.params:
            # a real (non-self-draft) draft tree must roll back too;
            # self-draft re-aliases from the target on redeploy(None)
            d["incumbent_draft"] = rep.engine.draft_params
        summary = d["canary"]
        summary["name"] = rep.name
        if cfg.probes is not None:
            fp_old = rep.probe(cfg.probes)
            self._count("fleet/canary/probes")
        rep.redeploy(d["params"], d.get("draft_params"))
        if cfg.probes is not None:
            fp_new = rep.probe(cfg.probes)
            self._count("fleet/canary/probes")
            dist = fingerprint_distance(fp_old, fp_new)
            self._gauge(
                "fleet/canary/fingerprint_distance", dist["distance"]
            )
            summary["fingerprint"] = {
                "old_digest": fp_old["digest"],
                "new_digest": fp_new["digest"],
                "distance": dist["distance"],
                "streams_differing": dist["streams_differing"],
                "new_finite": fp_new["finite"],
            }
            self._note(HealthEvent(
                "fleet_canary_fingerprint", "info", self.tick,
                float(dist["distance"]), 0.0,
                f"canary {rep.name} fingerprint "
                f"{fp_old['digest'][:12]} -> {fp_new['digest'][:12]} "
                f"(distance {dist['distance']:.3f}, "
                f"finite={fp_new['finite']})",
            ))
        d["updated"].append(rep.name)
        d["current"] = None
        d["phase"] = "canary"
        summary["window_open_tick"] = self.tick
        self.router.set_canary(rep.name, cfg.frac)
        if self.spans is not None:
            self.spans.begin_deploy_window(
                self.clock(), canary=rep.name, frac=cfg.frac
            )
        incumbents = [r for r in self.live if r.name != rep.name]
        self._canary_ctl = CanaryController(rep, incumbents, cfg)

    def _close_canary_window(self, verdict: str) -> Dict[str, object]:
        """Tear down the hold + window and fold the routing tallies
        and token exposure into the deploy's canary summary."""
        d = self.deploy
        stats = self.router.clear_canary()
        if self.spans is not None:
            self.spans.end_deploy_window(self.clock(), verdict=verdict)
        summary = d["canary"]
        summary["verdict"] = verdict
        summary["window_close_tick"] = self.tick
        summary["routed"] = stats["routed"]
        summary["canary_routed"] = stats["canary_routed"]
        exposure = (
            stats["canary_routed"] / stats["routed"]
            if stats["routed"] else 0.0
        )
        summary["exposure_frac"] = exposure
        self._gauge("fleet/canary/exposure_frac", exposure)
        if self._canary_ctl is not None:
            tok_c, tok_total = self._canary_ctl.token_exposure()
            summary["tokens_canary"] = tok_c
            summary["tokens_total"] = tok_total
        self._canary_ctl = None
        return summary

    def _canary_tick(self) -> None:
        """One tick of the open canary window: observe, and act on the
        verdict — FAIL halts immediately (the canary drains for
        rollback), PASS is accepted only after ``soak_ticks`` (early
        quiet is not evidence), and a window that reaches
        ``max_window_ticks`` without meeting the honesty floor closes
        INCONCLUSIVE with a warning and lets the deploy proceed (an
        idle fleet must not wedge a deploy forever)."""
        d = self.deploy
        cfg = d["canary_cfg"]
        summary = d["canary"]
        rep = self.replica(summary["name"])
        win_ticks = self.tick - summary["window_open_tick"]
        if rep.state != LIVE:
            # the canary died mid-window (crash/preempt/eject): the
            # unproven weights are gone with it and nothing else has
            # them — seal the deploy as rolled back
            self._close_canary_window("fail")
            summary["canary_died"] = True
            self._count("fleet/canary/verdict_fail")
            self._note(HealthEvent(
                "fleet_canary_verdict", "critical", self.tick, 0.0, 0.0,
                f"canary {rep.name} left the fleet mid-window "
                f"({rep.state}); deploy rolled back",
            ))
            self._seal_rolled_back()
            return
        ctl = self._canary_ctl
        ctl.observe()
        verdict = ctl.verdict()
        if verdict.status == "fail":
            self._gauge("fleet/canary/detect_ticks", win_ticks)
            summary["detect_ticks"] = win_ticks
            summary["failed_checks"] = [
                {k: v for k, v in c.items()}
                for c in verdict.failed
            ]
            self._close_canary_window("fail")
            self._count("fleet/canary/verdict_fail")
            d["phase"] = "rollback"
            rep.begin_drain(self.router.reroute, reason="canary_rollback")
            self._note(HealthEvent(
                "fleet_canary_verdict", "critical", self.tick,
                float(len(verdict.failed)), 0.0,
                f"canary {rep.name} FAILED after {win_ticks} ticks "
                f"({', '.join(c['metric'] for c in verdict.failed)}); "
                f"deploy halted, rolling back",
            ))
            return
        if verdict.status == "pass" and win_ticks >= cfg.soak_ticks:
            self._gauge("fleet/canary/detect_ticks", win_ticks)
            summary["detect_ticks"] = win_ticks
            self._close_canary_window("pass")
            self._count("fleet/canary/verdict_pass")
            d["phase"] = "rolling"
            self._note(HealthEvent(
                "fleet_canary_verdict", "info", self.tick,
                float(win_ticks), 0.0,
                f"canary {rep.name} PASSED after {win_ticks} ticks "
                f"(exposure {summary['exposure_frac']:.3f} <= "
                f"{cfg.frac}); deploy proceeding",
            ))
            return
        if win_ticks >= cfg.max_window_ticks:
            self._close_canary_window("inconclusive")
            d["phase"] = "rolling"
            self._note(HealthEvent(
                "fleet_canary_inconclusive", "warn", self.tick,
                float(win_ticks), float(cfg.max_window_ticks),
                f"canary {rep.name} window expired below the "
                f"min-sample floor after {win_ticks} ticks; deploy "
                f"proceeding UNPROVEN",
            ))

    def _seal_rolled_back(self) -> None:
        d = self.deploy
        d["finished_tick"] = self.tick
        d["draining_shed_after"] = self.shed_count("draining")
        d["lost_requests"] = (
            d["draining_shed_after"] - d["draining_shed_before"]
        )
        d["rolled_back"] = True
        self._strip_deploy_weights(d)
        self.deploy_history.append(d)
        self.deploy = None
        self._canary_ctl = None
        self._count("fleet/deploys_rolled_back")
        self._note(HealthEvent(
            "fleet_deploy_rollback", "critical", self.tick,
            float(d["lost_requests"]), 0.0,
            f"deploy rolled back at tick {self.tick}: canary "
            f"{d['canary'].get('name')} verdict "
            f"{d['canary'].get('verdict')}, "
            f"{d['lost_requests']} requests lost",
        ))

    def _finish_rollback(self, rep: EngineReplica) -> None:
        """The failed canary's drain sealed: rebuild it back onto the
        captured incumbent weights (bit-exact — the raw params object
        is reassigned, never re-derived) and seal the deploy as rolled
        back."""
        d = self.deploy
        rep.redeploy(d["incumbent_params"], d.get("incumbent_draft"))
        cfg = d.get("canary_cfg")
        if cfg is not None and cfg.probes is not None:
            fp = rep.probe(cfg.probes)
            self._count("fleet/canary/probes")
            d["canary"]["rollback_digest"] = fp["digest"]
        self._seal_rolled_back()

    # -- scaling -----------------------------------------------------------
    def _scale_out(self, event: HealthEvent) -> EngineReplica:
        self._count("fleet/scale_out")
        rep = self._spawn()
        self._note(event)
        return rep

    def _scale_in(self, event: HealthEvent) -> Optional[EngineReplica]:
        candidates = self.live
        if len(candidates) <= 1:
            return None
        # retire the least-loaded live replica (fewest requests to
        # migrate), name as the deterministic tie-break
        victim = min(candidates, key=lambda r: (r.depth, r.name))
        self._count("fleet/scale_in")
        victim.begin_drain(self.router.reroute, reason="scale_in")
        self._note(event)
        return victim

    # -- the tick ----------------------------------------------------------
    def step(self) -> None:
        """One fleet tick (see the module docstring for the order)."""
        tick = self.tick
        # 1. chaos: crash / preempt against the tick index.  Victims
        # are deterministic: the first live replica (crash) and the
        # last (preempt) — distinct under storm specs that fire both.
        live = self.live
        if live and chaos.active(chaos.FLEET_REPLICA_CRASH, tick):
            self.crash(live[0])
        live = self.live
        if live and chaos.active(chaos.FLEET_PREEMPT, tick):
            self.preempt(live[-1])
        # 2. rolling update state machine
        self._advance_deploy()
        # 3. route the door
        self.router.dispatch(self.replicas, tick)
        # 4. one scheduler iteration per active replica; seal finished
        # drains
        for rep in list(self.replicas):
            if rep.state not in (LIVE, DRAINING):
                continue
            if rep.sched.pending:
                rep.step()
            if rep.state == DRAINING and not rep.sched.pending:
                self._seal_drain(rep)
        # 5. health: hung / burning replicas are ejected
        for rep in self.live:
            if not self._check_hung(rep):
                self._check_burn(rep)
        # 6. autoscale.  Scale-OUT stays armed during a rolling update
        # (a deploy under pressure needs MORE capacity — and the
        # zero-downtime guard in _advance_deploy may be waiting on
        # exactly that); scale-in is suppressed until the deploy
        # seals, so capacity only ratchets up mid-deploy.
        if self.autoscaler is not None:
            if not self.live and self.door_depth:
                # total outage with traffic at the door: the burn-rate
                # SLI has no live replica to sample, so the normal
                # evaluation path can never fire — bootstrap capacity
                # directly (one replica per tick until one is live)
                self._scale_out(HealthEvent(
                    "fleet_scale_out", "critical", tick,
                    float(self.door_depth), 0.0,
                    f"no live replicas with {self.door_depth} requests "
                    f"at the door — emergency scale-out",
                ))
            else:
                event = self.autoscaler.evaluate(self.live, tick)
                if event is not None:
                    if event.rule == "fleet_scale_out":
                        self._scale_out(event)
                    elif self.deploy is None:
                        self._scale_in(event)
        self._gauge("fleet/replicas_live", len(self.live))
        self._gauge("fleet/door_depth", self.door_depth)
        self.registry.observe(tick, self._mstate)
        self.tick += 1

    # -- accounting --------------------------------------------------------
    def shed_count(self, reason: Optional[str] = None) -> int:
        """Terminal sheds across EVERY replica ever in the fleet
        (dead ones keep their ledger), optionally for one reason."""
        n = 0
        for rep in self.replicas:
            for req in rep.sched.shed:
                if reason is None or req.shed_reason == reason:
                    n += 1
        return n

    def completed_count(self) -> int:
        return sum(len(rep.sched.completed) for rep in self.replicas)

    def goodput(self) -> Dict[str, object]:
        """Fleet goodput across churn: every request exactly one
        fleet-wide terminal, re-routes excluded (they are hops, not
        outcomes)."""
        completed = self.completed_count()
        shed = self.shed_count()
        in_flight = self.door_depth + sum(
            r.depth for r in self.replicas if r.state in (LIVE, DRAINING)
        )
        submitted = completed + shed + in_flight
        return {
            "completed": completed,
            "shed_terminal": shed,
            "in_flight": in_flight,
            "accounted": submitted,
            "goodput": completed / submitted if submitted else None,
        }

    def leak_check(self) -> Dict[str, int]:
        """Re-prove every replica's page accounting (live, draining,
        ejected AND dead — an evacuated pool must be exactly empty)."""
        in_use = {}
        for rep in self.replicas:
            rep.sched.leak_check()
            in_use[rep.name] = rep.sched.pool.in_use
        return in_use

    def aggregate_values(self) -> Dict[str, float]:
        """Fleet-wide counter view: every replica registry fetched and
        its ``serve/*`` counters summed — the value source for
        :func:`~apex_tpu.observability.slo.fleet_slo_rules`."""
        out: Dict[str, float] = {}
        for rep in self.replicas:
            reg = rep.registry
            if reg is None:
                continue
            reg.fetch()
            for key, value in reg.values().items():
                if key.startswith("serve/") and reg.kind(key) == "counter":
                    out[key] = out.get(key, 0.0) + float(value)
        return out

    def spec_acceptance(self) -> Dict[str, float]:
        """Fleet-wide speculative-decoding acceptance: the router-side
        fold over every replica's draft/accept counters.  A per-replica
        rate can look fine while one stale-draft replica drags the
        fleet — this is the number a deploy decision should read."""
        vals = self.aggregate_values()
        drafted = vals.get("serve/spec_drafted", 0.0)
        accepted = vals.get("serve/spec_accepted", 0.0)
        return {
            "drafted": drafted,
            "accepted": accepted,
            "rate": accepted / drafted if drafted else 0.0,
        }

    def aggregate_scrapes(self) -> Dict[str, object]:
        """The router-side scrape fold: every replica with a running
        :class:`~apex_tpu.observability.ometrics.OpsServer` is scraped
        in-process and the expositions aggregate (counters sum)."""
        texts = [
            rep.ops.scrape() for rep in self.replicas
            if rep.ops is not None
        ]
        return aggregate_expositions(texts)

    def summary(self) -> Dict[str, object]:
        """The drill/ops snapshot."""
        return {
            "tick": self.tick,
            "replicas": [
                {
                    "name": r.name,
                    "state": r.state,
                    "end_cause": r.end_cause,
                    "completed": len(r.sched.completed),
                    "shed": len(r.sched.shed),
                    "pool_in_use": r.sched.pool.in_use,
                    "rebuilds": r.engine.rebuilds,
                }
                for r in self.replicas
            ],
            "door_depth": self.door_depth,
            "goodput": self.goodput(),
            "deploys": list(self.deploy_history),
            "autoscaler_decisions": (
                [e.rule for e in self.autoscaler.decisions]
                if self.autoscaler is not None else []
            ),
            "health_events": [e.rule for e in self.health_events],
        }
