"""One in-process serving replica — engine + scheduler + namespace.

An :class:`EngineReplica` is the fleet control plane's unit of
scheduling: a full engine/scheduler pair with its OWN
:class:`~apex_tpu.serve.cache.PagePool`,
:class:`~apex_tpu.observability.metrics.MetricRegistry`, and (optional)
:class:`~apex_tpu.observability.ometrics.OpsServer` — sharing only the
fleet's clock and :class:`~apex_tpu.observability.spans.SpanRecorder`
(request ids are globally unique, so every replica's request chains
merge onto one timeline).  Pages are replica-local by construction:
a request that leaves a replica (drain handoff, crash evacuation,
preemption) drops its pages and generated prefix and re-prefills on
its destination — what it KEEPS is its prompt, its original
``submitted_at`` (end-to-end TTFT honesty), and its shared retry
budget (``Request.retries`` travels with the object, so a request
that faults on replica A and again on replica B burns ONE
``max_retries`` budget, not one per replica).

Lifecycle::

    live ──(begin_drain)──▶ draining ──(finish_drain)──▶ dead
      │                                   └─(redeploy)──▶ live
      ├──(crash/evacuate)──▶ dead
      └──(eject/evacuate)──▶ ejected ──(rejoin)──▶ live

See docs/serving.md ("Fleet operations").
"""

from __future__ import annotations

from typing import Dict, List, Optional

from apex_tpu.serve.scheduler import (
    ContinuousBatchingScheduler,
    Request,
)

__all__ = [
    "LIVE",
    "DRAINING",
    "EJECTED",
    "DEAD",
    "EngineReplica",
]

LIVE = "live"
DRAINING = "draining"
#: evacuated for a health page (burn rate, hung iteration) — the
#: engine survives and the replica can :meth:`~EngineReplica.rejoin`
EJECTED = "ejected"
#: crashed, preempted away, scaled in, or retired — terminal
DEAD = "dead"


class EngineReplica:
    """A named scheduler/engine pair under fleet control.

    ``sched_kwargs`` pass through to the scheduler (queue bounds,
    retry budget, clamp knobs) — the fleet's retry semantics REQUIRE a
    uniform ``max_retries`` across replicas (a re-routed request's
    consumed budget must mean the same thing wherever it lands).
    """

    def __init__(self, name: str, engine, *, clock, spans=None,
                 registry=None, **sched_kwargs):
        self.name = str(name)
        self.engine = engine
        self.registry = registry if registry is not None else engine.registry
        self.sched = ContinuousBatchingScheduler(
            engine, registry=self.registry, clock=clock, spans=spans,
            **sched_kwargs,
        )
        self.state = LIVE
        #: why the current/last drain ran: "preempt" | "scale_in" |
        #: "deploy" (the fleet dispatches on it at finish_drain)
        self.drain_reason: Optional[str] = None
        #: why the replica ended (crash cause, eject cause, ...)
        self.end_cause: Optional[str] = None
        self.drain_reports: List[Dict[str, object]] = []
        #: golden-probe fingerprints taken on this replica, in order
        #: (:meth:`probe` appends) — the canary gate's identity ledger
        self.fingerprints: List[Dict[str, object]] = []
        self.ops = None

    def __repr__(self) -> str:
        return (
            f"EngineReplica({self.name!r}, state={self.state!r}, "
            f"depth={self.depth})"
        )

    # -- serving -----------------------------------------------------------
    @property
    def pending(self) -> bool:
        return self.sched.pending

    @property
    def depth(self) -> int:
        """Routing load signal: queued + running requests."""
        return len(self.sched.queue) + len(self.sched.running)

    @property
    def progress(self) -> int:
        """A counter that moves iff the replica is doing work — the
        fleet's hung-iteration detector watches it."""
        s = self.sched
        return s._tokens_out + len(s.completed) + len(s.shed)

    def step(self) -> None:
        self.sched.step()

    # -- ops export --------------------------------------------------------
    def start_ops(self, **kwargs):
        """An ephemeral-port :class:`~apex_tpu.observability.ometrics.
        OpsServer` namespaced by replica name: N replicas in one
        process each get their own ``/metrics`` on an OS-assigned port
        (``server.port`` after start) with no board-key collisions."""
        from apex_tpu.observability.ometrics import OpsServer

        registries = [self.registry] if self.registry is not None else []
        collect = self.registry.fetch if self.registry is not None else None
        kwargs.setdefault("collect", collect)
        self.ops = OpsServer(
            registries=registries, histograms=[self.sched.ttft_hist],
            name=self.name, port=0, **kwargs,
        ).start()
        return self.ops

    def stop_ops(self) -> None:
        if self.ops is not None:
            self.ops.stop()
            self.ops = None

    # -- drain (preempt / scale-in / rolling deploy) -----------------------
    def begin_drain(self, handoff, *, reason: str) -> int:
        """Enter the draining state: never-admitted work re-routes
        through ``handoff``, running + retrying work finishes HERE
        over the following fleet ticks (the preemption grace period).
        The fleet keeps stepping this replica until ``pending``
        clears, then calls :meth:`finish_drain`."""
        if self.state != LIVE:
            raise RuntimeError(
                f"replica {self.name} cannot drain from {self.state!r}"
            )
        self.state = DRAINING
        self.drain_reason = reason
        return self.sched.start_drain(handoff=handoff)

    def finish_drain(self) -> Dict[str, object]:
        """Seal the drain (pool re-proven empty) and report.  The
        caller decides what the replica becomes next (dead for a
        preemption/scale-in, :meth:`redeploy` for a rolling update)."""
        report = self.sched.finish_drain()
        report["replica"] = self.name
        report["reason"] = self.drain_reason
        self.drain_reports.append(report)
        return report

    def redeploy(self, params, draft_params=None) -> None:
        """Swap in new weights and return to service (the rolling
        update's per-replica step): the engine rebuilds through the
        SAME supervised path a fault recovery uses — ``full=True``
        recompiles the decode program now (re-verified when the
        engine was built with ``verify=True``) and drops every prefill
        bucket for lazy re-AOT on next use — then admissions resume.
        A speculative engine's draft weights ride the same deploy:
        ``draft_params`` swaps them explicitly; otherwise a self-draft
        engine re-aliases the NEW target params (a draft frozen on old
        weights would silently bleed acceptance every round)."""
        if self.sched.pending:
            raise RuntimeError(
                f"replica {self.name} redeployed with work in flight"
            )
        if self.sched.prefix is not None:
            # cached prefix runs hold old-weight K/V — garbage under
            # the new weights, and they pin pages the cache reset
            # below requires free
            self.sched.prefix.flush()
        # the drained pool is empty, so re-zero the KV arrays: stale
        # K/V written by the OUTGOING weights must not leak into the
        # new tenancy through recycled pages (a NaN-poisoned row
        # survives the attention mask — 0 * NaN — and would break the
        # canary rollback's bit-exact fingerprint)
        self.engine.reset_cache()
        self.engine.params = params
        if self.engine.spec is not None:
            self.engine.update_draft_params(draft_params)
        self.engine.rebuild(full=True)
        self.sched.resume()
        self.state = LIVE
        self.drain_reason = None

    def probe(self, probes) -> Dict[str, object]:
        """Golden-probe fingerprint of the CURRENT weights
        (:func:`apex_tpu.observability.canary.model_fingerprint`),
        appended to :attr:`fingerprints`.  Callers probe quiet
        replicas — freshly built, drained, or just-redeployed — where
        the pool has room for the probe's transient pages; the
        canary-gated deploy probes at exactly those moments."""
        from apex_tpu.observability.canary import model_fingerprint

        fp = model_fingerprint(self.engine, probes)
        self.fingerprints.append(fp)
        return fp

    # -- evacuation (crash / ejection) -------------------------------------
    def evacuate(self, cause: str) -> List[Request]:
        """Empty the replica NOW (a crash or health ejection — no
        grace period): every running request moves through the
        ``retrying`` phase (charging the SHARED retry budget — one
        that already burned it sheds ``retries_exhausted`` here,
        terminally), then the whole queue is offered out with pages
        dropped and prompts retained.  Returns the survivors for the
        router to re-route; the pool is left provably empty."""
        sched = self.sched
        out: List[Request] = []

        def accept(req: Request) -> bool:
            out.append(req)
            return True

        for i, req in enumerate(sched.slots):
            if req is None:
                continue
            sched.slots[i] = None
            sched._send_to_retry(req, cause)
        while sched.queue:
            req = sched.queue.popleft()
            sched._reroute_request(req, accept)
        if sched.prefix is not None:
            # cached runs are replica-local history: release the
            # cache's own references so the pool-empty proof below
            # covers the cache too (borrowed copies were already
            # dropped by the retry/re-route frees above)
            sched.prefix.flush()
        sched.leak_check()
        assert sched.pool.in_use == 0, (
            f"replica {self.name} evacuated with pages in use"
        )
        # the replica will never step again — publish NOW or the
        # retry/reroute counters this evacuation just wrote stay
        # unmaterialized on device state and vanish from every
        # fleet-level aggregation (the dead replica's ledger is part
        # of the fleet's goodput truth)
        sched._publish()
        self.end_cause = cause
        return out

    # -- health ------------------------------------------------------------
    def goodput_counts(self):
        """Cumulative ``(good, total)`` for the per-replica burn-rate
        tracker: completed vs terminally-resolved (``sched.shed`` holds
        only TERMINAL sheds — re-routed requests are not failures, they
        are still in flight elsewhere)."""
        done = len(self.sched.completed)
        return float(done), float(done + len(self.sched.shed))
