"""Input pipeline — memory-mapped token datasets, a sharded shuffling
loader with native batch assembly, and a background device prefetcher.

≙ the host-side input machinery the reference delegates to its examples
and to DALI: ``examples/imagenet/main_amp.py :: data_prefetcher`` (CUDA
side-stream prefetch overlapping H2D copies with compute) and the
fixed-format record readers its MLPerf BERT recipes use.  On TPU the
device side of a training job belongs to XLA; keeping the chip fed is
ordinary host engineering, so the hot loops here are native C++
(`apex_tpu._native`: threaded row gather, threaded MLM corruption) with
numpy fallbacks, and the host→device overlap uses a background thread
issuing ``jax.device_put`` ahead of consumption (the TPU analog of the
prefetcher's side stream).

Layout contract: a *token file* is a flat binary array of token ids
(any integer dtype); samples are consecutive ``seq_len`` windows.  This
is the standard packed-corpus format (GPT-style); record-structured data
can be expressed as ``seq_len`` = record length.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from apex_tpu import _native
from apex_tpu.observability.locks import TrackedLock

__all__ = [
    "TokenFileDataset",
    "DataLoader",
    "DevicePrefetcher",
    "write_token_file",
    "synthetic_token_corpus",
    "bert_mlm_batches",
    "pack_mlm_predictions",
]


def write_token_file(path, tokens: np.ndarray) -> None:
    """Write a flat token array as a raw binary token file."""
    np.ascontiguousarray(tokens).ravel().tofile(os.fspath(path))


def synthetic_token_corpus(
    path,
    *,
    vocab_size: int,
    num_tokens: int = 1_000_000,
    floor: int = 0,
    zipf_a: float = 1.3,
    seed: int = 0,
) -> str:
    """Write (once, atomically) a zipf-distributed synthetic token corpus.

    Cached by existence at ``path``; the write goes to a pid-suffixed
    temp name then ``os.replace``s into place, so an interrupted or
    concurrent first run can never leave a truncated file behind.  Token
    ids land in ``[floor, vocab_size)``.  Used by the examples when no
    ``--data`` file is given.
    """
    if vocab_size > 2**16:
        raise ValueError(
            f"vocab_size {vocab_size} exceeds the uint16 token format "
            "(ids would silently truncate); use a wider-dtype corpus"
        )
    path = os.fspath(path)
    meta_path = f"{path}.meta.json"
    meta = {
        "vocab_size": vocab_size, "num_tokens": num_tokens,
        "floor": floor, "zipf_a": zipf_a, "seed": seed,
    }
    # The cache key is the full generation-parameter set, recorded in a
    # sidecar (so explicit caller-chosen paths keep working).  A corpus
    # file WITHOUT a sidecar (legacy cache, or a token file the user put
    # at the cache path themselves) is reused as-is — the pre-sidecar
    # contract; only a sidecar that parses and disagrees triggers
    # regeneration.
    if os.path.exists(path):
        try:
            with open(meta_path) as f:
                recorded = json.load(f)
        except (OSError, ValueError):
            return path
        if recorded == meta:
            return path
    rng = np.random.default_rng(seed)
    toks = floor + (rng.zipf(zipf_a, size=num_tokens) % (vocab_size - floor))
    tmp = f"{path}.{os.getpid()}.tmp"
    write_token_file(tmp, toks.astype(np.uint16))
    meta_tmp = f"{meta_path}.{os.getpid()}.tmp"
    with open(meta_tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, path)
    os.replace(meta_tmp, meta_path)
    return path


class TokenFileDataset:
    """Memory-mapped view of a packed token file as fixed-length samples.

    ``stride`` defaults to ``seq_len`` (disjoint windows); a smaller
    stride yields overlapping windows (data augmentation for small
    corpora).  The file is never read eagerly — samples are assembled by
    the loader's native gather straight out of the page cache.
    """

    def __init__(self, path, seq_len: int, dtype=np.uint16,
                 stride: Optional[int] = None):
        if seq_len < 1:
            raise ValueError(f"seq_len must be >= 1, got {seq_len}")
        self.path = os.fspath(path)
        self.seq_len = int(seq_len)
        self.stride = self.seq_len if stride is None else int(stride)
        if self.stride < 1:
            raise ValueError(f"stride must be >= 1, got {stride}")
        self.tokens = np.memmap(self.path, dtype=dtype, mode="r")
        if self.tokens.size < self.seq_len:
            raise ValueError(
                f"{self.path}: {self.tokens.size} tokens < seq_len {seq_len}"
            )
        self.num_samples = (self.tokens.size - self.seq_len) // self.stride + 1

    def __len__(self) -> int:
        return self.num_samples

    def __getitem__(self, i: int) -> np.ndarray:
        if not 0 <= i < self.num_samples:
            raise IndexError(i)
        s = i * self.stride
        return np.asarray(self.tokens[s : s + self.seq_len])

    def sample_starts(self, indices: np.ndarray) -> np.ndarray:
        return np.asarray(indices, np.int64) * self.stride


class DataLoader:
    """Sharded, shuffled, epoch-based batch loader with native assembly.

    - ``shard=(rank, world)``: each rank sees a disjoint 1/world of every
      epoch's shuffled order (the dp/host sharding contract; ≙ torch
      DistributedSampler semantics the reference's examples rely on).
    - Shuffle order is ``seed``- and epoch-deterministic across ranks, so
      all ranks agree on the global permutation and slice it.
    - ``drop_last=True`` keeps batch shapes static — the XLA requirement;
      a partial trailing batch would trigger recompilation.
    - Batches are gathered by the threaded native memcpy
      (``_native.gather_rows``) into one contiguous ``(B, S)`` array.
    """

    def __init__(
        self,
        dataset: TokenFileDataset,
        batch_size: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        shard: Tuple[int, int] = (0, 1),
        drop_last: bool = True,
    ):
        rank, world = shard
        if not 0 <= rank < world:
            raise ValueError(f"shard rank {rank} not in [0, {world})")
        if not drop_last:
            raise NotImplementedError(
                "drop_last=False would produce a ragged final batch; XLA "
                "needs static shapes (pad at the dataset level instead)"
            )
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = int(seed)
        self.rank, self.world = rank, world
        per_rank = len(dataset) // world
        self.batches_per_epoch = per_rank // self.batch_size
        if self.batches_per_epoch < 1:
            raise ValueError(
                f"dataset ({len(dataset)} samples / world {world}) too "
                f"small for batch_size {batch_size}"
            )

    def epoch(self, epoch: int, start: int = 0) -> Iterator[np.ndarray]:
        """Yield this rank's ``(B, S)`` batches for one epoch, starting at
        in-epoch batch index ``start`` (an index-level seek: skipped
        batches are never gathered)."""
        n = len(self.dataset)
        if self.shuffle:
            order = np.random.default_rng(
                np.random.SeedSequence([self.seed, epoch])
            ).permutation(n)
        else:
            order = np.arange(n)
        mine = order[self.rank :: self.world]
        for b in range(start, self.batches_per_epoch):
            idx = mine[b * self.batch_size : (b + 1) * self.batch_size]
            starts = self.dataset.sample_starts(idx)
            yield _native.gather_rows(
                self.dataset.tokens, starts, self.dataset.seq_len
            )

    def iter_from(self, start_batch: int = 0) -> Iterator[np.ndarray]:
        """Endless epoch stream seeked to global batch ``start_batch`` —
        O(1) resume positioning (shuffle orders are (seed, epoch)-pure),
        vs. generating and discarding ``start_batch`` batches."""
        e, b = divmod(start_batch, self.batches_per_epoch)
        while True:
            yield from self.epoch(e, start=b)
            e, b = e + 1, 0

    def __iter__(self) -> Iterator[np.ndarray]:
        """Endless stream over epochs 0, 1, 2, ... (reshuffled each)."""
        return self.iter_from(0)


class DevicePrefetcher:
    """Background host→device prefetch (≙ ``data_prefetcher``'s CUDA
    side-stream overlap in the reference's ImageNet example).

    Wraps any iterator of (pytrees of) numpy arrays; a worker thread
    stays ``depth`` batches ahead, issuing ``jax.device_put`` (optionally
    with a ``device``/``Sharding``) so the transfer overlaps the step
    running on-device.  Iterate it like the original loader; call
    ``close()`` (or use as context manager) to stop the worker.

    Backpressure is bounded by construction — the hand-off queue holds
    at most ``depth`` batches, so a consumer that stops pulling stalls
    the worker instead of buffering the dataset into RAM — and both
    sides of the balance are measured: :attr:`stall_fraction` is the
    share of wall time the CONSUMER spent blocked on an empty queue
    (the input-bound signal, published to the board as
    ``data/input_stall_fraction`` for
    :class:`~apex_tpu.observability.health.InputStallRule` and for
    cross-checking the attribution layer's host-stall bucket), and
    :meth:`metrics` adds the producer-side wait plus queue occupancy.

    The board gauge is a SINGLE key: it belongs to the training input
    pipeline.  A second prefetcher in the same process (an eval
    loader, a side pipeline) would clobber it and misdirect
    ``InputStallRule`` — give it ``board_key=None`` (metrics stay
    available via :meth:`metrics`) or its own key.
    """

    _DONE = object()

    def __init__(self, it, device=None, depth: int = 2, *,
                 board_key: "str | None" = "data/input_stall_fraction"):
        import jax

        self._jax = jax
        self._device = device
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._src = iter(it)
        self._board_key = board_key
        self._t0 = time.monotonic()
        self._consumer_wait_s = 0.0  # queue empty: input-bound
        self._producer_wait_s = 0.0  # queue full: compute-bound (healthy)
        self._batches = 0
        self._occupancy_sum = 0.0
        # _producer_wait_s is the one field both sides touch: the
        # worker accumulates it, metrics() reads it from the consumer
        self._lock = TrackedLock("data.prefetch")
        self._worker = threading.Thread(target=self._fill, daemon=True)
        self._worker.start()

    def _put(self, item) -> bool:
        """Enqueue with stop-aware timeout polling; False when stopped
        (an unbounded blocking put could pin the worker forever if the
        consumer abandons iteration without close())."""
        t0 = time.monotonic()
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                with self._lock:
                    self._producer_wait_s += time.monotonic() - t0
                return True
            except queue.Full:
                continue
        return False

    def _fill(self):
        try:
            for batch in self._src:
                if self._stop.is_set():
                    return
                if self._device is not None:
                    batch = self._jax.device_put(batch, self._device)
                else:
                    batch = self._jax.device_put(batch)
                if not self._put(batch):
                    return
        except BaseException as e:  # surface worker errors to the consumer
            self._put(e)
            return
        self._put(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        # Stop-aware polling get, mirroring _put: an untimed get could hang
        # forever if close() (from another thread) drains the sentinel out
        # from under us.
        t0 = time.monotonic()
        self._occupancy_sum += self._q.qsize() / self._q.maxsize
        while True:
            if self._stop.is_set():
                raise StopIteration
            try:
                item = self._q.get(timeout=0.1)
                break
            except queue.Empty:
                continue
        if self._batches == 0:
            # the first fetch waits on worker spin-up + first fill — a
            # cold mmap/parse of the source is pipeline warm-up, not a
            # steady-state stall, and folding it in would keep the
            # fraction inflated (and InputStallRule paging) long into a
            # healthy run.  Start the stall clock at the first hand-off.
            self._t0 = time.monotonic()
        else:
            self._consumer_wait_s += time.monotonic() - t0
        if item is self._DONE:
            # terminal: the worker exits after one sentinel — record the
            # state so further next() calls don't block on an empty queue
            self._stop.set()
            raise StopIteration
        if isinstance(item, BaseException):
            self._stop.set()
            raise item
        self._batches += 1
        # publish only once the fraction means something: the first
        # batch's worker spin-up over a near-zero wall time would read
        # as a storm and page InputStallRule on every cold start
        if self._board_key is not None and self._batches >= 8:
            from apex_tpu.observability.metrics import board

            board.set(self._board_key, self.stall_fraction)
        return item

    @property
    def stall_fraction(self) -> float:
        """Share of wall time the consumer spent blocked on an empty
        prefetch queue — the "chip starved for input" fraction the
        attribution layer's host-stall bucket should roughly agree
        with."""
        wall = time.monotonic() - self._t0
        return min(1.0, self._consumer_wait_s / wall) if wall > 0 else 0.0

    def metrics(self) -> dict:
        """The pipeline-balance ledger: consumer stall (input-bound),
        producer wait (compute-bound backpressure — healthy), mean
        queue occupancy at fetch, batches served."""
        with self._lock:
            producer_wait_s = self._producer_wait_s
        return {
            "batches": self._batches,
            "stall_fraction": self.stall_fraction,
            "consumer_wait_s": self._consumer_wait_s,
            "producer_wait_s": producer_wait_s,
            "mean_occupancy": (
                self._occupancy_sum / self._batches if self._batches else 0.0
            ),
            "depth": self._q.maxsize,
        }

    def close(self):
        self._stop.set()
        # drain so a blocked put() can observe the stop flag
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._worker.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def pack_mlm_predictions(labels, max_predictions_per_seq=20, seq_first=True,
                         rng=None):
    """Dense MLM labels (S, B; -1 = unmasked) → the reference recipe's
    fixed-K prediction triple: ``(positions, label_ids, weights)``, each
    (K, B) with K = ``max_predictions_per_seq`` (≙ the BERT pretraining
    input tensors masked_lm_positions / masked_lm_ids / masked_lm_weights).

    Sequences with more than K masked positions are truncated; sequences
    with fewer are zero-padded with weight 0.  ``rng`` (a
    ``np.random.Generator``) selects the kept K uniformly at random from
    the masked set — the reference data pipeline's behavior, which keeps
    every position's selection probability uniform.  ``rng=None`` keeps
    the first K in position order (deterministic, but over-budget
    sequences then never train their latest masked positions — fine for
    benchmarks, biased for real training).  ``bert_pretrain_loss``
    consumes the triple to run the MLM head on K rows instead of all S.
    """
    labels = np.asarray(labels)
    if not seq_first:
        labels = labels.T
    k = max_predictions_per_seq
    mask = labels >= 0
    if rng is None or mask.sum(axis=0).max(initial=0) <= k:
        # stable argsort of ~mask floats masked row-indices to the front,
        # in position order; the first K per column are kept.  Also the
        # fast path when no column exceeds K: random selection would keep
        # the whole masked set anyway (pads are zeroed either way).
        order = np.argsort(~mask, axis=0, kind="stable")[:k]
    else:
        # random sort key among masked rows → uniform K-subset; then
        # reorder the selection so real rows come first in position
        # order and pad rows (unmasked, selected only under budget)
        # sit at the end — the reference layout
        key = np.where(mask, rng.random(mask.shape), 2.0)
        sel = np.argsort(key, axis=0)[:k]
        selmask = np.take_along_axis(mask, sel, axis=0)
        rank = np.where(selmask, sel, labels.shape[0] + sel)
        order = np.take_along_axis(
            sel, np.argsort(rank, axis=0, kind="stable"), axis=0
        )
    weights = np.take_along_axis(mask, order, axis=0)
    if order.shape[0] < k:  # K > S: zero-pad to keep the (K, B) contract
        pad = np.zeros((k - order.shape[0], order.shape[1]), order.dtype)
        order = np.concatenate([order, pad], axis=0)
        weights = np.concatenate(
            [weights, pad.astype(bool)], axis=0
        )
    ids = np.where(weights, np.take_along_axis(labels, order, axis=0), 0)
    positions = np.where(weights, order, 0)
    return (
        positions.astype(np.int32),
        ids.astype(np.int32),
        weights.astype(np.float32),
    )


def bert_mlm_batches(
    loader: DataLoader,
    *,
    seed: int = 0,
    mask_prob: float = 0.15,
    mask_id: int = 103,
    vocab_size: int = 30522,
    special_floor: int = 1000,
    seq_first: bool = True,
    start_step: int = 0,
    max_predictions_per_seq: "int | None" = None,
):
    """Endless BERT phase-1 batches from a token loader.

    Applies the native 80/10/10 MLM corruption (`_native.mlm_mask_batch`,
    deterministic in (seed, step, position)) and emits the batch dict
    ``bert_pretrain_loss`` consumes, seq-first by default.

    ``start_step`` seeks the stream for resume: the loader is positioned
    at that batch index (O(1), nothing gathered for skipped batches) and
    the corruption seed counter starts there, so batch N of a resumed
    stream is bit-identical to batch N of an uninterrupted one.

    ``max_predictions_per_seq``: when set, each batch also carries the
    fixed-K ``mlm_positions``/``mlm_label_ids``/``mlm_weights`` triple
    (:func:`pack_mlm_predictions` — the reference recipe's input format),
    which ``bert_pretrain_loss`` prefers over the dense labels.
    """
    step = start_step
    src = (
        loader.iter_from(start_step)
        if hasattr(loader, "iter_from")
        else iter(loader)
    )
    for tokens in src:
        ids = tokens.astype(np.int32)
        # Full-64-bit (seed, step) mix: golden-ratio affine map is injective
        # in step for a fixed seed and spreads seeds across the whole state
        # space (a shifted-XOR scheme would alias once step exceeded the
        # shift width).
        mix = (seed * 0x9E3779B97F4A7C15 + step) & 0xFFFFFFFFFFFFFFFF
        masked, labels = _native.mlm_mask_batch(
            ids,
            mix,
            mask_prob=mask_prob,
            mask_id=mask_id,
            vocab_size=vocab_size,
            special_floor=special_floor,
        )
        if seq_first:
            masked, labels = masked.T, labels.T
        b = tokens.shape[0]
        # NSP labels: deterministic pseudo-random 0/1 per (seed, step) so
        # the NSP head trains against a non-constant objective (an
        # all-zeros label would let it collapse to a constant prediction)
        nsp = np.random.default_rng(
            np.random.SeedSequence([seed, step, 0x4E53])
        ).integers(0, 2, size=(b,)).astype(np.int32)
        out = {
            "input_ids": masked,
            "token_type_ids": np.zeros_like(masked),
            "attention_mask": np.ones(
                (b, masked.shape[0] if seq_first else masked.shape[1]),
                np.int32,
            ),
            "mlm_labels": labels,
            "nsp_labels": nsp,
        }
        if max_predictions_per_seq:
            pos, pids, w = pack_mlm_predictions(
                labels, max_predictions_per_seq, seq_first=seq_first,
                # deterministic in (seed, step), independent of the
                # corruption stream: over-budget truncation selects a
                # uniform K-subset, reproducibly (resume-safe)
                rng=np.random.default_rng(
                    np.random.SeedSequence([seed, step, 0x4D50])
                ),
            )
            if not seq_first:
                pos, pids, w = pos.T, pids.T, w.T
            out.update(
                mlm_positions=pos, mlm_label_ids=pids, mlm_weights=w
            )
        yield out
        step += 1
