"""Autocast helpers — ≙ ``apex/_autocast_utils.py`` :: ``_cast_if_autocast_enabled``.

The reference checks ``torch.is_autocast_enabled()`` and casts extension
inputs to the autocast dtype so hand kernels compose with native amp.  The
JAX analog is explicit: ops take a :class:`~apex_tpu.amp.policy.Policy` (or
a dtype) and cast their floating inputs to its compute dtype.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax.numpy as jnp

from apex_tpu._tree_util import cast_floats

__all__ = ["_cast_if_autocast_enabled", "cast_inputs"]


def cast_inputs(args: Sequence[Any], policy_or_dtype: Optional[Any]):
    """Cast floating leaves of ``args`` to the policy's compute dtype.

    ``policy_or_dtype`` may be a Policy, a dtype, or None (no-op).
    """
    if policy_or_dtype is None:
        return tuple(args)
    dtype = getattr(policy_or_dtype, "compute_dtype", policy_or_dtype)
    return tuple(cast_floats(a, jnp.dtype(dtype)) for a in args)


def _cast_if_autocast_enabled(*args, policy=None):
    """Varargs form matching the reference's call shape
    (``_cast_if_autocast_enabled(x, y, ...)``).  With no ``policy`` this is
    the "autocast disabled" no-op; pass ``policy=`` (a Policy or dtype) for
    the enabled behavior."""
    return cast_inputs(args, policy)
