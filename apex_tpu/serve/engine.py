"""AOT-compiled inference engine — prefill/decode executables + cache.

The engine owns the three device-side pieces of the serving stack and
the proofs about them:

- **step programs** — one prefill executable per bucket shape and ONE
  decode executable for the full slot array, compiled ahead of time
  (``jit(...).lower(...).compile()``) at :meth:`InferenceEngine.build`.
  Steady-state serving calls compiled executables only: a retrace is
  impossible by construction, and :attr:`compile_counts` +
  a :class:`~apex_tpu.analysis.RetraceSentinel` per program pin it
  observably (``tests/test_serve.py``).
- **verification** — with ``verify=True`` (the default), the
  :mod:`apex_tpu.analysis` passes run over every step program at
  build (``lint_hlo`` on the one AOT-compiled module + ``lint_jaxpr``
  on a re-trace — the split-entry API exists exactly so the lint does
  not pay a second compile): transfer-free (no host round-trip inside
  a latency-critical step), donation-aliased (the KV pool updates in
  place — a dropped donation would double cache memory per step), plus
  the standard f64 screens.  Any ERROR finding fails the build;
  reports stay on :attr:`reports` and publish to the observability
  board.  ``engine.lint()`` / ``tools/graph_lint.py --target serve``
  re-prove the same through the full :func:`analysis.check` path.
- **cache + wires** — the paged KV pool (:mod:`apex_tpu.serve.cache`),
  optionally on the blockwise int8 KV wire, and optionally int8-packed
  weights (:func:`apex_tpu.serve.model.quantize_params`) dequantized
  inside the compiled step.
- **failure surface** — every step program computes an in-step
  non-finite screen over its logits (:attr:`last_prefill_finite` /
  :attr:`last_decode_finite` — the scheduler's poisoned-request
  quarantine evidence, no logits readback), chaos hooks at the
  ``serve.prefill`` / ``serve.decode`` sites make faults injectable
  from one ``APEX_TPU_CHAOS`` spec, and :meth:`rebuild` is the
  supervised recovery: re-run the AOT build (re-verified) while the
  cache arrays and pool are retained so surviving requests resume
  from their pages.  See docs/serving.md "Failure semantics".

Bucketed padding: a prompt compiles against the smallest bucket that
holds it (buckets are page multiples, powers-of-two by default), so the
number of distinct compiled shapes is ``len(prefill_buckets) + 1`` for
the life of the process.

The engine is deliberately scheduler-agnostic: it moves tokens and
pages, :class:`apex_tpu.serve.scheduler.ContinuousBatchingScheduler`
owns admission/shedding/SLOs, and both feed the same
:class:`~apex_tpu.observability.metrics.MetricRegistry`.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.gpt import GptConfig
from apex_tpu.observability.metrics import board
from apex_tpu.resilience import chaos
from apex_tpu.serve import cache as cache_lib
from apex_tpu.serve import model as model_lib
from apex_tpu.serve import spec as spec_lib

__all__ = ["ServeConfig", "InferenceEngine"]


def _default_buckets(page_size: int, max_len: int) -> Tuple[int, ...]:
    """Power-of-two page-multiple buckets covering [page, max_len]."""
    buckets = []
    b = page_size
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return tuple(buckets)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine shape/wire knobs (model shape lives in ``GptConfig``)."""

    page_size: int = 16
    #: pool size INCLUDING the reserved null page
    num_pages: int = 128
    #: decode slot count — the continuous batch's capacity
    max_batch: int = 4
    #: page-table width: the longest context is ``max_pages_per_seq *
    #: page_size`` tokens
    max_pages_per_seq: int = 8
    #: prefill bucket lengths (page multiples); () = powers of two up
    #: to the max context
    prefill_buckets: Tuple[int, ...] = ()
    #: "f32" keeps KV in the cache dtype; "int8" stores blockwise codes
    kv_wire: str = "f32"
    #: "f32" keeps weights dense; "int8" packs large leaves on the
    #: comm codec and dequantizes inside the compiled step
    weight_wire: str = "f32"
    #: static top-k cutoff for the fused in-step sampler (0 = full
    #: vocab); per-request temperature rides the call (temp<=0 stays
    #: greedy/argmax, bit-identical to the pre-sampling engine)
    top_k: int = 0
    #: PRNG seed for the fused sampler (one key per engine call,
    #: folded with the call index — deterministic replay)
    sample_seed: int = 0
    #: run analysis.check over every step program at build (ERROR
    #: findings raise)
    verify: bool = True
    #: static peak-HBM budget in bytes for each step program (weights
    #: + KV page pool + activations + scratch, from the compiled
    #: module's live ranges — apex_tpu.analysis.memory).  None skips
    #: the gate; with ``verify=True`` an over-budget program fails the
    #: BUILD, so a pool that never fit can't reach the first request.
    hbm_budget_bytes: Optional[int] = None

    def __post_init__(self):
        if self.kv_wire not in ("f32", "int8"):
            raise ValueError(f"kv_wire must be f32|int8, got {self.kv_wire!r}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if self.weight_wire not in ("f32", "int8"):
            raise ValueError(
                f"weight_wire must be f32|int8, got {self.weight_wire!r}"
            )
        usable = self.num_pages - 1
        if usable < self.max_pages_per_seq:
            raise ValueError(
                f"pool of {usable} usable pages cannot hold even one "
                f"max-length sequence ({self.max_pages_per_seq} pages)"
            )

    @property
    def max_context(self) -> int:
        return self.max_pages_per_seq * self.page_size

    def buckets(self) -> Tuple[int, ...]:
        if self.prefill_buckets:
            for b in self.prefill_buckets:
                if b % self.page_size or b > self.max_context:
                    raise ValueError(
                        f"bucket {b} must be a page multiple within "
                        f"max context {self.max_context}"
                    )
            return tuple(sorted(self.prefill_buckets))
        return _default_buckets(self.page_size, self.max_context)


class InferenceEngine:
    """AOT prefill/decode over the paged cache for a GPT param tree.

    >>> eng = InferenceEngine(cfg, params, ServeConfig(max_batch=4))
    >>> eng.build()                      # compile + verify (analysis)
    >>> logits, tok = eng.prefill(prompt_ids, page_ids)
    >>> toks = eng.decode(tokens, lengths, page_tables)

    The engine holds the cache arrays and rebinds them after every
    donated call; callers pass page ids / tables / lengths (the
    scheduler's bookkeeping) and get tokens back.
    """

    def __init__(
        self,
        cfg: GptConfig,
        params,
        serve: Optional[ServeConfig] = None,
        *,
        spec: Optional[spec_lib.SpecConfig] = None,
        registry=None,
    ):
        self.cfg = model_lib.validate_config(cfg)
        self.serve = serve or ServeConfig()
        if self.serve.max_context > cfg.max_seq_len:
            raise ValueError(
                f"max context {self.serve.max_context} exceeds the "
                f"model's max_seq_len {cfg.max_seq_len}"
            )
        if cfg.hidden_size % cfg.num_heads:
            raise ValueError("num_heads must divide hidden_size")
        self.registry = registry
        self.params = params
        if self.serve.weight_wire == "int8":
            self.params = model_lib.quantize_params(params)
        self.pool = cache_lib.PagePool(
            self.serve.num_pages, self.serve.page_size
        )
        self.cache = cache_lib.init_kv_pages(
            cfg.num_layers,
            self.serve.num_pages,
            cfg.num_heads,
            self.serve.page_size,
            cfg.hidden_size // cfg.num_heads,
            dtype=cfg.dtype,
            kv_wire=self.serve.kv_wire,
        )
        self._prefill: Dict[int, object] = {}
        self._chunk: Dict[int, object] = {}
        self._decode = None
        self._fork = None
        #: speculative decoding (docs/serving.md "Speculative
        #: decoding"): None = plain serving; a SpecConfig adds the
        #: draft model's params + KV pool and the draft/verify/rollback
        #: step programs, all compiled and verified like every other
        #: program
        self.spec = spec
        self._draft_cfg: Optional[GptConfig] = None
        self.draft_params = None
        self.draft_cache = None
        self._draft_prefill: Dict[int, object] = {}
        self._draft_decode = None
        self._verify = None
        self._rollback = None
        self._draft_rollback = None
        #: speculative round counter — the ``serve.draft`` chaos index
        self.spec_rounds = 0
        self.draft_prefill_calls = 0
        if spec is not None:
            dcfg = model_lib.validate_config(spec.draft_cfg or cfg)
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {dcfg.vocab_size} != target vocab "
                    f"{cfg.vocab_size} (proposals must share the "
                    f"token space)"
                )
            if self.serve.max_context > dcfg.max_seq_len:
                raise ValueError(
                    f"max context {self.serve.max_context} exceeds the "
                    f"draft model's max_seq_len {dcfg.max_seq_len}"
                )
            if dcfg.hidden_size % dcfg.num_heads:
                raise ValueError("draft num_heads must divide hidden_size")
            self._draft_cfg = dcfg
            if spec.draft_params is None:
                # self-draft: share the (possibly wire-packed) weights
                self.draft_params = self.params
            elif self.serve.weight_wire == "int8":
                self.draft_params = model_lib.quantize_params(
                    spec.draft_params
                )
            else:
                self.draft_params = spec.draft_params
            # the draft KV pool mirrors the target's page geometry so
            # ONE PagePool's page ids index both (draft pages ride the
            # "draft" namespace; only the per-page row shapes differ)
            self.draft_cache = cache_lib.init_kv_pages(
                dcfg.num_layers,
                self.serve.num_pages,
                dcfg.num_heads,
                self.serve.page_size,
                dcfg.hidden_size // dcfg.num_heads,
                dtype=dcfg.dtype,
                kv_wire=self.serve.kv_wire,
            )
        # the fused sampler's key chain: one fold per engine call
        self._rng_base = jax.random.PRNGKey(self.serve.sample_seed)
        #: optional :class:`~apex_tpu.observability.spans.SpanRecorder`
        #: — when set, every prefill/decode call records an
        #: ``engine/prefill`` / ``engine/decode`` span (the scheduler
        #: attaches its recorder here automatically)
        self.spans = None
        #: monotonically increasing call counters — the correlation
        #: ids linking a request's span chain to the engine batch
        #: iterations it rode (always counted, spans or not)
        self.decode_iters = 0
        self.prefill_calls = 0
        #: per-program AOT compile counter — the observable
        #: retrace-freedom pin (steady state never increments it; a
        #: supervised :meth:`rebuild` does, honestly)
        self.compile_counts: Dict[str, int] = {}
        #: supervised recoveries (:meth:`rebuild` calls) — 0 in steady
        #: state; every increment is a fault the scheduler survived
        self.rebuilds = 0
        #: the in-step non-finite screens of the LAST prefill/decode
        #: call — ``last_prefill_finite`` a bool, ``last_decode_finite``
        #: an ``(max_batch,)`` bool array (None before the first call).
        #: Computed INSIDE the compiled steps (no logits readback); the
        #: scheduler's poisoned-request quarantine reads them.
        self.last_prefill_finite: bool = True
        self.last_decode_finite: Optional[np.ndarray] = None
        self.reports: Dict[str, object] = {}
        self._sentinels: Dict[str, object] = {}
        self._publish_build_gauges()

    # -- build ------------------------------------------------------------
    def _publish_build_gauges(self) -> None:
        s = self.serve
        board.set("serve/page_size", s.page_size)
        board.set("serve/num_pages", s.num_pages - 1)
        board.set("serve/max_batch", s.max_batch)
        board.set("serve/max_context", s.max_context)
        board.set("serve/kv_wire", s.kv_wire)
        board.set("serve/weight_wire", s.weight_wire)
        if self.spec is not None:
            board.set("serve/spec_k", self.spec.k)
            board.set("serve/spec_mode", self.spec.mode)

    def _prefill_fn(self, bucket: int):
        s = self.serve
        np_ = bucket // s.page_size

        def fn(params, kv_pages, tokens, length, page_ids, temp, rng):
            return model_lib.prefill_body(
                self.cfg, params, kv_pages, tokens, length, page_ids,
                temp, rng,
                page_size=s.page_size,
                kv_wire=s.kv_wire,
                top_k=s.top_k,
            )

        fn.__name__ = f"serve_prefill_{bucket}"
        args = (
            self.params,
            self.cache,
            jnp.zeros((bucket, 1), jnp.int32),
            jnp.asarray(1, jnp.int32),
            jnp.zeros((np_,), jnp.int32),
            jnp.zeros((), jnp.float32),
            self._rng_base,
        )
        return fn, args

    def _chunk_fn(self, bucket: int):
        s = self.serve
        np_ = bucket // s.page_size

        def fn(params, kv_pages, tokens, length, offset, chunk_page_ids,
               page_table, temp, rng):
            return model_lib.chunk_prefill_body(
                self.cfg, params, kv_pages, tokens, length, offset,
                chunk_page_ids, page_table, temp, rng,
                page_size=s.page_size,
                kv_wire=s.kv_wire,
                top_k=s.top_k,
            )

        fn.__name__ = f"serve_chunk_prefill_{bucket}"
        args = (
            self.params,
            self.cache,
            jnp.zeros((bucket, 1), jnp.int32),
            jnp.asarray(1, jnp.int32),
            jnp.asarray(0, jnp.int32),
            jnp.zeros((np_,), jnp.int32),
            jnp.zeros((s.max_pages_per_seq,), jnp.int32),
            jnp.zeros((), jnp.float32),
            self._rng_base,
        )
        return fn, args

    def _decode_fn(self):
        s = self.serve

        def fn(params, kv_pages, tokens, lengths, page_tables, temps, rng):
            return model_lib.decode_body(
                self.cfg, params, kv_pages, tokens, lengths, page_tables,
                temps, rng,
                page_size=s.page_size, kv_wire=s.kv_wire, top_k=s.top_k,
            )

        fn.__name__ = "serve_decode"
        args = (
            self.params,
            self.cache,
            jnp.zeros((s.max_batch,), jnp.int32),
            jnp.zeros((s.max_batch,), jnp.int32),
            jnp.zeros((s.max_batch, s.max_pages_per_seq), jnp.int32),
            jnp.zeros((s.max_batch,), jnp.float32),
            jnp.zeros((s.max_batch, 2), jnp.uint32),
        )
        return fn, args

    def _fork_fn(self):
        def fn(kv_pages, src, dst):
            # copy-on-write fork: duplicate one page's rows (codes AND
            # scale planes under the int8 wire) across every layer
            return {
                name: arr.at[:, dst].set(arr[:, src])
                for name, arr in kv_pages.items()
            }

        fn.__name__ = "serve_fork_page"
        args = (
            self.cache,
            jnp.asarray(0, jnp.int32),
            jnp.asarray(0, jnp.int32),
        )
        return fn, args

    def _draft_fn(self):
        s = self.serve
        k = self.spec.k
        dcfg = self._draft_cfg

        def fn(params, kv_pages, tokens, lengths, page_tables, temps,
               stream_keys, gens):
            return spec_lib.draft_body(
                dcfg, params, kv_pages, tokens, lengths, page_tables,
                temps, stream_keys, gens,
                k=k, page_size=s.page_size, kv_wire=s.kv_wire,
                top_k=s.top_k,
            )

        fn.__name__ = "serve_draft_decode"
        args = (
            self.draft_params,
            self.draft_cache,
            jnp.zeros((s.max_batch,), jnp.int32),
            jnp.zeros((s.max_batch,), jnp.int32),
            jnp.zeros((s.max_batch, s.max_pages_per_seq), jnp.int32),
            jnp.zeros((s.max_batch,), jnp.float32),
            jnp.zeros((s.max_batch, 2), jnp.uint32),
            jnp.zeros((s.max_batch,), jnp.int32),
        )
        return fn, args

    def _verify_fn(self):
        s = self.serve
        k = self.spec.k

        def fn(params, kv_pages, tokens, draft_tokens, lengths,
               page_tables, temps, draft_probs, stream_keys, gens):
            return spec_lib.verify_body(
                self.cfg, params, kv_pages, tokens, draft_tokens,
                lengths, page_tables, temps, draft_probs, stream_keys,
                gens,
                page_size=s.page_size, kv_wire=s.kv_wire, top_k=s.top_k,
            )

        fn.__name__ = "serve_verify"
        args = (
            self.params,
            self.cache,
            jnp.zeros((s.max_batch,), jnp.int32),
            jnp.zeros((s.max_batch, k), jnp.int32),
            jnp.zeros((s.max_batch,), jnp.int32),
            jnp.zeros((s.max_batch, s.max_pages_per_seq), jnp.int32),
            jnp.zeros((s.max_batch,), jnp.float32),
            jnp.zeros((k, s.max_batch, self.cfg.vocab_size), jnp.float32),
            jnp.zeros((s.max_batch, 2), jnp.uint32),
            jnp.zeros((s.max_batch,), jnp.int32),
        )
        return fn, args

    def _rollback_fn(self, cache, name: str):
        s = self.serve
        # the stale span after a round is [new ctx, old ctx + k]: at
        # most k + 1 rows when nothing was accepted
        kmax = self.spec.k + 1

        def fn(kv_pages, starts, counts, page_tables):
            return spec_lib.rollback_body(
                kv_pages, starts, counts, page_tables,
                k=kmax, page_size=s.page_size, kv_wire=s.kv_wire,
            )

        fn.__name__ = name
        args = (
            cache,
            jnp.zeros((s.max_batch,), jnp.int32),
            jnp.zeros((s.max_batch,), jnp.int32),
            jnp.zeros((s.max_batch, s.max_pages_per_seq), jnp.int32),
        )
        return fn, args

    def _draft_prefill_fn(self, bucket: int):
        s = self.serve
        np_ = bucket // s.page_size
        dcfg = self._draft_cfg

        def fn(params, kv_pages, tokens, length, page_ids, temp, rng):
            return model_lib.prefill_body(
                dcfg, params, kv_pages, tokens, length, page_ids,
                temp, rng,
                page_size=s.page_size,
                kv_wire=s.kv_wire,
                top_k=s.top_k,
            )

        fn.__name__ = f"serve_draft_prefill_{bucket}"
        args = (
            self.draft_params,
            self.draft_cache,
            jnp.zeros((bucket, 1), jnp.int32),
            jnp.asarray(1, jnp.int32),
            jnp.zeros((np_,), jnp.int32),
            jnp.zeros((), jnp.float32),
            self._rng_base,
        )
        return fn, args

    def _compile(self, name: str, fn, args, *, donate: int = 1):
        from apex_tpu import analysis

        compiled = (
            jax.jit(fn, donate_argnums=(donate,)).lower(*args).compile()
        )
        if self.serve.verify:
            # lint the executable we just paid for (lint_hlo/lint_jaxpr
            # instead of analysis.check, which would trace+compile the
            # identical program a second time): HLO-level transfer +
            # donation-aliasing + static peak-HBM budget over the
            # compiled text (the KV page pool is a donated argument
            # with a static shape, so the pool is budgeted exactly),
            # jaxpr-level transfer/promotion over a cheap re-trace
            hlo_text = compiled.as_text()
            report = analysis.lint_hlo(
                hlo_text,
                donated=len(jax.tree_util.tree_leaves(args[donate])),
                hbm_budget=self.serve.hbm_budget_bytes,
                name=f"serve/{name}",
            )
            est = analysis.memory.estimate_peak(hlo_text)
            analysis.memory.publish_peak(est, prefix=f"serve/hbm/{name}")
            board.set("serve/peak_hbm_bytes", max(
                int(board.get("serve/peak_hbm_bytes") or 0),
                est["peak_bytes"],
            ))
            report.extend(
                analysis.lint_jaxpr(
                    jax.make_jaxpr(fn)(*args), name=f"serve/{name}"
                ).findings
            )
            analysis.publish_report(report)
            self.reports[name] = report
            errors = report.errors()
            if errors:
                raise RuntimeError(
                    f"serve step {name} failed graph lint with "
                    f"{len(errors)} ERROR finding(s):\n{report.render()}"
                )
        self.compile_counts[name] = self.compile_counts.get(name, 0) + 1
        self._sentinels[name] = analysis.RetraceSentinel(name=name)
        return compiled

    def build(self, buckets: Optional[Tuple[int, ...]] = None, *,
              chunked: bool = False):
        """Compile (and verify) the decode step and every prefill
        bucket eagerly.  Lazy compilation still happens on first use of
        a bucket that was skipped here.  ``chunked=True`` additionally
        warms every chunk-prefill bucket and the COW fork program —
        a prefix-cache/chunked-prefill deployment should pay those
        compiles at build, not inside the first cache hit's TTFT."""
        for b in buckets if buckets is not None else self.serve.buckets():
            self._get_prefill(b)
            if chunked:
                self._get_chunk(b)
            if self.spec is not None:
                self._get_draft_prefill(b)
        if chunked:
            self._get_fork()
        self._get_decode()
        if self.spec is not None:
            self._get_draft()
            self._get_verify()
            self._get_rollback()
            self._get_draft_rollback()
        return self

    def rebuild(self, *, full: bool = False):
        """Supervised recovery (docs/serving.md "Failure semantics"):
        re-run the AOT build — including the build-time ``verify``
        lint, so the replacement program is re-PROVEN, not assumed —
        and swap it in atomically, while the KV cache arrays and the
        page pool are retained, so surviving requests resume decoding
        from their existing pages with the generated prefix intact.

        The incumbent decode program stays SERVING until the
        replacement is ready: a transient fault does not corrupt a
        compiled executable, so recovery must not pause the batch for
        a recompile (if the incumbent is genuinely wedged it faults
        again and the scheduler's ``rebuild_limit`` bounds the loop —
        the scheduler defers this call to an idle point and escalates
        to a synchronous rebuild on a repeat fault).  By default only
        the decode program is rebuilt; ``full=True`` additionally
        drops every prefill bucket, which then recompiles lazily on
        next use.  The swap is one atomic attribute write.
        """
        self.rebuilds += 1
        if full:
            self._prefill.clear()
            self._chunk.clear()
            self._draft_prefill.clear()
            for name in list(self._sentinels):
                if name.startswith(
                    ("prefill", "chunk_prefill", "draft_prefill")
                ):
                    del self._sentinels[name]
        fn, args = self._decode_fn()
        self._decode = self._compile("decode", fn, args)
        if self.spec is not None:
            fn, args = self._draft_fn()
            self._draft_decode = self._compile("draft_decode", fn, args)
            fn, args = self._verify_fn()
            self._verify = self._compile("verify", fn, args)
        board.set("serve/engine_rebuilds", self.rebuilds)
        return self

    def _get_prefill(self, bucket: int):
        if bucket not in self._prefill:
            fn, args = self._prefill_fn(bucket)
            self._prefill[bucket] = self._compile(
                f"prefill_{bucket}", fn, args
            )
        return self._prefill[bucket]

    def _get_chunk(self, bucket: int):
        if bucket not in self._chunk:
            fn, args = self._chunk_fn(bucket)
            self._chunk[bucket] = self._compile(
                f"chunk_prefill_{bucket}", fn, args
            )
        return self._chunk[bucket]

    def _get_fork(self):
        if self._fork is None:
            fn, args = self._fork_fn()
            self._fork = self._compile("fork_page", fn, args, donate=0)
        return self._fork

    def _get_decode(self):
        if self._decode is None:
            fn, args = self._decode_fn()
            self._decode = self._compile("decode", fn, args)
        return self._decode

    def _get_draft(self):
        if self._draft_decode is None:
            fn, args = self._draft_fn()
            self._draft_decode = self._compile("draft_decode", fn, args)
        return self._draft_decode

    def _get_verify(self):
        if self._verify is None:
            fn, args = self._verify_fn()
            self._verify = self._compile("verify", fn, args)
        return self._verify

    def _get_rollback(self):
        if self._rollback is None:
            fn, args = self._rollback_fn(self.cache, "serve_rollback")
            self._rollback = self._compile("rollback", fn, args, donate=0)
        return self._rollback

    def _get_draft_rollback(self):
        if self._draft_rollback is None:
            fn, args = self._rollback_fn(
                self.draft_cache, "serve_draft_rollback"
            )
            self._draft_rollback = self._compile(
                "draft_rollback", fn, args, donate=0
            )
        return self._draft_rollback

    def _get_draft_prefill(self, bucket: int):
        if bucket not in self._draft_prefill:
            fn, args = self._draft_prefill_fn(bucket)
            self._draft_prefill[bucket] = self._compile(
                f"draft_prefill_{bucket}", fn, args
            )
        return self._draft_prefill[bucket]

    @property
    def retraces(self) -> int:
        return sum(s.retraces for s in self._sentinels.values())

    def lint(self, bucket: Optional[int] = None):
        """One merged :class:`apex_tpu.analysis.Report` over the
        prefill (smallest bucket by default) and decode step programs —
        the ``tools/graph_lint.py --target serve`` surface.  Unlike the
        build-time ``verify``, this never raises: findings come back
        for rendering."""
        from apex_tpu import analysis

        bucket = bucket or self.serve.buckets()[0]
        fn, args = self._prefill_fn(bucket)
        report = analysis.check(
            jax.jit(fn, donate_argnums=(1,)), *args,
            donate_argnums=(1,),
            hbm_budget=self.serve.hbm_budget_bytes,
            name=f"serve/prefill_{bucket}",
        )
        fn, args = self._decode_fn()
        dec = analysis.check(
            jax.jit(fn, donate_argnums=(1,)), *args,
            donate_argnums=(1,),
            hbm_budget=self.serve.hbm_budget_bytes,
            name="serve/decode",
        )
        analysis.attach_shard_sections(report, [
            (f"serve/prefill_{bucket}", report.hlo_text),
            ("serve/decode", dec.hlo_text),
        ])
        report.merge(dec)
        report.target = "serve"
        return report

    # -- serving calls ----------------------------------------------------
    def bucket_for(self, prompt_len: int) -> int:
        for b in self.serve.buckets():
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt of {prompt_len} tokens exceeds the max context "
            f"{self.serve.max_context}"
        )

    @staticmethod
    def _chaos_gate(site: str, call_idx: int):
        """Serving chaos hook (one ``APEX_TPU_CHAOS`` spec drives train
        AND serve drills): ``raise`` mode raises :class:`~apex_tpu.
        resilience.chaos.InjectedFault` standing in for a wedged or
        crashed step, ``stall`` sleeps (a hung device call — the
        scheduler's per-request decode timeouts see it), ``nan``/
        ``inf`` return the fault so the caller poisons its non-finite
        verdict (the quarantine drill).  ``call_idx`` is the 0-based
        prefill-call / decode-iteration index."""
        fault = chaos.active(site, call_idx)
        if fault is None:
            return None
        if fault.mode == "stall":
            time.sleep(fault.stall_seconds)
            return None
        if fault.mode in ("nan", "inf"):
            return fault
        raise chaos.InjectedFault(site, call_idx, fault.mode)

    def _sample_key(self, idx: int):
        """Deterministic per-call PRNG key for the fused sampler."""
        return jax.random.fold_in(self._rng_base, idx)

    def _stream_keys(self, streams):
        """Per-slot stream keys: ``fold_in(engine base, stream seed)``
        — a function of request IDENTITY, never of call counters, so a
        speculative rollback replays the same draws and a ``k = 0``
        spec stream equals the plain one (spec.py "RNG discipline")."""
        return jax.vmap(jax.random.fold_in, (None, 0))(
            self._rng_base, jnp.asarray(streams, jnp.uint32)
        )

    def prefill(self, prompt_ids, page_ids, *,
                temperature: float = 0.0) -> Tuple[np.ndarray, int]:
        """Run the prompt through the bucketed prefill: writes its K/V
        into ``page_ids`` (null-padded to the bucket's page count) and
        returns ``(last_logits (V,), first_token)``.  The first token
        is sampled in-step (``temperature<=0`` = greedy argmax); the
        in-step non-finite screen lands on
        :attr:`last_prefill_finite`."""
        poison = self._chaos_gate(chaos.SERVE_PREFILL, self.prefill_calls)
        n = len(prompt_ids)
        bucket = self.bucket_for(n)
        np_b = bucket // self.serve.page_size
        tokens = np.zeros((bucket, 1), np.int32)
        tokens[:n, 0] = np.asarray(prompt_ids, np.int32)
        ids = np.full((np_b,), cache_lib.NULL_PAGE, np.int32)
        ids[: len(page_ids)] = np.asarray(page_ids, np.int32)
        compiled = self._get_prefill(bucket)
        name = f"prefill_{bucket}"
        args = (
            self.params, self.cache, jnp.asarray(tokens),
            jnp.asarray(n, jnp.int32), jnp.asarray(ids),
            jnp.asarray(temperature, jnp.float32),
            self._sample_key(self.prefill_calls),
        )
        self._sentinels[name].observe(*args)
        self.prefill_calls += 1
        rec = self.spans
        t0 = rec.now() if rec is not None else None
        logits, next_token, finite, self.cache = compiled(*args)
        # logits stay ON DEVICE (lazy jax.Array): only the sampled
        # token and the scalar finite screen cross to the host — the
        # logits matrix is (V,)/(B, V) and most callers never read it
        first = int(next_token)
        self.last_prefill_finite = bool(finite) and poison is None
        if rec is not None:
            # int(next_token) above synced, so the span covers the real
            # device time, not just the async dispatch
            from apex_tpu.observability.spans import TRACK_ENGINE

            rec.span(
                "engine/prefill", t0, rec.now(), track=TRACK_ENGINE,
                bucket=bucket, tokens=n, call=self.prefill_calls,
            )
        return logits, first

    def chunk_prefill(self, chunk_ids, offset, page_table_row,
                      chunk_page_ids, *,
                      temperature: float = 0.0) -> Tuple[np.ndarray, int]:
        """One page-multiple prefill chunk with carry-in KV offset
        (:func:`apex_tpu.serve.model.chunk_prefill_body`): positions
        before ``offset`` are read from the paged cache through
        ``page_table_row`` — committed prefix-cache pages and this
        request's own earlier chunks alike — and the chunk's K/V are
        written to ``chunk_page_ids`` (null entries skip pages a
        borrowed cache run already holds).  Returns ``(last_logits
        (V,), next_token)`` for the chunk's final live position; the
        scheduler consumes the token only from the FINAL chunk.  Rides
        the ``serve.prefill`` chaos site and
        :attr:`last_prefill_finite` exactly like :meth:`prefill`."""
        poison = self._chaos_gate(chaos.SERVE_PREFILL, self.prefill_calls)
        n = len(chunk_ids)
        bucket = self.bucket_for(n)
        np_b = bucket // self.serve.page_size
        tokens = np.zeros((bucket, 1), np.int32)
        tokens[:n, 0] = np.asarray(chunk_ids, np.int32)
        ids = np.full((np_b,), cache_lib.NULL_PAGE, np.int32)
        ids[: len(chunk_page_ids)] = np.asarray(chunk_page_ids, np.int32)
        table = np.full(
            (self.serve.max_pages_per_seq,), cache_lib.NULL_PAGE, np.int32
        )
        table[: len(page_table_row)] = np.asarray(
            page_table_row, np.int32
        )
        compiled = self._get_chunk(bucket)
        name = f"chunk_prefill_{bucket}"
        args = (
            self.params, self.cache, jnp.asarray(tokens),
            jnp.asarray(n, jnp.int32), jnp.asarray(offset, jnp.int32),
            jnp.asarray(ids), jnp.asarray(table),
            jnp.asarray(temperature, jnp.float32),
            self._sample_key(self.prefill_calls),
        )
        self._sentinels[name].observe(*args)
        self.prefill_calls += 1
        rec = self.spans
        t0 = rec.now() if rec is not None else None
        logits, next_token, finite, self.cache = compiled(*args)
        first = int(next_token)
        self.last_prefill_finite = bool(finite) and poison is None
        if rec is not None:
            from apex_tpu.observability.spans import TRACK_ENGINE

            rec.span(
                "engine/prefill", t0, rec.now(), track=TRACK_ENGINE,
                bucket=bucket, tokens=n, offset=int(offset),
                call=self.prefill_calls, chunked=True,
            )
        return logits, first

    def fork_page(self, src: int, dst: int) -> None:
        """Copy-on-write fork: duplicate page ``src``'s content into
        ``dst`` across every layer (codes AND scale planes under the
        int8 KV wire) through one tiny compiled donated program — the
        device half of the scheduler's shared-tail-page fork."""
        compiled = self._get_fork()
        args = (
            self.cache,
            jnp.asarray(src, jnp.int32),
            jnp.asarray(dst, jnp.int32),
        )
        self._sentinels["fork_page"].observe(*args)
        self.cache = compiled(*args)

    def decode(self, tokens, lengths, page_tables, temps=None, *,
               streams=None, gens=None):
        """One decode iteration over the full slot array.  ``lengths``
        counts each slot's context INCLUDING the token being fed (0 =
        idle slot).  Returns ``(logits (B, V), next_tokens (B,))`` —
        ``next_tokens`` on host (the scheduler needs them), ``logits``
        left as a lazy on-device array so the hot serving loop never
        pays the (B, V) device→host copy it does not read.  The
        per-slot in-step non-finite screen lands on
        :attr:`last_decode_finite` (the quarantine evidence).

        ``streams``/``gens`` (both ``(B,)``) thread per-slot stream
        seeds and emission indices: each slot samples under the RAW
        ``fold_in(stream_key, gen)`` — the same key a ``k = 0``
        speculative round would consume, which is what makes the two
        paths bit-identical.  None keeps the legacy per-iteration key
        chain (one fold per call, split per slot)."""
        poison = self._chaos_gate(chaos.SERVE_DECODE, self.decode_iters)
        compiled = self._get_decode()
        if streams is None:
            rng = jax.vmap(jax.random.fold_in, (None, 0))(
                self._sample_key(self.decode_iters),
                jnp.arange(self.serve.max_batch, dtype=jnp.uint32),
            )
        else:
            rng = spec_lib._fold_each(
                self._stream_keys(streams), jnp.asarray(gens, jnp.int32)
            )
        args = (
            self.params,
            self.cache,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(lengths, jnp.int32),
            jnp.asarray(page_tables, jnp.int32),
            jnp.zeros((self.serve.max_batch,), jnp.float32)
            if temps is None else jnp.asarray(temps, jnp.float32),
            rng,
        )
        self._sentinels["decode"].observe(*args)
        self.decode_iters += 1
        rec = self.spans
        t0 = rec.now() if rec is not None else None
        logits, next_tokens, finite, self.cache = compiled(*args)
        out = np.asarray(next_tokens)
        finite_np = np.array(finite)
        if poison is not None:
            # an injected poisoned-logits fault: flag the first LIVE
            # slot exactly as the in-step screen would flag a real
            # non-finite row — the quarantine path downstream is the
            # production path, only the evidence is simulated
            live = np.flatnonzero(np.asarray(lengths) > 0)
            if live.size:
                finite_np[live[0]] = False
        self.last_decode_finite = finite_np
        if rec is not None:
            # np.asarray(next_tokens) above synced — real device time
            from apex_tpu.observability.spans import TRACK_ENGINE

            rec.span(
                "engine/decode", t0, rec.now(), track=TRACK_ENGINE,
                iter=self.decode_iters,
                batch=int((np.asarray(lengths) > 0).sum()),
            )
        return logits, out

    def reset_cache(self) -> None:
        """Re-zero the paged KV arrays (target AND draft).  Only legal
        with an EMPTY pool — live pages hold state requests will read.
        The deploy path calls this on every weight swap: freed pages
        are never scrubbed (a finite stale row costs nothing under the
        attention mask's exact-zero weights), but K/V written by
        NaN-poisoned weights breaks that bargain — ``0 * NaN`` is NaN,
        so one poisoned tenancy would haunt every later request (and
        the rollback's bit-exact fingerprint) through pages it no
        longer owns."""
        if self.pool.in_use != 0:
            raise RuntimeError(
                f"reset_cache with {self.pool.in_use} pages in use"
            )
        cfg = self.cfg
        self.cache = cache_lib.init_kv_pages(
            cfg.num_layers,
            self.serve.num_pages,
            cfg.num_heads,
            self.serve.page_size,
            cfg.hidden_size // cfg.num_heads,
            dtype=cfg.dtype,
            kv_wire=self.serve.kv_wire,
        )
        if self.draft_cache is not None:
            dcfg = self._draft_cfg
            self.draft_cache = cache_lib.init_kv_pages(
                dcfg.num_layers,
                self.serve.num_pages,
                dcfg.num_heads,
                self.serve.page_size,
                dcfg.hidden_size // dcfg.num_heads,
                dtype=dcfg.dtype,
                kv_wire=self.serve.kv_wire,
            )

    def probe_stream(self, prompt_ids, max_new_tokens: int):
        """Golden-probe hook (:mod:`apex_tpu.observability.canary`):
        run ONE prompt greedily (temperature 0) through prefill plus a
        single-slot decode loop and return ``(tokens,
        prefill_logits_bytes, finite)`` — the raw material of a model
        fingerprint.  Greedy argmax ignores the sampler rng, so the
        stream is a pure function of the weights + compiled programs;
        the prefill last-logits float32 bytes make the caller's digest
        sensitive to corruptions too small to flip any argmax.

        Pages come from the engine's own pool and are freed before
        returning; callers probe QUIET engines (drained replicas,
        freshly built engines), so the transient page hold never
        competes with live requests.  ``finite`` folds in the in-step
        non-finite screens — NaN-poisoned weights fingerprint honestly
        instead of crashing the probe."""
        n = len(prompt_ids)
        total = n + int(max_new_tokens)
        if total > self.serve.max_context:
            raise ValueError(
                f"probe needs {total} tokens of context, "
                f"max_context={self.serve.max_context}"
            )
        pages_needed = -(-total // self.serve.page_size)
        if pages_needed > self.serve.max_pages_per_seq:
            raise ValueError(
                f"probe needs {pages_needed} pages/seq, "
                f"max_pages_per_seq={self.serve.max_pages_per_seq}"
            )
        page_ids = self.pool.alloc(pages_needed)
        if page_ids is None:
            raise RuntimeError(
                f"probe_stream: page pool exhausted "
                f"({pages_needed} pages needed) — probe a quiet engine"
            )
        try:
            # prefill takes only the prompt-covering pages (its ids
            # buffer is bucket-sized); decode reaches the growth pages
            # through the full page-table row below
            prompt_pages = page_ids[: -(-n // self.serve.page_size)]
            logits, first = self.prefill(
                prompt_ids, prompt_pages, temperature=0.0
            )
            logits_bytes = np.asarray(logits, np.float32).tobytes()
            finite = bool(self.last_prefill_finite)
            tokens = [first]
            b = self.serve.max_batch
            table = np.full(
                (b, self.serve.max_pages_per_seq),
                cache_lib.NULL_PAGE, np.int32,
            )
            table[0, :pages_needed] = np.asarray(page_ids, np.int32)
            for i in range(int(max_new_tokens) - 1):
                tok = np.zeros((b,), np.int32)
                lengths = np.zeros((b,), np.int32)
                tok[0] = tokens[-1]
                lengths[0] = n + i + 1  # ctx incl. the fed token
                _, next_tokens = self.decode(tok, lengths, table)
                finite = finite and bool(
                    np.asarray(self.last_decode_finite)[0]
                )
                tokens.append(int(next_tokens[0]))
        finally:
            self.pool.free(page_ids)
        return tokens, logits_bytes, finite

    # -- speculative serving calls ----------------------------------------
    def draft_prefill(self, prompt_ids, page_ids) -> None:
        """Prefill the DRAFT model's KV for a prompt into the request's
        draft-namespace pages (the in-step sampled token is discarded —
        the target prefill's token is the stream's first).  Uses its
        own call counter so a speculative deployment leaves the target
        prefill/decode rng chains untouched (the greedy bit-identity
        gate compares spec and plain runs of the same workload)."""
        n = len(prompt_ids)
        bucket = self.bucket_for(n)
        np_b = bucket // self.serve.page_size
        tokens = np.zeros((bucket, 1), np.int32)
        tokens[:n, 0] = np.asarray(prompt_ids, np.int32)
        ids = np.full((np_b,), cache_lib.NULL_PAGE, np.int32)
        ids[: len(page_ids)] = np.asarray(page_ids, np.int32)
        compiled = self._get_draft_prefill(bucket)
        name = f"draft_prefill_{bucket}"
        args = (
            self.draft_params, self.draft_cache, jnp.asarray(tokens),
            jnp.asarray(n, jnp.int32), jnp.asarray(ids),
            jnp.zeros((), jnp.float32),
            jax.random.fold_in(self._rng_base, self.draft_prefill_calls),
        )
        self._sentinels[name].observe(*args)
        self.draft_prefill_calls += 1
        _logits, _tok, _finite, self.draft_cache = compiled(*args)

    def spec_step(self, tokens, lengths, page_tables, draft_tables,
                  temps, streams, gens):
        """One speculative round over the full slot array: the draft
        program proposes ``k`` tokens per live slot, then ONE verify
        program scores all ``k + 1`` positions and runs acceptance
        on-device.  Returns ``(out_tokens (B, k+1), n_accept (B,),
        finite (B,))`` on host — slot ``b`` emits ``out_tokens[b,
        :n_accept[b] + 1]``.

        Rides the ``serve.draft`` chaos site (a faulted draft degrades
        to zero-acceptance proposals — stream correctness never
        depends on the draft) and the ``serve.decode`` site for the
        verify step exactly like :meth:`decode`."""
        spec = self.spec
        s = self.serve
        round_idx = self.spec_rounds
        # the round cursor advances on ATTEMPTS, and before the chaos
        # gate: a raise-mode serve.draft fault must burn its round
        # index, or a planted one-shot storm re-fires at the same
        # index forever and wedges speculation permanently
        self.spec_rounds += 1
        fault = self._chaos_gate(chaos.SERVE_DRAFT, round_idx)
        poison = self._chaos_gate(chaos.SERVE_DECODE, self.decode_iters)
        tok = jnp.asarray(tokens, jnp.int32)
        lens = jnp.asarray(lengths, jnp.int32)
        temps_j = (jnp.zeros((s.max_batch,), jnp.float32)
                   if temps is None else jnp.asarray(temps, jnp.float32))
        keys = self._stream_keys(streams)
        gens_j = jnp.asarray(gens, jnp.int32)
        d_args = (
            self.draft_params, self.draft_cache, tok, lens,
            jnp.asarray(draft_tables, jnp.int32), temps_j, keys, gens_j,
        )
        compiled = self._get_draft()
        self._sentinels["draft_decode"].observe(*d_args)
        d_tokens, d_probs, d_finite, self.draft_cache = compiled(*d_args)
        bad = jnp.logical_not(d_finite)
        if fault is not None:
            bad = jnp.ones_like(bad)
        if spec.k:
            # a faulted/non-finite draft must not smuggle a token into
            # the stream: pin its proposals to one fixed id and claim
            # the matching point-mass draft distribution — the
            # rejection sampler preserves the target distribution for
            # ANY claimed q consistent with how d was drawn, and greedy
            # only ever emits the argmax chain, so a poisoned round
            # degrades to ~zero acceptance instead of corruption
            pin = jnp.full_like(d_tokens, self.cfg.vocab_size - 1)
            d_tokens = jnp.where(bad[:, None], pin, d_tokens)
            d_probs = jnp.where(
                bad[None, :, None],
                jax.nn.one_hot(
                    jnp.transpose(pin), self.cfg.vocab_size,
                    dtype=jnp.float32,
                ),
                d_probs,
            )
        v_args = (
            self.params, self.cache, tok, d_tokens, lens,
            jnp.asarray(page_tables, jnp.int32), temps_j, d_probs,
            keys, gens_j,
        )
        compiled = self._get_verify()
        self._sentinels["verify"].observe(*v_args)
        self.decode_iters += 1
        rec = self.spans
        t0 = rec.now() if rec is not None else None
        out_tokens, n_accept, finite, self.cache = compiled(*v_args)
        out = np.asarray(out_tokens)
        acc = np.asarray(n_accept)
        finite_np = np.array(finite)
        if poison is not None:
            live = np.flatnonzero(np.asarray(lengths) > 0)
            if live.size:
                finite_np[live[0]] = False
        self.last_decode_finite = finite_np
        if rec is not None:
            # np.asarray(out_tokens) above synced — real device time
            from apex_tpu.observability.spans import TRACK_ENGINE

            live_n = int((np.asarray(lengths) > 0).sum())
            rec.span(
                "engine/decode", t0, rec.now(), track=TRACK_ENGINE,
                iter=self.decode_iters, batch=live_n, spec=True,
                drafted=spec.k * live_n, accepted=int(acc.sum()),
            )
        return out, acc, finite_np

    def rollback(self, starts, counts, page_tables) -> None:
        """Zero the target-KV rows of rejected positions ``[starts[b],
        starts[b] + counts[b])`` through each slot's page table (the
        compiled truncation program — spec.py :func:`~apex_tpu.serve.
        spec.rollback_body`).  The scheduler COW-forked any shared tail
        page BEFORE the round, so every touched page is private."""
        compiled = self._get_rollback()
        args = (
            self.cache,
            jnp.asarray(starts, jnp.int32),
            jnp.asarray(counts, jnp.int32),
            jnp.asarray(page_tables, jnp.int32),
        )
        self._sentinels["rollback"].observe(*args)
        self.cache = compiled(*args)

    def draft_rollback(self, starts, counts, page_tables) -> None:
        """:meth:`rollback` for the draft KV pool (draft page ids)."""
        compiled = self._get_draft_rollback()
        args = (
            self.draft_cache,
            jnp.asarray(starts, jnp.int32),
            jnp.asarray(counts, jnp.int32),
            jnp.asarray(page_tables, jnp.int32),
        )
        self._sentinels["draft_rollback"].observe(*args)
        self.draft_cache = compiled(*args)

    def update_draft_params(self, draft_params) -> None:
        """Swap the draft weights in place (a fleet redeploy shipping a
        refreshed draft beside the target); wire-packs under int8
        weights.  ``None`` means "no new draft shipped": a SELF-draft
        engine re-aliases the (possibly just-redeployed) target params
        so the draft never goes stale against its own target; a
        distinct-draft engine keeps the draft it has.  The compiled
        draft programs are shape-specialized, so a different draft
        ARCHITECTURE needs a new engine."""
        if self.spec is None:
            raise ValueError("engine has no speculative config")
        if draft_params is None:
            if self.spec.draft_params is None:
                self.draft_params = self.params
        elif self.serve.weight_wire == "int8":
            self.draft_params = model_lib.quantize_params(draft_params)
        else:
            self.draft_params = draft_params
