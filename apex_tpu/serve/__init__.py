"""TPU serving — AOT inference engine, paged KV cache, continuous batching.

The decode half of apex_tpu (ROADMAP item 1): the training stack
produces a ``GptModel`` parameter tree; this package serves it under
heavy traffic with the same engineering discipline the training side
gets — AOT-compiled step programs proven transfer-free and
donation-aliased by :mod:`apex_tpu.analysis`, telemetry through the
:mod:`apex_tpu.observability` spine, and the ``parallel/comm.py``
blockwise int8 codec reused as the KV and weight wire formats.

- :mod:`apex_tpu.serve.cache` — :class:`PagePool` + the paged KV
  pool: fixed-size pages from one shared pool, so cache memory scales
  with live tokens and freeing is O(1) with no defragmentation.
- :mod:`apex_tpu.serve.model` — the functional prefill/decode
  re-expression of ``models/gpt.py`` (numerics pinned against
  ``GptModel.apply``), plus int8 weight wires.
- :mod:`apex_tpu.serve.engine` — :class:`InferenceEngine`: one AOT
  executable per prefill bucket + one for the decode slot array,
  verified at build.
- :mod:`apex_tpu.serve.scheduler` —
  :class:`ContinuousBatchingScheduler`: page-granular admission into
  the running decode batch, TTFT SLO deadlines, graceful shedding on
  pool exhaustion — and the serving resilience layer: bounded
  re-admission retries with the generated prefix retained,
  poisoned-request quarantine, supervised engine rebuild, an explicit
  overload degradation ladder (queue-cap fast-reject, token clamping,
  deadline shedding), and rolling-restart ``drain()``.  With
  ``prefix_cache=True`` a content-addressed :class:`PrefixCache`
  shares committed KV page runs across requests (copy-on-write,
  LRU-evicted under pool pressure) and ``prefill_chunk_tokens=``
  interleaves chunked prefills between decode iterations.  Chaos
  sites at ``serve.prefill``/``serve.decode``/``serve.admission``/
  ``serve.kv_alloc``/``serve.prefix_evict``/``serve.draft`` make
  every failure path
  drillable from one ``APEX_TPU_CHAOS`` spec
  (``tools/serve_chaos_drill.py``).

- :mod:`apex_tpu.serve.spec` — speculative decoding
  (:class:`SpecConfig`): a small draft model proposes ``k`` tokens,
  ONE target step verifies all of them, and the rejected tail's KV is
  rolled back in place.  Greedy acceptance is exact argmax match (the
  emitted stream is bit-identical to plain decode by construction);
  temperature mode uses the rejection sampler that provably preserves
  the target distribution.  Draft KV pages live in a distinct
  ``draft`` PagePool namespace that ``leak_check`` proves never leaks
  into the prefix cache; the ``serve.draft`` chaos site proves a
  faulted draft can slow a stream but never corrupt it.

Fused decode attention lives with the other kernels
(:func:`apex_tpu.ops.paged_decode_attention` /
``ops/pallas/decode_attention.py``).  Tour: ``docs/serving.md``;
runnable train→serve round-trip: ``examples/simple/serve/``.
"""

from apex_tpu.serve.cache import (  # noqa: F401
    NULL_PAGE,
    PagePool,
    PrefixCache,
    init_kv_pages,
    prefix_keys,
)
from apex_tpu.serve.engine import (  # noqa: F401
    InferenceEngine,
    ServeConfig,
)
from apex_tpu.serve.spec import (  # noqa: F401
    SpecConfig,
    draft_from_params,
    speculative_verify,
    target_probs,
)
from apex_tpu.serve.scheduler import (  # noqa: F401
    ContinuousBatchingScheduler,
    Request,
    SHED_REASONS,
    SHED_REROUTED,
    TTFT_COMPONENTS,
    declare_serve_metrics,
    ttft_attribution,
)

__all__ = [
    "NULL_PAGE",
    "PagePool",
    "PrefixCache",
    "init_kv_pages",
    "prefix_keys",
    "InferenceEngine",
    "ServeConfig",
    "SpecConfig",
    "draft_from_params",
    "speculative_verify",
    "target_probs",
    "ContinuousBatchingScheduler",
    "Request",
    "SHED_REASONS",
    "SHED_REROUTED",
    "TTFT_COMPONENTS",
    "declare_serve_metrics",
    "ttft_attribution",
]
