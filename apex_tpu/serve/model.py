"""Functional GPT forward for serving — prefill and decode bodies.

The training stack's :class:`apex_tpu.models.gpt.GptModel` is a flax
module built for ``value_and_grad`` over a full sequence; serving needs
the same weights driven through two different dataflows — a one-shot
**prefill** that also emits every position's K/V for the cache, and a
single-token **decode** that appends to and reads from the paged cache.
This module is the functional re-expression of ``GptBlock`` /
``GptModel`` over the ``GptModel.init`` parameter tree (the scanned
stack's leaves carry a leading ``num_layers`` axis, which maps directly
onto ``lax.scan`` here), kept numerically in lockstep with the training
forward:

- same compute-dtype discipline as ``ColumnParallelLinear`` /
  ``RowParallelLinear`` at tp=1 (matmul in ``cfg.dtype`` with
  ``preferred_element_type=f32``, cast back, bias in compute dtype);
- same fused LayerNorm, same f32 RoPE rotation
  (``ops.rope._apply``'s math), same causal flash attention for
  prefill, same tied-embedding f32 logits as ``gpt._tied_vocab_logits``
  — ``tests/test_serve.py`` pins prefill/decode logits against
  ``GptModel.apply`` itself.

Serving scope: dense blocks, single model shard (no SP/CP/MoE — the
engine validates).  **Weight wires**: :func:`quantize_params` /
:func:`dequantize_params` put the large parameter leaves on the
blockwise int8 code of ``parallel/comm.py`` (small leaves — biases, LN
affines — stay exact, mirroring ``sync_gradients``'s ``min_size``
rule); the engine dequantizes inside the compiled step, so the param
HBM footprint is the wire footprint.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.models.gpt import GptConfig, _rope_cos_sin
from apex_tpu.ops.attention import flash_attention
from apex_tpu.ops.layer_norm import fused_layer_norm_affine
from apex_tpu.ops.paged_attention import paged_decode_attention
from apex_tpu.ops.rope import fused_apply_rotary_pos_emb_cached, rotate_half
from apex_tpu.parallel import comm
from apex_tpu.serve import cache as cache_lib

__all__ = [
    "validate_config",
    "rope_tables",
    "PackedWeight",
    "quantize_params",
    "dequantize_params",
    "sample_tokens",
    "prefill_body",
    "chunk_prefill_body",
    "decode_body",
]

#: leaves smaller than this stay f32 under weight_wire="int8" (biases,
#: LN affines — the same noise-sensitivity rule as comm.sync_gradients)
WEIGHT_WIRE_MIN_SIZE = 1024


def validate_config(cfg: GptConfig) -> GptConfig:
    """Serving supports the dense single-shard GPT stack."""
    if cfg.sequence_parallel or cfg.context_parallel:
        raise ValueError(
            "serving requires sequence_parallel=False and "
            "context_parallel=None (the engine owns the whole sequence)"
        )
    if cfg.num_experts:
        raise ValueError("MoE serving is not supported yet")
    return cfg


def rope_tables(cfg: GptConfig):
    """Cached f32 cos/sin ``(max_seq_len, head_dim)`` in the model's
    rotate_half layout (None for non-rotary configs)."""
    if not cfg.rotary:
        return None, None
    head_dim = cfg.hidden_size // cfg.num_heads
    return _rope_cos_sin(cfg.max_seq_len, head_dim)


# ---------------------------------------------------------------------------
# parameter access + weight wires
# ---------------------------------------------------------------------------


def _tree(params):
    return params["params"]


@jax.tree_util.register_pytree_node_class
class PackedWeight:
    """A parameter leaf on the blockwise int8 wire: the codes and f32
    scales are the traced arrays; shape/size/block/dtype ride the
    treedef as static metadata (so a jitted step sees them as
    structure, not operands)."""

    def __init__(self, codes, scale, shape, n, block, dtype):
        self.codes = codes
        self.scale = scale
        self.shape = tuple(shape)
        self.n = int(n)
        self.block = int(block)
        self.dtype = dtype

    def unpack(self):
        flat = comm.dequantize_blocks(
            self.codes, self.scale, self.block, self.n
        )
        return flat.reshape(self.shape).astype(self.dtype)

    def tree_flatten(self):
        return (self.codes, self.scale), (
            self.shape, self.n, self.block, self.dtype,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)


def _is_packed(leaf) -> bool:
    return isinstance(leaf, PackedWeight)


def quantize_params(params, *, block: int = comm.DEFAULT_BLOCK,
                    min_size: int = WEIGHT_WIRE_MIN_SIZE):
    """Pack every parameter leaf of >= ``min_size`` elements onto the
    blockwise int8 wire (flattened, ``comm.quantize_blocks``); smaller
    leaves pass through exact.  Inverse: :func:`dequantize_params`."""

    def pack(leaf):
        if leaf.size < min_size:
            return leaf
        flat = jnp.ravel(leaf).astype(jnp.float32)
        codes, scale = comm.quantize_blocks(flat, block=block)
        return PackedWeight(
            codes, scale, leaf.shape, flat.shape[0], block, leaf.dtype
        )

    return jax.tree_util.tree_map(pack, params)


def dequantize_params(params):
    """Unpack a :func:`quantize_params` tree back to dense leaves —
    called INSIDE the compiled step, so the resident format stays
    int8."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.unpack() if _is_packed(leaf) else leaf,
        params, is_leaf=_is_packed,
    )


# ---------------------------------------------------------------------------
# functional layers (numerics of the flax stack at tp=1)
# ---------------------------------------------------------------------------


def _layer_norm(x, p, eps):
    return fused_layer_norm_affine(
        x, p["scale"], p["bias"], (x.shape[-1],), eps=eps
    )


def _linear(x, p, dtype):
    """tp=1 Column/RowParallelLinear numerics: compute-dtype matmul
    with f32 accumulation, cast back, bias in compute dtype."""
    y = jnp.matmul(
        x.astype(dtype), p["weight"].astype(dtype),
        preferred_element_type=jnp.float32,
    ).astype(dtype)
    return y + p["bias"].astype(dtype)


def _embed(p, ids, dtype):
    return jnp.take(p["weight"], ids, axis=0).astype(dtype)


def _logits(tree, h, dtype):
    """Tied-embedding vocab logits (``gpt._tied_vocab_logits`` at
    tp=1): f32 output."""
    embed = tree["word_embeddings"]["weight"]
    return jnp.matmul(
        h.astype(dtype), jnp.transpose(embed).astype(dtype),
        preferred_element_type=jnp.float32,
    )


def _rope_rows(x, cos, sin):
    """f32 rotate_half rotation with PER-SEQUENCE cos/sin rows
    ``(B, D)`` broadcast over heads — ``ops.rope._apply``'s math for
    the decode step, where every sequence sits at its own position."""
    with jax.named_scope("rope_f32"):
        xf = x.astype(jnp.float32)
    out = xf * cos[:, None, :] + rotate_half(xf) * sin[:, None, :]
    return out.astype(x.dtype)


def _mlp(x, bp, cfg):
    y = _layer_norm(x, bp["ln_mlp"], cfg.layer_norm_eps)
    y = _linear(y, bp["fc1"], cfg.dtype)
    y = jax.nn.gelu(y, approximate=True)
    y = _linear(y, bp["fc2"], cfg.dtype)
    return x + y


# ---------------------------------------------------------------------------
# fused sampling: greedy / temperature / top-k inside the compiled step
# ---------------------------------------------------------------------------


def _is_key_batch(rng, logits) -> bool:
    """True when ``rng`` is a PER-SLOT key batch aligned with the
    leading (batch) dim of ``logits`` — ``(B, 2)`` raw uint32 keys, or
    ``(B,)`` typed keys — rather than one key for the whole call."""
    if logits.ndim < 2:
        return False
    if jnp.issubdtype(rng.dtype, jax.dtypes.prng_key):
        return rng.ndim == 1 and rng.shape[0] == logits.shape[0]
    return rng.ndim == 2 and rng.shape[0] == logits.shape[0]


def sample_tokens(logits, temps, rng, *, top_k: int = 0):
    """Sample next tokens INSIDE the compiled step — the host never
    round-trips the logits ("LLM Inference Acceleration via Efficient
    Operation Fusion", PAPERS.md: keep the sampling tail fused).

    ``logits`` is ``(..., V)`` f32, ``temps`` broadcasts against the
    leading dims: a slot with ``temp <= 0`` decodes greedily (argmax —
    bit-identical to the pre-sampling engine), a positive temperature
    draws via the Gumbel-argmax trick over ``logits / temp`` after the
    static ``top_k`` mask (0 = full vocab).  ``rng`` is either one key
    for the whole call (legacy) or a per-slot key batch ``(B, 2)``
    aligned with ``logits``'s batch dim — the engine's per-request
    stream keys, a function of request identity and stream position
    rather than any global call counter, so a replayed or rolled-back
    stream re-draws bit-identically."""
    temps = jnp.asarray(temps, jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    vocab = logits.shape[-1]
    masked = logits
    if 0 < top_k < vocab:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        masked = jnp.where(logits < kth, -jnp.inf, logits)
    scaled = masked / jnp.maximum(temps, 1e-6)[..., None]
    if _is_key_batch(rng, logits):
        gumbel = jax.vmap(
            lambda kk: jax.random.gumbel(
                kk, logits.shape[1:], dtype=jnp.float32
            )
        )(rng)
    else:
        gumbel = jax.random.gumbel(rng, logits.shape, dtype=jnp.float32)
    sampled = jnp.argmax(scaled + gumbel, axis=-1).astype(jnp.int32)
    return jnp.where(temps > 0, sampled, greedy)


# ---------------------------------------------------------------------------
# prefill: full-sequence forward that also yields per-position K/V
# ---------------------------------------------------------------------------


def _prefill_block(cfg: GptConfig, bp, x, cos, sin):
    """One decoder block over ``x`` (S, B, hidden); returns the new
    hidden and this layer's rotated K + V as ``(B, H, S, D)``."""
    heads = cfg.num_heads
    head_dim = cfg.hidden_size // heads
    y = _layer_norm(x, bp["ln_attn"], cfg.layer_norm_eps)
    qkv = _linear(y, bp["qkv"], cfg.dtype)
    s, b = qkv.shape[0], qkv.shape[1]
    qkv = qkv.reshape(s, b, heads, 3, head_dim)
    q, k, v = (
        jnp.transpose(qkv[:, :, :, i], (1, 2, 0, 3)) for i in range(3)
    )
    if cfg.rotary:
        q = fused_apply_rotary_pos_emb_cached(q, cos, sin)
        k = fused_apply_rotary_pos_emb_cached(k, cos, sin)
    ctx = flash_attention(q, k, v, causal=True, scale=head_dim**-0.5)
    ctx = jnp.transpose(ctx, (2, 0, 1, 3)).reshape(s, b, heads * head_dim)
    attn = _linear(ctx, bp["out"], cfg.dtype)
    x = x + attn
    return _mlp(x, bp, cfg), (k, v)


def prefill_body(
    cfg: GptConfig,
    params,
    kv_pages: dict,
    tokens,          # (S, 1) int32 — one sequence, bucket-padded
    length,          # ()    int32 — live prompt positions
    page_ids,        # (S/page,) int32 — null-page entries pad the tail
    temp=None,       # ()    f32 sampling temperature (None = argmax)
    rng=None,        # PRNG key for the fused sampler
    *,
    page_size: int,
    kv_wire: str = "f32",
    top_k: int = 0,
):
    """Full prefill: forward the (padded) prompt, write every layer's
    K/V into the assigned pages, and return the last live position's
    logits.  Causality makes the padding free: a live query row never
    attends a padded (later) key, so the padded tail needs no mask —
    its garbage K/V land in pages the decode ``lengths`` never reads
    (or in the null page).

    Returns ``(logits (V,) f32, next_token () int32, finite () bool,
    kv_pages)`` — ``finite`` is the in-step non-finite screen
    (``isfinite(logits).all()``): the quarantine evidence the scheduler
    reads WITHOUT paying the (V,) device→host logits copy.
    """
    params = dequantize_params(params)
    tree = _tree(params)
    x = _embed(tree["word_embeddings"], tokens, cfg.dtype)  # (S, 1, h)
    s = tokens.shape[0]
    head_dim = cfg.hidden_size // cfg.num_heads
    cos = sin = None
    if cfg.rotary:
        cos, sin = _rope_cos_sin(s, head_dim)
    else:
        pos = tree["position_embeddings"][:s]
        x = x + pos[:, None, :].astype(cfg.dtype)

    bp = tree["layers"]["block"]

    def layer(carry, xs):
        x, new = _prefill_block(cfg, xs, carry, cos, sin)
        return x, new

    x, (k_all, v_all) = jax.lax.scan(layer, x, bp)
    # (L, 1, H, S, D) -> per-position rows (L, S, H, D) -> page blocks
    k_all = jnp.transpose(k_all[:, 0], (0, 2, 1, 3))
    v_all = jnp.transpose(v_all[:, 0], (0, 2, 1, 3))
    k_blocks = jax.vmap(
        lambda t: cache_lib.pack_prompt_pages(t, page_size)
    )(k_all)
    v_blocks = jax.vmap(
        lambda t: cache_lib.pack_prompt_pages(t, page_size)
    )(v_all)
    if kv_wire == "int8":
        k_codes, k_scale = cache_lib.encode_kv(k_blocks)
        v_codes, v_scale = cache_lib.encode_kv(v_blocks)
        kv_pages = dict(
            kv_pages,
            k=cache_lib.write_prompt_pages(kv_pages["k"], k_codes, page_ids),
            v=cache_lib.write_prompt_pages(kv_pages["v"], v_codes, page_ids),
            k_scale=cache_lib.write_prompt_pages(
                kv_pages["k_scale"], k_scale, page_ids
            ),
            v_scale=cache_lib.write_prompt_pages(
                kv_pages["v_scale"], v_scale, page_ids
            ),
        )
    else:
        kv_pages = dict(
            kv_pages,
            k=cache_lib.write_prompt_pages(kv_pages["k"], k_blocks, page_ids),
            v=cache_lib.write_prompt_pages(kv_pages["v"], v_blocks, page_ids),
        )

    h_last = jax.lax.dynamic_slice_in_dim(
        x[:, 0], jnp.maximum(length - 1, 0), 1, 0
    )  # (1, hidden)
    h_last = _layer_norm(h_last, tree["ln_f"], cfg.layer_norm_eps)
    logits = _logits(tree, h_last, cfg.dtype)[0]  # (V,) f32
    if rng is None:
        next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        next_token = sample_tokens(logits, temp, rng, top_k=top_k)
    finite = jnp.isfinite(logits).all()
    return logits, next_token, finite, kv_pages


# ---------------------------------------------------------------------------
# chunked prefill: a page-multiple prompt slice with carry-in KV offset
# ---------------------------------------------------------------------------


def _dequant_rows(codes, scale):
    """(..., page, D) int8 codes + (..., page) f32 scales -> f32 rows
    (the comm codec at block = D: one scale per row)."""
    return codes.astype(jnp.float32) * scale[..., None]


def chunk_prefill_body(
    cfg: GptConfig,
    params,
    kv_pages: dict,
    tokens,          # (C, 1) int32 — one chunk, bucket-padded
    length,          # ()     int32 — live tokens in THIS chunk
    offset,          # ()     int32 — absolute position of tokens[0]
    chunk_page_ids,  # (C/page,) int32 — null entries skip the write
                     # (cached pages a borrower must never rewrite)
    page_table,      # (NP,)  int32 — the request's full page table
    temp=None,       # ()     f32 sampling temperature (None = argmax)
    rng=None,        # PRNG key for the fused sampler
    *,
    page_size: int,
    kv_wire: str = "f32",
    top_k: int = 0,
):
    """One page-multiple prefill chunk with **carry-in KV offset**: the
    chunk's queries attend to every position before ``offset`` through
    the paged cache (a dense gather over ``page_table`` — committed
    prefix-cache pages and this request's own earlier chunks read the
    same way) plus the in-chunk keys causally.  Writes the chunk's K/V
    to ``chunk_page_ids``; entries pointing at the null page skip
    pages a borrowed cache run already holds (re-running the final
    chunk of a full-prefix hit recomputes the first token's logits
    WITHOUT touching shared pages).

    The chunk slicing is deterministic, so a cache-hit request that
    re-runs the same final chunk over bit-identical cached pages
    produces bit-identical logits to the cold run — the foundation of
    the serve_bench bit-identity proof.

    Returns ``(logits (V,) f32, next_token () int32, finite () bool,
    kv_pages)`` for the LAST live chunk position (only the final chunk's
    token is consumed; earlier chunks run for their KV writes).
    """
    params = dequantize_params(params)
    tree = _tree(params)
    x = _embed(tree["word_embeddings"], tokens, cfg.dtype)  # (C, 1, h)
    c = tokens.shape[0]
    heads = cfg.num_heads
    head_dim = cfg.hidden_size // heads
    positions = offset + jnp.arange(c, dtype=jnp.int32)
    cos_rows = sin_rows = None
    if cfg.rotary:
        cos_t, sin_t = _rope_cos_sin(cfg.max_seq_len, head_dim)
        cos_rows = jnp.take(cos_t, positions, axis=0)  # (C, D)
        sin_rows = jnp.take(sin_t, positions, axis=0)
    else:
        rows = jnp.take(tree["position_embeddings"], positions, axis=0)
        x = x + rows[:, None, :].astype(cfg.dtype)

    bp = tree["layers"]["block"]
    int8 = kv_wire == "int8"
    t_ctx = page_table.shape[0] * page_size
    # carry-in mask: gathered row t is absolute position t of this
    # sequence; only positions before the chunk are valid carry
    carry_valid = jnp.arange(t_ctx) < offset          # (T,)
    causal = (
        jnp.arange(c)[:, None] >= jnp.arange(c)[None, :]
    )                                                  # (C, C) in-chunk
    mask = jnp.concatenate(
        [jnp.broadcast_to(carry_valid[None, :], (c, t_ctx)), causal],
        axis=1,
    )[None]                                            # (1, C, T+C)
    scale = head_dim**-0.5
    big_neg = jnp.asarray(jnp.finfo(jnp.float32).min, jnp.float32)
    xs = (bp, kv_pages["k"], kv_pages["v"]) + (
        (kv_pages["k_scale"], kv_pages["v_scale"]) if int8 else ()
    )

    def layer(x, xs):
        if int8:
            lp, k_l, v_l, ks_l, vs_l = xs
        else:
            lp, k_l, v_l = xs
            ks_l = vs_l = None
        y = _layer_norm(x, lp["ln_attn"], cfg.layer_norm_eps)
        qkv = _linear(y, lp["qkv"], cfg.dtype)
        qkv = qkv.reshape(c, 1, heads, 3, head_dim)
        q, k, v = (
            jnp.transpose(qkv[:, :, :, i], (1, 2, 0, 3)) for i in range(3)
        )  # (1, H, C, D)
        if cfg.rotary:
            q = fused_apply_rotary_pos_emb_cached(q, cos_rows, sin_rows)
            k = fused_apply_rotary_pos_emb_cached(k, cos_rows, sin_rows)
        # carry-in K/V: dense gather of the whole page table, read
        # through the cache wire (exactly how decode reads it)
        if int8:
            k_ctx = _dequant_rows(k_l[page_table], ks_l[page_table])
            v_ctx = _dequant_rows(v_l[page_table], vs_l[page_table])
        else:
            k_ctx = k_l[page_table].astype(jnp.float32)
            v_ctx = v_l[page_table].astype(jnp.float32)
        # (NP, H, page, D) -> (H, T, D) in absolute position order
        k_ctx = jnp.transpose(k_ctx, (1, 0, 2, 3)).reshape(
            heads, t_ctx, head_dim
        )
        v_ctx = jnp.transpose(v_ctx, (1, 0, 2, 3)).reshape(
            heads, t_ctx, head_dim
        )
        # in-chunk keys stay exact (the same in-flight numerics the
        # monolithic prefill uses for every prompt position)
        kf = k[0].astype(jnp.float32)                  # (H, C, D)
        vf = v[0].astype(jnp.float32)
        k_all = jnp.concatenate([k_ctx, kf], axis=1)   # (H, T+C, D)
        v_all = jnp.concatenate([v_ctx, vf], axis=1)
        qf = q[0].astype(jnp.float32)                  # (H, C, D)
        scores = jnp.einsum("hcd,htd->hct", qf, k_all) * scale
        scores = jnp.where(mask, scores, big_neg)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("hct,htd->hcd", probs, v_all)  # (H, C, D)
        ctx = jnp.transpose(ctx, (1, 0, 2)).reshape(
            c, 1, heads * head_dim
        ).astype(cfg.dtype)
        x = x + _linear(ctx, lp["out"], cfg.dtype)
        x = _mlp(x, lp, cfg)
        # write the chunk's K/V pages (null entries dump cached pages'
        # re-runs into write-only garbage)
        k_rows = jnp.transpose(k[0], (1, 0, 2))        # (C, H, D)
        v_rows = jnp.transpose(v[0], (1, 0, 2))
        k_blocks = cache_lib.pack_prompt_pages(k_rows, page_size)
        v_blocks = cache_lib.pack_prompt_pages(v_rows, page_size)
        if int8:
            k_codes, k_sc = cache_lib.encode_kv(k_blocks)
            v_codes, v_sc = cache_lib.encode_kv(v_blocks)
            k_l = k_l.at[chunk_page_ids].set(k_codes.astype(k_l.dtype))
            v_l = v_l.at[chunk_page_ids].set(v_codes.astype(v_l.dtype))
            ks_l = ks_l.at[chunk_page_ids].set(k_sc)
            vs_l = vs_l.at[chunk_page_ids].set(v_sc)
            return x, (k_l, v_l, ks_l, vs_l)
        k_l = k_l.at[chunk_page_ids].set(k_blocks.astype(k_l.dtype))
        v_l = v_l.at[chunk_page_ids].set(v_blocks.astype(v_l.dtype))
        return x, (k_l, v_l)

    x, new = jax.lax.scan(layer, x, xs)
    if int8:
        kv_pages = dict(
            kv_pages, k=new[0], v=new[1], k_scale=new[2], v_scale=new[3]
        )
    else:
        kv_pages = dict(kv_pages, k=new[0], v=new[1])

    h_last = jax.lax.dynamic_slice_in_dim(
        x[:, 0], jnp.maximum(length - 1, 0), 1, 0
    )  # (1, hidden)
    h_last = _layer_norm(h_last, tree["ln_f"], cfg.layer_norm_eps)
    logits = _logits(tree, h_last, cfg.dtype)[0]  # (V,) f32
    if rng is None:
        next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        next_token = sample_tokens(logits, temp, rng, top_k=top_k)
    finite = jnp.isfinite(logits).all()
    return logits, next_token, finite, kv_pages


# ---------------------------------------------------------------------------
# decode: one token per running sequence through the paged cache
# ---------------------------------------------------------------------------


def _decode_step(
    cfg: GptConfig,
    tree,         # dequantized ``params["params"]`` tree
    kv_pages: dict,
    tokens,       # (B,) int32 — current token per slot
    lengths,      # (B,) int32 — context length AFTER this token; 0 = idle
    page_tables,  # (B, NP) int32
    *,
    page_size: int,
    kv_wire: str = "f32",
):
    """The shared decode compute: embed the token column, append each
    layer's K/V at this position's page slot, run the fused paged
    attention, and return the final-LN logits.  This ONE function is
    what both the plain decode program and the speculative verify scan
    (:func:`apex_tpu.serve.spec.verify_body`) execute — same math,
    same shapes, same kernels — which is precisely why a greedy
    speculative stream is bit-identical to the sequential baseline by
    construction.  Returns ``(logits (B, V) f32, kv_pages)``."""
    b = tokens.shape[0]
    heads = cfg.num_heads
    head_dim = cfg.hidden_size // heads
    x = _embed(tree["word_embeddings"], tokens, cfg.dtype)  # (B, hidden)

    pos = jnp.maximum(lengths - 1, 0)  # this token's position; idle -> 0
    page_ids = page_tables[jnp.arange(b), pos // page_size]  # (B,)
    slots = pos % page_size
    cos_rows = sin_rows = None
    if cfg.rotary:
        cos_t, sin_t = _rope_cos_sin(cfg.max_seq_len, head_dim)
        cos_rows = jnp.take(cos_t, pos, axis=0)  # (B, D)
        sin_rows = jnp.take(sin_t, pos, axis=0)
    else:
        rows = jnp.take(tree["position_embeddings"], pos, axis=0)
        x = x + rows.astype(cfg.dtype)

    bp = tree["layers"]["block"]
    int8 = kv_wire == "int8"
    xs = (bp, kv_pages["k"], kv_pages["v"]) + (
        (kv_pages["k_scale"], kv_pages["v_scale"]) if int8 else ()
    )

    def layer(x, xs):
        if int8:
            lp, k_l, v_l, ks_l, vs_l = xs
        else:
            lp, k_l, v_l = xs
            ks_l = vs_l = None
        y = _layer_norm(x, lp["ln_attn"], cfg.layer_norm_eps)
        qkv = _linear(y, lp["qkv"], cfg.dtype).reshape(
            b, heads, 3, head_dim
        )
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (B, H, D)
        if cfg.rotary:
            k = _rope_rows(k, cos_rows, sin_rows)
        if int8:
            k_codes, k_sc = cache_lib.encode_kv(k)
            v_codes, v_sc = cache_lib.encode_kv(v)
            k_l = cache_lib.append_token_kv(k_l, k_codes, page_ids, slots)
            v_l = cache_lib.append_token_kv(v_l, v_codes, page_ids, slots)
            ks_l = cache_lib.append_token_kv(ks_l, k_sc, page_ids, slots)
            vs_l = cache_lib.append_token_kv(vs_l, v_sc, page_ids, slots)
        else:
            k_l = cache_lib.append_token_kv(k_l, k, page_ids, slots)
            v_l = cache_lib.append_token_kv(v_l, v, page_ids, slots)
        ctx = paged_decode_attention(
            q, k_l, v_l, page_tables, lengths,
            scale=head_dim**-0.5,
            k_scale=ks_l, v_scale=vs_l,
            rope_cos=cos_rows if cfg.rotary else None,
            rope_sin=sin_rows if cfg.rotary else None,
        )
        ctx = ctx.astype(cfg.dtype).reshape(b, heads * head_dim)
        x = x + _linear(ctx, lp["out"], cfg.dtype)
        x = _mlp(x, lp, cfg)
        return x, (k_l, v_l, ks_l, vs_l) if int8 else (k_l, v_l)

    x, new = jax.lax.scan(layer, x, xs)
    if int8:
        kv_pages = dict(
            kv_pages, k=new[0], v=new[1], k_scale=new[2], v_scale=new[3]
        )
    else:
        kv_pages = dict(kv_pages, k=new[0], v=new[1])

    h = _layer_norm(x, tree["ln_f"], cfg.layer_norm_eps)
    logits = _logits(tree, h, cfg.dtype)  # (B, V) f32
    return logits, kv_pages


def decode_body(
    cfg: GptConfig,
    params,
    kv_pages: dict,
    tokens,       # (B,) int32 — current token per slot
    lengths,      # (B,) int32 — context length AFTER this token; 0 = idle
    page_tables,  # (B, NP) int32
    temps=None,   # (B,) f32 per-slot sampling temperature (None = argmax)
    rng=None,     # PRNG key (or per-slot key batch) for the sampler
    *,
    page_size: int,
    kv_wire: str = "f32",
    top_k: int = 0,
):
    """One continuous-batching decode iteration over the full slot
    array (:func:`_decode_step` plus the fused sampling tail).  Per
    layer: project the token, rotate K, append K/V to this position's
    page slot, and run the fused single-query paged attention (query
    RoPE + int8 dequant fused in the kernel).  Idle slots
    (``lengths == 0``) write into the null page and read zeros.

    Returns ``(logits (B, V) f32, next_tokens (B,) int32, finite (B,)
    bool, kv_pages)`` — ``finite[b]`` is slot ``b``'s in-step
    non-finite screen over its logits row: a poisoned sequence (NaN in
    its KV pages or a numerically blown state) flags ONLY its own
    slot, so the scheduler's quarantine can evict the offender without
    touching the rest of the batch or reading the (B, V) logits back.
    """
    params = dequantize_params(params)
    tree = _tree(params)
    logits, kv_pages = _decode_step(
        cfg, tree, kv_pages, tokens, lengths, page_tables,
        page_size=page_size, kv_wire=kv_wire,
    )
    if rng is None:
        next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        next_tokens = sample_tokens(logits, temps, rng, top_k=top_k)
    finite = jnp.isfinite(logits).all(axis=-1)
    return logits, next_tokens, finite, kv_pages
