"""Paged KV cache — block-pooled pages so memory scales with live tokens.

A serving process admits and retires sequences continuously; a
contiguous per-sequence KV buffer sized for the maximum context would
waste ``max_context - live`` slots per sequence and make admission a
memory-compaction problem.  The paged design (vLLM's PagedAttention,
PAPERS.md "LLM Inference Acceleration via Efficient Operation Fusion"
motivates the fused read side) splits the cache into fixed-size
**pages** drawn from one shared pool:

- **device side** — one pool per layer, stacked: ``k``/``v`` arrays of
  shape ``(L, P, H, page, D)`` (heads OUTSIDE the page dim — the layout
  :func:`apex_tpu.ops.paged_decode_attention` contracts with no
  transposes).  With ``kv_wire="int8"`` the pools hold blockwise int8
  codes plus f32 scale planes ``(L, P, H, page)`` — one scale per
  (head, token) row at ``block = head_dim``, the exact
  ``parallel/comm.py`` codec (:func:`~apex_tpu.parallel.comm.
  quantize_blocks`), so the KV wire format is the same code the
  gradient wire uses.
- **host side** — :class:`PagePool`, a free-list allocator.  Page 0 is
  the reserved **null page**: page-table entries beyond a sequence's
  live count point at it, padded prefill tails scatter into it, and
  idle decode slots append into it — it is write-only garbage that the
  ``lengths`` masking guarantees is never read.

There is no defragmentation pass and none is needed: pages are
fixed-size and fully owned by one sequence, so freeing a sequence
returns its pages to the free list with zero compaction — occupancy is
exactly ``live_pages / usable_pages`` at all times.

The device-side write helpers here are pure functions meant to be
called INSIDE the engine's jitted step programs; the engine donates the
cache arrays so the scatters update pages in place
(``analysis.check``'s donation lint proves the aliasing at build).
"""

from __future__ import annotations

import hashlib
from collections import Counter
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from apex_tpu.parallel import comm

__all__ = [
    "NULL_PAGE",
    "PagePool",
    "PrefixCache",
    "prefix_keys",
    "init_kv_pages",
    "encode_kv",
    "pack_prompt_pages",
    "write_prompt_pages",
    "append_token_kv",
]

#: page 0 — never allocated; the write-only garbage target for padded
#: tails and idle slots
NULL_PAGE = 0


class PagePool:
    """Host-side free-list allocator over ``num_pages`` device pages.

    Page 0 (:data:`NULL_PAGE`) is reserved, so ``num_pages - 1`` pages
    are usable.  ``alloc`` is all-or-nothing: a request that cannot get
    every page it asked for gets none (no partial admissions to later
    roll back — the scheduler's shedding logic stays trivial).

    Pages are **refcounted**: ``alloc`` hands a page out at refcount 1,
    :meth:`share` adds a reference (a prefix-cache borrow or the
    cache's own hold on a committed run), and :meth:`free` RELEASES one
    reference — the page returns to the free list only when the last
    holder lets go.  Every existing free path (retire, shed, reroute)
    is therefore automatically safe for shared pages: a retried request
    that borrowed cached pages decrements, it never yanks pages a
    co-rider still reads.
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.num_pages = num_pages
        self.page_size = page_size
        # LIFO free list: recently freed pages are re-used first (their
        # content is dead by construction, and re-use keeps the touched
        # working set small)
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        #: allocated page -> reference count (absent = free)
        self._refs: Dict[int, int] = {}
        #: allocated page -> namespace tag (absent = free).  The
        #: default namespace is ``"kv"`` (target-model KV); a
        #: speculative engine allocates its draft-model pages under
        #: ``"draft"`` so :meth:`leak_check` can prove draft pages
        #: never reach the prefix cache (a draft page's content is a
        #: DIFFERENT model's KV — sharing it into the target cache
        #: would corrupt every borrower bit-exactly enough to be
        #: missed by shape checks).
        self._ns: Dict[int, str] = {}

    @property
    def usable(self) -> int:
        return self.num_pages - 1

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.usable - self.available

    def occupancy(self) -> float:
        """Live fraction of the usable pool (0..1)."""
        return self.in_use / self.usable

    def pages_for(self, tokens: int) -> int:
        """Pages needed to hold ``tokens`` KV positions."""
        return -(-max(tokens, 0) // self.page_size)

    def alloc(self, n: int, ns: str = "kv") -> Optional[List[int]]:
        """``n`` pages, or None when the pool cannot cover all of them
        (all-or-nothing; never hands out :data:`NULL_PAGE`).  ``ns``
        tags the pages with a namespace (``"kv"`` target KV —
        the default — or ``"draft"`` for speculative-draft KV); the
        tag rides the page until its last reference is freed."""
        if n < 0:
            raise ValueError("cannot allocate a negative page count")
        if n > len(self._free):
            return None
        taken = [self._free.pop() for _ in range(n)]
        for p in taken:
            self._refs[p] = 1
            self._ns[p] = ns
        return taken

    def namespace(self, page: int) -> Optional[str]:
        """The namespace tag of an allocated page (None = free)."""
        return self._ns.get(page)

    def share(self, pages: List[int]) -> None:
        """Add one reference per page (a prefix-cache borrow, or the
        cache's own hold on a freshly committed run).  Sharing a page
        that is not allocated is a bug loud enough to raise."""
        for p in pages:
            if p not in self._refs:
                raise ValueError(f"cannot share unallocated page {p}")
        for p in pages:
            self._refs[p] += 1

    def refcount(self, page: int) -> int:
        """Current reference count of ``page`` (0 = free)."""
        return self._refs.get(page, 0)

    def free(self, pages: List[int]) -> None:
        """Release one reference per page; a page returns to the free
        list only at refcount 0 (shared pages survive their
        co-holders' frees)."""
        for p in pages:
            if not 0 < p < self.num_pages:
                raise ValueError(f"page {p} is not an allocatable page id")
            if p not in self._refs:
                raise ValueError(f"double free of page {p}")
        for p in pages:
            r = self._refs[p] - 1
            if r:
                self._refs[p] = r
            else:
                del self._refs[p]
                self._ns.pop(p, None)
                self._free.append(p)

    def leak_check(self, owned, cached=()) -> None:
        """Assert the pool's accounting is EXACT against the live
        ownership ledger: every allocated page's refcount equals the
        number of live holders claiming it, and every claimed page is
        allocated.

        ``owned`` is an iterable of per-request page lists (the
        scheduler's slots + retrying queue entries); ``cached`` is the
        prefix cache's committed-run pages (each entry holds exactly
        one reference of its own).  Raises ``ValueError`` naming the
        leaked (refcounted above the ownership ledger — e.g. allocated
        but unowned), foreign (claimed but not allocated), or
        double-owned (claimed by more holders than references — a
        duplicate claim that never went through :meth:`share`) pages —
        the invariant the serving chaos drill re-proves after every
        injected fault (docs/serving.md "Failure semantics")."""
        want: Counter = Counter()
        for pages in owned:
            want.update(pages)
        want.update(cached)
        problems = []
        over = sorted(p for p, c in want.items()
                      if c > self._refs.get(p, 0) and p in self._refs)
        if over:
            problems.append(f"pages owned by more than one request "
                            f"without a shared reference: {over}")
        leaked = sorted(p for p, r in self._refs.items() if r > want[p])
        foreign = sorted(set(want) - set(self._refs))
        if leaked:
            problems.append(
                f"leaked pages (allocated references owned by no live "
                f"request or cache entry): {leaked}"
            )
        if foreign:
            problems.append(
                f"foreign pages (owned but not allocated): "
                f"{foreign}"
            )
        draft_cached = sorted(
            p for p in cached if self._ns.get(p, "kv") != "kv"
        )
        if draft_cached:
            problems.append(
                f"draft-namespace pages shared into the prefix cache: "
                f"{draft_cached}"
            )
        if problems:
            raise ValueError(
                "PagePool leak check failed: " + "; ".join(problems)
            )


# ---------------------------------------------------------------------------
# cross-request prefix cache: content hash -> committed KV page run
# ---------------------------------------------------------------------------


def prefix_keys(prompt, page_size: int) -> List[Tuple[bytes, int]]:
    """Chained page-granularity content keys for a prompt:
    ``key_i = H(key_{i-1} || tokens[i*page:(i+1)*page])`` — a page's key
    commits to EVERY token before it, so two prompts share a key iff
    they share the whole prefix up to that page.  The final partial
    page (if any) gets a key too: only a whole-prompt hit can reuse a
    partially-filled tail page, because its content embeds the exact
    partial token run.  Returns ``[(key, tokens_through_here), ...]``.
    """
    out: List[Tuple[bytes, int]] = []
    key = b"apex-prefix-v1"
    for start in range(0, len(prompt), page_size):
        block = np.asarray(prompt[start:start + page_size], np.int32)
        key = hashlib.blake2b(
            key + block.tobytes(), digest_size=16
        ).digest()
        out.append((key, start + len(block)))
    return out


class _CacheEntry:
    __slots__ = ("key", "page", "tokens", "parent", "children", "tick")

    def __init__(self, key, page, tokens, parent, tick):
        self.key = key
        self.page = page          # the committed device page id
        self.tokens = tokens      # prompt tokens through this page
        self.parent = parent      # previous key in the chain (or None)
        self.children = 0         # cached entries chaining through us
        self.tick = tick          # LRU clock

    def __repr__(self):
        return (f"_CacheEntry(page={self.page}, tokens={self.tokens}, "
                f"children={self.children}, tick={self.tick})")


class PrefixCache:
    """Content-addressed map from chained prompt-prefix hashes to
    committed KV page runs in one :class:`PagePool`.

    - **commit** — after a prompt's prefill completes (and before its
      first decode append), each of its pages is published under its
      chain key with one cache-owned :meth:`PagePool.share` reference,
      so the run outlives the committing request.
    - **match** — an admitted prompt walks its key chain for the
      longest cached run; :meth:`borrow` adds one reference per page
      for the borrower (released by the borrower's ordinary
      ``pool.free`` on retire/shed/retry — refcounts make every
      existing free path shared-safe).
    - **copy-on-write** — fully-filled shared pages are never written
      again (decode appends land past them), so they are shared
      forever; a shared partially-filled TAIL page is forked by the
      scheduler before its first append (``refcount > 1`` at the
      append page is the trigger).
    - **eviction** — :meth:`evict` frees least-recently-used entries
      with NO borrowers (pool refcount 1 = the cache's own reference),
      leaf-first along the chain so a parent with a cached child is
      never evicted from under it.

    The cache is host-side bookkeeping only; page content lives in the
    engine's donated KV arrays and is never touched here.
    """

    def __init__(self, pool: PagePool):
        self.pool = pool
        self.entries: Dict[bytes, _CacheEntry] = {}
        self._tick = 0
        # cumulative ledger (the scheduler mirrors these to counters)
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.evictions = 0
        self.commits = 0

    def __len__(self) -> int:
        return len(self.entries)

    def cached_pages(self) -> List[int]:
        """Pages the cache holds a reference on (the ``cached=`` arm of
        :meth:`PagePool.leak_check`)."""
        return [e.page for e in self.entries.values()]

    # -- lookup ------------------------------------------------------------
    def _walk(self, prompt) -> List[_CacheEntry]:
        """Longest cached run from page 0: consecutive full-page
        entries, plus the partial tail entry only when everything
        before it matched (a tail key embeds the whole prompt)."""
        run: List[_CacheEntry] = []
        for key, _end in prefix_keys(prompt, self.pool.page_size):
            e = self.entries.get(key)
            if e is None:
                break
            run.append(e)
        return run

    def peek_tokens(self, prompt) -> int:
        """Match length in tokens WITHOUT touching LRU state or
        borrowing — the router's affinity probe."""
        run = self._walk(prompt)
        return run[-1].tokens if run else 0

    def match(self, prompt) -> Tuple[List[int], int]:
        """``(pages, tokens)`` of the longest cached prefix run
        (LRU-touched).  The pages are NOT yet borrowed — call
        :meth:`borrow` once the request's remaining allocation
        succeeded (all-or-nothing admission must not hold references
        it may have to unwind)."""
        run = self._walk(prompt)
        self._tick += 1
        if not run:
            self.misses += 1
            return [], 0
        for e in run:
            e.tick = self._tick
        self.hits += 1
        self.hit_tokens += run[-1].tokens
        return [e.page for e in run], run[-1].tokens

    def borrow(self, pages: List[int]) -> None:
        """One reference per matched page for the borrowing request —
        from here on the borrower's normal ``pool.free`` is the
        release."""
        self.pool.share(pages)

    # -- publication -------------------------------------------------------
    def commit(self, prompt, pages: List[int]) -> int:
        """Publish a prefilled prompt's pages under their chain keys
        (one cache-owned reference each); keys already cached keep
        their incumbent page (two racing cold prefills of the same
        prompt do not double-publish).  The chain stops at the first
        key whose incumbent differs from ours — a child entry must
        chain through OUR parent pages or a later match would stitch
        pages from different runs.  Returns the number of new
        entries."""
        self._tick += 1
        added = 0
        parent = None
        for (key, end), page in zip(
            prefix_keys(prompt, self.pool.page_size), pages
        ):
            e = self.entries.get(key)
            if e is not None:
                e.tick = self._tick
                if e.page != page:
                    # an equivalent run is already published; our copy
                    # of the suffix would chain through pages the
                    # cached parent run does not reference
                    break
                parent = key
                continue
            self.pool.share([page])
            self.entries[key] = _CacheEntry(
                key, page, end, parent, self._tick
            )
            if parent is not None:
                self.entries[parent].children += 1
            parent = key
            added += 1
        if added:
            self.commits += 1
        return added

    # -- eviction ----------------------------------------------------------
    def _evictable(self) -> List[_CacheEntry]:
        """Leaf entries (no cached children) with no live borrowers
        (pool refcount 1 = only the cache's own reference), oldest
        first."""
        return sorted(
            (e for e in self.entries.values()
             if e.children == 0 and self.pool.refcount(e.page) == 1),
            key=lambda e: (e.tick, e.page),
        )

    def _drop(self, e: _CacheEntry) -> None:
        del self.entries[e.key]
        if e.parent is not None and e.parent in self.entries:
            self.entries[e.parent].children -= 1
        self.pool.free([e.page])
        self.evictions += 1

    def evict(self, need: Optional[int] = None) -> int:
        """Free least-recently-used borrower-free cached pages until
        ``need`` pages came back to the pool (None = everything
        evictable).  A parent whose last cached child is evicted
        becomes a leaf and is considered in the same sweep.  Entries
        with live borrowers are NEVER evicted — a borrowed stream's
        pages stay resident by construction.  Returns pages freed."""
        freed = 0
        while need is None or freed < need:
            cands = self._evictable()
            if not cands:
                break
            take = cands if need is None else cands[: need - freed]
            for e in take:
                self._drop(e)
                freed += 1
                if need is not None and freed >= need:
                    break
        return freed

    def flush(self) -> int:
        """Teardown (drain seal / replica evacuation): release EVERY
        cache-owned reference unconditionally — entries with live
        borrowers only drop the cache's hold, the borrowers' own
        references keep those pages allocated.  Returns the entry
        count released."""
        n = len(self.entries)
        for e in list(self.entries.values()):
            self.pool.free([e.page])
        self.entries.clear()
        self.evictions += n
        return n


# ---------------------------------------------------------------------------
# device-side pure helpers (called inside the engine's jitted steps)
# ---------------------------------------------------------------------------


def init_kv_pages(
    num_layers: int,
    num_pages: int,
    num_heads: int,
    page_size: int,
    head_dim: int,
    *,
    dtype=jnp.bfloat16,
    kv_wire: str = "f32",
) -> dict:
    """Fresh zeroed pool arrays: ``{"k", "v"}`` of ``(L, P, H, page,
    D)``, plus ``{"k_scale", "v_scale"}`` ``(L, P, H, page)`` f32 planes
    under ``kv_wire="int8"`` (codes then carry dtype int8)."""
    if kv_wire not in ("f32", "int8"):
        raise ValueError(f"kv_wire must be 'f32' or 'int8', got {kv_wire!r}")
    shape = (num_layers, num_pages, num_heads, page_size, head_dim)
    store = jnp.int8 if kv_wire == "int8" else dtype
    cache = {
        "k": jnp.zeros(shape, store),
        "v": jnp.zeros(shape, store),
    }
    if kv_wire == "int8":
        # two DISTINCT buffers: the engine donates the whole cache
        # tree, and donating one shared buffer twice is a runtime error
        cache["k_scale"] = jnp.ones(shape[:-1], jnp.float32)
        cache["v_scale"] = jnp.ones(shape[:-1], jnp.float32)
    return cache


def encode_kv(x):
    """Blockwise int8 codes + scales for KV rows ``(..., D)`` — the
    ``parallel/comm.py`` codec at ``block = D`` (one f32 scale per
    (head, token) row; an all-zero row gets scale 1.0, so the null page
    stays NaN-free)."""
    d = x.shape[-1]
    codes, scale = comm.quantize_blocks(x.astype(jnp.float32), block=d)
    return codes, scale[..., 0]


def pack_prompt_pages(kv, page_size: int):
    """``(S, H, D)`` per-position rows -> ``(NP, H, page, D)`` page
    blocks (``S`` must be a page multiple — prefill buckets are)."""
    s, h, d = kv.shape
    if s % page_size:
        raise ValueError(f"prompt length {s} is not a page multiple")
    return jnp.transpose(
        kv.reshape(s // page_size, page_size, h, d), (0, 2, 1, 3)
    )


def write_prompt_pages(pages, new, page_ids):
    """Scatter layer-stacked page blocks ``new`` ``(L, NP, H, page,
    D[, ...])`` into the pool ``pages`` ``(L, P, H, page, D[, ...])`` at
    ``page_ids`` ``(NP,)``.  Entries pointing at the null page dump the
    padded tail there (never read back)."""
    return pages.at[:, page_ids].set(new.astype(pages.dtype))


def append_token_kv(pages, rows, page_ids, slots):
    """Scatter one token's rows ``(B, H, D[, ...])`` into ``pages``
    ``(P, H, page, D[, ...])`` at ``(page_ids[b], slots[b])`` per
    sequence — the per-layer decode append (idle slots target the null
    page)."""
    return pages.at[page_ids, :, slots].set(rows.astype(pages.dtype))
