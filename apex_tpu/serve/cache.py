"""Paged KV cache — block-pooled pages so memory scales with live tokens.

A serving process admits and retires sequences continuously; a
contiguous per-sequence KV buffer sized for the maximum context would
waste ``max_context - live`` slots per sequence and make admission a
memory-compaction problem.  The paged design (vLLM's PagedAttention,
PAPERS.md "LLM Inference Acceleration via Efficient Operation Fusion"
motivates the fused read side) splits the cache into fixed-size
**pages** drawn from one shared pool:

- **device side** — one pool per layer, stacked: ``k``/``v`` arrays of
  shape ``(L, P, H, page, D)`` (heads OUTSIDE the page dim — the layout
  :func:`apex_tpu.ops.paged_decode_attention` contracts with no
  transposes).  With ``kv_wire="int8"`` the pools hold blockwise int8
  codes plus f32 scale planes ``(L, P, H, page)`` — one scale per
  (head, token) row at ``block = head_dim``, the exact
  ``parallel/comm.py`` codec (:func:`~apex_tpu.parallel.comm.
  quantize_blocks`), so the KV wire format is the same code the
  gradient wire uses.
- **host side** — :class:`PagePool`, a free-list allocator.  Page 0 is
  the reserved **null page**: page-table entries beyond a sequence's
  live count point at it, padded prefill tails scatter into it, and
  idle decode slots append into it — it is write-only garbage that the
  ``lengths`` masking guarantees is never read.

There is no defragmentation pass and none is needed: pages are
fixed-size and fully owned by one sequence, so freeing a sequence
returns its pages to the free list with zero compaction — occupancy is
exactly ``live_pages / usable_pages`` at all times.

The device-side write helpers here are pure functions meant to be
called INSIDE the engine's jitted step programs; the engine donates the
cache arrays so the scatters update pages in place
(``analysis.check``'s donation lint proves the aliasing at build).
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from apex_tpu.parallel import comm

__all__ = [
    "NULL_PAGE",
    "PagePool",
    "init_kv_pages",
    "encode_kv",
    "pack_prompt_pages",
    "write_prompt_pages",
    "append_token_kv",
]

#: page 0 — never allocated; the write-only garbage target for padded
#: tails and idle slots
NULL_PAGE = 0


class PagePool:
    """Host-side free-list allocator over ``num_pages`` device pages.

    Page 0 (:data:`NULL_PAGE`) is reserved, so ``num_pages - 1`` pages
    are usable.  ``alloc`` is all-or-nothing: a request that cannot get
    every page it asked for gets none (no partial admissions to later
    roll back — the scheduler's shedding logic stays trivial).
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.num_pages = num_pages
        self.page_size = page_size
        # LIFO free list: recently freed pages are re-used first (their
        # content is dead by construction, and re-use keeps the touched
        # working set small)
        self._free: List[int] = list(range(num_pages - 1, 0, -1))

    @property
    def usable(self) -> int:
        return self.num_pages - 1

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.usable - self.available

    def occupancy(self) -> float:
        """Live fraction of the usable pool (0..1)."""
        return self.in_use / self.usable

    def pages_for(self, tokens: int) -> int:
        """Pages needed to hold ``tokens`` KV positions."""
        return -(-max(tokens, 0) // self.page_size)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` pages, or None when the pool cannot cover all of them
        (all-or-nothing; never hands out :data:`NULL_PAGE`)."""
        if n < 0:
            raise ValueError("cannot allocate a negative page count")
        if n > len(self._free):
            return None
        taken = [self._free.pop() for _ in range(n)]
        return taken

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if not 0 < p < self.num_pages:
                raise ValueError(f"page {p} is not an allocatable page id")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
            self._free.append(p)

    def leak_check(self, owned) -> None:
        """Assert the pool's accounting is EXACT against the live
        ownership ledger: every allocated page is owned by exactly one
        live request and every owned page is allocated.

        ``owned`` is an iterable of per-request page lists (the
        scheduler's slots + retrying queue entries).  Raises
        ``ValueError`` naming the leaked (allocated but unowned),
        foreign (owned but free/out-of-range), or double-owned pages —
        the invariant the serving chaos drill re-proves after every
        injected fault (docs/serving.md "Failure semantics")."""
        owned_flat: List[int] = []
        for pages in owned:
            owned_flat.extend(pages)
        owned_set = set(owned_flat)
        problems = []
        if len(owned_flat) != len(owned_set):
            seen, dups = set(), set()
            for p in owned_flat:
                (dups if p in seen else seen).add(p)
            problems.append(f"pages owned by more than one request: "
                            f"{sorted(dups)}")
        allocated = set(range(1, self.num_pages)) - set(self._free)
        leaked = allocated - owned_set
        foreign = owned_set - allocated
        if leaked:
            problems.append(
                f"leaked pages (allocated, owned by no live request): "
                f"{sorted(leaked)}"
            )
        if foreign:
            problems.append(
                f"foreign pages (owned but not allocated): "
                f"{sorted(foreign)}"
            )
        if problems:
            raise ValueError(
                "PagePool leak check failed: " + "; ".join(problems)
            )


# ---------------------------------------------------------------------------
# device-side pure helpers (called inside the engine's jitted steps)
# ---------------------------------------------------------------------------


def init_kv_pages(
    num_layers: int,
    num_pages: int,
    num_heads: int,
    page_size: int,
    head_dim: int,
    *,
    dtype=jnp.bfloat16,
    kv_wire: str = "f32",
) -> dict:
    """Fresh zeroed pool arrays: ``{"k", "v"}`` of ``(L, P, H, page,
    D)``, plus ``{"k_scale", "v_scale"}`` ``(L, P, H, page)`` f32 planes
    under ``kv_wire="int8"`` (codes then carry dtype int8)."""
    if kv_wire not in ("f32", "int8"):
        raise ValueError(f"kv_wire must be 'f32' or 'int8', got {kv_wire!r}")
    shape = (num_layers, num_pages, num_heads, page_size, head_dim)
    store = jnp.int8 if kv_wire == "int8" else dtype
    cache = {
        "k": jnp.zeros(shape, store),
        "v": jnp.zeros(shape, store),
    }
    if kv_wire == "int8":
        # two DISTINCT buffers: the engine donates the whole cache
        # tree, and donating one shared buffer twice is a runtime error
        cache["k_scale"] = jnp.ones(shape[:-1], jnp.float32)
        cache["v_scale"] = jnp.ones(shape[:-1], jnp.float32)
    return cache


def encode_kv(x):
    """Blockwise int8 codes + scales for KV rows ``(..., D)`` — the
    ``parallel/comm.py`` codec at ``block = D`` (one f32 scale per
    (head, token) row; an all-zero row gets scale 1.0, so the null page
    stays NaN-free)."""
    d = x.shape[-1]
    codes, scale = comm.quantize_blocks(x.astype(jnp.float32), block=d)
    return codes, scale[..., 0]


def pack_prompt_pages(kv, page_size: int):
    """``(S, H, D)`` per-position rows -> ``(NP, H, page, D)`` page
    blocks (``S`` must be a page multiple — prefill buckets are)."""
    s, h, d = kv.shape
    if s % page_size:
        raise ValueError(f"prompt length {s} is not a page multiple")
    return jnp.transpose(
        kv.reshape(s // page_size, page_size, h, d), (0, 2, 1, 3)
    )


def write_prompt_pages(pages, new, page_ids):
    """Scatter layer-stacked page blocks ``new`` ``(L, NP, H, page,
    D[, ...])`` into the pool ``pages`` ``(L, P, H, page, D[, ...])`` at
    ``page_ids`` ``(NP,)``.  Entries pointing at the null page dump the
    padded tail there (never read back)."""
    return pages.at[:, page_ids].set(new.astype(pages.dtype))


def append_token_kv(pages, rows, page_ids, slots):
    """Scatter one token's rows ``(B, H, D[, ...])`` into ``pages``
    ``(P, H, page, D[, ...])`` at ``(page_ids[b], slots[b])`` per
    sequence — the per-layer decode append (idle slots target the null
    page)."""
    return pages.at[page_ids, :, slots].set(rows.astype(pages.dtype))
