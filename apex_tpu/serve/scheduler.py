"""Continuous batching — admission, decode slots, SLOs, shedding.

The throughput lever of a serving system is keeping the decode batch
full: a decode iteration costs nearly the same whether 1 or
``max_batch`` sequences ride it (the weights are read either way), so
every empty slot is wasted HBM bandwidth.
:class:`ContinuousBatchingScheduler` admits new sequences INTO the
running batch at page granularity — a prefill is slotted between decode
iterations (bucketed padding keeps the compiled-shape count finite),
the new sequence joins the very next decode, and finished sequences
free their pages to the pool immediately.

Admission control and degradation are explicit:

- a request is admitted when a decode slot is free AND the page pool
  covers its prompt (``PagePool.alloc`` is all-or-nothing);
- a queued request whose **TTFT SLO deadline** has already passed while
  the pool stays exhausted is **shed** (rejected loudly — the client
  can retry elsewhere) instead of silently blowing its latency budget;
- when a RUNNING sequence needs a growth page and the pool is empty,
  the youngest running request is shed to keep the older ones making
  progress (LIFO victim: it has the least sunk prefill cost).

Every iteration publishes the serving gauges through the shared
:class:`~apex_tpu.observability.metrics.MetricRegistry` — queue depth,
batch fill, page-pool occupancy, tokens/s, TTFT — the same spine
training telemetry rides, so :class:`~apex_tpu.observability.health.
TTFTRule` / :class:`~apex_tpu.observability.health.QueueDepthRule`
watchdogs page the same health layer (``docs/serving.md``).
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import time
from typing import Deque, List, Optional

import numpy as np

from apex_tpu.serve.cache import NULL_PAGE

__all__ = ["Request", "ContinuousBatchingScheduler", "declare_serve_metrics"]

_ids = itertools.count()

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
SHED = "shed"

#: default for ``ContinuousBatchingScheduler(registry=...)``: inherit
#: the engine's registry.  Pass ``registry=None`` to run with NO
#: telemetry (e.g. a baseline probe that must not pollute the engine
#: registry's observation stream).
ENGINE_REGISTRY = object()


@dataclasses.dataclass
class Request:
    """One generation request and its lifecycle ledger."""

    prompt: List[int]
    max_new_tokens: int = 16
    #: TTFT SLO in milliseconds; None = best-effort (never shed by
    #: deadline, only as a growth-page victim)
    slo_ttft_ms: Optional[float] = None
    eos_token: Optional[int] = None
    rid: int = dataclasses.field(default_factory=lambda: next(_ids))

    # -- runtime ledger (scheduler-owned) --------------------------------
    status: str = QUEUED
    tokens: List[int] = dataclasses.field(default_factory=list)
    pages: List[int] = dataclasses.field(default_factory=list)
    #: KV positions written (prompt + generated-and-fed tokens)
    ctx_len: int = 0
    submitted_at: Optional[float] = None
    first_token_at: Optional[float] = None
    done_at: Optional[float] = None

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.submitted_at is None or self.first_token_at is None:
            return None
        return 1e3 * (self.first_token_at - self.submitted_at)


def declare_serve_metrics(registry) -> None:
    """Declare the serving metric set on a registry (idempotent)."""
    for g in ("serve/queue_depth", "serve/batch_fill",
              "serve/page_occupancy", "serve/tokens_per_s",
              "serve/ttft_ms"):
        registry.gauge(g)
    for c in ("serve/admitted", "serve/completed", "serve/shed",
              "serve/tokens_out", "serve/prefills", "serve/decode_steps"):
        registry.counter(c)


class ContinuousBatchingScheduler:
    """Drive an :class:`~apex_tpu.serve.engine.InferenceEngine` with
    continuous batching.

    >>> sched = ContinuousBatchingScheduler(engine)
    >>> sched.submit(Request(prompt=[...], max_new_tokens=32))
    >>> while sched.pending:
    ...     sched.step()
    """

    def __init__(self, engine, *, registry=ENGINE_REGISTRY,
                 clock=time.monotonic, window: int = 32):
        self.engine = engine
        self.pool = engine.pool
        self.serve = engine.serve
        self.clock = clock
        self.queue: Deque[Request] = collections.deque()
        self.slots: List[Optional[Request]] = [None] * self.serve.max_batch
        self.completed: List[Request] = []
        self.shed: List[Request] = []
        self._step = 0
        # tokens/s over a sliding window of (time, cumulative tokens)
        self._tokens_out = 0
        self._window: Deque = collections.deque(maxlen=window)
        self.registry = (
            engine.registry if registry is ENGINE_REGISTRY else registry
        )
        self._mstate = None
        if self.registry is not None:
            declare_serve_metrics(self.registry)
            self._mstate = self.registry.init()

    # -- bookkeeping ------------------------------------------------------
    @property
    def running(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    @property
    def pending(self) -> bool:
        return bool(self.queue) or any(s is not None for s in self.slots)

    def batch_fill(self) -> float:
        return len(self.running) / len(self.slots)

    def submit(self, req: Request) -> Request:
        req.status = QUEUED
        req.submitted_at = self.clock()
        self.queue.append(req)
        return req

    def _page_table_row(self, req: Request) -> np.ndarray:
        row = np.full((self.serve.max_pages_per_seq,), NULL_PAGE, np.int32)
        row[: len(req.pages)] = req.pages
        return row

    def _retire(self, req: Request, status: str) -> None:
        if req.pages:
            self.pool.free(req.pages)
            req.pages = []
        req.status = status
        req.done_at = self.clock()
        (self.completed if status == DONE else self.shed).append(req)

    def _shed_request(self, req: Request) -> None:
        self._retire(req, SHED)
        self._count("serve/shed")

    # -- admission --------------------------------------------------------
    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def _admit_one(self) -> bool:
        """Try to move the queue head into a free slot (prefill now).
        Returns True when a request was admitted or shed (progress)."""
        if not self.queue:
            return False
        slot = self._free_slot()
        if slot is None:
            return False
        req = self.queue[0]
        if len(req.prompt) > self.serve.max_context:
            self.queue.popleft()
            self._shed_request(req)
            return True
        need = self.pool.pages_for(len(req.prompt))
        pages = self.pool.alloc(need)
        if pages is None:
            # pool exhausted: shed only once the TTFT budget is already
            # blown — before that the request just waits its turn
            if (
                req.slo_ttft_ms is not None
                and 1e3 * (self.clock() - req.submitted_at) > req.slo_ttft_ms
            ):
                self.queue.popleft()
                self._shed_request(req)
                return True
            return False
        self.queue.popleft()
        req.pages = pages
        _, first = self.engine.prefill(req.prompt, pages)
        req.ctx_len = len(req.prompt)
        req.tokens.append(first)
        req.first_token_at = self.clock()
        req.status = RUNNING
        self.slots[slot] = req
        self._tokens_out += 1
        self._count("serve/admitted")
        self._count("serve/prefills")
        self._count("serve/tokens_out")
        self._gauge("serve/ttft_ms", req.ttft_ms)
        if self._finished(req):
            self.slots[slot] = None
            self._retire(req, DONE)
            self._count("serve/completed")
        return True

    def _finished(self, req: Request) -> bool:
        if len(req.tokens) >= req.max_new_tokens:
            return True
        if req.eos_token is not None and req.tokens and (
            req.tokens[-1] == req.eos_token
        ):
            return True
        # context capacity: the NEXT fed token would not fit
        return req.ctx_len + 1 > self.serve.max_context

    # -- decode -----------------------------------------------------------
    def _ensure_growth_page(self, req: Request) -> bool:
        """The next append lands at position ``ctx_len``; allocate its
        page if the sequence is about to cross a page boundary."""
        if req.ctx_len // self.serve.page_size < len(req.pages):
            return True
        got = self.pool.alloc(1)
        if got is None:
            return False
        req.pages.extend(got)
        return True

    def _decode_once(self) -> None:
        b = len(self.slots)
        tokens = np.zeros((b,), np.int32)
        lengths = np.zeros((b,), np.int32)
        tables = np.full(
            (b, self.serve.max_pages_per_seq), NULL_PAGE, np.int32
        )
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            if not self._ensure_growth_page(req):
                # pool exhausted mid-decode: shed the youngest running
                # request (least sunk cost) and retry this one
                victims = sorted(
                    self.running, key=lambda r: r.submitted_at or 0.0
                )
                victim = victims[-1]
                v_slot = self.slots.index(victim)
                self.slots[v_slot] = None
                self._shed_request(victim)
                # the victim's row may already be staged for this
                # iteration — clear it so the decode never touches its
                # (now freed) pages
                tokens[v_slot] = 0
                lengths[v_slot] = 0
                tables[v_slot] = NULL_PAGE
                if victim is req or not self._ensure_growth_page(req):
                    if self.slots[i] is req:
                        self.slots[i] = None
                        self._shed_request(req)
                    continue
            tokens[i] = req.tokens[-1]
            lengths[i] = req.ctx_len + 1  # context incl. the fed token
            tables[i] = self._page_table_row(req)
        if not any(s is not None for s in self.slots):
            return
        _, next_tokens = self.engine.decode(tokens, lengths, tables)
        self._count("serve/decode_steps")
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            req.ctx_len += 1
            req.tokens.append(int(next_tokens[i]))
            self._tokens_out += 1
            self._count("serve/tokens_out")
            if self._finished(req):
                self.slots[i] = None
                self._retire(req, DONE)
                self._count("serve/completed")

    # -- metrics ----------------------------------------------------------
    def _count(self, name: str, n: float = 1.0) -> None:
        if self._mstate is not None:
            self._mstate = self.registry.update(self._mstate, {name: n})

    def _gauge(self, name: str, value) -> None:
        if self._mstate is not None and value is not None:
            self._mstate = self.registry.update(
                self._mstate, {name: float(value)}
            )

    def _publish(self) -> None:
        now = self.clock()
        self._window.append((now, self._tokens_out))
        tps = 0.0
        if len(self._window) >= 2:
            (t0, n0), (t1, n1) = self._window[0], self._window[-1]
            if t1 > t0:
                tps = (n1 - n0) / (t1 - t0)
        self._gauge("serve/queue_depth", len(self.queue))
        self._gauge("serve/batch_fill", self.batch_fill())
        self._gauge("serve/page_occupancy", self.pool.occupancy())
        self._gauge("serve/tokens_per_s", tps)
        if self._mstate is not None:
            self.registry.observe(self._step, self._mstate)

    # -- the iteration ----------------------------------------------------
    def step(self) -> None:
        """One continuous-batching iteration: admit (prefill) into free
        slots, then one decode pass over the running batch."""
        # admit until slots or pages run out — each prefill slots in
        # between decode iterations by construction
        while self._admit_one():
            pass
        self._decode_once()
        self._step += 1
        self._publish()

    def run(self, max_steps: int = 10_000) -> None:
        """Drain: step until every submitted request completed or shed."""
        for _ in range(max_steps):
            if not self.pending:
                return
            self.step()
        raise RuntimeError(
            f"scheduler did not drain within {max_steps} iterations"
        )
