"""Continuous batching — admission, decode slots, SLOs, shedding.

The throughput lever of a serving system is keeping the decode batch
full: a decode iteration costs nearly the same whether 1 or
``max_batch`` sequences ride it (the weights are read either way), so
every empty slot is wasted HBM bandwidth.
:class:`ContinuousBatchingScheduler` admits new sequences INTO the
running batch at page granularity — a prefill is slotted between decode
iterations (bucketed padding keeps the compiled-shape count finite),
the new sequence joins the very next decode, and finished sequences
free their pages to the pool immediately.

Admission control and degradation are explicit:

- a request is admitted when a decode slot is free AND the page pool
  covers its prompt (``PagePool.alloc`` is all-or-nothing);
- a queued request whose **TTFT SLO deadline** has already passed while
  the pool stays exhausted is **shed** (rejected loudly — the client
  can retry elsewhere) instead of silently blowing its latency budget;
- when a RUNNING sequence needs a growth page and the pool is empty,
  the youngest running request is shed to keep the older ones making
  progress (LIFO victim: it has the least sunk prefill cost).

Every shed carries a **reason** (:data:`SHED_REASONS`): the single
``serve/shed`` counter is split into per-reason counters so "we shed
3%" becomes "we shed 3%, all of it deadline-in-queue — admission is
starved, not the decode batch".

**Failure is a scheduling input** (docs/serving.md "Failure semantics
& degradation ladder").  The request lifecycle carries recovery
guarantees:

- **bounded re-admission retries** — a prefill/decode fault or a
  blown per-request decode timeout sends the request to the
  ``retrying`` phase with its pages and generated prefix RETAINED;
  re-admission resumes decode from the last completed iteration (no
  re-prefill once the first token exists), bounded by ``max_retries``
  and ledgered as ``shed(retries_exhausted)`` past it;
- **poisoned-request quarantine** — a non-finite logits row (the
  engine's in-step screen) evicts ONLY the offending slot, ledgered
  ``shed(poisoned)``; the rest of the batch keeps decoding;
- **engine supervision** — a crashed decode step moves every running
  request to ``retrying`` (re-admitted on the very next iteration,
  riding the incumbent compiled program) and schedules the engine's
  supervised :meth:`~apex_tpu.serve.engine.InferenceEngine.rebuild`
  for the next idle point, escalating to a synchronous rebuild on a
  repeat fault (bounded by ``rebuild_limit``) — one transient fault
  never turns into a recompile-sized latency cliff for the whole
  queue;
- **graceful drain** (:meth:`ContinuousBatchingScheduler.drain`) —
  rolling-restart shutdown: stop admitting new work, finish running
  (and retrying) decodes, shed the never-admitted queue loudly as
  ``shed(draining)``, and report the drained state with the page pool
  provably empty.

Overload walks an explicit **degradation ladder**, each rung a
distinct ledger reason on the span state machine, metrics board, and
OpenMetrics export:

1. **backpressure** — a bounded admission queue (``max_queue_depth``)
   fast-rejects at submit time, ``shed(queue_full)``: the client gets
   an immediate retry-elsewhere signal instead of a blown deadline;
2. **max-new-tokens clamping** — past ``clamp_occupancy`` pool
   pressure (or a half-full bounded queue), admissions are clamped to
   ``clamp_max_new_tokens`` (``serve/clamped`` counter + a
   ``req/clamped`` span instant carrying the original budget);
3. **deadline shedding** — the existing TTFT-SLO rung,
   ``shed(deadline)``.

:meth:`leak_check` (``PagePool.leak_check`` against the live ownership
ledger) is asserted after every shed/free path when ``leak_checks=``
is on (the default), so page accounting stays provably exact through
every fault.

Every iteration publishes the serving gauges through the shared
:class:`~apex_tpu.observability.metrics.MetricRegistry` — queue depth,
batch fill, page-pool occupancy, tokens/s, TTFT — the same spine
training telemetry rides, so :class:`~apex_tpu.observability.health.
TTFTRule` / :class:`~apex_tpu.observability.health.QueueDepthRule`
watchdogs page the same health layer (``docs/serving.md``).

**Prefix caching & chunked prefill** (``docs/serving.md``): with
``prefix_cache=True`` every admitted prompt is matched against a
content-addressed cache of committed KV page runs
(:class:`~apex_tpu.serve.cache.PrefixCache`) — hit pages are borrowed
(refcounted, copy-on-write on the first divergent append) and their
prefill is SKIPPED; only the prompt's final chunk re-runs, so a shared
system prompt is paid for once.  ``prefill_chunk_tokens=`` additionally
slices cold prefills into page-multiple chunks advanced one per step
between decode iterations (the ``prefilling`` slot phase), so a long
cold prompt no longer stalls running streams.  Both default OFF.

**Speculative decoding** (``docs/serving.md`` "Speculative decoding"):
when the engine carries a :class:`~apex_tpu.serve.spec.SpecConfig`,
spec-eligible slots ride a propose → verify → accept/rollback round
per iteration instead of a single-token decode — a small draft model
proposes ``k`` tokens from its own KV pages (allocated in the
``draft`` PagePool namespace, never shared into the prefix cache) and
ONE target step scores all ``k+1`` positions.  Greedy acceptance is an
exact argmax match, so the emitted stream is bit-identical to plain
decode by construction; temperature mode uses the rejection sampler
that provably preserves the target distribution.  The scheduler owns
the per-slot state machine: mixed spec/plain batches, demotion on
draft faults (``serve.draft`` chaos site — a broken draft can slow a
stream but never corrupt it), COW-forking the whole speculative window
BEFORE a round so rejected-tail truncation never writes a shared page,
and a degradation-ladder fallback to plain decode when the windowed
acceptance rate collapses below ``min_accept_rate`` (sticky until
:meth:`resume`).

**TTFT attribution** (``docs/observability.md``): each completed
request's TTFT decomposes into four components that sum to the
measured TTFT *by construction* (the same remainder discipline
:mod:`~apex_tpu.observability.attribution` applies to step time):

- ``queue_wait`` — time the request sat in the queue while admission
  was **resource-blocked** (no free decode slot, or the page pool
  could not cover the queue head);
- ``cached_prefill`` — the prefix-cache share of the post-admission
  phase (hash/match/borrow and page allocation up to the first engine
  call); exactly 0.0 when the cache is off;
- ``prefill``    — admission to first token (the prefill program);
- ``contention`` — the remainder of the pre-admission wait: the
  request was admissible but the scheduler was busy running decode
  iterations for the requests already in the batch.

Per-component p50/p95/p99 gauges and the queue-wait fraction publish
through the registry on the observation cadence;
:class:`~apex_tpu.observability.health.QueueWaitFractionRule` alerts
when TTFT is dominated by starved admission.  With a
:class:`~apex_tpu.observability.spans.SpanRecorder` attached
(``spans=``), every request additionally records its full span chain
``queued → admitted → prefill → decode[i] → done|shed(reason)`` with
engine decode-iteration correlation ids — the per-request causal
record ``tools/timeline.py`` merges into one Perfetto timeline.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import time
import zlib
from typing import Deque, Dict, List, Optional

import numpy as np

from apex_tpu.observability.meter import percentile as _percentile
from apex_tpu.observability.ometrics import (
    DEFAULT_LATENCY_BUCKETS_MS,
    Histogram,
)
from apex_tpu.resilience import chaos
from apex_tpu.serve.cache import NULL_PAGE, PrefixCache

__all__ = [
    "Request",
    "ContinuousBatchingScheduler",
    "declare_serve_metrics",
    "ttft_attribution",
    "SHED_REASONS",
    "SHED_REROUTED",
    "TTFT_COMPONENTS",
]

_ids = itertools.count()

QUEUED = "queued"
RUNNING = "running"
#: chunked prefill in flight: the request holds a decode slot (so its
#: pages and position are pinned) but rides NO decode iteration until
#: its final prefill chunk produced the first token
PREFILLING = "prefilling"
#: fault recovery: the request left the batch (or never reached it)
#: after a fault and waits at the queue front for bounded re-admission
#: with its pages and generated prefix retained
RETRYING = "retrying"
DONE = "done"
SHED = "shed"

#: shed reasons, each with its own ``serve/shed_<reason>`` counter:
#: ``deadline`` (queued past its TTFT SLO while the pool stayed
#: exhausted), ``growth_victim`` (youngest running request shed to free
#: a growth page), ``pool_exhausted`` (a running request could not grow
#: even after a victim shed), ``oversize`` (prompt exceeds the max
#: context), ``poisoned`` (non-finite logits row — quarantined, only
#: the offending slot), ``queue_full`` (backpressure fast-reject at the
#: bounded admission queue), ``retries_exhausted`` (a faulting request
#: burned its re-admission budget), ``draining`` (never-admitted work
#: rejected during a graceful rolling-restart drain), ``rerouted``
#: (never-admitted work a :meth:`~ContinuousBatchingScheduler.drain`
#: ``handoff=`` target accepted — the request is NOT terminal: it left
#: THIS replica's ledger and continues on another one).
SHED_DEADLINE = "deadline"
SHED_GROWTH_VICTIM = "growth_victim"
SHED_POOL_EXHAUSTED = "pool_exhausted"
SHED_OVERSIZE = "oversize"
SHED_POISONED = "poisoned"
SHED_QUEUE_FULL = "queue_full"
SHED_RETRIES_EXHAUSTED = "retries_exhausted"
SHED_DRAINING = "draining"
SHED_REROUTED = "rerouted"
SHED_REASONS = (
    SHED_DEADLINE, SHED_GROWTH_VICTIM, SHED_POOL_EXHAUSTED, SHED_OVERSIZE,
    SHED_POISONED, SHED_QUEUE_FULL, SHED_RETRIES_EXHAUSTED, SHED_DRAINING,
    SHED_REROUTED,
)

#: TTFT attribution components (ms); they sum to the measured TTFT by
#: construction — see the module docstring.  ``cached_prefill`` is the
#: prefix-cache share of the post-admission phase (hash/match/borrow/
#: alloc up to the first engine call); it is EXACTLY 0.0 when the
#: cache is off, so the legacy three-component sum is unchanged.
TTFT_COMPONENTS = ("queue_wait", "cached_prefill", "prefill", "contention")

def ttft_attribution(comps) -> Dict[str, object]:
    """Aggregate per-request TTFT components
    (:meth:`Request.ttft_components` dicts) into per-component
    p50/p95/p99 + the queue-wait fraction — the ONE aggregation behind
    both the scheduler's ``serve/ttft_*`` registry gauges and the
    ``tools/serve_bench.py`` artifact, so the two surfaces
    ``verify_tier1.sh`` cross-checks can never drift apart."""
    out: Dict[str, object] = {}
    for comp in TTFT_COMPONENTS:
        vals = sorted(c[f"{comp}_ms"] for c in comps)
        out[f"{comp}_ms"] = {
            tag: _percentile(vals, q)
            for q, tag in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99"))
        }
    total_ttft = sum(c["ttft_ms"] for c in comps)
    out["queue_wait_fraction"] = (
        sum(c["queue_wait_ms"] for c in comps) / total_ttft
        if total_ttft > 0 else 0.0
    )
    out["samples"] = len(comps)
    return out


#: default for ``ContinuousBatchingScheduler(registry=...)``: inherit
#: the engine's registry.  Pass ``registry=None`` to run with NO
#: telemetry (e.g. a baseline probe that must not pollute the engine
#: registry's observation stream).
ENGINE_REGISTRY = object()


@dataclasses.dataclass
class Request:
    """One generation request and its lifecycle ledger."""

    prompt: List[int]
    max_new_tokens: int = 16
    #: TTFT SLO in milliseconds; None = best-effort (never shed by
    #: deadline, only as a growth-page victim)
    slo_ttft_ms: Optional[float] = None
    eos_token: Optional[int] = None
    #: per-request decode timeout: a decode iteration this request rode
    #: exceeding it discards the iteration's token for THIS request and
    #: sends it through bounded re-admission retry (prefix preserved).
    #: None inherits the scheduler's default (usually also None).
    decode_timeout_ms: Optional[float] = None
    #: sampling temperature for the fused in-step sampler; <= 0 is
    #: greedy argmax (bit-identical to the pre-sampler engine)
    temperature: float = 0.0
    #: per-request sampling-stream seed: every temperature draw for
    #: this stream keys off ``fold_in(engine base, stream_seed)`` then
    #: the emission index — a function of request identity and stream
    #: position, never of engine call counters, so a speculative
    #: rollback replays identically and a ``k = 0`` spec stream equals
    #: the plain one.  None derives a seed from :attr:`rid` (distinct
    #: per request); pass an explicit seed to reproduce a stream
    #: across schedulers/replicas.
    stream_seed: Optional[int] = None
    rid: int = dataclasses.field(default_factory=lambda: next(_ids))

    # -- runtime ledger (scheduler-owned) --------------------------------
    status: str = QUEUED
    tokens: List[int] = dataclasses.field(default_factory=list)
    pages: List[int] = dataclasses.field(default_factory=list)
    #: KV positions written (prompt + generated-and-fed tokens)
    ctx_len: int = 0
    submitted_at: Optional[float] = None
    #: popped from the queue with pages granted (prefill dispatch)
    admitted_at: Optional[float] = None
    first_token_at: Optional[float] = None
    done_at: Optional[float] = None
    #: why this request was shed (one of :data:`SHED_REASONS`), else None
    shed_reason: Optional[str] = None
    #: accumulated seconds the request sat in the queue while admission
    #: was resource-blocked (the ``queue_wait`` TTFT component)
    queue_blocked_s: float = 0.0
    #: start of the current resource-blocked interval (scheduler-owned)
    blocked_since: Optional[float] = None
    #: engine decode iterations this request rode (correlation ids
    #: into the ``serve/engine`` span track)
    first_decode_iter: Optional[int] = None
    last_decode_iter: Optional[int] = None
    #: re-admission retries consumed (bounded by the scheduler's
    #: ``max_retries``); the last cause rides the span record
    retries: int = 0
    #: original ``max_new_tokens`` when the overload ladder clamped it
    #: (None = never clamped)
    clamped_from: Optional[int] = None
    # -- prefix cache / chunked prefill (scheduler-owned) ----------------
    #: prompt tokens already covered by KV pages (cache hit + completed
    #: prefill chunks); equals ``len(prompt)`` once prefill is done
    prefill_pos: int = 0
    #: prompt tokens the prefix cache covered at admission (0 = miss)
    cache_hit_tokens: int = 0
    #: leading pages of :attr:`pages` borrowed from the cache (refcount
    #: shared — chunk writes to them are redirected to the null page)
    cache_hit_pages: int = 0
    #: the cache was already probed for this request (the match/borrow
    #: runs ONCE, even when admission then blocks on the pool)
    cache_probed: bool = False
    #: first engine prefill/chunk call for this request — splits the
    #: post-admission phase into ``cached_prefill`` (match/borrow/alloc)
    #: and ``prefill`` (compute); None = cache off, component is 0.0
    prefill_started_at: Optional[float] = None
    # -- speculative decoding (scheduler-owned) --------------------------
    #: draft-model KV pages (``"draft"`` pool namespace) mirroring
    #: :attr:`pages` position-for-position; freed on every retire path
    draft_pages: List[int] = dataclasses.field(default_factory=list)
    #: False once this request's draft state is unusable (draft prefill
    #: faulted): the stream decodes plain — spec is an accelerator, a
    #: broken draft must never cost the stream more than speed
    spec_ok: bool = True

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.submitted_at is None or self.first_token_at is None:
            return None
        return 1e3 * (self.first_token_at - self.submitted_at)

    def ttft_components(self) -> Optional[Dict[str, float]]:
        """``{ttft_ms, queue_wait_ms, cached_prefill_ms, prefill_ms,
        contention_ms}`` — the four components sum to ``ttft_ms`` by
        construction (contention is the remainder of the pre-admission
        wait; ``cached_prefill_ms`` is exactly 0.0 when the prefix
        cache is off)."""
        if (
            self.submitted_at is None
            or self.admitted_at is None
            or self.first_token_at is None
        ):
            return None
        queue_wait = 1e3 * self.queue_blocked_s
        cached = (
            1e3 * (self.prefill_started_at - self.admitted_at)
            if self.prefill_started_at is not None else 0.0
        )
        prefill = 1e3 * (
            self.first_token_at
            - (self.prefill_started_at
               if self.prefill_started_at is not None
               else self.admitted_at)
        )
        contention = (
            1e3 * (self.admitted_at - self.submitted_at) - queue_wait
        )
        return {
            "ttft_ms": self.ttft_ms,
            "queue_wait_ms": queue_wait,
            "cached_prefill_ms": cached,
            "prefill_ms": prefill,
            "contention_ms": contention,
        }


def declare_serve_metrics(registry) -> None:
    """Declare the serving metric set on a registry (idempotent)."""
    for g in ("serve/queue_depth", "serve/batch_fill",
              "serve/page_occupancy", "serve/tokens_per_s",
              "serve/ttft_ms", "serve/draining"):
        registry.gauge(g)
    for c in ("serve/admitted", "serve/completed", "serve/shed",
              "serve/tokens_out", "serve/prefills", "serve/decode_steps",
              # the failure/degradation ledger (docs/serving.md
              # "Failure semantics"): retries + re-admissions, clamped
              # admissions, per-request decode timeouts, engine faults
              # and supervised rebuilds, chaos-visible admission and
              # page-allocation faults, graceful drains
              "serve/retries", "serve/readmitted", "serve/clamped",
              "serve/decode_timeouts", "serve/engine_faults",
              "serve/engine_rebuilds", "serve/admission_faults",
              "serve/kv_alloc_faults", "serve/drains",
              # prefix-cache ledger (docs/serving.md "Prefix caching"):
              # admission hits/misses, tokens whose prefill the cache
              # skipped, COW tail-page forks, committed runs, LRU
              # evictions under pool pressure + forced chaos sweeps
              "serve/prefix_hits", "serve/prefix_misses",
              "serve/prefix_hit_tokens", "serve/prefix_forks",
              "serve/prefix_commits", "serve/prefix_evictions",
              "serve/prefix_evict_faults",
              # speculative-decoding ledger (docs/serving.md
              # "Speculative decoding"): rounds, proposals drafted /
              # accepted / rejected, rollback programs run, ladder
              # fallbacks to plain decode, faulted draft calls
              "serve/spec_rounds", "serve/spec_drafted",
              "serve/spec_accepted", "serve/spec_rejected",
              "serve/spec_rollbacks", "serve/spec_fallbacks",
              "serve/draft_faults"):
        registry.counter(c)
    registry.gauge("serve/prefix_cached_pages")
    # windowed acceptance rate + emitted tokens per slot decode step —
    # the SpecAcceptanceRule watchdog and the bench read these
    registry.gauge("serve/spec_accept_rate")
    registry.gauge("serve/spec_tokens_per_step")
    # per-reason shed breakdown (sums to serve/shed)
    for reason in SHED_REASONS:
        registry.counter(f"serve/shed_{reason}")
    # TTFT attribution: per-component percentiles over the recent
    # completion window, plus the fraction the watchdog judges
    for comp in TTFT_COMPONENTS:
        for tag in ("p50", "p95", "p99"):
            registry.gauge(f"serve/ttft_{comp}_ms_{tag}", "ms")
    registry.gauge("serve/ttft_queue_wait_fraction")


class ContinuousBatchingScheduler:
    """Drive an :class:`~apex_tpu.serve.engine.InferenceEngine` with
    continuous batching.

    >>> sched = ContinuousBatchingScheduler(engine)
    >>> sched.submit(Request(prompt=[...], max_new_tokens=32))
    >>> while sched.pending:
    ...     sched.step()

    ``spans`` attaches a :class:`~apex_tpu.observability.spans.
    SpanRecorder`: the scheduler records each request's lifecycle span
    chain and hands the same recorder to the engine for its
    prefill/decode-iteration spans (taking over from any previous
    scheduler's recorder, and sharing a non-default ``clock`` with the
    recorder so the whole record stays on one time basis).
    """

    def __init__(self, engine, *, registry=ENGINE_REGISTRY,
                 clock=time.monotonic, window: int = 32,
                 spans=None, attribution_window: int = 128,
                 max_queue_depth: Optional[int] = None,
                 max_retries: int = 2,
                 decode_timeout_ms: Optional[float] = None,
                 clamp_max_new_tokens: Optional[int] = None,
                 clamp_occupancy: float = 0.75,
                 clamp_queue_depth: Optional[int] = None,
                 rebuild_limit: int = 2,
                 leak_checks: bool = True,
                 prefix_cache: bool = False,
                 prefill_chunk_tokens: Optional[int] = None):
        self.engine = engine
        self.pool = engine.pool
        self.serve = engine.serve
        self.clock = clock
        # cross-request prefix cache + chunked prefill (docs/serving.md
        # "Prefix caching & chunked prefill"); both default OFF — the
        # monolithic cold path stays byte-for-byte the legacy one
        self.prefix = PrefixCache(self.pool) if prefix_cache else None
        if prefill_chunk_tokens is not None and (
            prefill_chunk_tokens <= 0
            or prefill_chunk_tokens % self.serve.page_size
        ):
            raise ValueError(
                "prefill_chunk_tokens must be a positive multiple of "
                f"page_size={self.serve.page_size}, got "
                f"{prefill_chunk_tokens}"
            )
        self.prefill_chunk_tokens = prefill_chunk_tokens
        # failure/degradation knobs (docs/serving.md "Failure
        # semantics & degradation ladder")
        self.max_queue_depth = max_queue_depth
        self.max_retries = max_retries
        self.decode_timeout_ms = decode_timeout_ms
        self.clamp_max_new_tokens = clamp_max_new_tokens
        self.clamp_occupancy = clamp_occupancy
        self.clamp_queue_depth = clamp_queue_depth
        if clamp_queue_depth is None and max_queue_depth is not None:
            self.clamp_queue_depth = max(1, max_queue_depth // 2)
        self.rebuild_limit = rebuild_limit
        self.leak_checks = leak_checks
        # speculative decoding (docs/serving.md "Speculative
        # decoding"): per-round (drafted, accepted, emitted,
        # slot_steps) window driving the acceptance gauges and the
        # degradation-ladder fallback; sticky until resume()
        self._spec_window: Optional[Deque] = (
            collections.deque(maxlen=engine.spec.window)
            if engine.spec is not None else None
        )
        self._spec_fallback = False
        self.draining = False
        self._drain_handoff = None
        self._drain_rerouted = 0
        self._rebuild_pending = False
        self._rebuilds_started = 0
        self._admissions = 0   # chaos index for the serve.admission site
        self._kv_allocs = 0    # chaos index for the serve.kv_alloc site
        self.leak_checks_run = 0
        self.queue: Deque[Request] = collections.deque()
        self.slots: List[Optional[Request]] = [None] * self.serve.max_batch
        self.completed: List[Request] = []
        self.shed: List[Request] = []
        self._step = 0
        # tokens/s over a sliding window of (time, cumulative tokens)
        self._tokens_out = 0
        self._window: Deque = collections.deque(maxlen=window)
        self.registry = (
            engine.registry if registry is ENGINE_REGISTRY else registry
        )
        self.spans = spans
        # this scheduler owns the engine's recorder for its lifetime —
        # a later scheduler on the same engine takes over cleanly
        # (spans=None DETACHES a retired scheduler's recorder) instead
        # of feeding a dead recorder events uncorrelated to any chain
        engine.spans = spans
        if spans is not None:
            if clock is not time.monotonic:
                # ONE time basis per recorder: the request ledger uses
                # this clock, so the engine spans (rec.now()) must too
                # — a mixed-clock record would merge into a timeline
                # that silently misplaces half its tracks.  Export
                # alignment via the wall-clock anchor assumes the
                # default monotonic clock.
                spans.clock = clock
        # recent completions' TTFT components — the percentile window
        self._comps: Deque[Dict[str, float]] = collections.deque(
            maxlen=attribution_window
        )
        # host-side TTFT distribution: the OpenMetrics histogram an
        # --ops-port scrape exposes and the latency-SLO burn-rate math
        # reads (good = observations under the deadline bucket) — one
        # bisect per admission, registry or not
        self.ttft_hist = Histogram(
            "serve/ttft_hist_ms", DEFAULT_LATENCY_BUCKETS_MS, unit="ms",
            help="TTFT distribution over admitted requests",
        )
        self._published_done = 0
        self._mstate = None
        if self.registry is not None:
            declare_serve_metrics(self.registry)
            self._mstate = self.registry.init()

    # -- bookkeeping ------------------------------------------------------
    @property
    def running(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    @property
    def pending(self) -> bool:
        return bool(self.queue) or any(s is not None for s in self.slots)

    def batch_fill(self) -> float:
        return len(self.running) / len(self.slots)

    def submit(self, req: Request) -> Request:
        req.status = QUEUED
        now = self.clock()
        if req.submitted_at is None:
            # a re-routed request (fleet handoff / crash evacuation)
            # keeps its ORIGINAL submission time: its end-to-end TTFT
            # and SLO deadline are measured from the client's submit,
            # not from the hop onto this replica
            req.submitted_at = now
        if self.spans is not None:
            self.spans.request_event(
                req.rid, QUEUED, now,
                prompt_tokens=len(req.prompt),
                slo_ttft_ms=req.slo_ttft_ms,
            )
        # degradation rung 1 — backpressure: a bounded queue rejects at
        # the front door (the client can retry elsewhere NOW) instead
        # of queueing work that will only blow its deadline later.  A
        # draining scheduler rejects everything new the same loud way.
        if self.draining:
            self._shed_request(req, SHED_DRAINING)
            return req
        if (
            self.max_queue_depth is not None
            and len(self.queue) >= self.max_queue_depth
        ):
            self._shed_request(req, SHED_QUEUE_FULL)
            return req
        self.queue.append(req)
        return req

    def _page_table_row(self, req: Request) -> np.ndarray:
        row = np.full((self.serve.max_pages_per_seq,), NULL_PAGE, np.int32)
        row[: len(req.pages)] = req.pages
        return row

    def _close_blocked(self, req: Request, now: float) -> None:
        if req.blocked_since is not None:
            req.queue_blocked_s += now - req.blocked_since
            req.blocked_since = None

    def _span_terminal(self, req: Request, status: str,
                       reason: Optional[str]) -> None:
        rec = self.spans
        if rec is None:
            return
        args: Dict[str, object] = {}
        if status == DONE:
            args["tokens"] = len(req.tokens)
        else:
            args["reason"] = reason
            if req.submitted_at is not None and req.done_at is not None:
                args["waited_ms"] = 1e3 * (req.done_at - req.submitted_at)
        if req.first_decode_iter is not None:
            args["first_iter"] = req.first_decode_iter
            args["last_iter"] = req.last_decode_iter
        # a request retired straight out of prefill (finished or shed
        # at its first token) still owns its TTFT attribution — attach
        # it here so the req/prefill span carries the components
        if rec.open_requests.get(req.rid) == "prefill":
            comps = req.ttft_components()
            if comps:
                args.update(comps)
        rec.request_event(req.rid, status, req.done_at, **args)

    def _retire(self, req: Request, status: str,
                reason: Optional[str] = None) -> None:
        if req.pages:
            self.pool.free(req.pages)
            req.pages = []
        if req.draft_pages:
            self.pool.free(req.draft_pages)
            req.draft_pages = []
        req.status = status
        req.shed_reason = reason if status == SHED else None
        req.done_at = self.clock()
        self._close_blocked(req, req.done_at)
        self._span_terminal(req, status, reason)
        if status == DONE:
            self.completed.append(req)
            comps = req.ttft_components()
            if comps is not None:
                self._comps.append(comps)
        else:
            self.shed.append(req)
        if self.leak_checks:
            # every shed/free path funnels through here: page
            # accounting is re-proven exact on each of them
            self.leak_check()

    def _shed_request(self, req: Request, reason: str) -> None:
        self._retire(req, SHED, reason)
        self._count("serve/shed")
        self._count(f"serve/shed_{reason}")

    def _reroute_request(self, req: Request, handoff) -> bool:
        """Offer a never-admitted request to a drain ``handoff``
        target instead of shedding it (docs/serving.md "Fleet
        operations").  Any retained pages are dropped FIRST — pages
        are replica-local, a re-routed request re-prefills elsewhere —
        then the target decides.  On acceptance the request leaves
        this replica's ledger as ``shed(rerouted)`` on the counters
        (so the per-reason breakdown still sums to ``serve/shed``) but
        is NOT terminal: no shed span, no ``self.shed`` entry — the
        handoff target owns its lifecycle now.  On refusal the caller
        falls back to the loud ``shed(draining)`` path."""
        if req.pages:
            self.pool.free(req.pages)
            req.pages = []
        if req.draft_pages:
            self.pool.free(req.draft_pages)
            req.draft_pages = []
        if not handoff(req):
            return False
        self._count("serve/shed")
        self._count(f"serve/shed_{SHED_REROUTED}")
        if self.leak_checks:
            self.leak_check()
        return True

    # -- page accounting ---------------------------------------------------
    def owned_pages(self) -> List[List[int]]:
        """The live ownership ledger: per-request page lists across the
        running slots AND the retrying queue entries (a retrying
        request keeps its pages — that is what makes resume cheap)."""
        owned = [r.pages for r in self.slots if r is not None and r.pages]
        owned.extend(r.pages for r in self.queue if r.pages)
        owned.extend(
            r.draft_pages for r in self.slots
            if r is not None and r.draft_pages
        )
        owned.extend(r.draft_pages for r in self.queue if r.draft_pages)
        return owned

    def leak_check(self) -> None:
        """Assert ``PagePool`` accounting is exact against
        :meth:`owned_pages` (raises ``ValueError`` naming the pages).
        Runs automatically after every shed/free path when
        ``leak_checks=True`` (the default).  The check is
        O(num_pages) per retirement — negligible at test/CI pool
        sizes; a latency-critical deployment with a very large pool
        can pass ``leak_checks=False`` and rely on the chaos drill's
        continuous proof instead."""
        self.pool.leak_check(
            self.owned_pages(),
            cached=self.prefix.cached_pages()
            if self.prefix is not None else (),
        )
        self.leak_checks_run += 1

    def _alloc(self, n: int, ns: str = "kv") -> Optional[List[int]]:
        """Pool allocation behind the ``serve.kv_alloc`` chaos site: an
        active fault forces the all-or-nothing failure path (returns
        None), driving the same shedding/backpressure machinery a
        genuinely exhausted pool drives — no separate failure code.
        An exhausted pool first reclaims idle prefix-cache runs (LRU,
        never a borrowed page) before the failure path is taken —
        cached history is strictly lower-priority than live work.
        ``ns`` is the page namespace (``"draft"`` for speculative draft
        KV — the tag ``leak_check`` screens the prefix cache against)."""
        idx = self._kv_allocs
        self._kv_allocs += 1
        if chaos.active(chaos.SERVE_KV_ALLOC, idx) is not None:
            self._count("serve/kv_alloc_faults")
            return None
        got = self.pool.alloc(n, ns=ns)
        if got is None and self.prefix is not None:
            freed = self.prefix.evict(need=n)
            if freed:
                self._count("serve/prefix_evictions", freed)
                # prove the ledger exact right after the sweep — before
                # the retry hands out pages no request owns yet
                if self.leak_checks:
                    self.leak_check()
                got = self.pool.alloc(n, ns=ns)
        return got

    # -- fault recovery ----------------------------------------------------
    def _send_to_retry(self, req: Request, cause: str) -> None:
        """Bounded re-admission: the request keeps its pages and its
        generated prefix and re-enters through the queue FRONT; past
        ``max_retries`` it is shed as ``retries_exhausted`` instead of
        looping forever on a persistent fault."""
        if req.retries >= self.max_retries:
            self._shed_request(req, SHED_RETRIES_EXHAUSTED)
            return
        req.retries += 1
        req.status = RETRYING
        req.blocked_since = None
        self._count("serve/retries")
        if self.spans is not None:
            self.spans.request_event(
                req.rid, RETRYING, self.clock(),
                cause=cause, attempt=req.retries,
            )
        self.queue.appendleft(req)
        if self.leak_checks:
            self.leak_check()

    def _on_engine_fault(self, error: BaseException) -> None:
        """Supervise an engine decode fault with an escalating policy:

        - every running request moves to ``retrying`` (pages + prefix
          retained) and re-enters the batch on the very next
          iteration, riding the INCUMBENT compiled program — a
          transient fault does not corrupt an executable, and pausing
          the whole batch for a recompile would turn one fault into a
          latency cliff for every queued request;
        - a supervised AOT rebuild (re-verified replacement program)
          is scheduled and runs at the next idle point (queue and
          slots empty, or :meth:`drain`) — off the traffic path, where
          the recompile cannot contend with live prefill/decode;
        - a SECOND fault arriving before the deferred rebuild ran
          escalates: the optimistic read was wrong, the program is
          suspect, and the rebuild runs synchronously NOW (the honest
          pause).  Past ``rebuild_limit`` the fault is re-raised — a
          persistently crashing engine must not loop silently."""
        self._count("serve/engine_faults")
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            self.slots[i] = None
            self._send_to_retry(req, f"engine:{type(error).__name__}")
        if self._rebuilds_started >= self.rebuild_limit:
            raise RuntimeError(
                f"engine fault after {self._rebuilds_started} supervised "
                f"rebuilds (rebuild_limit={self.rebuild_limit})"
            ) from error
        if self._rebuild_pending:
            self._run_rebuild()  # repeat fault: rebuild before retrying
        else:
            self._rebuild_pending = True

    def _run_rebuild(self) -> None:
        self._rebuild_pending = False
        self._rebuilds_started += 1
        self._count("serve/engine_rebuilds")
        try:
            self.engine.rebuild()
        except BaseException as e:
            raise RuntimeError("supervised engine rebuild failed") from e

    def flush_rebuild(self) -> bool:
        """Run a deferred engine rebuild now if one is owed (idle
        point / rolling restart); returns True when a rebuild ran."""
        if not self._rebuild_pending:
            return False
        self._run_rebuild()
        return True

    # -- admission --------------------------------------------------------
    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def _overloaded(self) -> bool:
        """Degradation rung 2's trigger: pool pressure past
        ``clamp_occupancy`` or a bounded queue past
        ``clamp_queue_depth``."""
        if self.pool.occupancy() >= self.clamp_occupancy:
            return True
        return (
            self.clamp_queue_depth is not None
            and len(self.queue) >= self.clamp_queue_depth
        )

    def _readmit(self, req: Request, slot: int) -> bool:
        """Re-admit a retrying request that already has its first
        token: pages and prefix were retained, so it drops straight
        back into a decode slot and resumes from where it left off —
        no re-prefill, no TTFT mutation."""
        now = self.clock()
        req.status = RUNNING
        req.blocked_since = None
        self.slots[slot] = req
        self._count("serve/readmitted")
        if self.spans is not None:
            self.spans.request_event(
                req.rid, "decode", now,
                resumed=True, attempt=req.retries,
            )
        return True

    def _admit_one(self) -> bool:
        """Try to move the queue head into a free slot (prefill now,
        or straight back to decode for a retrying request).  Returns
        True when a request was admitted or shed (progress)."""
        if not self.queue:
            return False
        slot = self._free_slot()
        if slot is None:
            return False
        # chaos: the serve.admission site — a transient admission-path
        # fault leaves the head queued (retried next iteration), never
        # kills the process
        idx = self._admissions
        self._admissions += 1
        try:
            chaos.maybe_fail(chaos.SERVE_ADMISSION, idx)
        except chaos.InjectedFault:
            self._count("serve/admission_faults")
            return False
        req = self.queue[0]
        if self.draining and req.status != RETRYING:
            # drain admits nothing new; in-flight (retrying) work may
            # still re-enter to finish.  With a handoff target the
            # never-admitted head re-routes instead of shedding.
            self.queue.popleft()
            if self._drain_handoff is not None and self._reroute_request(
                req, self._drain_handoff
            ):
                self._drain_rerouted += 1
            else:
                self._shed_request(req, SHED_DRAINING)
            return True
        if req.status == RETRYING and req.first_token_at is not None:
            self.queue.popleft()
            return self._readmit(req, slot)
        if len(req.prompt) > self.serve.max_context:
            self.queue.popleft()
            self._shed_request(req, SHED_OVERSIZE)
            return True
        need = self.pool.pages_for(len(req.prompt))
        if (
            self.prefix is not None
            and not req.cache_probed
            and req.first_token_at is None
        ):
            # ONE cache probe per request: match + borrow pin the hit
            # run (refcount+1 per page) BEFORE any allocation, so the
            # LRU eviction the allocation below may trigger can never
            # reclaim the pages this request is about to ride.  The
            # borrowed pages sit on ``req.pages`` from here on — the
            # ownership ledger covers them whether the request admits
            # now, waits pool-blocked in the queue, retries, or sheds.
            req.cache_probed = True
            hit_pages, hit_tokens = self.prefix.match(req.prompt)
            if hit_tokens:
                self.prefix.borrow(hit_pages)
                req.pages = list(hit_pages)
                req.cache_hit_pages = len(hit_pages)
                req.cache_hit_tokens = hit_tokens
                self._count("serve/prefix_hits")
                self._count("serve/prefix_hit_tokens", hit_tokens)
            else:
                self._count("serve/prefix_misses")
        if len(req.pages) < need:
            grown = self._alloc(need - len(req.pages))
            pages = None if grown is None else req.pages + grown
            if pages is None:
                # pool exhausted: shed only once the TTFT budget is
                # already blown — before that the request just waits
                if (
                    req.slo_ttft_ms is not None
                    and 1e3 * (self.clock() - req.submitted_at)
                    > req.slo_ttft_ms
                ):
                    self.queue.popleft()
                    self._shed_request(req, SHED_DEADLINE)
                    return True
                return False
        else:
            pages = req.pages  # retained across a prefill retry
        # the ledger owns the target pages from here on — set BEFORE the
        # draft allocation below so a draft-side wait or shed can never
        # strand freshly-allocated target pages outside the ledger
        req.pages = pages
        if self.engine.spec is not None and req.spec_ok:
            # speculative decoding: the draft model mirrors the target's
            # page span in its own "draft" namespace.  All-or-nothing,
            # same wait/shed semantics as the target allocation — a
            # request never admits with a half-provisioned draft cache.
            dneed = need - len(req.draft_pages)
            if dneed > 0:
                dgot = self._alloc(dneed, ns="draft")
                if dgot is None:
                    if (
                        req.slo_ttft_ms is not None
                        and 1e3 * (self.clock() - req.submitted_at)
                        > req.slo_ttft_ms
                    ):
                        self.queue.popleft()
                        self._shed_request(req, SHED_DEADLINE)
                        return True
                    return False
                req.draft_pages.extend(dgot)
        # degradation rung 2 — clamp the token budget while overloaded:
        # admit MORE requests shallower instead of fewer deeper
        if (
            self.clamp_max_new_tokens is not None
            and req.max_new_tokens > self.clamp_max_new_tokens
            and self._overloaded()
        ):
            req.clamped_from = req.max_new_tokens
            req.max_new_tokens = self.clamp_max_new_tokens
            self._count("serve/clamped")
            if self.spans is not None:
                self.spans.instant(
                    "req/clamped", self.clock(), track="serve/requests",
                    lane=req.rid, max_new_tokens=req.max_new_tokens,
                    clamped_from=req.clamped_from,
                )
        self.queue.popleft()
        now = self.clock()
        self._close_blocked(req, now)
        req.admitted_at = now
        if self.spans is not None:
            self.spans.request_event(
                req.rid, "prefill", now,
                bucket=self.engine.bucket_for(len(req.prompt)),
                prompt_tokens=len(req.prompt), pages=len(pages),
                **({"cached_tokens": req.cache_hit_tokens}
                   if req.cache_hit_tokens else {}),
                **({"attempt": req.retries} if req.retries else {}),
            )
        if self.prefix is not None or self.prefill_chunk_tokens is not None:
            # prefix-cache / chunked mode: the slot is taken NOW (pages
            # and position pinned) but the prefill itself advances one
            # page-multiple chunk per step, interleaved between decode
            # iterations — a long cold prompt no longer stalls running
            # streams, and a cache hit re-runs only its final chunk
            return self._start_chunked_prefill(req, slot)
        try:
            _, first = self.engine.prefill(
                req.prompt, pages, temperature=req.temperature
            )
        except Exception as e:
            # a crashed prefill is transient by default: the request
            # keeps its pages and re-enters through bounded retry (the
            # pages carry no trusted content yet — the retry prefills
            # them again)
            self._count("serve/engine_faults")
            self._send_to_retry(req, f"prefill:{type(e).__name__}")
            return True
        if not self.engine.last_prefill_finite:
            # poisoned at the first token: quarantine the request, not
            # the process — its logits are not evidence of anything
            self._shed_request(req, SHED_POISONED)
            return True
        return self._finish_prefill(req, slot, first)

    def _start_chunked_prefill(self, req: Request, slot: int) -> bool:
        """Enter the ``prefilling`` phase: position the prefill cursor
        past the cache hit (floored to the chunk grain so a hit re-runs
        the exact same FINAL chunk the cold run executed — that is what
        makes the hit's first token bit-identical under a fixed
        ``prefill_chunk_tokens``) and park the request in its slot.
        :meth:`_advance_prefills` runs one chunk per step from here."""
        n = len(req.prompt)
        grain = self.prefill_chunk_tokens or self.serve.page_size
        # never skip the last position: its logits make the first token
        req.prefill_pos = (min(req.cache_hit_tokens, n - 1) // grain) * grain
        if self.prefix is not None:
            req.prefill_started_at = self.clock()
        req.status = PREFILLING
        self.slots[slot] = req
        return True

    def _advance_prefill(self, req: Request, slot: int) -> None:
        """Run ONE prefill chunk for a ``prefilling`` slot.  The chunk
        starts page-aligned (admission floors the cursor, chunks are
        page multiples), so chunk-local KV blocks map 1:1 onto the
        request's absolute pages; blocks that land on borrowed cache
        pages are redirected to the null page — a hit NEVER rewrites a
        page another request may be reading."""
        n = len(req.prompt)
        ps = self.serve.page_size
        start = req.prefill_pos
        end = min(start + (self.prefill_chunk_tokens or n), n)
        first_page = start // ps
        chunk_pages = [
            NULL_PAGE if pi < req.cache_hit_pages else req.pages[pi]
            for pi in range(first_page, (end - 1) // ps + 1)
        ]
        try:
            _, first = self.engine.chunk_prefill(
                req.prompt[start:end], start, req.pages, chunk_pages,
                temperature=req.temperature,
            )
        except Exception as e:
            self._count("serve/engine_faults")
            self.slots[slot] = None
            self._send_to_retry(req, f"prefill:{type(e).__name__}")
            return
        if not self.engine.last_prefill_finite:
            self.slots[slot] = None
            self._shed_request(req, SHED_POISONED)
            return
        req.prefill_pos = end
        if end == n:
            self._finish_prefill(req, slot, first)

    def _advance_prefills(self) -> None:
        for i, req in enumerate(self.slots):
            if req is not None and req.status == PREFILLING:
                self._advance_prefill(req, i)

    def _finish_prefill(self, req: Request, slot: int, first: int) -> bool:
        """First-token bookkeeping shared by the monolithic and chunked
        prefill paths; in cache mode also COMMITS the prompt's pages to
        the prefix cache so every later request sharing the prefix pays
        only its tail chunk."""
        req.ctx_len = len(req.prompt)
        req.tokens.append(first)
        req.first_token_at = self.clock()
        req.status = RUNNING
        self.slots[slot] = req
        self._tokens_out += 1
        self._count("serve/admitted")
        self._count("serve/prefills")
        self._count("serve/tokens_out")
        self._gauge("serve/ttft_ms", req.ttft_ms)
        self.ttft_hist.observe(req.ttft_ms)
        if self.prefix is not None:
            added = self.prefix.commit(
                req.prompt,
                req.pages[: self.pool.pages_for(len(req.prompt))],
            )
            if added:
                self._count("serve/prefix_commits", added)
        if self.engine.spec is not None and req.spec_ok:
            # warm the draft KV over the prompt so proposals start from
            # the same context the target sees.  A crashed draft prefill
            # DEMOTES the request to plain decode — the draft is an
            # accelerator, never a correctness dependency.
            try:
                self.engine.draft_prefill(req.prompt, req.draft_pages)
            except Exception:
                self._count("serve/draft_faults")
                req.spec_ok = False
        if self._finished(req):
            self.slots[slot] = None
            self._retire(req, DONE)
            self._count("serve/completed")
        elif self.spans is not None:
            # entering the decode phase: the closing event carries the
            # full TTFT attribution onto the req/prefill span
            self.spans.request_event(
                req.rid, "decode", req.first_token_at,
                **(req.ttft_components() or {}),
            )
        return True

    def _finished(self, req: Request) -> bool:
        if len(req.tokens) >= req.max_new_tokens:
            return True
        if req.eos_token is not None and req.tokens and (
            req.tokens[-1] == req.eos_token
        ):
            return True
        # context capacity: the NEXT fed token would not fit
        return req.ctx_len + 1 > self.serve.max_context

    # -- decode -----------------------------------------------------------
    def _ensure_target_page(self, req: Request, idx: int) -> bool:
        """Make target page ``idx`` writable: allocate it if the span
        has not reached it yet, and copy-on-write fork it first when it
        is SHARED (a borrowed cache run's tail, or this request's own
        pages after it committed them) — a fresh page gets a device
        copy of the shared one, the shared reference is dropped, and
        appends proceed on the private copy; co-readers never see the
        write."""
        if idx < len(req.pages):
            page = req.pages[idx]
            if self.pool.refcount(page) > 1:
                got = self._alloc(1)
                if got is None:
                    return False
                self.engine.fork_page(page, got[0])
                self.pool.free([page])
                req.pages[idx] = got[0]
                if req.cache_hit_pages > idx:
                    req.cache_hit_pages = idx
                self._count("serve/prefix_forks")
            return True
        while len(req.pages) <= idx:
            got = self._alloc(1)
            if got is None:
                return False
            req.pages.extend(got)
        return True

    def _ensure_growth_page(self, req: Request) -> bool:
        """The next append lands at position ``ctx_len``; allocate (or
        COW-fork) its page if needed."""
        return self._ensure_target_page(
            req, req.ctx_len // self.serve.page_size
        )

    def _ensure_spec_span(self, req: Request) -> bool:
        """Provision the whole speculative window BEFORE the round: a
        spec round may write target KV at positions ``ctx_len`` through
        ``ctx_len + k``, so every page that span touches must be
        private and writable NOW.  This is the real COW obligation of
        speculative decoding — rejected positions are overwritten in
        place, which is only safe because no shared page is ever
        written.  The draft span grows in the ``draft`` namespace
        alongside.  Returns False on allocation failure (the caller
        demotes the slot to plain decode for this round)."""
        ps = self.serve.page_size
        k = self.engine.spec.k
        for idx in range(req.ctx_len // ps, (req.ctx_len + k) // ps + 1):
            if idx >= self.serve.max_pages_per_seq:
                return False
            if not self._ensure_target_page(req, idx):
                return False
            while len(req.draft_pages) <= idx:
                got = self._alloc(1, ns="draft")
                if got is None:
                    return False
                req.draft_pages.extend(got)
        return True

    def _decode_once(self) -> None:
        """One decode pass over the running batch: speculative rounds
        for spec-eligible slots (unless the degradation ladder tripped
        the acceptance fallback), plain single-token decode for the
        rest."""
        if self.engine.spec is not None and not self._spec_fallback:
            self._spec_decode_once()
        else:
            self._plain_decode_once(None)

    def _plain_decode_once(self, only: Optional[set]) -> None:
        """One plain (single-token) decode iteration.  ``only`` limits
        the pass to the given slot indices (the non-speculative side of
        a mixed batch); ``None`` rides every running slot."""
        b = len(self.slots)
        tokens = np.zeros((b,), np.int32)
        lengths = np.zeros((b,), np.int32)
        temps = np.zeros((b,), np.float32)
        streams = np.zeros((b,), np.uint32)
        gens = np.zeros((b,), np.int32)
        tables = np.full(
            (b, self.serve.max_pages_per_seq), NULL_PAGE, np.int32
        )
        for i, req in enumerate(self.slots):
            if req is None or req.status == PREFILLING:
                # a prefilling slot rides no decode iteration — its
                # context advances one chunk per step instead
                continue
            if only is not None and i not in only:
                continue
            if not self._ensure_growth_page(req):
                # pool exhausted mid-decode: shed the youngest running
                # request (least sunk cost) and retry this one
                victims = sorted(
                    self.running, key=lambda r: r.submitted_at or 0.0
                )
                victim = victims[-1]
                v_slot = self.slots.index(victim)
                self.slots[v_slot] = None
                self._shed_request(victim, SHED_GROWTH_VICTIM)
                # the victim's row may already be staged for this
                # iteration — clear it so the decode never touches its
                # (now freed) pages
                tokens[v_slot] = 0
                lengths[v_slot] = 0
                tables[v_slot] = NULL_PAGE
                if victim is req or not self._ensure_growth_page(req):
                    if self.slots[i] is req:
                        self.slots[i] = None
                        self._shed_request(req, SHED_POOL_EXHAUSTED)
                    continue
            tokens[i] = req.tokens[-1]
            lengths[i] = req.ctx_len + 1  # context incl. the fed token
            temps[i] = req.temperature
            streams[i] = self._stream(req)
            gens[i] = len(req.tokens) - 1
            tables[i] = self._page_table_row(req)
        if not lengths.any():
            return
        t0 = self.clock()
        try:
            _, next_tokens = self.engine.decode(
                tokens, lengths, tables, temps,
                streams=streams, gens=gens,
            )
        except Exception as e:
            # a crashed decode step produced nothing host-side: every
            # rider keeps its prefix and pages and re-enters through
            # bounded retry while the engine rebuilds under supervision
            self._on_engine_fault(e)
            return
        elapsed_ms = 1e3 * (self.clock() - t0)
        finite = self.engine.last_decode_finite
        self._count("serve/decode_steps")
        # engine-numbered iteration id: the correlation key linking a
        # request's decode span to the engine batch iterations it rode
        it = getattr(self.engine, "decode_iters", None)
        for i, req in enumerate(self.slots):
            if req is None or req.status == PREFILLING:
                continue
            if only is not None and i not in only:
                continue
            if finite is not None and not bool(finite[i]):
                # poisoned-request quarantine: a non-finite logits row
                # evicts ONLY the offending slot — its token is
                # garbage, its KV is suspect — while the rest of the
                # batch keeps its tokens from this very iteration
                self.slots[i] = None
                self._shed_request(req, SHED_POISONED)
                continue
            timeout_ms = (
                req.decode_timeout_ms
                if req.decode_timeout_ms is not None
                else self.decode_timeout_ms
            )
            if timeout_ms is not None and elapsed_ms > timeout_ms:
                # a hung iteration (per-request budget): discard this
                # request's token from the suspect step — the KV append
                # is positionally idempotent, so the retried decode
                # rewrites the same slot — and re-admit with the prefix
                # preserved
                self._count("serve/decode_timeouts")
                self.slots[i] = None
                self._send_to_retry(
                    req, f"decode_timeout:{elapsed_ms:.0f}ms"
                )
                continue
            if it is not None:
                if req.first_decode_iter is None:
                    req.first_decode_iter = it
                req.last_decode_iter = it
            req.ctx_len += 1
            req.tokens.append(int(next_tokens[i]))
            self._tokens_out += 1
            self._count("serve/tokens_out")
            if self._finished(req):
                self.slots[i] = None
                self._retire(req, DONE)
                self._count("serve/completed")

    # -- speculative decoding ---------------------------------------------
    def _stream(self, req: Request) -> int:
        """Stable per-request sampling-stream id.  The engine folds it
        into its base key and each emission folds its position index, so
        the sampled token at (request, position) is a pure function of
        request identity — a rollback replay, a spec bonus draw, and
        plain decode all reproduce the exact same stream."""
        if req.stream_seed is not None:
            return req.stream_seed
        return zlib.crc32(str(req.rid).encode()) & 0x7FFFFFFF

    def _spec_decode_once(self) -> None:
        """Partition the running batch: slots with a healthy draft ride
        a speculative round (propose k, verify once, roll back the
        rejected tail); everything else — draft-demoted requests, slots
        whose window cannot be provisioned, streams near the context
        ceiling — rides plain decode.  Mixed batches are the steady
        state, not an edge case."""
        k = self.engine.spec.k
        spec_idx: List[int] = []
        plain_idx: List[int] = []
        for i, req in enumerate(self.slots):
            if req is None or req.status == PREFILLING:
                continue
            if (
                req.spec_ok
                and req.draft_pages
                and req.ctx_len + 1 + k <= self.serve.max_context
            ):
                spec_idx.append(i)
            else:
                plain_idx.append(i)
        for i in list(spec_idx):
            if not self._ensure_spec_span(self.slots[i]):
                # cannot provision the whole window: demote for THIS
                # round only — the pool may free up by the next one
                spec_idx.remove(i)
                plain_idx.append(i)
        if spec_idx:
            self._spec_round(spec_idx, k)
        if plain_idx:
            self._plain_decode_once(set(plain_idx))

    def _spec_round(self, idx: List[int], k: int) -> None:
        """One propose → verify → accept/rollback round for the given
        slots.  The verify step scans the SAME per-token program body
        plain decode runs, so every accepted token is bit-identical to
        the token plain decode would have produced; the rejected tail's
        KV (target and draft) is truncated afterwards so no stale entry
        outlives the round."""
        b = len(self.slots)
        tokens = np.zeros((b,), np.int32)
        lengths = np.zeros((b,), np.int32)
        temps = np.zeros((b,), np.float32)
        streams = np.zeros((b,), np.uint32)
        gens = np.zeros((b,), np.int32)
        tables = np.full(
            (b, self.serve.max_pages_per_seq), NULL_PAGE, np.int32
        )
        dtables = np.full(
            (b, self.serve.max_pages_per_seq), NULL_PAGE, np.int32
        )
        for i in idx:
            req = self.slots[i]
            tokens[i] = req.tokens[-1]
            lengths[i] = req.ctx_len + 1  # context incl. the fed token
            temps[i] = req.temperature
            streams[i] = self._stream(req)
            gens[i] = len(req.tokens) - 1
            tables[i] = self._page_table_row(req)
            dtables[i, : len(req.draft_pages)] = req.draft_pages
        t0 = self.clock()
        try:
            out, acc, finite = self.engine.spec_step(
                tokens, lengths, tables, dtables, temps, streams, gens
            )
        except chaos.InjectedFault as e:
            if getattr(e, "site", None) == chaos.SERVE_DRAFT:
                # a faulted draft never corrupts a stream: the round
                # was abandoned BEFORE any verify-side KV write, and
                # every rider falls back to plain decode this iteration
                self._count("serve/draft_faults")
                self._plain_decode_once(set(idx))
                return
            self._on_engine_fault(e)
            return
        except Exception as e:
            self._on_engine_fault(e)
            return
        elapsed_ms = 1e3 * (self.clock() - t0)
        self._count("serve/decode_steps")
        self._count("serve/spec_rounds")
        it = getattr(self.engine, "decode_iters", None)
        rb_starts = np.zeros((b,), np.int32)
        rb_counts = np.zeros((b,), np.int32)
        drafted = accepted = emitted = slot_steps = 0
        for i in idx:
            req = self.slots[i]
            slot_steps += 1
            if finite is not None and not bool(finite[i]):
                # poisoned VERIFY output — the target's own logits are
                # garbage, same quarantine as a poisoned plain step
                self.slots[i] = None
                self._shed_request(req, SHED_POISONED)
                continue
            timeout_ms = (
                req.decode_timeout_ms
                if req.decode_timeout_ms is not None
                else self.decode_timeout_ms
            )
            if timeout_ms is not None and elapsed_ms > timeout_ms:
                self._count("serve/decode_timeouts")
                self.slots[i] = None
                self._send_to_retry(
                    req, f"decode_timeout:{elapsed_ms:.0f}ms"
                )
                continue
            if it is not None:
                if req.first_decode_iter is None:
                    req.first_decode_iter = it
                req.last_decode_iter = it
            a = int(acc[i])
            drafted += k
            accepted += a
            start_ctx = req.ctx_len
            n_emit = 0
            for t in out[i, : a + 1]:
                req.ctx_len += 1
                req.tokens.append(int(t))
                n_emit += 1
                self._tokens_out += 1
                if self._finished(req):
                    break
            emitted += n_emit
            self._count("serve/tokens_out", n_emit)
            if self._finished(req):
                self.slots[i] = None
                self._retire(req, DONE)
                self._count("serve/completed")
            else:
                # the round wrote target KV at [start_ctx, start_ctx+k];
                # everything past the new context is a rejected draft's
                # residue and is truncated below (slots that retired or
                # shed keep counts 0 — the rollback masks them to the
                # null page)
                stale = start_ctx + k + 1 - req.ctx_len
                if stale > 0:
                    rb_starts[i] = req.ctx_len
                    rb_counts[i] = stale
        if rb_counts.any():
            self.engine.rollback(rb_starts, rb_counts, tables)
            self.engine.draft_rollback(rb_starts, rb_counts, dtables)
            self._count(
                "serve/spec_rollbacks", int((rb_counts > 0).sum())
            )
        self._count("serve/spec_drafted", drafted)
        self._count("serve/spec_accepted", accepted)
        if drafted > accepted:
            self._count("serve/spec_rejected", drafted - accepted)
        if self._spec_window is not None:
            self._spec_window.append(
                (drafted, accepted, emitted, slot_steps)
            )
            if len(self._spec_window) == self._spec_window.maxlen:
                tot_d = sum(w[0] for w in self._spec_window)
                tot_a = sum(w[1] for w in self._spec_window)
                if tot_d and (
                    tot_a / tot_d < self.engine.spec.min_accept_rate
                ):
                    # degradation ladder: speculation is costing more
                    # than it saves — fall back to plain decode until
                    # an operator resume() re-arms it
                    self._spec_fallback = True
                    self._count("serve/spec_fallbacks")

    # -- metrics ----------------------------------------------------------
    def _count(self, name: str, n: float = 1.0) -> None:
        if self._mstate is not None:
            self._mstate = self.registry.update(self._mstate, {name: n})

    def _gauge(self, name: str, value) -> None:
        if self._mstate is not None and value is not None:
            self._mstate = self.registry.update(
                self._mstate, {name: float(value)}
            )

    def _publish_attribution(self) -> None:
        """Percentile gauges over the recent completion window — one
        batched registry update, recomputed only when new completions
        arrived since the last publish."""
        if (
            self._mstate is None
            or not self._comps
            or len(self.completed) == self._published_done
        ):
            return
        self._published_done = len(self.completed)
        attr = ttft_attribution(self._comps)
        updates: Dict[str, float] = {}
        for comp in TTFT_COMPONENTS:
            for tag, value in attr[f"{comp}_ms"].items():
                updates[f"serve/ttft_{comp}_ms_{tag}"] = value
        updates["serve/ttft_queue_wait_fraction"] = attr[
            "queue_wait_fraction"
        ]
        self._mstate = self.registry.update(self._mstate, updates)

    def _publish(self) -> None:
        now = self.clock()
        self._window.append((now, self._tokens_out))
        tps = 0.0
        if len(self._window) >= 2:
            (t0, n0), (t1, n1) = self._window[0], self._window[-1]
            if t1 > t0:
                tps = (n1 - n0) / (t1 - t0)
        self._gauge("serve/queue_depth", len(self.queue))
        self._gauge("serve/batch_fill", self.batch_fill())
        self._gauge("serve/page_occupancy", self.pool.occupancy())
        self._gauge("serve/tokens_per_s", tps)
        if self.prefix is not None:
            self._gauge(
                "serve/prefix_cached_pages",
                float(len(self.prefix.cached_pages())),
            )
        if self._spec_window:
            tot_d = sum(w[0] for w in self._spec_window)
            tot_a = sum(w[1] for w in self._spec_window)
            tot_e = sum(w[2] for w in self._spec_window)
            tot_s = sum(w[3] for w in self._spec_window)
            self._gauge(
                "serve/spec_accept_rate", tot_a / tot_d if tot_d else 0.0
            )
            self._gauge(
                "serve/spec_tokens_per_step",
                tot_e / tot_s if tot_s else 0.0,
            )
        self._publish_attribution()
        if self._mstate is not None:
            self.registry.observe(self._step, self._mstate)

    # -- the iteration ----------------------------------------------------
    def step(self) -> None:
        """One continuous-batching iteration: admit (prefill) into free
        slots, then one decode pass over the running batch."""
        # admit until slots or pages run out — each prefill slots in
        # between decode iterations by construction
        while self._admit_one():
            pass
        if self.queue:
            # admission gave up with requests still queued: they are
            # resource-blocked (no slot / pool cannot cover the head)
            # from here until the next admission attempt — the
            # queue_wait TTFT component.  Only pre-first-token requests
            # accrue it: a retrying request past its first token is in
            # RECOVERY wait, which must not pollute TTFT attribution
            # (the components would stop summing to the measured TTFT).
            now = self.clock()
            for r in self.queue:
                if r.first_token_at is None and r.blocked_since is None:
                    r.blocked_since = now
        if self.prefix is not None and chaos.active(
            chaos.SERVE_PREFIX_EVICT, self._step
        ) is not None:
            # forced full eviction sweep (the ``serve.prefix_evict``
            # chaos drill): every idle cached run is reclaimed at once
            # — borrowed pages MUST survive (refcount > 1 is never
            # evictable) and the ledger must stay exact, proven by the
            # leak check right here
            self._count("serve/prefix_evict_faults")
            freed = self.prefix.evict()
            if freed:
                self._count("serve/prefix_evictions", freed)
            if self.leak_checks:
                self.leak_check()
        self._advance_prefills()
        self._decode_once()
        self._step += 1
        self._publish()
        if self._rebuild_pending and not self.pending:
            # idle point reached in a caller-driven step() loop: run
            # the owed rebuild now, off the traffic path (run()/drain()
            # reach the same flush through their own exits)
            self.flush_rebuild()

    def run(self, max_steps: int = 10_000) -> None:
        """Drain: step until every submitted request completed or shed.
        An engine rebuild deferred during the run executes at the idle
        exit — off the traffic path."""
        for _ in range(max_steps):
            if not self.pending:
                self.flush_rebuild()
                return
            self.step()
        raise RuntimeError(
            f"scheduler did not drain within {max_steps} iterations"
        )

    def drain(self, max_steps: int = 10_000, *,
              handoff=None) -> Dict[str, object]:
        """Graceful drain for a rolling restart (docs/serving.md
        "Failure semantics"): stop admitting new work, let running
        decodes AND in-flight retrying re-admissions finish, then
        report the drained state with the page pool provably empty.
        The scheduler stays drained: subsequent submits are rejected
        until :meth:`resume` is called.

        ``handoff`` — a ``callable(Request) -> bool`` (e.g. a fleet
        router's re-route hook): each never-admitted queue entry is
        OFFERED to it instead of being shed; on acceptance the request
        leaves this replica as ``shed(rerouted)`` on the ledger and
        continues elsewhere with its prompt and shared retry budget
        intact.  Without a handoff (or when it refuses) the entry is
        shed loudly as ``draining`` — the client retries on another
        replica itself."""
        self.start_drain(handoff=handoff)
        for _ in range(max_steps):
            if not self.pending:
                break
            self.step()
        else:
            raise RuntimeError(
                f"drain did not complete within {max_steps} iterations"
            )
        return self.finish_drain()

    def start_drain(self, *, handoff=None) -> int:
        """Enter the draining state (phase 1 of :meth:`drain`): stop
        admitting new work, hand never-admitted queue entries to
        ``handoff`` (or shed them as ``draining``), keep in-flight
        retrying work.  Returns the re-routed count.  Split out of
        :meth:`drain` so a fleet control plane can drain a replica
        INCREMENTALLY — ticking :meth:`step` itself on a shared fleet
        clock while the other replicas keep serving — instead of
        monopolizing the loop until this replica is empty; call
        :meth:`finish_drain` once :attr:`pending` clears."""
        self.draining = True
        self._drain_handoff = handoff
        self._count("serve/drains")
        self._gauge("serve/draining", 1.0)
        # hand off (or reject) never-admitted work now; retrying
        # requests are in-flight (they hold pages and a prefix) and
        # get to finish here
        kept = [r for r in self.queue if r.status == RETRYING]
        rejected = [r for r in self.queue if r.status != RETRYING]
        self.queue = collections.deque(kept)
        rerouted = 0
        for req in rejected:
            if handoff is not None and self._reroute_request(req, handoff):
                rerouted += 1
            else:
                self._shed_request(req, SHED_DRAINING)
        self._drain_rerouted = rerouted
        return rerouted

    def finish_drain(self) -> Dict[str, object]:
        """Seal a drain (phase 3): settle any owed rebuild, re-prove
        the pool empty, and report — :meth:`drain`'s exit, also called
        directly by a fleet that drove the intervening steps itself."""
        # an incremental drain can still be re-routing through
        # _admit_one up to the last step — count those too
        self._drain_handoff = None
        self.flush_rebuild()  # settle any rebuild owed from the storm
        if self.prefix is not None:
            # a drained replica keeps no cached history: release every
            # cache-owned reference so the pool is PROVABLY empty below
            self.prefix.flush()
        self.leak_check()
        self._publish()
        return {
            "drained": True,
            "completed": len(self.completed),
            "shed": len(self.shed),
            "rerouted": self._drain_rerouted,
            "pool_in_use": self.pool.in_use,
            "engine_rebuilds": self.engine.rebuilds,
            "leak_checks_run": self.leak_checks_run,
        }

    def resume(self) -> None:
        """Leave the drained state (the rolling restart completed):
        submissions are accepted again and the ``serve/draining``
        gauge clears — a resumed replica must not keep reporting
        itself as draining."""
        self.draining = False
        self._gauge("serve/draining", 0.0)
        # re-arm speculation: a fresh deploy may carry a better draft,
        # so the acceptance fallback and its window reset here
        self._spec_fallback = False
        if self._spec_window is not None:
            self._spec_window.clear()
