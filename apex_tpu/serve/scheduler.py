"""Continuous batching — admission, decode slots, SLOs, shedding.

The throughput lever of a serving system is keeping the decode batch
full: a decode iteration costs nearly the same whether 1 or
``max_batch`` sequences ride it (the weights are read either way), so
every empty slot is wasted HBM bandwidth.
:class:`ContinuousBatchingScheduler` admits new sequences INTO the
running batch at page granularity — a prefill is slotted between decode
iterations (bucketed padding keeps the compiled-shape count finite),
the new sequence joins the very next decode, and finished sequences
free their pages to the pool immediately.

Admission control and degradation are explicit:

- a request is admitted when a decode slot is free AND the page pool
  covers its prompt (``PagePool.alloc`` is all-or-nothing);
- a queued request whose **TTFT SLO deadline** has already passed while
  the pool stays exhausted is **shed** (rejected loudly — the client
  can retry elsewhere) instead of silently blowing its latency budget;
- when a RUNNING sequence needs a growth page and the pool is empty,
  the youngest running request is shed to keep the older ones making
  progress (LIFO victim: it has the least sunk prefill cost).

Every shed carries a **reason** (:data:`SHED_REASONS`): the single
``serve/shed`` counter is split into per-reason counters so "we shed
3%" becomes "we shed 3%, all of it deadline-in-queue — admission is
starved, not the decode batch".

Every iteration publishes the serving gauges through the shared
:class:`~apex_tpu.observability.metrics.MetricRegistry` — queue depth,
batch fill, page-pool occupancy, tokens/s, TTFT — the same spine
training telemetry rides, so :class:`~apex_tpu.observability.health.
TTFTRule` / :class:`~apex_tpu.observability.health.QueueDepthRule`
watchdogs page the same health layer (``docs/serving.md``).

**TTFT attribution** (``docs/observability.md``): each completed
request's TTFT decomposes into three components that sum to the
measured TTFT *by construction* (the same remainder discipline
:mod:`~apex_tpu.observability.attribution` applies to step time):

- ``queue_wait`` — time the request sat in the queue while admission
  was **resource-blocked** (no free decode slot, or the page pool
  could not cover the queue head);
- ``prefill``    — admission to first token (the prefill program);
- ``contention`` — the remainder of the pre-admission wait: the
  request was admissible but the scheduler was busy running decode
  iterations for the requests already in the batch.

Per-component p50/p95/p99 gauges and the queue-wait fraction publish
through the registry on the observation cadence;
:class:`~apex_tpu.observability.health.QueueWaitFractionRule` alerts
when TTFT is dominated by starved admission.  With a
:class:`~apex_tpu.observability.spans.SpanRecorder` attached
(``spans=``), every request additionally records its full span chain
``queued → admitted → prefill → decode[i] → done|shed(reason)`` with
engine decode-iteration correlation ids — the per-request causal
record ``tools/timeline.py`` merges into one Perfetto timeline.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import time
from typing import Deque, Dict, List, Optional

import numpy as np

from apex_tpu.observability.meter import percentile as _percentile
from apex_tpu.observability.ometrics import (
    DEFAULT_LATENCY_BUCKETS_MS,
    Histogram,
)
from apex_tpu.serve.cache import NULL_PAGE

__all__ = [
    "Request",
    "ContinuousBatchingScheduler",
    "declare_serve_metrics",
    "ttft_attribution",
    "SHED_REASONS",
    "TTFT_COMPONENTS",
]

_ids = itertools.count()

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
SHED = "shed"

#: shed reasons, each with its own ``serve/shed_<reason>`` counter:
#: ``deadline`` (queued past its TTFT SLO while the pool stayed
#: exhausted), ``growth_victim`` (youngest running request shed to free
#: a growth page), ``pool_exhausted`` (a running request could not grow
#: even after a victim shed), ``oversize`` (prompt exceeds the max
#: context).
SHED_DEADLINE = "deadline"
SHED_GROWTH_VICTIM = "growth_victim"
SHED_POOL_EXHAUSTED = "pool_exhausted"
SHED_OVERSIZE = "oversize"
SHED_REASONS = (
    SHED_DEADLINE, SHED_GROWTH_VICTIM, SHED_POOL_EXHAUSTED, SHED_OVERSIZE,
)

#: TTFT attribution components (ms); they sum to the measured TTFT by
#: construction — see the module docstring
TTFT_COMPONENTS = ("queue_wait", "prefill", "contention")

def ttft_attribution(comps) -> Dict[str, object]:
    """Aggregate per-request TTFT components
    (:meth:`Request.ttft_components` dicts) into per-component
    p50/p95/p99 + the queue-wait fraction — the ONE aggregation behind
    both the scheduler's ``serve/ttft_*`` registry gauges and the
    ``tools/serve_bench.py`` artifact, so the two surfaces
    ``verify_tier1.sh`` cross-checks can never drift apart."""
    out: Dict[str, object] = {}
    for comp in TTFT_COMPONENTS:
        vals = sorted(c[f"{comp}_ms"] for c in comps)
        out[f"{comp}_ms"] = {
            tag: _percentile(vals, q)
            for q, tag in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99"))
        }
    total_ttft = sum(c["ttft_ms"] for c in comps)
    out["queue_wait_fraction"] = (
        sum(c["queue_wait_ms"] for c in comps) / total_ttft
        if total_ttft > 0 else 0.0
    )
    out["samples"] = len(comps)
    return out


#: default for ``ContinuousBatchingScheduler(registry=...)``: inherit
#: the engine's registry.  Pass ``registry=None`` to run with NO
#: telemetry (e.g. a baseline probe that must not pollute the engine
#: registry's observation stream).
ENGINE_REGISTRY = object()


@dataclasses.dataclass
class Request:
    """One generation request and its lifecycle ledger."""

    prompt: List[int]
    max_new_tokens: int = 16
    #: TTFT SLO in milliseconds; None = best-effort (never shed by
    #: deadline, only as a growth-page victim)
    slo_ttft_ms: Optional[float] = None
    eos_token: Optional[int] = None
    rid: int = dataclasses.field(default_factory=lambda: next(_ids))

    # -- runtime ledger (scheduler-owned) --------------------------------
    status: str = QUEUED
    tokens: List[int] = dataclasses.field(default_factory=list)
    pages: List[int] = dataclasses.field(default_factory=list)
    #: KV positions written (prompt + generated-and-fed tokens)
    ctx_len: int = 0
    submitted_at: Optional[float] = None
    #: popped from the queue with pages granted (prefill dispatch)
    admitted_at: Optional[float] = None
    first_token_at: Optional[float] = None
    done_at: Optional[float] = None
    #: why this request was shed (one of :data:`SHED_REASONS`), else None
    shed_reason: Optional[str] = None
    #: accumulated seconds the request sat in the queue while admission
    #: was resource-blocked (the ``queue_wait`` TTFT component)
    queue_blocked_s: float = 0.0
    #: start of the current resource-blocked interval (scheduler-owned)
    blocked_since: Optional[float] = None
    #: engine decode iterations this request rode (correlation ids
    #: into the ``serve/engine`` span track)
    first_decode_iter: Optional[int] = None
    last_decode_iter: Optional[int] = None

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.submitted_at is None or self.first_token_at is None:
            return None
        return 1e3 * (self.first_token_at - self.submitted_at)

    def ttft_components(self) -> Optional[Dict[str, float]]:
        """``{ttft_ms, queue_wait_ms, prefill_ms, contention_ms}`` —
        the three components sum to ``ttft_ms`` by construction
        (contention is the remainder of the pre-admission wait)."""
        if (
            self.submitted_at is None
            or self.admitted_at is None
            or self.first_token_at is None
        ):
            return None
        queue_wait = 1e3 * self.queue_blocked_s
        prefill = 1e3 * (self.first_token_at - self.admitted_at)
        contention = (
            1e3 * (self.admitted_at - self.submitted_at) - queue_wait
        )
        return {
            "ttft_ms": self.ttft_ms,
            "queue_wait_ms": queue_wait,
            "prefill_ms": prefill,
            "contention_ms": contention,
        }


def declare_serve_metrics(registry) -> None:
    """Declare the serving metric set on a registry (idempotent)."""
    for g in ("serve/queue_depth", "serve/batch_fill",
              "serve/page_occupancy", "serve/tokens_per_s",
              "serve/ttft_ms"):
        registry.gauge(g)
    for c in ("serve/admitted", "serve/completed", "serve/shed",
              "serve/tokens_out", "serve/prefills", "serve/decode_steps"):
        registry.counter(c)
    # per-reason shed breakdown (sums to serve/shed)
    for reason in SHED_REASONS:
        registry.counter(f"serve/shed_{reason}")
    # TTFT attribution: per-component percentiles over the recent
    # completion window, plus the fraction the watchdog judges
    for comp in TTFT_COMPONENTS:
        for tag in ("p50", "p95", "p99"):
            registry.gauge(f"serve/ttft_{comp}_ms_{tag}", "ms")
    registry.gauge("serve/ttft_queue_wait_fraction")


class ContinuousBatchingScheduler:
    """Drive an :class:`~apex_tpu.serve.engine.InferenceEngine` with
    continuous batching.

    >>> sched = ContinuousBatchingScheduler(engine)
    >>> sched.submit(Request(prompt=[...], max_new_tokens=32))
    >>> while sched.pending:
    ...     sched.step()

    ``spans`` attaches a :class:`~apex_tpu.observability.spans.
    SpanRecorder`: the scheduler records each request's lifecycle span
    chain and hands the same recorder to the engine for its
    prefill/decode-iteration spans (taking over from any previous
    scheduler's recorder, and sharing a non-default ``clock`` with the
    recorder so the whole record stays on one time basis).
    """

    def __init__(self, engine, *, registry=ENGINE_REGISTRY,
                 clock=time.monotonic, window: int = 32,
                 spans=None, attribution_window: int = 128):
        self.engine = engine
        self.pool = engine.pool
        self.serve = engine.serve
        self.clock = clock
        self.queue: Deque[Request] = collections.deque()
        self.slots: List[Optional[Request]] = [None] * self.serve.max_batch
        self.completed: List[Request] = []
        self.shed: List[Request] = []
        self._step = 0
        # tokens/s over a sliding window of (time, cumulative tokens)
        self._tokens_out = 0
        self._window: Deque = collections.deque(maxlen=window)
        self.registry = (
            engine.registry if registry is ENGINE_REGISTRY else registry
        )
        self.spans = spans
        # this scheduler owns the engine's recorder for its lifetime —
        # a later scheduler on the same engine takes over cleanly
        # (spans=None DETACHES a retired scheduler's recorder) instead
        # of feeding a dead recorder events uncorrelated to any chain
        engine.spans = spans
        if spans is not None:
            if clock is not time.monotonic:
                # ONE time basis per recorder: the request ledger uses
                # this clock, so the engine spans (rec.now()) must too
                # — a mixed-clock record would merge into a timeline
                # that silently misplaces half its tracks.  Export
                # alignment via the wall-clock anchor assumes the
                # default monotonic clock.
                spans.clock = clock
        # recent completions' TTFT components — the percentile window
        self._comps: Deque[Dict[str, float]] = collections.deque(
            maxlen=attribution_window
        )
        # host-side TTFT distribution: the OpenMetrics histogram an
        # --ops-port scrape exposes and the latency-SLO burn-rate math
        # reads (good = observations under the deadline bucket) — one
        # bisect per admission, registry or not
        self.ttft_hist = Histogram(
            "serve/ttft_hist_ms", DEFAULT_LATENCY_BUCKETS_MS, unit="ms",
            help="TTFT distribution over admitted requests",
        )
        self._published_done = 0
        self._mstate = None
        if self.registry is not None:
            declare_serve_metrics(self.registry)
            self._mstate = self.registry.init()

    # -- bookkeeping ------------------------------------------------------
    @property
    def running(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    @property
    def pending(self) -> bool:
        return bool(self.queue) or any(s is not None for s in self.slots)

    def batch_fill(self) -> float:
        return len(self.running) / len(self.slots)

    def submit(self, req: Request) -> Request:
        req.status = QUEUED
        req.submitted_at = self.clock()
        self.queue.append(req)
        if self.spans is not None:
            self.spans.request_event(
                req.rid, QUEUED, req.submitted_at,
                prompt_tokens=len(req.prompt),
                slo_ttft_ms=req.slo_ttft_ms,
            )
        return req

    def _page_table_row(self, req: Request) -> np.ndarray:
        row = np.full((self.serve.max_pages_per_seq,), NULL_PAGE, np.int32)
        row[: len(req.pages)] = req.pages
        return row

    def _close_blocked(self, req: Request, now: float) -> None:
        if req.blocked_since is not None:
            req.queue_blocked_s += now - req.blocked_since
            req.blocked_since = None

    def _span_terminal(self, req: Request, status: str,
                       reason: Optional[str]) -> None:
        rec = self.spans
        if rec is None:
            return
        args: Dict[str, object] = {}
        if status == DONE:
            args["tokens"] = len(req.tokens)
        else:
            args["reason"] = reason
            if req.submitted_at is not None and req.done_at is not None:
                args["waited_ms"] = 1e3 * (req.done_at - req.submitted_at)
        if req.first_decode_iter is not None:
            args["first_iter"] = req.first_decode_iter
            args["last_iter"] = req.last_decode_iter
        # a request retired straight out of prefill (finished or shed
        # at its first token) still owns its TTFT attribution — attach
        # it here so the req/prefill span carries the components
        if rec.open_requests.get(req.rid) == "prefill":
            comps = req.ttft_components()
            if comps:
                args.update(comps)
        rec.request_event(req.rid, status, req.done_at, **args)

    def _retire(self, req: Request, status: str,
                reason: Optional[str] = None) -> None:
        if req.pages:
            self.pool.free(req.pages)
            req.pages = []
        req.status = status
        req.shed_reason = reason if status == SHED else None
        req.done_at = self.clock()
        self._close_blocked(req, req.done_at)
        self._span_terminal(req, status, reason)
        if status == DONE:
            self.completed.append(req)
            comps = req.ttft_components()
            if comps is not None:
                self._comps.append(comps)
        else:
            self.shed.append(req)

    def _shed_request(self, req: Request, reason: str) -> None:
        self._retire(req, SHED, reason)
        self._count("serve/shed")
        self._count(f"serve/shed_{reason}")

    # -- admission --------------------------------------------------------
    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def _admit_one(self) -> bool:
        """Try to move the queue head into a free slot (prefill now).
        Returns True when a request was admitted or shed (progress)."""
        if not self.queue:
            return False
        slot = self._free_slot()
        if slot is None:
            return False
        req = self.queue[0]
        if len(req.prompt) > self.serve.max_context:
            self.queue.popleft()
            self._shed_request(req, SHED_OVERSIZE)
            return True
        need = self.pool.pages_for(len(req.prompt))
        pages = self.pool.alloc(need)
        if pages is None:
            # pool exhausted: shed only once the TTFT budget is already
            # blown — before that the request just waits its turn
            if (
                req.slo_ttft_ms is not None
                and 1e3 * (self.clock() - req.submitted_at) > req.slo_ttft_ms
            ):
                self.queue.popleft()
                self._shed_request(req, SHED_DEADLINE)
                return True
            return False
        self.queue.popleft()
        now = self.clock()
        self._close_blocked(req, now)
        req.admitted_at = now
        req.pages = pages
        if self.spans is not None:
            self.spans.request_event(
                req.rid, "prefill", now,
                bucket=self.engine.bucket_for(len(req.prompt)),
                prompt_tokens=len(req.prompt), pages=len(pages),
            )
        _, first = self.engine.prefill(req.prompt, pages)
        req.ctx_len = len(req.prompt)
        req.tokens.append(first)
        req.first_token_at = self.clock()
        req.status = RUNNING
        self.slots[slot] = req
        self._tokens_out += 1
        self._count("serve/admitted")
        self._count("serve/prefills")
        self._count("serve/tokens_out")
        self._gauge("serve/ttft_ms", req.ttft_ms)
        self.ttft_hist.observe(req.ttft_ms)
        if self._finished(req):
            self.slots[slot] = None
            self._retire(req, DONE)
            self._count("serve/completed")
        elif self.spans is not None:
            # entering the decode phase: the closing event carries the
            # full TTFT attribution onto the req/prefill span
            self.spans.request_event(
                req.rid, "decode", req.first_token_at,
                **(req.ttft_components() or {}),
            )
        return True

    def _finished(self, req: Request) -> bool:
        if len(req.tokens) >= req.max_new_tokens:
            return True
        if req.eos_token is not None and req.tokens and (
            req.tokens[-1] == req.eos_token
        ):
            return True
        # context capacity: the NEXT fed token would not fit
        return req.ctx_len + 1 > self.serve.max_context

    # -- decode -----------------------------------------------------------
    def _ensure_growth_page(self, req: Request) -> bool:
        """The next append lands at position ``ctx_len``; allocate its
        page if the sequence is about to cross a page boundary."""
        if req.ctx_len // self.serve.page_size < len(req.pages):
            return True
        got = self.pool.alloc(1)
        if got is None:
            return False
        req.pages.extend(got)
        return True

    def _decode_once(self) -> None:
        b = len(self.slots)
        tokens = np.zeros((b,), np.int32)
        lengths = np.zeros((b,), np.int32)
        tables = np.full(
            (b, self.serve.max_pages_per_seq), NULL_PAGE, np.int32
        )
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            if not self._ensure_growth_page(req):
                # pool exhausted mid-decode: shed the youngest running
                # request (least sunk cost) and retry this one
                victims = sorted(
                    self.running, key=lambda r: r.submitted_at or 0.0
                )
                victim = victims[-1]
                v_slot = self.slots.index(victim)
                self.slots[v_slot] = None
                self._shed_request(victim, SHED_GROWTH_VICTIM)
                # the victim's row may already be staged for this
                # iteration — clear it so the decode never touches its
                # (now freed) pages
                tokens[v_slot] = 0
                lengths[v_slot] = 0
                tables[v_slot] = NULL_PAGE
                if victim is req or not self._ensure_growth_page(req):
                    if self.slots[i] is req:
                        self.slots[i] = None
                        self._shed_request(req, SHED_POOL_EXHAUSTED)
                    continue
            tokens[i] = req.tokens[-1]
            lengths[i] = req.ctx_len + 1  # context incl. the fed token
            tables[i] = self._page_table_row(req)
        if not any(s is not None for s in self.slots):
            return
        _, next_tokens = self.engine.decode(tokens, lengths, tables)
        self._count("serve/decode_steps")
        # engine-numbered iteration id: the correlation key linking a
        # request's decode span to the engine batch iterations it rode
        it = getattr(self.engine, "decode_iters", None)
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            if it is not None:
                if req.first_decode_iter is None:
                    req.first_decode_iter = it
                req.last_decode_iter = it
            req.ctx_len += 1
            req.tokens.append(int(next_tokens[i]))
            self._tokens_out += 1
            self._count("serve/tokens_out")
            if self._finished(req):
                self.slots[i] = None
                self._retire(req, DONE)
                self._count("serve/completed")

    # -- metrics ----------------------------------------------------------
    def _count(self, name: str, n: float = 1.0) -> None:
        if self._mstate is not None:
            self._mstate = self.registry.update(self._mstate, {name: n})

    def _gauge(self, name: str, value) -> None:
        if self._mstate is not None and value is not None:
            self._mstate = self.registry.update(
                self._mstate, {name: float(value)}
            )

    def _publish_attribution(self) -> None:
        """Percentile gauges over the recent completion window — one
        batched registry update, recomputed only when new completions
        arrived since the last publish."""
        if (
            self._mstate is None
            or not self._comps
            or len(self.completed) == self._published_done
        ):
            return
        self._published_done = len(self.completed)
        attr = ttft_attribution(self._comps)
        updates: Dict[str, float] = {}
        for comp in TTFT_COMPONENTS:
            for tag, value in attr[f"{comp}_ms"].items():
                updates[f"serve/ttft_{comp}_ms_{tag}"] = value
        updates["serve/ttft_queue_wait_fraction"] = attr[
            "queue_wait_fraction"
        ]
        self._mstate = self.registry.update(self._mstate, updates)

    def _publish(self) -> None:
        now = self.clock()
        self._window.append((now, self._tokens_out))
        tps = 0.0
        if len(self._window) >= 2:
            (t0, n0), (t1, n1) = self._window[0], self._window[-1]
            if t1 > t0:
                tps = (n1 - n0) / (t1 - t0)
        self._gauge("serve/queue_depth", len(self.queue))
        self._gauge("serve/batch_fill", self.batch_fill())
        self._gauge("serve/page_occupancy", self.pool.occupancy())
        self._gauge("serve/tokens_per_s", tps)
        self._publish_attribution()
        if self._mstate is not None:
            self.registry.observe(self._step, self._mstate)

    # -- the iteration ----------------------------------------------------
    def step(self) -> None:
        """One continuous-batching iteration: admit (prefill) into free
        slots, then one decode pass over the running batch."""
        # admit until slots or pages run out — each prefill slots in
        # between decode iterations by construction
        while self._admit_one():
            pass
        if self.queue:
            # admission gave up with requests still queued: they are
            # resource-blocked (no slot / pool cannot cover the head)
            # from here until the next admission attempt — the
            # queue_wait TTFT component
            now = self.clock()
            for r in self.queue:
                if r.blocked_since is None:
                    r.blocked_since = now
        self._decode_once()
        self._step += 1
        self._publish()

    def run(self, max_steps: int = 10_000) -> None:
        """Drain: step until every submitted request completed or shed."""
        for _ in range(max_steps):
            if not self.pending:
                return
            self.step()
        raise RuntimeError(
            f"scheduler did not drain within {max_steps} iterations"
        )
