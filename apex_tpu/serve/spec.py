"""Speculative decoding — draft proposals, one-step verify, rollback.

A small **draft** model proposes ``k`` tokens; ONE target-model program
verifies all of them by scoring ``k+1`` positions in a single scan, so
each expensive target dispatch emits up to ``k+1`` tokens ("LLM
Inference Acceleration via Efficient Operation Fusion", PAPERS.md: the
verification step replaces ``k`` sequential decode dispatches with one
denser program).  Three device bodies live here, compiled by the
engine exactly like every other step program:

- :func:`draft_body` — a ``k+1``-step scan over the draft model: feed
  the stream's last token, then each proposal, so the draft KV cache
  stays in lockstep with the proposals (the extra step writes the last
  proposal's KV; its logits are discarded).
- :func:`verify_body` — a scan of :func:`apex_tpu.serve.model.
  _decode_step` — the EXACT function the plain decode program runs —
  over the ``k+1`` token columns at successive lengths.  Position
  ``j``'s logits are therefore bit-identical to what ``j`` sequential
  decode iterations would have produced, which is what makes the
  greedy speculative stream bit-identical to the non-speculative
  baseline *by construction*, not by tolerance.
- :func:`rollback_body` — per-slot KV truncation: zero the rows of
  rejected positions through the page table (int8 wire: codes to 0,
  scales to the init value 1.0).  Rejected rows are overwritten before
  any read even without it (the next round's writes start exactly at
  the first stale position), so rollback is hygiene the leak/COW
  drills can assert against, not a correctness crutch — the REAL
  correctness obligation is the scheduler's pre-round COW fork of
  shared tail pages, which keeps both verify writes and this rollback
  off pages a co-reader holds.

**Acceptance** (:func:`speculative_verify`, pure and CPU-testable):

- greedy (``temp <= 0``): proposal ``d_{j+1}`` is accepted iff it
  equals ``argmax`` of the target's position-``j`` logits; the emitted
  run ``tgt_0..tgt_a`` IS the sequential greedy chain.
- temperature: the Leviathan et al. rejection sampler — accept
  ``d_{j+1}`` with probability ``min(1, p_j(d)/q_j(d))``, emit a
  residual sample from ``normalize(max(p_j - q_j, 0))`` on the first
  rejection, a bonus sample from ``p_k`` when everything is accepted.
  The emitted marginal is exactly the target softmax (the chi-square
  test in ``tests/test_serve.py`` proves it empirically), and the
  ``k = 0`` stream is bit-identical to plain decode because the bonus
  sample is literally :func:`~apex_tpu.serve.model.sample_tokens`
  under the same per-slot stream key.

**RNG discipline**: every draw keys off ``fold_in(stream_key,
emission_index)`` — a function of the request's identity and its
position in the stream, never of a global call counter — so a
rollback replays bit-identically and a ``k = 0`` speculative
temperature stream equals the non-speculative one.  Acceptance
uniforms and draft proposals ride distinct ``fold_in`` tags off the
same chain so no draw is ever reused.

Draft KV pages live in the same :class:`~apex_tpu.serve.cache.
PagePool` under the ``"draft"`` page namespace; ``leak_check`` proves
they are neither leaked nor shared into the :class:`~apex_tpu.serve.
cache.PrefixCache`.  See docs/serving.md "Speculative decoding".
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.models.gpt import GptConfig
from apex_tpu.serve import model as model_lib

__all__ = [
    "SpecConfig",
    "DRAFT_TAG",
    "ACCEPT_TAG",
    "target_probs",
    "speculative_verify",
    "draft_body",
    "verify_body",
    "rollback_body",
    "draft_from_params",
]

#: ``fold_in`` sub-stream tags: the emission key at index ``g`` is the
#: RAW ``fold_in(stream_key, g)`` (so ``k = 0`` equals plain decode);
#: draft proposals and acceptance uniforms fold these tags on top.
DRAFT_TAG = 0x0D12AF7
ACCEPT_TAG = 0x0ACCE97


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Speculative-decoding knobs for an
    :class:`~apex_tpu.serve.engine.InferenceEngine`.

    ``mode`` names the intended acceptance regime — ``"greedy"``
    (exact-match, bit-identical output) or ``"temperature"`` (the
    rejection sampler).  The compiled verify program always dispatches
    per slot on the request temperature (``temp <= 0`` slots are
    exact-match either way), so a mixed batch is safe in both modes;
    the field exists so deployments state their contract and the
    scheduler can gate accordingly.
    """

    #: the draft model's parameter tree (``GptModel.init`` layout)
    draft_params: object
    #: proposals per round; each target dispatch emits up to ``k + 1``
    #: tokens.  ``k = 0`` degenerates to plain decode through the
    #: verify program (the rng-discipline regression pin).
    k: int = 4
    mode: str = "greedy"
    #: draft model shape; None = the target config (self-draft — the
    #: "friendly draft" whose greedy acceptance is 100% by definition)
    draft_cfg: Optional[GptConfig] = None
    #: degradation ladder: once the windowed acceptance rate over
    #: ``window`` rounds falls below this floor, the scheduler falls
    #: back to plain decode (``serve/spec_fallbacks``) — a draft that
    #: stopped predicting must not keep taxing every round
    min_accept_rate: float = 0.3
    window: int = 64

    def __post_init__(self):
        if self.k < 0:
            raise ValueError(f"k must be >= 0, got {self.k}")
        if self.mode not in ("greedy", "temperature"):
            raise ValueError(
                f"mode must be greedy|temperature, got {self.mode!r}"
            )
        if not 0.0 <= self.min_accept_rate <= 1.0:
            raise ValueError("min_accept_rate must be within [0, 1]")
        if self.window < 1:
            raise ValueError("window must be >= 1")


def draft_from_params(params, num_layers: int):
    """A draft parameter tree from the FIRST ``num_layers`` blocks of a
    scanned GPT tree (embeddings, final LN and any position table are
    shared with the target) — the ``serve_bench --draft-layers N``
    draft: same checkpoint, truncated depth, no second training run."""
    if num_layers < 1:
        raise ValueError(f"draft needs >= 1 layer, got {num_layers}")
    tree = dict(params["params"])
    block = jax.tree_util.tree_map(
        lambda leaf: leaf[:num_layers], tree["layers"]["block"]
    )
    tree["layers"] = {"block": block}
    return {"params": tree}


# ---------------------------------------------------------------------------
# pure acceptance machinery (CPU-testable, used inside the verify program)
# ---------------------------------------------------------------------------


def target_probs(logits, temps, *, top_k: int = 0):
    """The sampling distribution :func:`~apex_tpu.serve.model.
    sample_tokens` draws from — softmax of the top-k-masked logits
    scaled by the temperature.  ``logits`` is ``(..., V)`` f32,
    ``temps`` broadcasts over the leading dims.  Rows with
    ``temp <= 0`` are greedy point masses in spirit; their rows here
    are computed at the clamped temperature and must not be consumed
    (the greedy acceptance path never reads them)."""
    temps = jnp.asarray(temps, jnp.float32)
    vocab = logits.shape[-1]
    masked = logits
    if 0 < top_k < vocab:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        masked = jnp.where(logits < kth, -jnp.inf, logits)
    scaled = masked / jnp.maximum(temps, 1e-6)[..., None]
    return jax.nn.softmax(scaled, axis=-1)


def _fold_each(keys, data):
    """Per-slot ``fold_in`` over a ``(B, 2)`` key batch."""
    return jax.vmap(jax.random.fold_in)(
        keys, jnp.broadcast_to(jnp.asarray(data, jnp.uint32),
                               (keys.shape[0],))
        if jnp.ndim(data) == 0 else jnp.asarray(data, jnp.uint32)
    )


def _residual_sample(p, q, keys):
    """Categorical draw from ``normalize(max(p - q, 0))`` per slot via
    Gumbel-argmax (the mathematically-zero all-zero-residual corner
    falls back to token 0 — it is unreachable when ``p != q`` and
    irrelevant when ``p == q``, where rejection never happens)."""
    res = jnp.maximum(p - q, 0.0)
    logr = jnp.where(res > 0, jnp.log(jnp.maximum(res, 1e-38)), -jnp.inf)
    gumbel = jax.vmap(
        lambda kk: jax.random.gumbel(kk, logr.shape[1:], jnp.float32)
    )(keys)
    return jnp.argmax(logr + gumbel, axis=-1).astype(jnp.int32)


def speculative_verify(ver_logits, draft_tokens, draft_probs, temps,
                       stream_keys, gens, *, top_k: int = 0):
    """Device-side acceptance over one speculative round.

    - ``ver_logits`` ``(k+1, B, V)`` f32 — target logits at positions
      ``j = 0..k`` (position ``j`` scored after consuming column ``j``);
    - ``draft_tokens`` ``(B, k)`` — proposals ``d_1..d_k``; proposal
      ``d_{j+1}`` is judged against position ``j``'s logits;
    - ``draft_probs`` ``(k, B, V)`` — the draft distribution each
      proposal was drawn from (temperature slots only);
    - ``stream_keys`` ``(B, 2)`` uint32 per-slot stream keys, ``gens``
      ``(B,)`` int32 tokens generated so far (the emission index base).

    Returns ``(out_tokens (B, k+1), n_accept (B,))``: slot ``s`` emits
    ``out_tokens[s, :n_accept[s] + 1]`` — its accepted proposals plus
    the correction (first rejection) or bonus (full acceptance) token.
    """
    kp1, b, _ = ver_logits.shape
    k = kp1 - 1
    temps = jnp.asarray(temps, jnp.float32)
    tgt = jnp.argmax(ver_logits, axis=-1).astype(jnp.int32)  # (k+1, B)
    greedy_out = jnp.transpose(tgt)                          # (B, k+1)
    if k == 0:
        bonus = model_lib.sample_tokens(
            ver_logits[0], temps,
            _fold_each(stream_keys, gens), top_k=top_k,
        )
        return bonus[:, None], jnp.zeros((b,), jnp.int32)

    # greedy: d_{j+1} accepted iff it equals the position-j argmax
    g_accept = jnp.transpose(draft_tokens) == tgt[:k]        # (k, B)

    # temperature: u <= p_j(d) / q_j(d), with the same key chain the
    # emitted token at index j would consume (ACCEPT_TAG sub-stream)
    p = target_probs(ver_logits, temps[None, :], top_k=top_k)  # (k+1,B,V)
    d_cols = jnp.transpose(draft_tokens)                     # (k, B)
    rows = jnp.arange(b)
    p_d = jax.vmap(lambda pj, dj: pj[rows, dj])(p[:k], d_cols)
    q_d = jax.vmap(lambda qj, dj: qj[rows, dj])(draft_probs, d_cols)

    def u_at(j):
        keys = _fold_each(_fold_each(stream_keys, gens + j), ACCEPT_TAG)
        return jax.vmap(lambda kk: jax.random.uniform(kk, ()))(keys)

    u = jnp.stack([u_at(j) for j in range(k)])               # (k, B)
    t_accept = u * jnp.maximum(q_d, 1e-38) < p_d
    accept = jnp.where(temps[None, :] > 0, t_accept, g_accept)
    # leading-run length: proposals past the first rejection are dead
    n_accept = jnp.sum(
        jnp.cumprod(accept.astype(jnp.int32), axis=0), axis=0
    ).astype(jnp.int32)                                      # (B,)

    # temperature emissions: accepted drafts verbatim, then at index
    # a the residual sample (a < k) or the bonus sample (a == k) —
    # each emission index j consumes the RAW key fold_in(stream, g+j)
    corrections = []
    for j in range(k + 1):
        keys = _fold_each(stream_keys, gens + j)
        if j < k:
            corrections.append(_residual_sample(p[j], draft_probs[j], keys))
        else:
            corrections.append(
                model_lib.sample_tokens(
                    ver_logits[k], temps, keys, top_k=top_k
                )
            )
    corr = jnp.stack(corrections)                            # (k+1, B)
    idx = jnp.arange(k + 1)[:, None]                         # (k+1, 1)
    drafts_pad = jnp.concatenate(
        [d_cols, jnp.zeros((1, b), jnp.int32)], axis=0
    )                                                        # (k+1, B)
    temp_out = jnp.where(idx < n_accept[None, :], drafts_pad, corr)
    out = jnp.where(temps[None, :] > 0, temp_out, tgt)
    return jnp.transpose(out), n_accept


# ---------------------------------------------------------------------------
# device bodies (compiled by the engine)
# ---------------------------------------------------------------------------


def draft_body(cfg: GptConfig, params, kv_pages: dict, tokens, lengths,
               page_tables, temps, stream_keys, gens, *, k: int,
               page_size: int, kv_wire: str = "f32", top_k: int = 0):
    """``k+1``-step proposal scan over the draft model.  Step ``j``
    feeds the current token at length ``lengths + j`` (writing its
    draft KV) and samples the next proposal from the draft distribution
    (``DRAFT_TAG`` sub-stream; greedy slots argmax).  The last step
    exists only for its KV write, keeping the draft cache in lockstep
    through full-acceptance rounds.  Idle slots (``lengths == 0``)
    stay masked to the null page for every step.

    Returns ``(draft_tokens (B, k), draft_probs (k, B, V), finite
    (B,), kv_pages)``.
    """
    params = model_lib.dequantize_params(params)
    tree = params["params"]

    def step(carry, j):
        cur, kv = carry
        eff = jnp.where(lengths > 0, lengths + j, 0)
        logits, kv = model_lib._decode_step(
            cfg, tree, kv, cur, eff, page_tables,
            page_size=page_size, kv_wire=kv_wire,
        )
        keys = _fold_each(_fold_each(stream_keys, gens + j), DRAFT_TAG)
        nxt = model_lib.sample_tokens(logits, temps, keys, top_k=top_k)
        q = target_probs(logits, temps, top_k=top_k)
        fin = jnp.isfinite(logits).all(axis=-1)
        return (nxt, kv), (nxt, q, fin)

    (_, kv_pages), (toks, probs, fins) = jax.lax.scan(
        step, (tokens, kv_pages), jnp.arange(k + 1)
    )
    draft_tokens = jnp.transpose(toks[:k]) if k else jnp.zeros(
        (tokens.shape[0], 0), jnp.int32
    )
    return draft_tokens, probs[:k], fins.all(axis=0), kv_pages


def verify_body(cfg: GptConfig, params, kv_pages: dict, tokens,
                draft_tokens, lengths, page_tables, temps, draft_probs,
                stream_keys, gens, *, page_size: int,
                kv_wire: str = "f32", top_k: int = 0):
    """ONE target program scoring ``k+1`` positions: a scan of the
    plain decode step (:func:`~apex_tpu.serve.model._decode_step` —
    same function, same shapes, same paged-attention kernel) over the
    columns ``[t_last, d_1..d_k]`` at successive lengths, writing each
    column's KV at its position exactly as ``k+1`` sequential decode
    iterations would.  Acceptance runs on-device
    (:func:`speculative_verify`); only the small token/count arrays
    cross to the host.

    Returns ``(out_tokens (B, k+1), n_accept (B,), finite (B,),
    kv_pages)`` — ``finite[b]`` is slot ``b``'s non-finite screen over
    ALL ``k+1`` of its logits rows.
    """
    params = model_lib.dequantize_params(params)
    tree = params["params"]
    k = draft_tokens.shape[1]
    cols = jnp.concatenate([tokens[:, None], draft_tokens], axis=1)

    def step(kv, j):
        eff = jnp.where(lengths > 0, lengths + j, 0)
        logits, kv = model_lib._decode_step(
            cfg, tree, kv, jnp.take(cols, j, axis=1), eff, page_tables,
            page_size=page_size, kv_wire=kv_wire,
        )
        return kv, logits

    kv_pages, ver_logits = jax.lax.scan(
        step, kv_pages, jnp.arange(k + 1)
    )
    out_tokens, n_accept = speculative_verify(
        ver_logits, draft_tokens, draft_probs, temps, stream_keys,
        gens, top_k=top_k,
    )
    finite = jnp.isfinite(ver_logits).all(axis=(0, 2))
    return out_tokens, n_accept, finite, kv_pages


def rollback_body(kv_pages: dict, starts, counts, page_tables, *,
                  k: int, page_size: int, kv_wire: str = "f32"):
    """Per-slot KV-length truncation: zero the rows of positions
    ``[starts[b], starts[b] + counts[b])`` through slot ``b``'s page
    table (codes to 0; int8 scale planes back to the init value 1.0).
    Masked rows (past a slot's count, or slots with ``counts == 0``)
    land on the null page.  The caller guarantees every touched page
    is private (the scheduler COW-forks shared tail pages BEFORE the
    round that might roll back) — that is what makes the truncation
    safe next to a borrowed prefix-cache run."""
    b = starts.shape[0]
    width = page_tables.shape[1]

    def zero_step(kv, j):
        pos = starts + j
        live = (j < counts) & (starts > 0)
        page_idx = jnp.clip(pos // page_size, 0, width - 1)
        page_ids = jnp.where(
            live, page_tables[jnp.arange(b), page_idx], 0
        )
        slots = pos % page_size
        out = {}
        for name, arr in kv.items():
            fill = 1.0 if name.endswith("_scale") else 0
            upd = jnp.full(
                (b, arr.shape[0], arr.shape[2]) + arr.shape[4:],
                fill, arr.dtype,
            )
            out[name] = arr.at[:, page_ids, :, slots].set(upd)
        return out, None

    kv_pages, _ = jax.lax.scan(
        zero_step, dict(kv_pages), jnp.arange(max(k, 1))
    )
    return kv_pages
