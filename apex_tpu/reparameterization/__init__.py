"""Weight-norm reparameterization — ≙ ``apex/reparameterization/``
(``weight_norm.py`` :: ``WeightNorm``, ``reparameterization.py`` ::
``Reparameterization.apply``).

The reference mutates torch modules in place, splitting ``weight`` into
``weight_g`` (norm) and ``weight_v`` (direction) and recomputing
``weight = g · v/‖v‖`` in a pre-forward hook.  Flax modules are immutable,
so the TPU-native shape is (a) a wrapper module :class:`WeightNorm` that
owns ``g``/``v`` params around any child, and (b) the pure param-tree
transforms :func:`apply_weight_norm` / :func:`remove_weight_norm` that
split/merge an existing checkpoint the same way.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["WeightNorm", "apply_weight_norm", "remove_weight_norm", "compute_weight"]


def _norm_keepdims(v: jax.Array, dim: Optional[int]) -> jax.Array:
    """‖v‖₂ reduced over every axis except ``dim`` (torch _norm semantics)."""
    v32 = v.astype(jnp.float32)
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(v32)))
    axes = tuple(a for a in range(v.ndim) if a != (dim % v.ndim))
    return jnp.sqrt(jnp.sum(jnp.square(v32), axis=axes, keepdims=True))


def compute_weight(g: jax.Array, v: jax.Array, dim: Optional[int] = 0) -> jax.Array:
    """``w = g · v/‖v‖`` — ≙ Reparameterization.compute_weight."""
    return (g.astype(jnp.float32) * v.astype(jnp.float32) / _norm_keepdims(v, dim)).astype(
        v.dtype
    )


def apply_weight_norm(params: Any, name: str = "kernel", dim: Optional[int] = 0) -> Any:
    """Split every ``name`` leaf in a param tree into ``name_g``/``name_v``.

    ≙ apply_weight_norm(module, name, dim) — checkpoint-level, not
    module-level: feed the result to a model whose layers were wrapped in
    :class:`WeightNorm`, or recombine with :func:`remove_weight_norm`.
    """
    if isinstance(params, dict):
        out = {}
        for k, sub in params.items():
            if k == name and isinstance(sub, jax.Array):
                out[f"{name}_g"] = _norm_keepdims(sub, dim).astype(sub.dtype)
                out[f"{name}_v"] = sub
            else:
                out[k] = apply_weight_norm(sub, name, dim)
        return out
    return params


def remove_weight_norm(params: Any, name: str = "kernel", dim: Optional[int] = 0) -> Any:
    """Inverse of :func:`apply_weight_norm` — ≙ remove_weight_norm."""
    if isinstance(params, dict):
        out = {}
        keys = set(params)
        for k, sub in params.items():
            if k == f"{name}_v" and f"{name}_g" in keys:
                out[name] = compute_weight(params[f"{name}_g"], sub, dim)
            elif k == f"{name}_g" and f"{name}_v" in keys:
                continue
            else:
                out[k] = remove_weight_norm(sub, name, dim)
        return out
    return params


class WeightNorm(nn.Module):
    """Wrapper module computing ``w = g·v/‖v‖`` for a child's kernels.

    Usage::

        WeightNorm(nn.Dense(features=64))

    Thin shim over :class:`flax.linen.WeightNorm` (same math as the
    reference's pre-forward hook, applied functionally).  ``dim`` follows
    torch semantics — the axis kept per-unit; flax Dense kernels are
    ``(in, out)`` so the default ``dim=-1`` matches torch Linear's
    ``dim=0`` over its ``(out, in)`` weights.
    """

    layer: nn.Module
    dim: Optional[int] = -1
    epsilon: float = 1e-12

    @nn.compact
    def __call__(self, *args, **kwargs):
        inner = nn.WeightNorm(
            self.layer,
            epsilon=self.epsilon,
            use_scale=True,
            feature_axes=None if self.dim is None else self.dim,
        )
        return inner(*args, **kwargs)
