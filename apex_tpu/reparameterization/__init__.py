"""Weight-norm reparameterization — ≙ ``apex/reparameterization/``
(``weight_norm.py`` :: ``WeightNorm``, ``reparameterization.py`` ::
``Reparameterization.apply``).

The reference mutates torch modules in place, splitting ``weight`` into
``weight_g`` (norm) and ``weight_v`` (direction) and recomputing
``weight = g · v/‖v‖`` in a pre-forward hook.  Flax modules are immutable,
so the TPU-native shape is:

- :class:`WeightNorm` — a wrapper module (thin shim over
  ``flax.linen.WeightNorm``) computing ``g · v/‖v‖`` at apply time;
- :func:`apply_weight_norm` / :func:`remove_weight_norm` — pure
  *checkpoint-level* transforms splitting/merging a plain param tree the
  torch way (``kernel`` ⇄ ``kernel_g``/``kernel_v``);
- :func:`to_wrapper_params` — converts a plain (un-split) param tree of the
  wrapped layer into the variable layout :class:`WeightNorm` expects, so a
  checkpoint trained without weight norm can be loaded into a wrapped model.

``dim`` convention: the axis kept per-unit.  Flax kernels are ``(in, out)``
so the default ``dim=-1`` corresponds to torch Linear's ``dim=0`` over its
``(out, in)`` weights.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "WeightNorm",
    "apply_weight_norm",
    "remove_weight_norm",
    "to_wrapper_params",
    "compute_weight",
]

_ArrayTypes = (jax.Array, np.ndarray)


def _norm_keepdims(v, dim: Optional[int]):
    """‖v‖₂ reduced over every axis except ``dim`` (torch _norm semantics)."""
    v32 = jnp.asarray(v).astype(jnp.float32)
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(v32)))
    axes = tuple(a for a in range(v32.ndim) if a != (dim % v32.ndim))
    return jnp.sqrt(jnp.sum(jnp.square(v32), axis=axes, keepdims=True))


def compute_weight(g, v, dim: Optional[int] = -1):
    """``w = g · v/‖v‖`` — ≙ Reparameterization.compute_weight."""
    v = jnp.asarray(v)
    g32 = jnp.asarray(g).astype(jnp.float32)
    if dim is not None and g32.ndim != v.ndim:
        # feature-shaped g (flax scale layout) → broadcastable keepdims
        shape = [1] * v.ndim
        shape[dim % v.ndim] = v.shape[dim % v.ndim]
        g32 = g32.reshape(shape)
    return (g32 * v.astype(jnp.float32) / _norm_keepdims(v, dim)).astype(v.dtype)


def _is_leaf(x) -> bool:
    return isinstance(x, _ArrayTypes)


def apply_weight_norm(params: Any, name: str = "kernel", dim: Optional[int] = -1) -> Any:
    """Split every ``name`` leaf in a param tree into ``name_g``/``name_v``.

    ≙ torch ``apply_weight_norm(module, name, dim)`` at checkpoint level.
    The result round-trips through :func:`remove_weight_norm`; it is NOT
    the :class:`WeightNorm` module's layout — use :func:`to_wrapper_params`
    for that.  Accepts dict/FrozenDict trees with jax or numpy leaves.
    """
    if isinstance(params, Mapping):
        out = {}
        for k, sub in params.items():
            if k == name and _is_leaf(sub):
                g = _norm_keepdims(sub, dim)
                out[f"{name}_g"] = g.astype(jnp.asarray(sub).dtype)
                out[f"{name}_v"] = sub
            else:
                out[k] = apply_weight_norm(sub, name, dim)
        return out
    return params


def remove_weight_norm(params: Any, name: str = "kernel", dim: Optional[int] = -1) -> Any:
    """Inverse of :func:`apply_weight_norm` — ≙ remove_weight_norm."""
    if isinstance(params, Mapping):
        out = {}
        keys = set(params)
        for k, sub in params.items():
            if k == f"{name}_v" and f"{name}_g" in keys:
                out[name] = compute_weight(params[f"{name}_g"], sub, dim)
            elif k == f"{name}_g" and f"{name}_v" in keys:
                continue
            else:
                out[k] = remove_weight_norm(sub, name, dim)
        return out
    return params


def to_wrapper_params(
    plain_params: Mapping,
    name: str = "kernel",
    dim: Optional[int] = -1,
) -> dict:
    """Plain params of a layer → the :class:`WeightNorm` wrapper's layout.

    ``{'params': {'kernel': w, 'bias': b}}`` becomes
    ``{'params': {'layer': {...}, 'WeightNorm_0': {'layer/kernel/scale': g}}}``
    with ``g = ‖w‖`` per kept-axis unit, so the wrapped module initially
    computes exactly ``w`` (flax WeightNorm stores the un-normalized kernel
    as the direction and normalizes at apply time).
    """
    inner = plain_params.get("params", plain_params)
    scales = {}
    for k, sub in inner.items():
        if k == name and _is_leaf(sub):
            g = _norm_keepdims(sub, dim)
            scales[f"layer/{name}/scale"] = jnp.ravel(g).astype(
                jnp.asarray(sub).dtype
            )
    out = {"layer": dict(inner), "WeightNorm_0": scales}
    return {"params": out} if "params" in plain_params else out


class WeightNorm(nn.Module):
    """Wrapper module computing ``w = g·v/‖v‖`` for a child's kernels.

    Usage::

        WeightNorm(nn.Dense(features=64))

    Thin shim over :class:`flax.linen.WeightNorm` (same math as the
    reference's pre-forward hook, applied functionally).  Load plain
    checkpoints via :func:`to_wrapper_params`.
    """

    layer: nn.Module
    dim: Optional[int] = -1
    epsilon: float = 1e-12

    @nn.compact
    def __call__(self, *args, **kwargs):
        inner = nn.WeightNorm(
            self.layer,
            epsilon=self.epsilon,
            use_scale=True,
            feature_axes=None if self.dim is None else self.dim,
        )
        return inner(*args, **kwargs)
